#!/usr/bin/env python
"""Deterministic fault-injecting HTTP range server (stdlib only).

The ``http(s)://`` counterpart of the ``emu://`` object-store
emulator: serves files under a root directory through real HTTP
Range/ETag/If-Match semantics, with a scripted fault schedule keyed
by a server-wide request counter — no wall-clock, no RNG — so a
failing run replays identically and tier-1 never needs the network.

Fault knobs (0/empty disables; ``match`` scopes faults to requests
whose URL path contains the substring):

* ``throttle_every`` — every Nth request answers 429 with a
  ``Retry-After`` header (``retry_after_s``).
* ``error_every``    — every Nth request answers 503.
* ``reset_every``    — every Nth request drops the connection before
  writing a status line (client sees a reset/remote-disconnect).
* ``short_every``    — every Nth GET advertises the full
  ``Content-Length`` but writes half the body and closes (client
  sees a short/incomplete read).
* ``slow_ms``        — fixed pause before every matching response
  (the tail-latency replica hedging exists to route around).
* ``etag_flip_at``   — from request N on, the served ETag changes
  generation (as if the object were rewritten): conditional
  ``If-Match`` GETs keyed on the old tag answer 412.

Usage (library)::

    from tools.httpfault import FaultPlan, serve
    with serve(root_dir, FaultPlan(throttle_every=3)) as base:
        src = HttpByteRangeSource(base + "/data/f.parquet")

Usage (CLI)::

    python -m tools.httpfault --root DIR [--port 0] \
        [--throttle-every N] [--error-every N] [--reset-every N] \
        [--short-every N] [--slow-ms MS] [--etag-flip-at N] \
        [--url-file PATH]

Prints the base URL on stdout (and to ``--url-file`` for shell
orchestration), then serves until SIGTERM/SIGINT.
"""

from __future__ import annotations

import argparse
import contextlib
import email.utils
import hashlib
import os
import sys
import threading
import time
import urllib.parse
from dataclasses import dataclass
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

__all__ = ["FaultPlan", "FaultHTTPServer", "serve", "main"]


@dataclass
class FaultPlan:
    """The scripted fault schedule (see module docstring)."""

    throttle_every: int = 0
    error_every: int = 0
    reset_every: int = 0
    short_every: int = 0
    slow_ms: float = 0.0
    etag_flip_at: int = 0
    retry_after_s: float = 0.01
    match: str = ""

    def applies(self, path: str) -> bool:
        return not self.match or self.match in path


class FaultHTTPServer(ThreadingHTTPServer):
    """ThreadingHTTPServer carrying the fault plan and the request
    counter every fault decision keys on."""

    daemon_threads = True

    def __init__(self, addr, root: str, plan: FaultPlan | None = None):
        super().__init__(addr, _Handler)
        self.root = os.path.abspath(root)
        self.plan = plan if plan is not None else FaultPlan()
        self._lock = threading.Lock()  # guards _requests
        self._requests = 0

    def next_request(self) -> int:
        with self._lock:
            self._requests += 1
            return self._requests

    @property
    def base_url(self) -> str:
        host, port = self.server_address[:2]
        return f"http://{host}:{port}"


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    server_version = "httpfault/1"

    def log_message(self, fmt, *args):  # quiet by default
        if os.environ.get("TPQ_HTTPFAULT_LOG"):
            super().log_message(fmt, *args)

    # -- object resolution ------------------------------------------------
    def _resolve(self):
        """URL path -> (fs path, size, mtime_ns) or None (404'd)."""
        raw = urllib.parse.unquote(
            urllib.parse.urlsplit(self.path).path)
        fs = os.path.abspath(os.path.join(
            self.server.root, raw.lstrip("/")))
        prefix = self.server.root.rstrip(os.sep) + os.sep
        if fs != self.server.root and not fs.startswith(prefix):
            self.send_error(404, "outside root")
            return None
        try:
            st = os.stat(fs)
        except OSError:
            self.send_error(404, "no such object")
            return None
        if not os.path.isfile(fs):
            self.send_error(404, "not a file")
            return None
        return fs, st.st_size, st.st_mtime_ns

    def _etag(self, fs, size, mtime_ns, n: int) -> str:
        gen = (2 if self.server.plan.etag_flip_at
               and n >= self.server.plan.etag_flip_at else 1)
        h = hashlib.sha1(
            f"{fs}|{size}|{mtime_ns}|g{gen}".encode()).hexdigest()[:20]
        return f'"{h}"'

    # -- the scripted faults ----------------------------------------------
    def _scripted_fault(self, n: int, *, get: bool) -> str | None:
        """Apply any pre-body fault due at request ``n``.  Returns
        ``"handled"`` when a response (or abort) was already issued,
        ``"short"`` when the GET body must be truncated, else None."""
        plan = self.server.plan
        if not plan.applies(self.path):
            return None
        if plan.slow_ms > 0:
            time.sleep(plan.slow_ms / 1e3)
        if plan.reset_every and n % plan.reset_every == 0:
            # die before the status line: the client observes a
            # remote disconnect / connection reset
            self.close_connection = True
            with contextlib.suppress(OSError):
                self.connection.shutdown(2)  # SHUT_RDWR
            return "handled"
        if plan.throttle_every and n % plan.throttle_every == 0:
            self.send_response(429)
            self.send_header("Retry-After",
                             f"{plan.retry_after_s:g}")
            self.send_header("Content-Length", "0")
            self.end_headers()
            return "handled"
        if plan.error_every and n % plan.error_every == 0:
            self.send_response(503)
            self.send_header("Content-Length", "0")
            self.end_headers()
            return "handled"
        if get and plan.short_every and n % plan.short_every == 0:
            return "short"
        return None

    # -- verbs ------------------------------------------------------------
    def do_HEAD(self):
        n = self.server.next_request()
        obj = self._resolve()
        if obj is None:
            return
        if self._scripted_fault(n, get=False) == "handled":
            return
        fs, size, mtime_ns = obj
        self.send_response(200)
        self.send_header("ETag", self._etag(fs, size, mtime_ns, n))
        self.send_header("Accept-Ranges", "bytes")
        self.send_header("Content-Length", str(size))
        self.send_header("Last-Modified",
                         email.utils.formatdate(mtime_ns / 1e9,
                                                usegmt=True))
        self.end_headers()

    def do_GET(self):
        n = self.server.next_request()
        obj = self._resolve()
        if obj is None:
            return
        fault = self._scripted_fault(n, get=True)
        if fault == "handled":
            return
        fs, size, mtime_ns = obj
        etag = self._etag(fs, size, mtime_ns, n)
        cond = self.headers.get("If-Match")
        if cond is not None and cond.strip() not in (etag, "*"):
            self.send_response(412)
            self.send_header("ETag", etag)
            self.send_header("Content-Length", "0")
            self.end_headers()
            return
        rng = self._parse_range(size)
        if rng == "bad":
            self.send_response(416)
            self.send_header("Content-Range", f"bytes */{size}")
            self.send_header("Content-Length", "0")
            self.end_headers()
            return
        if rng is None:
            status, start, length = 200, 0, size
        else:
            start, length = rng
            status = 206
        self.send_response(status)
        self.send_header("ETag", etag)
        self.send_header("Accept-Ranges", "bytes")
        self.send_header("Content-Length", str(length))
        if status == 206:
            self.send_header(
                "Content-Range",
                f"bytes {start}-{start + length - 1}/{size}")
        self.end_headers()
        with open(fs, "rb") as f:
            f.seek(start)
            body = f.read(length)
        if fault == "short" and len(body) > 1:
            # advertise full length, ship half, hang up: the client
            # must detect the short read and retry
            self.wfile.write(body[: len(body) // 2])
            self.wfile.flush()
            self.close_connection = True
            with contextlib.suppress(OSError):
                self.connection.shutdown(2)
            return
        self.wfile.write(body)

    def _parse_range(self, size: int):
        """``bytes=a-b`` -> (start, length); None = whole object;
        ``"bad"`` = unsatisfiable (416)."""
        hdr = self.headers.get("Range")
        if not hdr or not hdr.startswith("bytes="):
            return None
        spec = hdr[len("bytes="):].split(",")[0].strip()
        first, _, last = spec.partition("-")
        try:
            if first:
                start = int(first)
                end = int(last) if last else size - 1
            else:  # suffix form: bytes=-N
                start = max(0, size - int(last))
                end = size - 1
        except ValueError:
            return "bad"
        if start >= size or start < 0 or end < start:
            return "bad"
        end = min(end, size - 1)
        return start, end - start + 1


@contextlib.contextmanager
def serve(root: str, plan: FaultPlan | None = None, port: int = 0):
    """Start a fault server over ``root`` on localhost; yields the
    base URL; shuts down on exit."""
    srv = FaultHTTPServer(("127.0.0.1", port), root, plan)
    t = threading.Thread(target=srv.serve_forever,
                         name="httpfault", daemon=True)
    t.start()
    try:
        yield srv.base_url
    finally:
        srv.shutdown()
        srv.server_close()
        t.join(10.0)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--root", required=True,
                    help="directory served as the object store")
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument("--throttle-every", type=int, default=0)
    ap.add_argument("--error-every", type=int, default=0)
    ap.add_argument("--reset-every", type=int, default=0)
    ap.add_argument("--short-every", type=int, default=0)
    ap.add_argument("--slow-ms", type=float, default=0.0)
    ap.add_argument("--etag-flip-at", type=int, default=0)
    ap.add_argument("--retry-after-s", type=float, default=0.01)
    ap.add_argument("--match", default="",
                    help="apply faults only to URL paths containing "
                         "this substring")
    ap.add_argument("--url-file", default="",
                    help="also write the base URL to this file")
    args = ap.parse_args(argv)
    plan = FaultPlan(
        throttle_every=args.throttle_every,
        error_every=args.error_every,
        reset_every=args.reset_every,
        short_every=args.short_every,
        slow_ms=args.slow_ms,
        etag_flip_at=args.etag_flip_at,
        retry_after_s=args.retry_after_s,
        match=args.match)
    srv = FaultHTTPServer(("127.0.0.1", args.port), args.root, plan)
    print(srv.base_url, flush=True)
    if args.url_file:
        with open(args.url_file, "w") as f:
            f.write(srv.base_url)
    try:
        srv.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        srv.server_close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
