"""Scan-scale sweep with output-placement legs (SCAN_SCALE_r06).

Successor of the ShardedScan half of ``tools/scan_scale_curve.py``:
fixed total work on 1/2/4/8-device meshes, phases = scan (host plan +
stage + kernel dispatch per unit) and gather, but the gather now runs
THREE legs per mesh size:

* ``replicated``   — the seed out-sharding: every decoded byte
  all-gathered to every device.  r05 pinned its defect: ``gather_s``
  nearly doubles 1→8 devices at fixed work because the shipped volume
  is data x n_devices.
* ``gather_to``    — one consumer device (``gather_to=devices[0]``):
  the volume is the data, once — cost must stay flat in mesh size.
* ``sharded2``     — a 2-way consumer mesh (``NamedSharding`` over a
  "data" axis): each destination shard receives its half.

Each leg also records what the reshard ACTUALLY shipped from the new
exactly-merging counters (``gather_bytes_moved`` /
``gather_bytes_replicated`` / ``gather_reshard_s``), so the r05 "is
the volume irreducible?" question is answered by counters, and every
placed leg is parity-checked against the replicated values in-run.

On virtual CPU devices every "device" is the same host, so absolute
speedup is meaningless — what this measures is how the orchestration
and the shipped volume scale with the mesh, which IS transferable to
real chips (the phases are the same code).  ``tools/
bench_opportunist.sh`` queues this sweep on the first healthy device
window to capture the real-ICI curve.

    python tools/bench_scan_scale.py [out.json]

Env: TPQ_SCAN_SCALE_UNITS (default 16), TPQ_SCAN_SCALE_VALUES
(default 1_000_000 per unit), TPQ_SCAN_SCALE_REPS (default 3, first
rep is compile warmup), TPQ_SCAN_SCALE_BACKEND=device to run on the
real accelerator (default: the pinned virtual-8 CPU mesh; the
opportunist loop passes device).
"""

import io
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

if __name__ == "__main__" and \
        os.environ.get("TPQ_SCAN_SCALE_BACKEND", "cpu") != "device":
    from tools._pin import pin_cpu

    pin_cpu(devices=8)

import jax  # noqa: E402
import numpy as np  # noqa: E402


def _legs(nd):
    """(name, placement kwargs) per leg; the sharded-consumer leg
    shrinks to the devices the mesh actually has."""
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    devs = jax.local_devices()
    consumer = Mesh(np.asarray(devs[: min(2, nd)]), ("data",))
    return [
        ("replicated", {}),
        ("gather_to", {"gather_to": devs[0]}),
        ("sharded2", {"out_sharding": NamedSharding(consumer,
                                                    P("data"))}),
    ]


def bench_sharded_scan(n_units, nv, reps):
    from tpuparquet import CompressionCodec, FileWriter
    from tpuparquet.shard.mesh import make_mesh
    from tpuparquet.shard.scan import ShardedScan, gather_column
    from tpuparquet.stats import collect_stats

    rng = np.random.default_rng(6)
    buf = io.BytesIO()
    w = FileWriter(buf, "message m { required int64 v; }",
                   codec=CompressionCodec.SNAPPY)
    for _ in range(n_units):
        w.write_columns({"v": rng.integers(0, 1 << 40, size=nv)})
    w.close()

    curves = {name: [] for name, _ in _legs(8)}
    avail = len(jax.local_devices())
    for nd in (n for n in (1, 2, 4, 8) if n <= avail):
        mesh = make_mesh(nd, sp=1)
        best_scan = None
        results = None
        ref = None
        best_gather = {}
        for rep in range(reps):
            buf.seek(0)
            scan = ShardedScan([buf], mesh=mesh)
            t0 = time.perf_counter()
            results = scan.run()
            for res in results:
                for c in res.values():
                    c.block_until_ready()
            t_scan = time.perf_counter() - t0
            for name, kw in _legs(nd):
                with collect_stats() as st:
                    t1 = time.perf_counter()
                    vals, counts = gather_column(mesh, results, "v",
                                                 **kw)
                    jax.block_until_ready(vals)
                    t_gather = time.perf_counter() - t1
                if rep == 0:
                    if name == "replicated":
                        ref = (np.asarray(vals), counts)
                    else:
                        # placed legs must be byte-identical to the
                        # replicated gather (padding rows aside)
                        got = np.asarray(vals)[: len(ref[1])]
                        np.testing.assert_array_equal(got, ref[0])
                    continue  # compile warmup
                cur = best_gather.get(name)
                if cur is None or t_gather < cur["gather_s"]:
                    best_gather[name] = {
                        "gather_s": t_gather,
                        "bytes_moved": st.gather_bytes_moved,
                        "bytes_replicated": st.gather_bytes_replicated,
                        "reshard_s": round(st.gather_reshard_s, 3),
                    }
            if rep == 0:
                continue
            if best_scan is None or t_scan < best_scan:
                best_scan = t_scan
        true_bytes = n_units * nv * 8
        for name, rec in best_gather.items():
            g = rec["gather_s"]
            curves[name].append({
                "devices": nd,
                "scan_s": round(best_scan, 3),
                "gather_s": round(g, 3),
                "values_per_sec": round(n_units * nv
                                        / (best_scan + g), 1),
                "bytes_moved": rec["bytes_moved"],
                "bytes_replicated": rec["bytes_replicated"],
                "reshard_s": rec["reshard_s"],
                "moved_over_true": round(rec["bytes_moved"]
                                         / true_bytes, 2),
            })
    return {"n_units": n_units, "values_per_unit": nv,
            "legs": curves}


def main():
    out_path = (sys.argv[1] if len(sys.argv) > 1
                else "SCAN_SCALE_r06.json")
    n_units = int(os.environ.get("TPQ_SCAN_SCALE_UNITS", 16))
    nv = int(os.environ.get("TPQ_SCAN_SCALE_VALUES", 1_000_000))
    # rep 0 is always compile warmup, so fewer than 2 reps would
    # measure nothing and crash the summary on empty legs
    reps = max(int(os.environ.get("TPQ_SCAN_SCALE_REPS", 3)), 2)
    t0 = time.time()
    scan = bench_sharded_scan(n_units, nv, reps)
    legs = scan["legs"]

    nds = [p["devices"] for p in legs["replicated"]]
    hi, lo = max(nds), min(nds)

    def g(leg, nd):
        return next(p["gather_s"] for p in legs[leg]
                    if p["devices"] == nd)

    rec = {
        "backend": jax.devices()[0].platform + "-virtual-8"
        if jax.devices()[0].platform == "cpu"
        else jax.devices()[0].device_kind,
        "sharded_scan": scan,
        # the ROADMAP-item-5 acceptance observable: max-mesh gather
        # over min-mesh gather at fixed work, per leg (bar: <= 1.3 on
        # the consumer-aligned legs)
        "acceptance": {
            f"replicated_{hi}v{lo}": round(g("replicated", hi)
                                           / g("replicated", lo), 2),
            f"gather_to_{hi}v{lo}": round(g("gather_to", hi)
                                          / g("gather_to", lo), 2),
            f"sharded2_{hi}v{lo}": round(g("sharded2", hi)
                                         / g("sharded2", lo), 2),
        },
        "finding": (
            "consumer-aligned placement kills the gather wall: the "
            "replicated leg ships data x n_devices (visible in "
            "bytes_replicated) and its gather_s grows with the mesh; "
            "the gather_to/sharded2 legs ship the data once "
            "(bytes_replicated == 0) and stay flat 1->8 devices at "
            "fixed work; placed values parity-checked against the "
            "replicated gather in-run"),
        "wall_s": round(time.time() - t0, 1),
    }
    print(json.dumps(rec, indent=1))
    with open(out_path, "w") as f:
        json.dump(rec, f, indent=1)


if __name__ == "__main__":
    main()
