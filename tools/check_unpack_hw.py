"""On-chip bit-unpack parity sweep: XLA and Pallas vs the CPU oracle.

Codifies the hardware check that caught the Mosaic straddle-shift
miscompile (see ``kernels/bitunpack.py:_unpack_block_unrolled``): on
TPU v5e, the ``(lo >> sh) | (hi << (32-sh))`` formulation corrupted
every width >= 17 while interpret mode was clean.  The shipped kernel
uses the multiply workaround; this sweep re-verifies both device
formulations at every width against the NumPy oracle so a Mosaic or
XLA regression (or a workaround regression) is caught in one minute of
tunnel time.

Usage: python tools/check_unpack_hw.py [n_values]   (default 1M)
Exit code 0 = all clean.
"""

from __future__ import annotations

import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    import jax

    from tpuparquet.cpu.bitpack import pack, unpack
    from tpuparquet.kernels.bitunpack import (pad_to_words, unpack_u32,
                                              unpack_u32_pallas)

    n = int(sys.argv[1]) if len(sys.argv) > 1 else 1_000_000
    print(f"backend={jax.default_backend()}  n={n}")
    rng = np.random.default_rng(1)
    failures = 0
    for width in range(1, 33):
        vals = rng.integers(0, 1 << width, size=n, dtype=np.uint64)
        packed = pack(vals, width)
        oracle = unpack(packed, n, width).astype(np.uint32)
        words = jax.device_put(pad_to_words(packed, width, n).reshape(-1))
        for name, fn in (("xla", unpack_u32), ("pallas", unpack_u32_pallas)):
            got = np.asarray(fn(words, width, n))
            bad = np.nonzero(got != oracle)[0]
            if bad.size:
                failures += 1
                lanes = sorted(set((bad % 32).tolist()))
                print(f"FAIL width {width:2d} {name}: {bad.size} bad, "
                      f"lanes {lanes[:8]}")
    print("ALL CLEAN (widths 1..32, xla + pallas)" if not failures
          else f"{failures} (width, path) failures")
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
