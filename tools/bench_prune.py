"""Predicate-pushdown bench: e2e scan wall vs selectivity.

Writes a clustered corpus (``x`` sorted across row groups — the shape
production partition/cluster keys have), then times a full unfiltered
``ShardedScan`` against filtered scans at ~1%, ~10% and ~50%
selectivity.  The acceptance bar (ISSUE 7): filtered e2e time scales
with selectivity — >= 5x speedup at 1% vs unfiltered — and the
pruning counters account exactly for every row: every row is either
statically pruned, filtered out exactly, or returned.

Output: ``PRUNE_r01.json`` at the repo root (or ``--out``).

Knobs: ``TPQ_PRUNE_BENCH_ROWS`` (default 50_000_000),
``TPQ_PRUNE_BENCH_FILES`` (default 8), ``TPQ_PRUNE_BENCH_REPS``
(default 2; best-of wall per leg).
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np  # noqa: E402


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, ""))
    except ValueError:
        return default


def write_corpus(d: str, total_rows: int, n_files: int,
                 rows_per_rg: int) -> list:
    from tpuparquet.format.metadata import CompressionCodec
    from tpuparquet.io.writer import FileWriter

    paths = []
    rng = np.random.default_rng(42)
    written = 0
    per_file = (total_rows + n_files - 1) // n_files
    for fi in range(n_files):
        p = os.path.join(d, f"prune_{fi:02d}.parquet")
        with open(p, "wb") as fh:
            w = FileWriter(fh, "message m { required int64 x; "
                               "required double v; required int64 t; }",
                           codec=CompressionCodec.SNAPPY)
            left = min(per_file, total_rows - written)
            while left > 0:
                n = min(rows_per_rg, left)
                lo = written
                w.write_columns({
                    "x": np.arange(lo, lo + n, dtype=np.int64),
                    "v": rng.random(n),
                    "t": rng.integers(0, 1 << 40, n),
                })
                written += n
                left -= n
            w.close()
        paths.append(p)
    return paths


def run_scan(paths, filt):
    """One e2e ShardedScan; returns (wall_s, rows_out, stats)."""
    from tpuparquet.shard.scan import ShardedScan
    from tpuparquet.stats import collect_stats

    t0 = time.perf_counter()
    with collect_stats() as st:
        s = ShardedScan(paths, filter=filt)
        try:
            rows = 0
            for _k, out in s.run_iter():
                c = out["x"]
                c.block_until_ready()
                rows += c.num_values
        finally:
            s.close()
    return time.perf_counter() - t0, rows, st


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser()
    ap.add_argument("--rows", type=int,
                    default=_env_int("TPQ_PRUNE_BENCH_ROWS", 50_000_000))
    ap.add_argument("--files", type=int,
                    default=_env_int("TPQ_PRUNE_BENCH_FILES", 8))
    ap.add_argument("--reps", type=int,
                    default=_env_int("TPQ_PRUNE_BENCH_REPS", 2))
    ap.add_argument("--out", default=os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "PRUNE_r01.json"))
    ap.add_argument("--keep-corpus", default="")
    args = ap.parse_args(argv)

    from tpuparquet.filter import col

    total = args.rows
    rows_per_rg = max(total // (args.files * 8), 1)
    d = args.keep_corpus or tempfile.mkdtemp(prefix="tpq_prune_")
    t0 = time.perf_counter()
    paths = write_corpus(d, total, args.files, rows_per_rg)
    write_s = time.perf_counter() - t0
    print(f"corpus: {total:,} rows in {len(paths)} files "
          f"({rows_per_rg:,} rows/rg), wrote in {write_s:.1f}s",
          flush=True)

    legs = {
        "unfiltered": (None, total),
        "sel_50pct": (col("x") < int(total * 0.50), int(total * 0.50)),
        "sel_10pct": (col("x") < int(total * 0.10), int(total * 0.10)),
        "sel_1pct": (col("x") < int(total * 0.01), int(total * 0.01)),
    }
    report = {"rows": total, "files": len(paths),
              "rows_per_rg": rows_per_rg,
              "page_rows": _env_int("TPQ_PAGE_ROWS", 0),
              "write_s": round(write_s, 3),
              "reps": args.reps, "legs": {}}
    ok = True
    notes = []
    walls = {}
    for name, (filt, expect) in legs.items():
        best = None
        leg = None
        for _rep in range(max(args.reps, 1)):
            wall, rows, st = run_scan(paths, filt)
            if best is None or wall < best:
                best = wall
                d_st = st.as_dict()
                leg = {
                    "wall_s": round(wall, 3),
                    "rows_out": rows,
                    "row_groups_pruned": d_st["row_groups_pruned"],
                    "pages_pruned": d_st["pages_pruned"],
                    "rows_pruned": d_st["rows_pruned"],
                    "filter_rows_in": d_st["filter_rows_in"],
                    "filter_rows_out": d_st["filter_rows_out"],
                    "selectivity": d_st["selectivity"],
                }
        walls[name] = best
        if leg["rows_out"] != expect:
            ok = False
            notes.append(f"{name}: rows_out {leg['rows_out']} != "
                         f"expected {expect}")
        if filt is not None:
            # exact accounting: every row pruned, filtered, or kept
            if leg["rows_pruned"] + leg["filter_rows_in"] != total:
                ok = False
                notes.append(
                    f"{name}: rows_pruned {leg['rows_pruned']} + "
                    f"filter_rows_in {leg['filter_rows_in']} != {total}")
            if leg["filter_rows_out"] != leg["rows_out"]:
                ok = False
                notes.append(f"{name}: filter_rows_out != rows_out")
        report["legs"][name] = leg
        print(f"  {name}: {leg['wall_s']}s, {leg['rows_out']:,} rows, "
              f"{leg['row_groups_pruned']} rgs pruned", flush=True)

    base = walls["unfiltered"]
    for name, floor in (("sel_1pct", 5.0), ("sel_10pct", 1.5),
                        ("sel_50pct", 1.0)):
        sp = base / walls[name] if walls[name] else float("inf")
        report["legs"][name]["speedup_vs_unfiltered"] = round(sp, 2)
        if sp < floor:
            ok = False
            notes.append(f"{name}: speedup {sp:.2f}x < floor {floor}x")
    # monotone: tighter predicates are never slower
    if not (walls["sel_1pct"] <= walls["sel_10pct"] * 1.25
            <= walls["sel_50pct"] * 1.25 * 1.25):
        notes.append("walls not monotone in selectivity (noise?)")

    report["ok"] = ok
    report["notes"] = notes
    with open(args.out, "w") as f:
        json.dump(report, f, indent=2, sort_keys=True)
        f.write("\n")
    print(json.dumps({"ok": ok, "speedup_1pct":
                      report["legs"]["sel_1pct"]
                      ["speedup_vs_unfiltered"],
                      "out": args.out}))
    if not args.keep_corpus:
        for p in paths:
            try:
                os.unlink(p)
            except OSError:
                pass
        try:
            os.rmdir(d)
        except OSError:
            pass
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
