"""Same-process A/B of the wire transports (round-4 verdict item 3).

For each bench config: e2e device decode with the gated transports ON
vs OFF (TPQ_DEVICE_PLANES / TPQ_DEVICE_SNAPPY flipped between passes in
this process), plus bytes_staged for each.  Run on the real chip:

    timeout 1800 python tools/bench_wire.py [target_values]
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def measure(reader, reps: int = 3):
    from tpuparquet.kernels.device import read_row_groups_device
    from tpuparquet.stats import collect_stats

    best, staged = float("inf"), 0
    for _ in range(reps):
        with collect_stats() as st:
            t0 = time.perf_counter()
            for _rg, out in read_row_groups_device(reader):
                for c in out.values():
                    c.block_until_ready()
            dt = time.perf_counter() - t0
        best = min(best, dt)
        staged = st.bytes_staged
    return best, staged


def main() -> None:
    if len(sys.argv) > 1:
        os.environ["TPQ_BENCH_TARGET"] = sys.argv[1]
    import bench
    from tpuparquet import FileReader

    for name, builder in [("1-plain", bench.build_config1),
                          ("2-taxi", bench.build_config2),
                          ("3-delta-nested", bench.build_config3),
                          ("4-wide-string", bench.build_config4)]:
        buf = builder()
        reader = FileReader(buf)
        n = sum(rg.num_rows for rg in reader.meta.row_groups)
        os.environ["TPQ_DEVICE_PLANES"] = "0"
        os.environ["TPQ_DEVICE_SNAPPY"] = "0"
        measure(reader, reps=1)  # warmup/compile
        off_s, off_b = measure(reader)
        os.environ["TPQ_DEVICE_PLANES"] = "1"
        os.environ["TPQ_DEVICE_SNAPPY"] = "1"
        measure(reader, reps=1)
        on_s, on_b = measure(reader)
        print(json.dumps({
            "config": name, "rows": n,
            "off_s": round(off_s, 3), "on_s": round(on_s, 3),
            "speedup": round(off_s / on_s, 3),
            "off_staged_mb": round(off_b / 1e6, 1),
            "on_staged_mb": round(on_b / 1e6, 1),
        }), flush=True)
        os.environ.pop("TPQ_DEVICE_PLANES", None)
        os.environ.pop("TPQ_DEVICE_SNAPPY", None)


if __name__ == "__main__":
    main()
