#!/usr/bin/env python
"""Always-on telemetry overhead bench: recorder/metrics on vs off.

Decodes the 50M-value taxi shape (``bench.build_config2``) through a
``ShardedScan`` under three telemetry configurations:

* ``off``        — recorder disabled, live metrics disabled, no
                   collector: the bare hot path (what a no-obs build
                   would run).
* ``always_on``  — the DEFAULT shipping configuration: flight
                   recorder armed, live metrics folding at unit
                   boundaries, causal tracing compiled in but OFF
                   (``TPQ_TRACE`` unset), still no user collector.
                   Its delta vs ``off`` staying at the r07-recorded
                   noise level is the proof that the round-16 trace
                   hot-site guards cost nothing when disabled.
* ``trace_on``   — ``always_on`` plus the causal tracer ARMED
                   (``TPQ_TRACE=1``, sample 1.0): what a diagnosis
                   session pays.
* ``profile_on`` — ``always_on`` plus the round-20 sampling profiler
                   ARMED at its default rate (``TPQ_PROFILE=1``):
                   what a live flamegraph costs while it runs.
* ``collected``  — a full ``collect_stats(events=True)`` scope on top
                   (the post-hoc regime's known cost, for scale).

Reports min/median walls over ``--reps`` repetitions and the
``always_on`` overhead vs ``off`` in percent — the number
``BENCH_NOTES_r07.md`` records and the CI stage bounds
(``--assert-overhead PCT`` exits nonzero past the bound).

Usage::

    JAX_PLATFORMS=cpu python tools/bench_obs.py \
        [--values 50000000] [--reps 3] [--assert-overhead 25] [--out F]
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def _decode_once(buf):
    from tpuparquet.shard.scan import ShardedScan

    buf.seek(0)
    scan = ShardedScan([buf])
    n = 0
    for _k, cols in scan.run_iter():
        for c in cols.values():
            c.block_until_ready()
        n += 1
    return n


def _run_leg(buf, name: str, reps: int) -> dict:
    from tpuparquet.obs import live, profiler, recorder, trace

    from tpuparquet.stats import collect_stats

    walls = []
    for _ in range(reps):
        trace.set_tracing(False)
        profiler.set_profiling(False)
        if name == "off":
            recorder.set_ring(0)
            os.environ["TPQ_LIVE_METRICS"] = "0"
            ctx = None
        elif name == "always_on":
            recorder.set_ring(recorder.ring_default() or 256)
            os.environ["TPQ_LIVE_METRICS"] = "1"
            ctx = None
        elif name == "trace_on":
            # the round-16 causal tracer ARMED on top of the shipping
            # default: spans per unit/stage/chunk, whole-trace
            # sampling at 1.0 — the worst case the TPQ_TRACE knob buys
            recorder.set_ring(recorder.ring_default() or 256)
            os.environ["TPQ_LIVE_METRICS"] = "1"
            trace.set_tracing(True)
            ctx = None
        elif name == "profile_on":
            # the round-20 sampling profiler ARMED at the default
            # rate: sys._current_frames() walks on a jittered grid,
            # stage/wait tagging live at every hot site
            recorder.set_ring(recorder.ring_default() or 256)
            os.environ["TPQ_LIVE_METRICS"] = "1"
            profiler.set_profiling(True)
            ctx = None
        else:  # collected
            recorder.set_ring(recorder.ring_default() or 256)
            os.environ["TPQ_LIVE_METRICS"] = "1"
            ctx = collect_stats(events=True)
        live.reset_registry()
        t0 = time.perf_counter()
        if ctx is None:
            units = _decode_once(buf)
        else:
            with ctx:
                units = _decode_once(buf)
        walls.append(time.perf_counter() - t0)
    profiler.set_profiling(False)
    return {"leg": name, "units": units, "reps": reps,
            "wall_s_min": round(min(walls), 4),
            "wall_s_median": round(statistics.median(walls), 4),
            "wall_s_all": [round(w, 4) for w in walls]}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--values", type=int, default=50_000_000,
                    help="total values in the taxi-shaped corpus")
    ap.add_argument("--reps", type=int, default=3)
    ap.add_argument("--assert-overhead", type=float, default=None,
                    metavar="PCT",
                    help="exit 1 if always_on exceeds off by more "
                         "than PCT%% (on min walls)")
    ap.add_argument("--out", default="",
                    help="also write the JSON report here")
    ap.add_argument("--device", action="store_true",
                    help="measure on the default (device) backend "
                         "instead of pinning CPU")
    args = ap.parse_args(argv)

    if not args.device:
        # telemetry overhead is a HOST-side property: pin the CPU
        # backend via jax.config (the env var alone is overridden by
        # this environment's sitecustomize axon registration), so the
        # guard measures the hot path it was calibrated against even
        # on a TPU-attached host
        import jax

        jax.config.update("jax_platforms", "cpu")

    import bench

    buf = bench.build_config2(n_values=args.values)
    # one warmup decode: jit compilation must not land in any leg
    _decode_once(buf)

    legs = [_run_leg(buf, name, args.reps)
            for name in ("off", "always_on", "trace_on", "profile_on",
                         "collected")]
    by = {leg["leg"]: leg for leg in legs}
    base = by["off"]["wall_s_min"]
    overhead = {
        name: round((by[name]["wall_s_min"] / base - 1.0) * 100, 2)
        for name in ("always_on", "trace_on", "profile_on",
                     "collected")
    }
    report = {
        "bench": "obs_overhead",
        "values": args.values,
        "legs": legs,
        "overhead_pct_vs_off_min": overhead,
    }
    print(json.dumps(report, indent=2))
    if args.out:
        with open(args.out, "w") as f:
            json.dump(report, f, indent=2)
    if args.assert_overhead is not None \
            and overhead["always_on"] > args.assert_overhead:
        print(f"bench_obs: always_on overhead "
              f"{overhead['always_on']}% exceeds the "
              f"{args.assert_overhead}% bound", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
