"""Generate the checked-in torn-file corpus (tests/corpus/torn/).

Writes a small deterministic multi-row-group file (the oracle), then
derives torn variants from its bytes: truncations at a row-group
boundary, at an interior page boundary, mid-page, plus a
corrupted-footer variant and a hint-less truncation (salvage must come
from a donor).  A manifest records, for each variant, how many complete
row groups salvage is expected to recover — the truncation-sweep test
(tests/test_salvage.py) asserts salvage recovers exactly those, bit
exact against the oracle.

Run from the repo root:  python tools/make_torn_corpus.py
Regenerate only when the writer's byte layout intentionally changes.
"""

from __future__ import annotations

import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from tpuparquet import CompressionCodec, FileWriter  # noqa: E402
from tpuparquet.cpu.plain import ByteArrayColumn  # noqa: E402
from tpuparquet.format.recover import forward_scan  # noqa: E402
from tpuparquet.format.footer import read_file_metadata  # noqa: E402

OUT = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "tests", "corpus", "torn")

N_RG = 3
N = 120  # rows per row group


def write_oracle(path: str, salvage_hint: bool) -> bytes:
    rng = np.random.default_rng(20260804)
    with open(path, "wb") as f:
        w = FileWriter(
            f,
            "message torn { required int64 a; optional binary s (STRING);"
            " required double x; }",
            codec=CompressionCodec.SNAPPY,
            salvage_hint=salvage_hint,
        )
        for rg in range(N_RG):
            mask = (np.arange(N) % 6) != 0
            w.write_columns(
                {
                    "a": np.arange(rg * N, (rg + 1) * N, dtype=np.int64),
                    "s": ByteArrayColumn.from_list(
                        [b"row-%05d" % v
                         for v in rng.integers(0, 99999, int(mask.sum()))]),
                    "x": rng.standard_normal(N),
                },
                masks={"s": mask},
            )
        w.close()
    with open(path, "rb") as f:
        return f.read()


def rg_ends(path: str) -> list[int]:
    """Byte offset just past the last chunk of each row group."""
    with open(path, "rb") as f:
        meta = read_file_metadata(f)
    ends = []
    for rg in meta.row_groups:
        end = 0
        for cc in rg.columns:
            cm = cc.meta_data
            start = cm.data_page_offset
            if cm.dictionary_page_offset is not None:
                start = min(start, cm.dictionary_page_offset)
            end = max(end, start + cm.total_compressed_size)
        ends.append(end)
    return ends


def main() -> None:
    os.makedirs(OUT, exist_ok=True)
    manifest = {"description": __doc__.strip().splitlines()[0],
                "rows_per_row_group": N, "row_groups": N_RG, "files": {}}

    oracle = os.path.join(OUT, "oracle.parquet")
    data = write_oracle(oracle, salvage_hint=True)
    manifest["files"]["oracle.parquet"] = {
        "kind": "intact", "expect_row_groups": N_RG}

    pages, stop = forward_scan(data)
    assert stop["reason"] == "bad-header", stop  # stops at the footer
    ends = rg_ends(oracle)
    assert len(ends) == N_RG

    def emit(name, blob, expect_rgs, kind, **extra):
        with open(os.path.join(OUT, name), "wb") as f:
            f.write(blob)
        manifest["files"][name] = {
            "kind": kind, "expect_row_groups": expect_rgs,
            "bytes": len(blob), **extra}

    # cut exactly at the end of row group 2's bytes (all of rg 0+1 and
    # rg 2's pages survive, but no footer): salvage recovers all three
    emit("cut_rg_boundary.parquet", data[: ends[2]], 3,
         "truncated-at-row-group-boundary", cut=ends[2])

    # cut at an interior page boundary inside row group 1: every page of
    # rg 0 survives plus a partial rg 1 -> exactly rg 0 recovers
    mid = [p for p in pages if ends[0] < p.data_end < ends[1]]
    cut = mid[len(mid) // 2].data_end
    emit("cut_page_boundary.parquet", data[:cut], 1,
         "truncated-at-page-boundary", cut=cut)

    # cut mid-page inside row group 2's first page -> rg 0+1 recover
    pg = next(p for p in pages if p.data_end > ends[1])
    cut = (pg.data_start + pg.data_end) // 2
    emit("cut_mid_page.parquet", data[:cut], 2,
         "truncated-mid-page", cut=cut)

    # footer torn: full data present but the thrift blob is damaged —
    # valid-prefix salvage cannot trust it; forward scan recovers all 3
    blob = bytearray(data)
    for off in range(len(blob) - 40, len(blob) - 20):
        blob[off] ^= 0x5A
    emit("footer_torn.parquet", bytes(blob), 3, "corrupt-footer-thrift")

    # hint-less torn file: salvage requires a donor (the oracle)
    nohint = os.path.join(OUT, "_nohint_tmp.parquet")
    nh = write_oracle(nohint, salvage_hint=False)
    nh_ends = rg_ends(nohint)
    os.unlink(nohint)
    emit("nohint_cut.parquet", nh[: nh_ends[1]], 2,
         "truncated-no-hint", needs_donor=True, cut=nh_ends[1])

    with open(os.path.join(OUT, "manifest.json"), "w") as f:
        json.dump(manifest, f, indent=2, sort_keys=True)
        f.write("\n")
    print(f"wrote {len(manifest['files'])} fixtures + manifest to {OUT}")


if __name__ == "__main__":
    main()
