"""Thread-safety pass: module-level state is guarded; locks are acyclic.

The serve-regime roadmap (long-lived multi-tenant process) makes
"module global mutated off-thread" the highest-risk latent bug class:
it works in every test and loses state under production concurrency.
This pass inventories **module-level mutable state** in every module
of ``tpuparquet/`` that imports ``threading`` and requires each piece
to be one of:

* ``threading.local()`` — per-thread by construction;
* a lock/condition itself;
* an instance of a *self-synchronized* class (its ``__init__`` binds
  a ``threading.Lock``/``RLock``, or delegates to another
  self-synchronized class such as ``ThreadSlots``);
* mutated **only under a module-level lock** (every rebind of a
  ``global``, and every container mutation, lexically inside
  ``with <lock>:``);
* or explicitly allowlisted with a reason (the atomic
  reference-swap globals like ``faults._active`` are the intended
  tenants).

It also extracts the **static lock-acquisition graph** — "while
holding lock A, code may call into something that takes lock B" —
across the threaded modules and rejects cycles (including self-loops:
``threading.Lock`` is not reentrant).  Call resolution is
name-based and conservative: same-module functions, imported
module members, ``self.`` methods, and attribute calls whose method
name is defined by analyzed classes (ambiguous names fan out to every
definer — a false edge can only *add* scrutiny, never hide a cycle).
"""

from __future__ import annotations

import ast

from .astutil import Finding, RepoTree, call_name

PASS = "thread-safety"

_LOCK_CTORS = ("Lock", "RLock", "Condition", "Semaphore",
               "BoundedSemaphore")
_CONTAINER_CTORS = ("dict", "list", "set", "deque", "OrderedDict",
                    "defaultdict", "WeakSet", "WeakValueDictionary",
                    "WeakKeyDictionary", "Counter")
_MUTATORS = ("append", "add", "update", "extend", "insert", "remove",
             "discard", "clear", "pop", "popitem", "setdefault",
             "appendleft", "extendleft")
#: method names too generic to resolve call edges through
_GENERIC_METHODS = frozenset({
    "get", "pop", "update", "add", "append", "items", "keys",
    "values", "copy", "clear", "extend", "remove", "discard",
    "setdefault", "popitem", "join", "start", "put", "read", "write",
    "close", "acquire", "release", "wait", "notify", "notify_all",
    "sort", "insert", "index", "count", "encode", "decode", "format",
    "split", "strip", "startswith", "endswith", "record",
})


def _imports_threading(mod: ast.AST) -> bool:
    for node in ast.walk(mod):
        if isinstance(node, ast.Import):
            if any(a.name == "threading" for a in node.names):
                return True
        elif isinstance(node, ast.ImportFrom):
            if node.module == "threading":
                return True
    return False


def _ctor_name(value) -> str | None:
    """The constructor name of a call expression, if any."""
    if isinstance(value, ast.Call):
        return call_name(value)
    return None


class _Module:
    """Per-module facts the pass reasons over."""

    def __init__(self, path: str, mod: ast.AST):
        self.path = path
        self.mod = mod
        self.locks: set[str] = set()       # module-level lock names
        self.locals_: set[str] = set()     # threading.local names
        self.containers: dict[str, int] = {}   # name -> def line
        self.instances: dict[str, tuple] = {}  # name -> (ctor, line)
        self.scalars: dict[str, int] = {}  # every other module name
        self.globals_: set[str] = set()    # names rebound via global
        self.classes: dict[str, ast.ClassDef] = {}
        self.functions: dict[str, ast.AST] = {}
        self.imports: dict[str, str] = {}  # local alias -> source name
        self._scan()

    def _scan(self) -> None:
        for node in self.mod.body:
            if isinstance(node, ast.ClassDef):
                self.classes[node.name] = node
            elif isinstance(node, (ast.FunctionDef,
                                   ast.AsyncFunctionDef)):
                self.functions[node.name] = node
            targets = []
            if isinstance(node, ast.Assign):
                targets = [t for t in node.targets
                           if isinstance(t, ast.Name)]
                value = node.value
            elif isinstance(node, ast.AnnAssign) and \
                    isinstance(node.target, ast.Name):
                targets = [node.target]
                value = node.value
            else:
                continue
            ctor = _ctor_name(value)
            for t in targets:
                if t.id == "__all__":
                    continue
                if ctor in _LOCK_CTORS:
                    self.locks.add(t.id)
                elif ctor == "local":
                    self.locals_.add(t.id)
                elif ctor in _CONTAINER_CTORS or \
                        isinstance(value, (ast.Dict, ast.List,
                                           ast.Set)):
                    self.containers[t.id] = node.lineno
                elif ctor is not None and ctor[:1].isupper():
                    self.instances[t.id] = (ctor, node.lineno)
                else:
                    self.scalars[t.id] = node.lineno
        for node in ast.walk(self.mod):
            if isinstance(node, ast.Global):
                self.globals_.update(node.names)
            elif isinstance(node, ast.ImportFrom):
                for a in node.names:
                    self.imports[a.asname or a.name] = a.name


def _held_module_locks(node, module: _Module) -> set[str]:
    """Module-level lock names held (via ``with``) at ``node``."""
    from .astutil import ancestors

    held: set[str] = set()
    for a in ancestors(node):
        if isinstance(a, ast.With):
            for item in a.items:
                ctx = item.context_expr
                if isinstance(ctx, ast.Name) and ctx.id in module.locks:
                    held.add(ctx.id)
    return held


def _self_synchronized(ctor: str, mod: _Module,
                       mods: dict[str, _Module],
                       _seen: frozenset = frozenset()) -> bool:
    """Is class ``ctor`` self-synchronized?  True when its __init__
    binds a lock attribute, or binds an attribute to another
    self-synchronized class (``ThreadSlots`` delegation)."""
    if ctor in _seen:
        return False
    cls = mod.classes.get(ctor)
    home = mod
    if cls is None:
        # imported class: resolve by name across analyzed modules
        for m in mods.values():
            if ctor in m.classes:
                cls, home = m.classes[ctor], m
                break
    if cls is None:
        return False
    init = next((n for n in cls.body
                 if isinstance(n, ast.FunctionDef)
                 and n.name == "__init__"), None)
    if init is None:
        return False
    for node in ast.walk(init):
        if isinstance(node, ast.Assign) and \
                isinstance(node.value, ast.Call):
            name = call_name(node.value)
            if name in _LOCK_CTORS or name == "local":
                return True
            if name and name[:1].isupper() and _self_synchronized(
                    name, home, mods, _seen | {ctor}):
                return True
    return False


# ----------------------------------------------------------------------
# Mutable-state findings
# ----------------------------------------------------------------------

def _state_findings(module: _Module,
                    mods: dict[str, _Module]) -> list[Finding]:
    findings: list[Finding] = []

    # unsynchronized module-level instances
    for name, (ctor, line) in sorted(module.instances.items()):
        if _self_synchronized(ctor, module, mods):
            continue
        findings.append(Finding(
            PASS, module.path, line, "unsynchronized-module-instance",
            name,
            f"module-level {name} = {ctor}(...) in a threaded module, "
            f"and {ctor} has no lock of its own — concurrent use "
            f"races unless every access is externally serialized "
            f"(allowlist with the reason if so)"))

    # unguarded rebinds of globals
    interesting = (set(module.scalars) | set(module.containers)
                   | set(module.instances)) & module.globals_
    for node in ast.walk(module.mod):
        if not isinstance(node, (ast.Assign, ast.AnnAssign)):
            continue
        targets = node.targets if isinstance(node, ast.Assign) \
            else [node.target]
        for t in targets:
            if not (isinstance(t, ast.Name) and t.id in interesting):
                continue
            fn = _enclosing_fn(node)
            if fn is None:
                continue  # the module-level definition itself
            if _held_module_locks(node, module):
                continue
            findings.append(Finding(
                PASS, module.path, node.lineno,
                "unlocked-global-rebind", t.id,
                f"global {t.id} rebound in {fn.name}() outside any "
                f"module lock — racing rebinds can lose one writer's "
                f"update (allowlist only if this is a deliberate "
                f"atomic reference swap)"))

    # unguarded container mutations
    for node in ast.walk(module.mod):
        name = mut = None
        if isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and \
                isinstance(node.func.value, ast.Name) and \
                node.func.attr in _MUTATORS:
            name, mut = node.func.value.id, node.func.attr
        elif isinstance(node, (ast.Assign, ast.AugAssign)):
            tgts = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            for t in tgts:
                if isinstance(t, ast.Subscript) and \
                        isinstance(t.value, ast.Name):
                    name, mut = t.value.id, "[]="
        elif isinstance(node, ast.Delete):
            for t in node.targets:
                if isinstance(t, ast.Subscript) and \
                        isinstance(t.value, ast.Name):
                    name, mut = t.value.id, "del[]"
        if name is None or name not in module.containers:
            continue
        if _enclosing_fn(node) is None:
            continue  # import-time population is single-threaded
        if _held_module_locks(node, module):
            continue
        findings.append(Finding(
            PASS, module.path, node.lineno, "unlocked-module-state",
            name,
            f"module-level container {name} mutated (.{mut}) outside "
            f"any module lock in a threaded module — concurrent "
            f"mutation corrupts or loses entries"))
    return findings


def _enclosing_fn(node):
    from .astutil import enclosing_function

    return enclosing_function(node)


# ----------------------------------------------------------------------
# Lock-acquisition graph
# ----------------------------------------------------------------------

def _lock_exprs(item_ctx, module: _Module, cls_locks: set[str]):
    """Lock identity of a with-item context expr, or None."""
    if isinstance(item_ctx, ast.Name) and item_ctx.id in module.locks:
        return (module.path, item_ctx.id)
    if isinstance(item_ctx, ast.Attribute) and \
            isinstance(item_ctx.value, ast.Name) and \
            item_ctx.value.id == "self" and item_ctx.attr in cls_locks:
        return (module.path, f"self.{item_ctx.attr}")
    return None


def _class_locks(cls: ast.ClassDef) -> set[str]:
    out: set[str] = set()
    for node in ast.walk(cls):
        if isinstance(node, ast.Assign) and \
                isinstance(node.value, ast.Call) and \
                call_name(node.value) in _LOCK_CTORS:
            for t in node.targets:
                if isinstance(t, ast.Attribute) and \
                        isinstance(t.value, ast.Name) and \
                        t.value.id == "self":
                    out.add(t.attr)
    return out


def _build_lock_graph(mods: dict[str, _Module]):
    """Edges (lockA, lockB, file, line): holding A, a call chain can
    acquire B.  Lock identity: (module-path, name) for module locks,
    (module-path, Class._attr) for instance locks."""
    # function universe: (path, qualname) -> (fnnode, module, class|None)
    funcs: dict[tuple, tuple] = {}
    method_index: dict[str, list[tuple]] = {}
    for m in mods.values():
        for fname, fn in m.functions.items():
            funcs[(m.path, fname)] = (fn, m, None)
        for cname, cls in m.classes.items():
            for node in cls.body:
                if isinstance(node, ast.FunctionDef):
                    funcs[(m.path, f"{cname}.{node.name}")] = \
                        (node, m, cls)
                    method_index.setdefault(node.name, []).append(
                        (m.path, f"{cname}.{node.name}"))

    def resolve_call(call: ast.Call, m: _Module, cls) -> list[tuple]:
        f = call.func
        if isinstance(f, ast.Name):
            if (m.path, f.id) in funcs:
                return [(m.path, f.id)]
            src = m.imports.get(f.id)
            if src:
                for om in mods.values():
                    if (om.path, src) in funcs:
                        return [(om.path, src)]
            return []
        if isinstance(f, ast.Attribute):
            if isinstance(f.value, ast.Name) and f.value.id == "self" \
                    and cls is not None:
                key = (m.path, f"{cls.name}.{f.attr}")
                return [key] if key in funcs else []
            if f.attr in _GENERIC_METHODS:
                return []
            return method_index.get(f.attr, [])
        return []

    # locks each function acquires directly
    def direct_locks(fnkey) -> set[tuple]:
        fn, m, cls = funcs[fnkey]
        cls_locks = _class_locks(cls) if cls is not None else set()
        out = set()
        for node in ast.walk(fn):
            if isinstance(node, ast.With):
                for item in node.items:
                    lk = _lock_exprs(item.context_expr, m, cls_locks)
                    if lk is not None:
                        name = lk[1]
                        if name.startswith("self.") and cls is not None:
                            lk = (lk[0],
                                  f"{cls.name}.{name[5:]}")
                        out.add(lk)
        return out

    # transitive: locks reachable from calling fnkey, computed as a
    # fixpoint over the whole call graph — recursion with memoization
    # would cache cycle-truncated partial results for mutually
    # recursive functions and silently hide edges (and with them,
    # deadlock cycles)
    callees: dict[tuple, set[tuple]] = {}
    reach: dict[tuple, set[tuple]] = {}
    for fnkey, (fn, m, cls) in funcs.items():
        outs: set[tuple] = set()
        for node in ast.walk(fn):
            if isinstance(node, ast.Call):
                outs.update(resolve_call(node, m, cls))
        callees[fnkey] = outs
        reach[fnkey] = set(direct_locks(fnkey))
    changed = True
    while changed:
        changed = False
        for fnkey, outs in callees.items():
            r = reach[fnkey]
            before = len(r)
            for c in outs:
                r |= reach[c]
            if len(r) != before:
                changed = True

    def reachable_locks(fnkey) -> set[tuple]:
        return reach[fnkey]

    edges: set[tuple] = set()
    for fnkey, (fn, m, cls) in funcs.items():
        cls_locks = _class_locks(cls) if cls is not None else set()
        for node in ast.walk(fn):
            if not isinstance(node, ast.With):
                continue
            held = []
            for item in node.items:
                lk = _lock_exprs(item.context_expr, m, cls_locks)
                if lk is not None:
                    name = lk[1]
                    if name.startswith("self.") and cls is not None:
                        lk = (lk[0], f"{cls.name}.{name[5:]}")
                    held.append(lk)
            if not held:
                continue
            acquired: set[tuple] = set()
            for stmt in node.body:
                for sub in ast.walk(stmt):
                    if isinstance(sub, ast.With):
                        for item in sub.items:
                            lk = _lock_exprs(item.context_expr, m,
                                             cls_locks)
                            if lk is not None:
                                name = lk[1]
                                if name.startswith("self.") and \
                                        cls is not None:
                                    lk = (lk[0],
                                          f"{cls.name}.{name[5:]}")
                                acquired.add(lk)
                    elif isinstance(sub, ast.Call):
                        for callee in resolve_call(sub, m, cls):
                            acquired |= reachable_locks(callee)
            for a in held:
                for b in acquired:
                    edges.add((a, b, m.path, node.lineno))
    return edges


def _find_cycles(edges) -> list[list]:
    graph: dict = {}
    meta: dict = {}
    for a, b, path, line in edges:
        graph.setdefault(a, set()).add(b)
        meta[(a, b)] = (path, line)
    cycles: list[list] = []
    seen_cycles: set = set()

    def dfs(start, node, stack, visited):
        for nxt in sorted(graph.get(node, ())):
            if nxt == start:
                cyc = tuple(stack)
                key = frozenset(cyc)
                if key not in seen_cycles:
                    seen_cycles.add(key)
                    cycles.append(list(stack) + [start])
            elif nxt not in visited and len(stack) < 8:
                visited.add(nxt)
                dfs(start, nxt, stack + [nxt], visited)
    for n in sorted(graph):
        dfs(n, n, [n], {n})
    return [(c, meta.get((c[0], c[1]), ("", 0))) for c in cycles]


def threaded_modules(tree: RepoTree) -> list[str]:
    out = []
    for path, mod in tree.modules("tpuparquet/"):
        if _imports_threading(mod):
            out.append(path)
    return out


def run(tree: RepoTree) -> list[Finding]:
    findings: list[Finding] = []
    mods: dict[str, _Module] = {}
    for path, mod in tree.modules("tpuparquet/"):
        if _imports_threading(mod):
            mods[path] = _Module(path, mod)
    for m in mods.values():
        findings.extend(_state_findings(m, mods))
    for cyc, (path, line) in _find_cycles(_build_lock_graph(mods)):
        names = " -> ".join(f"{p.split('/')[-1]}:{n}" for p, n in cyc)
        findings.append(Finding(
            PASS, path or cyc[0][0], line, "lock-cycle", names,
            f"static lock-acquisition cycle {names} — two threads "
            f"entering from different ends deadlock (threading.Lock "
            f"is not reentrant, so a self-loop deadlocks one thread "
            f"alone)"))
    return findings
