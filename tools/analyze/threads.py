"""Thread-safety pass v2: whole-program lock order + guarded state.

The serve-regime roadmap (long-lived multi-tenant process) makes
"module global mutated off-thread" the highest-risk latent bug class:
it works in every test and loses state under production concurrency.
This pass inventories **module-level mutable state** in every module
of ``tpuparquet/`` that imports ``threading`` and requires each piece
to be one of:

* ``threading.local()`` — per-thread by construction;
* a lock/condition itself;
* an instance of a *self-synchronized* class (its ``__init__`` binds
  a ``threading.Lock``/``RLock``, or delegates to another
  self-synchronized class such as ``ThreadSlots``);
* mutated **only under a module-level lock** (every rebind of a
  ``global``, and every container mutation, lexically inside
  ``with <lock>:``);
* or explicitly allowlisted with a reason (the atomic
  reference-swap globals like ``faults._active`` are the intended
  tenants).

v2 extends the round-13 lock-graph half from "threaded modules only,
module-level + ``self.`` locks" to a **whole-program analysis** over
all of ``tpuparquet/``:

* Lock identity is the **creation site** ``path:lineno`` of the
  ``threading.Lock()``/``RLock()``/``Condition()`` constructor call —
  the same key the runtime recorder (``tpuparquet/lockcheck.py``)
  observes, so the static graph and the recorded graph are directly
  comparable (``python -m tools.analyze --verify-lockcheck``).
* The function universe includes **nested functions** (thread-pool
  task closures), and call resolution follows **function-valued
  arguments** — ``ex.submit(_task, ...)``, ``threading.Thread(
  target=fn)``, ``retry_transient(_one)`` — so "caller holds L,
  worker acquires M" becomes a visible L→M edge across the pool
  submission boundary.
* ``with`` lock expressions resolve through lightweight type
  inference: own-class attributes, annotated parameters and return
  types, ``v = Ctor(...)`` locals, module-level singletons, and
  one level of attribute aliasing (``self._io_lock = nh.lock``).
  A *lockish-named* ``with`` expression that still fails to resolve
  is its own finding (``unresolved-lock-with``) — the graph refuses
  to silently drop what it cannot model.
* Cycles (including self-loops — two instances from one creation
  site, or a genuine reentrant acquire) are findings; ``RLock`` and
  ``Condition`` sites are exempt from the SELF-loop rule only, since
  same-thread reacquisition is their contract.

Call resolution stays conservative: ambiguous attribute calls fan
out to every analyzed definer — a false edge can only *add*
scrutiny, never hide a cycle.
"""

from __future__ import annotations

import ast

from .astutil import Finding, RepoTree, call_name

PASS = "thread-safety"

_LOCK_CTORS = ("Lock", "RLock", "Condition", "Semaphore",
               "BoundedSemaphore")
#: reentrant by contract: a self-loop (same creation site reacquired
#: while held) is the normal operating mode, not a deadlock
_REENTRANT_KINDS = frozenset({"RLock", "Condition"})
_CONTAINER_CTORS = ("dict", "list", "set", "deque", "OrderedDict",
                    "defaultdict", "WeakSet", "WeakValueDictionary",
                    "WeakKeyDictionary", "Counter")
_MUTATORS = ("append", "add", "update", "extend", "insert", "remove",
             "discard", "clear", "pop", "popitem", "setdefault",
             "appendleft", "extendleft")
#: method names too generic to resolve call edges through
_GENERIC_METHODS = frozenset({
    "get", "pop", "update", "add", "append", "items", "keys",
    "values", "copy", "clear", "extend", "remove", "discard",
    "setdefault", "popitem", "join", "start", "put", "read", "write",
    "close", "acquire", "release", "wait", "notify", "notify_all",
    "sort", "insert", "index", "count", "encode", "decode", "format",
    "split", "strip", "startswith", "endswith", "record", "result",
    "submit", "map", "shutdown", "done", "cancel", "set",
})
#: with-expression names that LOOK like locks; failing to resolve one
#: of these is a finding, failing to resolve `with open(...)` is not
_LOCKISH = ("lock", "mutex", "_cv", "cv", "cond")


def _imports_threading(mod: ast.AST) -> bool:
    for node in ast.walk(mod):
        if isinstance(node, ast.Import):
            if any(a.name == "threading" for a in node.names):
                return True
        elif isinstance(node, ast.ImportFrom):
            if node.module == "threading":
                return True
    return False


def _ctor_name(value) -> str | None:
    """The constructor name of a call expression, if any."""
    if isinstance(value, ast.Call):
        return call_name(value)
    return None


class _Module:
    """Per-module facts the mutable-state half reasons over."""

    def __init__(self, path: str, mod: ast.AST):
        self.path = path
        self.mod = mod
        self.locks: set[str] = set()       # module-level lock names
        self.locals_: set[str] = set()     # threading.local names
        self.containers: dict[str, int] = {}   # name -> def line
        self.instances: dict[str, tuple] = {}  # name -> (ctor, line)
        self.scalars: dict[str, int] = {}  # every other module name
        self.globals_: set[str] = set()    # names rebound via global
        self.classes: dict[str, ast.ClassDef] = {}
        self.functions: dict[str, ast.AST] = {}
        self.imports: dict[str, str] = {}  # local alias -> source name
        #: plain ``import X`` aliases — attribute calls through these
        #: are stdlib/external and must not fan out by method name
        self.module_imports: set[str] = set()
        #: ``_RealLock = threading.Lock`` style ctor aliases -> kind
        self.lock_ctor_aliases: dict[str, str] = {}
        self._scan()

    def _lock_kind(self, ctor: str | None) -> str | None:
        if ctor in _LOCK_CTORS:
            return ctor
        return self.lock_ctor_aliases.get(ctor or "")

    def _scan(self) -> None:
        for node in self.mod.body:
            if isinstance(node, ast.ClassDef):
                self.classes[node.name] = node
            elif isinstance(node, (ast.FunctionDef,
                                   ast.AsyncFunctionDef)):
                self.functions[node.name] = node
            targets = []
            if isinstance(node, ast.Assign):
                targets = [t for t in node.targets
                           if isinstance(t, ast.Name)]
                value = node.value
            elif isinstance(node, ast.AnnAssign) and \
                    isinstance(node.target, ast.Name):
                targets = [node.target]
                value = node.value
            else:
                continue
            # constructor aliasing: `_RealLock = threading.Lock`
            # (the lockcheck idiom for keeping a pre-patch original)
            alias_kind = None
            if isinstance(value, ast.Attribute) and \
                    value.attr in _LOCK_CTORS:
                alias_kind = value.attr
            elif isinstance(value, ast.Name) and \
                    value.id in _LOCK_CTORS:
                alias_kind = value.id
            if alias_kind is not None:
                for t in targets:
                    self.lock_ctor_aliases[t.id] = alias_kind
                continue
            ctor = _ctor_name(value)
            for t in targets:
                if t.id == "__all__":
                    continue
                if self._lock_kind(ctor):
                    self.locks.add(t.id)
                elif ctor == "local":
                    self.locals_.add(t.id)
                elif ctor in _CONTAINER_CTORS or \
                        isinstance(value, (ast.Dict, ast.List,
                                           ast.Set)):
                    self.containers[t.id] = node.lineno
                elif ctor is not None and ctor[:1].isupper():
                    self.instances[t.id] = (ctor, node.lineno)
                else:
                    self.scalars[t.id] = node.lineno
        for node in ast.walk(self.mod):
            if isinstance(node, ast.Global):
                self.globals_.update(node.names)
            elif isinstance(node, ast.ImportFrom):
                for a in node.names:
                    self.imports[a.asname or a.name] = a.name
            elif isinstance(node, ast.Import):
                for a in node.names:
                    self.module_imports.add(
                        a.asname or a.name.split(".")[0])


def _held_module_locks(node, module: _Module) -> set[str]:
    """Module-level lock names held (via ``with``) at ``node``."""
    from .astutil import ancestors

    held: set[str] = set()
    for a in ancestors(node):
        if isinstance(a, ast.With):
            for item in a.items:
                ctx = item.context_expr
                if isinstance(ctx, ast.Name) and ctx.id in module.locks:
                    held.add(ctx.id)
    return held


def _self_synchronized(ctor: str, mod: _Module,
                       mods: dict[str, _Module],
                       _seen: frozenset = frozenset()) -> bool:
    """Is class ``ctor`` self-synchronized?  True when its __init__
    binds a lock attribute, or binds an attribute to another
    self-synchronized class (``ThreadSlots`` delegation)."""
    if ctor in _seen:
        return False
    cls = mod.classes.get(ctor)
    home = mod
    if cls is None:
        # imported class: resolve by name across analyzed modules
        for m in mods.values():
            if ctor in m.classes:
                cls, home = m.classes[ctor], m
                break
    if cls is None:
        return False
    init = next((n for n in cls.body
                 if isinstance(n, ast.FunctionDef)
                 and n.name == "__init__"), None)
    if init is None:
        return False
    for node in ast.walk(init):
        if isinstance(node, ast.Assign) and \
                isinstance(node.value, ast.Call):
            name = call_name(node.value)
            if name in _LOCK_CTORS or name == "local":
                return True
            if name and name[:1].isupper() and _self_synchronized(
                    name, home, mods, _seen | {ctor}):
                return True
    return False


# ----------------------------------------------------------------------
# Mutable-state findings
# ----------------------------------------------------------------------

def _state_findings(module: _Module,
                    mods: dict[str, _Module]) -> list[Finding]:
    findings: list[Finding] = []

    # unsynchronized module-level instances
    for name, (ctor, line) in sorted(module.instances.items()):
        if _self_synchronized(ctor, module, mods):
            continue
        findings.append(Finding(
            PASS, module.path, line, "unsynchronized-module-instance",
            name,
            f"module-level {name} = {ctor}(...) in a threaded module, "
            f"and {ctor} has no lock of its own — concurrent use "
            f"races unless every access is externally serialized "
            f"(allowlist with the reason if so)"))

    # unguarded rebinds of globals
    interesting = (set(module.scalars) | set(module.containers)
                   | set(module.instances)) & module.globals_
    for node in ast.walk(module.mod):
        if not isinstance(node, (ast.Assign, ast.AnnAssign)):
            continue
        targets = node.targets if isinstance(node, ast.Assign) \
            else [node.target]
        for t in targets:
            if not (isinstance(t, ast.Name) and t.id in interesting):
                continue
            fn = _enclosing_fn(node)
            if fn is None:
                continue  # the module-level definition itself
            if _held_module_locks(node, module):
                continue
            findings.append(Finding(
                PASS, module.path, node.lineno,
                "unlocked-global-rebind", t.id,
                f"global {t.id} rebound in {fn.name}() outside any "
                f"module lock — racing rebinds can lose one writer's "
                f"update (allowlist only if this is a deliberate "
                f"atomic reference swap)"))

    # unguarded container mutations
    for node in ast.walk(module.mod):
        name = mut = None
        if isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and \
                isinstance(node.func.value, ast.Name) and \
                node.func.attr in _MUTATORS:
            name, mut = node.func.value.id, node.func.attr
        elif isinstance(node, (ast.Assign, ast.AugAssign)):
            tgts = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            for t in tgts:
                if isinstance(t, ast.Subscript) and \
                        isinstance(t.value, ast.Name):
                    name, mut = t.value.id, "[]="
        elif isinstance(node, ast.Delete):
            for t in node.targets:
                if isinstance(t, ast.Subscript) and \
                        isinstance(t.value, ast.Name):
                    name, mut = t.value.id, "del[]"
        if name is None or name not in module.containers:
            continue
        if _enclosing_fn(node) is None:
            continue  # import-time population is single-threaded
        if _held_module_locks(node, module):
            continue
        findings.append(Finding(
            PASS, module.path, node.lineno, "unlocked-module-state",
            name,
            f"module-level container {name} mutated (.{mut}) outside "
            f"any module lock in a threaded module — concurrent "
            f"mutation corrupts or loses entries"))
    return findings


def _enclosing_fn(node):
    from .astutil import enclosing_function

    return enclosing_function(node)


# ----------------------------------------------------------------------
# Whole-program lock-acquisition graph
# ----------------------------------------------------------------------
#
# Lock identity: (site, label, kind) where site == "path:lineno" of
# the threading ctor CALL node — the exact string lockcheck records
# at runtime.  The graph builder below is deliberately one big
# closure-free object so the --lock-graph export, the run() findings
# and the --verify-lockcheck comparison all read one memoized result.

_GRAPH_MEMO = "thread-safety/lock-graph"


class _ClassF:
    """Per-class facts for lock/type resolution."""

    def __init__(self, path: str, node: ast.ClassDef):
        self.path = path
        self.node = node
        self.name = node.name
        self.bases = [b.id for b in node.bases
                      if isinstance(b, ast.Name)]
        self.lock_attrs: dict[str, tuple] = {}   # attr -> (site, kind)
        self.attr_types: dict[str, str] = {}     # attr -> class name
        self.ret_types: dict[str, str] = {}      # method -> class name
        self.alias_assigns: list[tuple] = []     # (attr, value, fnkey)


def _ann_name(ann) -> str | None:
    """Type name out of an annotation node (``_IoHandle`` or
    ``"_IoHandle"`` — quoting is how reader.py forward-refs).  For a
    union (``RangeSourceFile | object``) the first CapWord component
    wins: the lock graph is a superset, so resolving the one repo
    facade in the union is what makes its lock edges visible —
    stdlib/opaque members contribute no repo locks anyway."""
    if isinstance(ann, ast.Name):
        return ann.id
    if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
        parts = [p.strip() for p in ann.value.split("|")]
        for p in parts:
            if p and p[:1].isupper() and p.isidentifier():
                return p
        return parts[0] if parts and parts[0].isidentifier() else None
    if isinstance(ann, ast.Attribute):
        return ann.attr
    if isinstance(ann, ast.BinOp) and isinstance(ann.op, ast.BitOr):
        return _ann_name(ann.left) or _ann_name(ann.right)
    return None


def _shallow_walk(root):
    """Walk ``root``'s subtree WITHOUT descending into nested
    function/class definitions (they are separate universe entries);
    lambdas ARE descended into — a lambda body runs as part of
    whatever invokes the enclosing function's callback."""
    stack = list(ast.iter_child_nodes(root))
    while stack:
        n = stack.pop()
        yield n
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.ClassDef)):
            continue
        stack.extend(ast.iter_child_nodes(n))


class _Program:
    """Whole-program facts + the lock graph over ``tpuparquet/``."""

    def __init__(self, tree: RepoTree):
        self.tree = tree
        self.mods: dict[str, _Module] = {}
        self.classes: dict[str, list[_ClassF]] = {}  # name -> defs
        # function universe: key=(path, qualname)
        self.funcs: dict[tuple, tuple] = {}   # key -> (node, mod, clsF)
        self.parent: dict[tuple, tuple] = {}  # key -> enclosing fn key
        self.nested: dict[tuple, dict] = {}   # key -> {name: child key}
        self.top_by_name: dict[str, list] = {}
        self.method_index: dict[str, list] = {}
        self._localfacts: dict[tuple, tuple] = {}
        self.sites: dict[str, dict] = {}      # site -> {label, kind}
        # (a_site, b_site) -> (path, line, a_label, b_label)
        self.edges: dict[tuple, tuple] = {}
        self.unresolved: list[tuple] = []     # (path, line, expr, fn)
        self._subs: dict | None = None        # base name -> [_ClassF]
        self._build()

    # -- fact collection -------------------------------------------------

    def _build(self) -> None:
        for path, mod in self.tree.modules("tpuparquet/"):
            self.mods[path] = _Module(path, mod)
        for path, m in self.mods.items():
            for cname, cls in m.classes.items():
                cf = _ClassF(path, cls)
                self._collect_class(cf, m)
                self.classes.setdefault(cname, []).append(cf)
        for path, m in self.mods.items():
            self._collect_funcs(path, m, m.mod.body, "", None, None)
        # module-level lock sites
        for path, m in self.mods.items():
            for node in m.mod.body:
                tgts, value = self._assign(node)
                kind = m._lock_kind(_ctor_name(value))
                if kind:
                    site = f"{path}:{value.lineno}"
                    for t in tgts:
                        self._add_site(site, self._label(path, t), kind)
                        m.locks.add(t)
        # alias resolution (one level: self.X = nh.lock etc.)
        for defs in self.classes.values():
            for cf in defs:
                for attr, value, fnkey in cf.alias_assigns:
                    lk = self._lock_of(value, fnkey)
                    if lk is not None:
                        cf.lock_attrs[attr] = lk
                        continue
                    t = self._type_of(value, fnkey)
                    if t is not None:
                        cf.attr_types[attr] = t
        self._build_graph()

    @staticmethod
    def _assign(node):
        if isinstance(node, ast.Assign):
            return ([t.id for t in node.targets
                     if isinstance(t, ast.Name)], node.value)
        if isinstance(node, ast.AnnAssign) and \
                isinstance(node.target, ast.Name):
            return ([node.target.id], node.value)
        return ([], None)

    @staticmethod
    def _label(path: str, qual: str) -> str:
        return f"{path.rsplit('/', 1)[-1]}:{qual}"

    def _add_site(self, site: str, label: str, kind: str) -> None:
        self.sites.setdefault(site, {"label": label, "kind": kind})

    def _collect_class(self, cf: _ClassF, m: _Module) -> None:
        for mnode in cf.node.body:
            if not isinstance(mnode, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                continue
            rt = _ann_name(mnode.returns)
            if rt:
                cf.ret_types[mnode.name] = rt
            fnkey = (cf.path, f"{cf.name}.{mnode.name}")
            for node in ast.walk(mnode):
                if not isinstance(node, ast.Assign):
                    continue
                for t in node.targets:
                    if not (isinstance(t, ast.Attribute)
                            and isinstance(t.value, ast.Name)
                            and t.value.id == "self"):
                        continue
                    ctor = _ctor_name(node.value)
                    kind = m._lock_kind(ctor)
                    if kind:
                        site = f"{cf.path}:{node.value.lineno}"
                        label = self._label(
                            cf.path, f"{cf.name}.{t.attr}")
                        self._add_site(site, label, kind)
                        cf.lock_attrs[t.attr] = (site, kind)
                    elif ctor and ctor.lstrip("_")[:1].isupper():
                        # CapWord possibly behind a privacy prefix:
                        # ``self._pool = _HttpConnPool(...)`` must
                        # type the attr or the pool's lock reach
                        # (its Condition) vanishes from the graph
                        cf.attr_types[t.attr] = ctor
                    elif isinstance(node.value,
                                    (ast.Attribute, ast.Name)):
                        cf.alias_assigns.append(
                            (t.attr, node.value, fnkey))

    def _collect_funcs(self, path, m, body, prefix, cls, parent):
        for node in body:
            if isinstance(node, ast.ClassDef):
                cf = next((c for c in self.classes.get(node.name, ())
                           if c.path == path and c.node is node), None)
                self._collect_funcs(path, m, node.body,
                                    f"{prefix}{node.name}.", cf, parent)
            elif isinstance(node, (ast.FunctionDef,
                                   ast.AsyncFunctionDef)):
                key = (path, f"{prefix}{node.name}")
                self.funcs[key] = (node, m, cls)
                if parent is not None:
                    self.parent[key] = parent
                    self.nested.setdefault(parent, {})[node.name] = key
                if prefix == "":
                    self.top_by_name.setdefault(
                        node.name, []).append(key)
                elif cls is not None and \
                        prefix == f"{cls.name}." and \
                        node.name not in _GENERIC_METHODS:
                    self.method_index.setdefault(
                        node.name, []).append(key)
                # nested defs inherit the class context: closures over
                # ``self`` are how pool tasks reach instance locks
                self._collect_funcs(
                    path, m, node.body,
                    f"{prefix}{node.name}.<locals>.", cls, key)

    # -- type / lock resolution ------------------------------------------

    def _class_of(self, name: str | None, path: str) -> "_ClassF | None":
        if not name:
            return None
        defs = self.classes.get(name) or []
        for cf in defs:
            if cf.path == path:
                return cf
        return defs[0] if defs else None

    def _subclasses(self) -> dict:
        subs = self._subs
        if subs is None:
            subs = {}
            for defs in self.classes.values():
                for cf in defs:
                    for b in cf.bases:
                        subs.setdefault(b, []).append(cf)
            self._subs = subs
        return subs

    def _virtual(self, cf: "_ClassF", attr: str) -> list:
        """Method keys for ``attr`` as seen from static type ``cf``:
        the definition found up the base chain PLUS every override in
        transitive subclasses.  A call through a base-typed reference
        dispatches to whichever override the runtime object carries
        (``ByteRangeSource.get_range`` runs a subclass ``_read_raw``),
        so every override must contribute its lock reach."""
        out: list = []
        base, seen = cf, set()
        while base is not None and base.name not in seen:
            seen.add(base.name)
            key = (base.path, f"{base.name}.{attr}")
            if key in self.funcs:
                out.append(key)
                break
            base = self._class_of(
                base.bases[0] if base.bases else None, base.path)
        stack, walked = [cf.name], set()
        while stack:
            n = stack.pop()
            if n in walked:
                continue
            walked.add(n)
            for sub in self._subclasses().get(n, ()):
                key = (sub.path, f"{sub.name}.{attr}")
                if key in self.funcs and key not in out:
                    out.append(key)
                stack.append(sub.name)
        return out

    def _class_lock(self, cf: "_ClassF | None", attr: str,
                    _seen=()) -> tuple | None:
        while cf is not None and cf not in _seen:
            if attr in cf.lock_attrs:
                return cf.lock_attrs[attr]
            _seen = _seen + (cf,)
            cf = self._class_of(cf.bases[0] if cf.bases else None,
                                cf.path)
        return None

    def _class_type(self, cf: "_ClassF | None", attr: str) -> str | None:
        while cf is not None:
            if attr in cf.attr_types:
                return cf.attr_types[attr]
            cf = self._class_of(cf.bases[0] if cf.bases else None,
                                cf.path)
        return None

    def _local_facts(self, fnkey) -> tuple:
        """(param+local types, local lock aliases) for one function."""
        if fnkey in self._localfacts:
            return self._localfacts[fnkey]
        fn, m, cls = self.funcs[fnkey]
        types: dict[str, str] = {}
        locks: dict[str, tuple] = {}
        self._localfacts[fnkey] = (types, locks)  # break self-cycles
        args = fn.args
        for a in (args.posonlyargs + args.args + args.kwonlyargs):
            t = _ann_name(a.annotation)
            if t:
                types[a.arg] = t
        for node in _shallow_walk(fn):
            if not isinstance(node, ast.Assign) or \
                    len(node.targets) != 1 or \
                    not isinstance(node.targets[0], ast.Name):
                continue
            name = node.targets[0].id
            lk = self._lock_of(node.value, fnkey, _local=(types, locks))
            if lk is not None:
                locks[name] = lk
                continue
            t = self._type_of(node.value, fnkey,
                              _local=(types, locks))
            if t is not None:
                types[name] = t
        return self._localfacts[fnkey]

    def _type_of(self, expr, fnkey, _local=None) -> str | None:
        fn, m, cls = self.funcs[fnkey]
        types = (_local or self._local_facts(fnkey))[0]
        if isinstance(expr, ast.Name):
            if expr.id == "self" and cls is not None:
                return cls.name
            if expr.id in types:
                return types[expr.id]
            inst = m.instances.get(expr.id)
            if inst:
                return inst[0]
            return None
        if isinstance(expr, ast.Attribute):
            base = self._type_of(expr.value, fnkey, _local=_local)
            return self._class_type(self._class_of(base, m.path),
                                    expr.attr)
        if isinstance(expr, ast.Call):
            name = call_name(expr)
            if name and self.classes.get(name):
                return name
            # annotated return of a resolvable method/function
            f = expr.func
            if isinstance(f, ast.Attribute):
                base = self._type_of(f.value, fnkey, _local=_local)
                cf = self._class_of(base, m.path)
                while cf is not None:
                    if f.attr in cf.ret_types:
                        return cf.ret_types[f.attr]
                    cf = self._class_of(
                        cf.bases[0] if cf.bases else None, cf.path)
        return None

    def _lock_of(self, expr, fnkey, _local=None) -> tuple | None:
        """(site, kind) of a lock-valued expression, or None."""
        fn, m, cls = self.funcs[fnkey]
        if isinstance(expr, ast.Name):
            locks = (_local or self._local_facts(fnkey))[1]
            if expr.id in locks:
                return locks[expr.id]
            if expr.id in m.locks:
                site = self._module_lock_site(m.path, expr.id)
                if site:
                    return site
            src = m.imports.get(expr.id)
            if src:
                for om in self.mods.values():
                    if src in om.locks:
                        site = self._module_lock_site(om.path, src)
                        if site:
                            return site
            return None
        if isinstance(expr, ast.Attribute):
            # module-alias attribute: rangecache._LOCK
            if isinstance(expr.value, ast.Name):
                alias = expr.value.id
                src = m.imports.get(alias)
                for om in self.mods.values():
                    if om.path.rsplit("/", 1)[-1][:-3] in (alias, src) \
                            and expr.attr in om.locks:
                        site = self._module_lock_site(om.path,
                                                      expr.attr)
                        if site:
                            return site
            base = self._type_of(expr.value, fnkey, _local=_local)
            return self._class_lock(self._class_of(base, m.path),
                                    expr.attr)
        return None

    def _module_lock_site(self, path: str, name: str) -> tuple | None:
        label = self._label(path, name)
        for site, info in self.sites.items():
            if info["label"] == label and site.startswith(path + ":"):
                return (site, info["kind"])
        return None

    # -- call resolution -------------------------------------------------

    def _resolve_ref(self, expr, fnkey) -> list:
        """Function keys an expression may refer to (no fanout)."""
        fn, m, cls = self.funcs[fnkey]
        if isinstance(expr, ast.Name):
            # lexical scope chain: nested defs of this fn, then of the
            # enclosing fns, then module level, then imports
            k = fnkey
            while k is not None:
                child = self.nested.get(k, {}).get(expr.id)
                if child:
                    return [child]
                k = self.parent.get(k)
            if (m.path, expr.id) in self.funcs:
                return [(m.path, expr.id)]
            src = m.imports.get(expr.id)
            if src:
                return [key for key in self.top_by_name.get(src, ())]
            return []
        if isinstance(expr, ast.Attribute):
            if isinstance(expr.value, ast.Name) and \
                    expr.value.id == "self" and cls is not None:
                return self._virtual(cls, expr.attr)
            t = self._type_of(expr.value, fnkey)
            cf = self._class_of(t, m.path)
            if cf is not None:
                keys = self._virtual(cf, expr.attr)
                if keys:
                    return keys
            if isinstance(expr.value, ast.Name):
                # imported-module function: faults.retry_transient
                alias = expr.value.id
                src = m.imports.get(alias, alias)
                for om_path in self.mods:
                    if om_path.rsplit("/", 1)[-1][:-3] in (alias, src):
                        key = (om_path, expr.attr)
                        if key in self.funcs:
                            return [key]
            return []
        return []

    def _callees(self, call: ast.Call, fnkey) -> list:
        fn, m, cls = self.funcs[fnkey]
        out = self._resolve_ref(call.func, fnkey)
        if not out and isinstance(call.func, ast.Attribute) and \
                call.func.attr not in _GENERIC_METHODS and \
                not (isinstance(call.func.value, ast.Name)
                     and call.func.value.id in m.module_imports):
            out = list(self.method_index.get(call.func.attr, ()))
        # function-valued arguments: submit(_task), Thread(target=fn),
        # retry_transient(_one) — treated as potential invocations so
        # pool-mediated acquisition stays visible
        for arg in list(call.args) + [kw.value for kw in call.keywords]:
            if isinstance(arg, (ast.Name, ast.Attribute)):
                out.extend(self._resolve_ref(arg, fnkey))
        return out

    # -- graph -----------------------------------------------------------

    def _with_locks(self, w: ast.With, fnkey) -> list:
        """Resolved (site, kind) per with-item; records unresolved
        lockish expressions."""
        fn, m, cls = self.funcs[fnkey]
        out = []
        for item in w.items:
            ctx = item.context_expr
            if not isinstance(ctx, (ast.Name, ast.Attribute)):
                continue
            lk = self._lock_of(ctx, fnkey)
            if lk is not None:
                out.append(lk)
                continue
            leaf = ctx.id if isinstance(ctx, ast.Name) else ctx.attr
            low = leaf.lower()
            if any(p in low for p in _LOCKISH):
                self.unresolved.append(
                    (m.path, ctx.lineno, ast.unparse(ctx),
                     fnkey[1]))
        return out

    def _build_graph(self) -> None:
        callees: dict[tuple, set] = {}
        reach: dict[tuple, set] = {}
        for fnkey, (fn, m, cls) in self.funcs.items():
            outs: set = set()
            direct: set = set()
            for node in _shallow_walk(fn):
                if isinstance(node, ast.Call):
                    outs.update(self._callees(node, fnkey))
                elif isinstance(node, ast.With):
                    direct.update(self._with_locks(node, fnkey))
            callees[fnkey] = outs
            reach[fnkey] = direct
        # fixpoint over the whole call graph — recursion with
        # memoization would cache cycle-truncated partial results for
        # mutually recursive functions and silently hide edges (and
        # with them, deadlock cycles)
        changed = True
        while changed:
            changed = False
            for fnkey, outs in callees.items():
                r = reach[fnkey]
                before = len(r)
                for c in outs:
                    r |= reach.get(c, set())
                if len(r) != before:
                    changed = True
        # edges: for every with-block, held -> (nested acquires +
        # everything reachable through calls inside the block)
        for fnkey, (fn, m, cls) in self.funcs.items():
            for node in _shallow_walk(fn):
                if not isinstance(node, ast.With):
                    continue
                held = self._with_locks(node, fnkey)
                if not held:
                    continue
                acquired: set = set()
                for stmt in node.body:
                    if isinstance(stmt, (ast.FunctionDef,
                                         ast.AsyncFunctionDef,
                                         ast.ClassDef)):
                        continue
                    for sub in [stmt] + list(_shallow_walk(stmt)):
                        if isinstance(sub, ast.With):
                            acquired.update(
                                self._with_locks(sub, fnkey))
                        elif isinstance(sub, ast.Call):
                            for c in self._callees(sub, fnkey):
                                acquired |= reach.get(c, set())
                for a_site, a_kind in held:
                    for b_site, b_kind in acquired:
                        key = (a_site, b_site)
                        if key not in self.edges:
                            self.edges[key] = (
                                m.path, node.lineno,
                                self.sites[a_site]["label"],
                                self.sites[b_site]["label"])

    # -- verdicts --------------------------------------------------------

    def cycles(self) -> list:
        """Cycles over the edge set; self-loops only for
        non-reentrant kinds."""
        graph: dict[str, set] = {}
        for (a, b) in self.edges:
            if a == b:
                if self.sites[a]["kind"] in _REENTRANT_KINDS:
                    continue
                graph.setdefault(a, set()).add(b)
            else:
                graph.setdefault(a, set()).add(b)
        cycles: list[list] = []
        seen: set = set()

        def dfs(start, node, stack, visited):
            for nxt in sorted(graph.get(node, ())):
                if nxt == start:
                    key = frozenset(stack)
                    if key not in seen:
                        seen.add(key)
                        cycles.append(list(stack) + [start])
                elif nxt not in visited and len(stack) < 8:
                    visited.add(nxt)
                    dfs(start, nxt, stack + [nxt], visited)
        for n in sorted(graph):
            dfs(n, n, [n], {n})
        return cycles


def _program(tree: RepoTree) -> _Program:
    prog = tree.memo.get(_GRAPH_MEMO)
    if prog is None:
        prog = tree.memo[_GRAPH_MEMO] = _Program(tree)
    return prog


def static_graph(tree: RepoTree) -> dict:
    """The whole-program lock graph as one JSON-able document —
    the reference the runtime recorder's dump is verified against."""
    prog = _program(tree)
    return {
        "sites": {s: dict(info) for s, info in
                  sorted(prog.sites.items())},
        "edges": sorted([a, b] for (a, b) in prog.edges),
        "unresolved": [
            {"file": p, "line": ln, "expr": e, "function": fn}
            for p, ln, e, fn in sorted(set(prog.unresolved))],
    }


def verify_runtime_graph(tree: RepoTree, recorded: dict) -> list[str]:
    """Check a ``lockcheck`` dump against the static graph: recorded
    repo-lock edges must be a SUBSET of the static edges (else the
    static analysis failed to model a real call path), and the
    recorded graph must carry no cycle violations.  Returns problem
    strings (empty = verified).  Only edges with both endpoints in
    ``tpuparquet/`` are compared — test/tool locks are recorded for
    the cycle check but have no static counterpart here."""
    prog = _program(tree)
    problems = []
    for v in recorded.get("violations") or []:
        problems.append(f"runtime violation: {v}")
    static_edges = set(map(tuple, (static_graph(tree)["edges"])))
    for entry in recorded.get("edges") or []:
        a, b = entry[0], entry[1]
        if not (a.startswith("tpuparquet/")
                and b.startswith("tpuparquet/")):
            continue
        if a == b:
            continue  # same creation site: no order within one site
        if (a, b) not in static_edges:
            problems.append(
                f"recorded edge {a} -> {b} absent from the static "
                f"lock graph — the analysis is missing a call path")
    return problems


def threaded_modules(tree: RepoTree) -> list[str]:
    out = []
    for path, mod in tree.modules("tpuparquet/"):
        if _imports_threading(mod):
            out.append(path)
    return out


def run(tree: RepoTree) -> list[Finding]:
    findings: list[Finding] = []
    mods: dict[str, _Module] = {}
    for path, mod in tree.modules("tpuparquet/"):
        if _imports_threading(mod):
            mods[path] = _Module(path, mod)
    for m in mods.values():
        findings.extend(_state_findings(m, mods))
    prog = _program(tree)
    for path, line, expr, fn in sorted(set(prog.unresolved)):
        findings.append(Finding(
            PASS, path, line, "unresolved-lock-with", expr,
            f"`with {expr}:` in {fn}() looks like a lock acquisition "
            f"the analyzer cannot resolve to a creation site — the "
            f"lock graph would silently miss its edges; name the "
            f"lock via an attribute/annotation the pass can follow, "
            f"or allowlist with the reason"))
    for cyc in prog.cycles():
        names = " -> ".join(prog.sites[s]["label"] for s in cyc)
        path, line = cyc[0].rsplit(":", 1)
        findings.append(Finding(
            PASS, path, int(line), "lock-cycle", names,
            f"static lock-acquisition cycle {names} — two threads "
            f"entering from different ends deadlock (threading.Lock "
            f"is not reentrant, so a self-loop deadlocks one thread "
            f"alone; RLock/Condition self-loops are exempt)"))
    return findings
