"""Atomic-write pass: durable artifacts go through tmp+rename.

Every durable runtime artifact in the tree — cursor checkpoints,
scan-progress status frames, post-mortems, metrics snapshots — is
published with the atomic discipline (same-directory tmp,
``os.replace``, fsync where loss matters) via
``obs.live.atomic_write_text`` or ``shard.scan.save_cursor_file``.  A
plain ``open(path, "w")`` on such a path can expose a torn file to a
concurrent reader (``parquet-tool top``, a Prometheus scraper, a
resuming scan) or lose the artifact on crash mid-write.

The pass flags every *text-mode* write-open in ``tpuparquet/`` whose
enclosing function does not itself complete the tmp+``os.replace``
dance.  Binary write-opens are out of scope: those are user-requested
parquet data files whose torn-write story is the salvage layer, not
the atomic-rename discipline.  User-requested export APIs that take
an explicit path/stream (event-log dumps, Chrome traces) are the
allowlist's territory — with a reason each.
"""

from __future__ import annotations

import ast

from .astutil import (Finding, RepoTree, call_name, const_str,
                      enclosing_function)

PASS = "atomic-write"

_WRITE_MODES = ("w", "wt", "a", "at", "w+", "a+", "x", "xt")


def _write_mode(call: ast.Call) -> bool:
    """Is this an ``open`` call in a text write mode?"""
    if call_name(call) != "open":
        return False
    mode = None
    if len(call.args) > 1:
        mode = const_str(call.args[1])
    for kw in call.keywords:
        if kw.arg == "mode":
            mode = const_str(kw.value)
    return mode in _WRITE_MODES


def _replaces_atomically(fn) -> bool:
    """Does the function body call ``os.replace``/``os.rename``
    (the promote step of the tmp+rename discipline)?"""
    for node in ast.walk(fn):
        if isinstance(node, ast.Call) and \
                call_name(node) in ("replace", "rename"):
            return True
    return False


def run(tree: RepoTree) -> list[Finding]:
    findings: list[Finding] = []
    for path, mod in tree.modules("tpuparquet/"):
        for node in ast.walk(mod):
            if not (isinstance(node, ast.Call) and _write_mode(node)):
                continue
            fn = enclosing_function(node)
            fname = fn.name if fn is not None else "<module>"
            if fn is not None and _replaces_atomically(fn):
                continue  # tmp + os.replace in the same function
            findings.append(Finding(
                PASS, path, node.lineno, "non-atomic-write", fname,
                f"text-mode open(..., 'w') in {fname}() without a "
                f"tmp+os.replace promote — a concurrent reader can "
                f"see a torn file and a crash mid-write loses the "
                f"artifact; route it through obs.live."
                f"atomic_write_text (or justify in the allowlist)"))
    return findings
