"""Resource-lifecycle pass: what is acquired must be released.

The round-8 ``FileReader`` fd leak and the round-18 torn-tmp sweep
are one bug class: a resource acquired (file descriptor, arena lease,
disk-cache tmp, ring segment) with a raise-able path between the
acquire and the release — or no release at all.  This pass finds the
acquire sites structurally and requires each to be one of:

* managed — ``with open(...)`` / ``with closing(v)`` / ``with v:``;
* released on ALL paths — the release call sits in a ``finally`` or
  an ``except`` handler (release-on-error exists), or nothing that
  can raise runs between the acquire and the release;
* ownership-transferred — the handle is returned/yielded, stored on
  ``self``/a container, or passed into a call that takes it over;
* or allowlisted with a reason (the arena pool's documented
  drop-lease-on-error escape hatch is the intended tenant).

Constructors get their own rule (``ctor-leak-on-error``): a resource
bound to ``self`` in ``__init__`` followed by top-level statements
that can raise OUTSIDE a try that closes it leaks the handle on a
failed construction — ``__init__`` raising means nobody ever holds
the instance to close it.

Handle *registries* get a third rule (``container-leak``): an acquire
stored into a container attribute — ``self._handles[key] = open(...)``,
the ``DatasetWriter`` shape — transfers ownership to the OBJECT, not
to the enclosing function, so the function-local rules above cannot
see it.  The transfer is legitimate only when some *other* method of
the owning class drains the registry (references the container attr
and performs a release call — a ``_release()``/``close()`` that
iterates the dict closing each handle).  A class that fills such a
registry and never drains it leaks every entry.

Acquire vocabulary: ``open``, ``os.open``, ``os.fdopen``,
``tempfile.mkstemp``, ``lease_arena``, ``.lease()``.  Release
vocabulary: ``.close()``, ``.release()``, ``os.close``,
``return_arena``, ``give_back``.
"""

from __future__ import annotations

import ast

from .astutil import Finding, RepoTree, ancestors

PASS = "resource-lifecycle"

_ACQ_NAMES = ("open", "lease_arena", "mkstemp", "lease")
_ACQ_ATTRS = {("os", "open"), ("os", "fdopen"),
              ("tempfile", "mkstemp")}
_REL_METHODS = ("close", "release")
_REL_FUNCS = ("return_arena", "give_back")
_REL_ATTRS = {("os", "close")}


def _is_acquire(call: ast.Call) -> bool:
    f = call.func
    if isinstance(f, ast.Name):
        return f.id in _ACQ_NAMES
    if isinstance(f, ast.Attribute):
        if isinstance(f.value, ast.Name) and \
                (f.value.id, f.attr) in _ACQ_ATTRS:
            return True
        return f.attr == "lease"
    return False


def _uses(node: ast.AST, name: str) -> bool:
    return any(isinstance(n, ast.Name) and n.id == name
               for n in ast.walk(node))


def _is_release_of(node: ast.AST, name: str) -> bool:
    """Does this subtree release local ``name``?"""
    for n in ast.walk(node):
        if not isinstance(n, ast.Call):
            continue
        f = n.func
        if isinstance(f, ast.Attribute) and f.attr in _REL_METHODS \
                and _uses(f.value, name):
            return True
        if isinstance(f, ast.Name) and f.id in _REL_FUNCS and \
                any(_uses(a, name) for a in n.args):
            return True
        if isinstance(f, ast.Attribute) and \
                isinstance(f.value, ast.Name) and \
                (f.value.id, f.attr) in _REL_ATTRS and \
                any(_uses(a, name) for a in n.args):
            return True
    return False


def _escapes(fn, name: str, after_line: int) -> bool:
    """Ownership leaves the function: returned/yielded, stored on an
    attribute/container, or handed to a non-release call."""
    for n in ast.walk(fn):
        if getattr(n, "lineno", 0) < after_line:
            continue
        if isinstance(n, (ast.Return, ast.Yield, ast.YieldFrom)) and \
                n.value is not None and _uses(n.value, name):
            return True
        if isinstance(n, ast.Assign) and _uses(n.value, name):
            for t in n.targets:
                if isinstance(t, (ast.Attribute, ast.Subscript)):
                    return True
        if isinstance(n, ast.Call):
            f = n.func
            is_rel = (isinstance(f, ast.Attribute)
                      and f.attr in _REL_METHODS) or \
                (isinstance(f, ast.Name) and f.id in _REL_FUNCS)
            if is_rel:
                continue
            args = list(n.args) + [kw.value for kw in n.keywords]
            if any(_uses(a, name) for a in args):
                return True
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)) and \
                any(_uses(s, name) for s in n.body):
            return True  # captured by a closure: lifetime is its own
    return False


def _with_managed(fn, name: str) -> bool:
    for n in ast.walk(fn):
        if isinstance(n, ast.With):
            for item in n.items:
                if _uses(item.context_expr, name):
                    return True
    return False


def _protected_release(fn, name: str) -> bool:
    """A release of ``name`` exists on an error path: in a
    ``finally`` block or an ``except`` handler."""
    for n in ast.walk(fn):
        if isinstance(n, ast.Try):
            if any(_is_release_of(s, name) for s in n.finalbody):
                return True
            for h in n.handlers:
                if any(_is_release_of(s, name) for s in h.body):
                    return True
    return False


def _risky(stmt: ast.stmt) -> bool:
    """Can this statement raise for a reason the analyzer should care
    about?  Any call or explicit raise counts; plain attribute/const
    assignments do not."""
    for n in ast.walk(stmt):
        if isinstance(n, (ast.Call, ast.Raise, ast.Assert)):
            return True
    return False


def _body_of(stmt: ast.stmt):
    """The statement list that directly contains ``stmt``."""
    parent = getattr(stmt, "_tpq_parent", None)
    if parent is None:
        return None
    for field in ("body", "orelse", "finalbody"):
        seq = getattr(parent, field, None)
        if isinstance(seq, list) and stmt in seq:
            return seq
    if isinstance(parent, ast.ExceptHandler) and stmt in parent.body:
        return parent.body
    return None


def _stmt_of(node: ast.AST) -> ast.stmt | None:
    cur = node
    for a in ancestors(node):
        if isinstance(cur, ast.stmt) and _body_of(cur) is not None:
            return cur
        cur = a
    return cur if isinstance(cur, ast.stmt) else None


def _check_local(fn, fname, path, stmt, name, findings) -> None:
    line = stmt.lineno
    if _with_managed(fn, name):
        return
    released = any(
        _is_release_of(n, name) for n in ast.walk(fn)
        if isinstance(n, ast.stmt) and getattr(n, "lineno", 0) >= line
        and n is not stmt)
    if not released:
        if _escapes(fn, name, line):
            return
        findings.append(Finding(
            PASS, path, line, "unreleased-acquire",
            f"{fname}:{name}",
            f"{name} acquired in {fname}() is never released, "
            f"returned, stored, or handed off — the handle leaks on "
            f"every path"))
        return
    if _protected_release(fn, name):
        return
    # released, but only on the straight-line path: any raise-able
    # statement between acquire and release leaks it
    siblings = _body_of(stmt)
    risky_between = False
    if siblings is not None:
        started = False
        for s in siblings:
            if s is stmt:
                started = True
                continue
            if not started:
                continue
            if _is_release_of(s, name):
                break
            if _risky(s):
                risky_between = True
                break
    if risky_between:
        findings.append(Finding(
            PASS, path, line, "leak-on-error", f"{fname}:{name}",
            f"{name} acquired in {fname}() is released only on the "
            f"no-error path — a raise between the acquire and the "
            f"release leaks the handle; move the release to a "
            f"finally (or use a with-block)"))


def _check_ctor(cls_name, init, path, stmt, attr, findings) -> None:
    """``self.attr = open(...)`` in __init__: later top-level risky
    statements must live inside a try that closes it on error."""
    line = stmt.lineno

    def releases_attr(node) -> bool:
        for n in ast.walk(node):
            if isinstance(n, ast.Call) and \
                    isinstance(n.func, ast.Attribute):
                # self.attr.close() or self.close()
                f = n.func
                if f.attr in _REL_METHODS:
                    v = f.value
                    if isinstance(v, ast.Attribute) and \
                            v.attr == attr:
                        return True
                    if isinstance(v, ast.Name) and v.id == "self":
                        return True
                if f.attr.startswith("close") and \
                        isinstance(f.value, ast.Name) and \
                        f.value.id == "self":
                    return True
        return False

    def protected(try_node: ast.Try) -> bool:
        if any(releases_attr(s) for s in try_node.finalbody):
            return True
        return any(releases_attr(s) for h in try_node.handlers
                   for s in h.body)

    started = False
    for s in init.body:
        if s is stmt or (getattr(s, "lineno", 0) == line
                         and not started):
            started = True
            if s is stmt:
                continue
        if not started:
            continue
        if isinstance(s, ast.Try) and protected(s):
            return  # everything past here is guarded
        if s is not stmt and _risky(s):
            findings.append(Finding(
                PASS, path, s.lineno, "ctor-leak-on-error",
                f"{cls_name}.__init__:{attr}",
                f"self.{attr} holds a live handle but this statement "
                f"can raise before any try/close guard — a failed "
                f"{cls_name}() leaks the handle, since no caller "
                f"ever receives the instance to close it"))
            return


def _check_container(cls_node, cls_name, acq_fn, path, stmt, attr,
                     findings) -> None:
    """``self.attr[key] = open(...)``: directory-scoped ownership
    transfer.  Legitimate only when another method of the class
    drains the registry — references ``self.attr`` and performs a
    release call in the same body."""
    for m in cls_node.body:
        if not isinstance(m, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                or m is acq_fn:
            continue
        refs_container = any(
            isinstance(n, ast.Attribute) and n.attr == attr
            and isinstance(n.value, ast.Name) and n.value.id == "self"
            for n in ast.walk(m))
        if not refs_container:
            continue
        releases = any(
            isinstance(n, ast.Call)
            and ((isinstance(n.func, ast.Attribute)
                  and n.func.attr in _REL_METHODS)
                 or (isinstance(n.func, ast.Name)
                     and n.func.id in _REL_FUNCS)
                 or (isinstance(n.func, ast.Attribute)
                     and isinstance(n.func.value, ast.Name)
                     and (n.func.value.id, n.func.attr) in _REL_ATTRS))
            for n in ast.walk(m))
        if releases:
            return
    findings.append(Finding(
        PASS, path, stmt.lineno, "container-leak",
        f"{cls_name}:{attr}",
        f"handles stored into registry self.{attr} in "
        f"{acq_fn.name}() are never drained — no other method of "
        f"{cls_name} references the container and releases; every "
        f"entry leaks when the instance is dropped"))


def run(tree: RepoTree) -> list[Finding]:
    findings: list[Finding] = []
    for path, mod in tree.modules("tpuparquet/"):
        for fn in ast.walk(mod):
            if not isinstance(fn, (ast.FunctionDef,
                                   ast.AsyncFunctionDef)):
                continue
            cls = None
            parent = getattr(fn, "_tpq_parent", None)
            if isinstance(parent, ast.ClassDef):
                cls = parent.name
            for node in ast.walk(fn):
                if not (isinstance(node, ast.Assign)
                        and isinstance(node.value, ast.Call)
                        and _is_acquire(node.value)
                        and len(node.targets) == 1):
                    continue
                stmt = _stmt_of(node)
                if stmt is None:
                    continue
                t = node.targets[0]
                if isinstance(t, ast.Name):
                    # skip when assigned inside a with-item scope of
                    # the same statement handled structurally
                    _check_local(fn, fn.name, path, stmt, t.id,
                                 findings)
                elif isinstance(t, ast.Attribute) and \
                        isinstance(t.value, ast.Name) and \
                        t.value.id == "self" and \
                        fn.name == "__init__" and cls is not None:
                    _check_ctor(cls, fn, path, stmt, t.attr, findings)
                elif isinstance(t, ast.Subscript) and \
                        isinstance(t.value, ast.Attribute) and \
                        isinstance(t.value.value, ast.Name) and \
                        t.value.value.id == "self" and \
                        cls is not None:
                    _check_container(parent, cls, fn, path, stmt,
                                     t.value.attr, findings)
    return findings
