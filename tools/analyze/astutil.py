"""Shared AST plumbing for the tpq-analyze passes.

Every pass consumes a :class:`RepoTree`: a parsed snapshot of the
source files a pass may reason about (library, tools, tests, README).
Trees come from disk for the real gate (:meth:`RepoTree.from_disk`)
or from in-memory ``{relpath: source}`` dicts for the seeded-bug
fixtures in ``tests/test_analyze.py`` — passes never touch the
filesystem themselves, so a fixture IS a repo as far as a pass can
tell.

Parsed modules carry parent links (:func:`attach_parents`) because
most invariants here are about *context* — "is this call inside a
loop", "is this store under a ``with`` on a module lock" — which bare
``ast`` nodes cannot answer.
"""

from __future__ import annotations

import ast
import dataclasses
import os

__all__ = ["Finding", "RepoTree", "attach_parents", "ancestors",
           "enclosing_function", "call_name", "const_str"]


@dataclasses.dataclass
class Finding:
    """One analyzer verdict: where, which pass, which rule, and why.

    ``key`` is the *stable identity* used for allowlist matching —
    a symbol/site/knob name, never a line number (lines drift with
    every edit; a justified exception should survive reformatting)."""

    pass_name: str
    file: str
    line: int
    code: str
    key: str
    why: str

    def as_dict(self) -> dict:
        return {
            "pass": self.pass_name,
            "file": self.file,
            "line": self.line,
            "code": self.code,
            "key": self.key,
            "why": self.why,
        }

    def __str__(self) -> str:
        return (f"{self.file}:{self.line}: [{self.pass_name}/"
                f"{self.code}] {self.key}: {self.why}")


class RepoTree:
    """Parsed view of the repo for the passes.

    ``files`` maps repo-relative posix paths to source text; parsed
    ASTs (with parent links) are cached per path.  Files that fail to
    parse surface as a ``parse-error`` finding from every pass that
    asks for them rather than crashing the gate."""

    #: source roots the real gate loads, relative to the repo root
    PY_ROOTS = ("tpuparquet", "tools", "tests")
    PY_TOP = ("bench.py",)

    def __init__(self, files: dict[str, str],
                 readme: str | None = None):
        self.files = dict(files)
        self.readme = readme
        self._asts: dict[str, ast.AST | None] = {}
        self.parse_errors: list[tuple[str, str]] = []
        #: cross-pass scratch cache keyed by pass-chosen names (the
        #: thread-safety pass parks its whole-program lock graph here
        #: so ``static_graph``/``--lock-graph`` don't recompute it);
        #: scoped to THIS tree, so fixtures never see stale facts
        self.memo: dict = {}

    @classmethod
    def from_disk(cls, root: str) -> "RepoTree":
        files: dict[str, str] = {}
        for top in cls.PY_ROOTS:
            base = os.path.join(root, top)
            for dirpath, dirnames, filenames in os.walk(base):
                dirnames[:] = [d for d in dirnames
                               if d != "__pycache__"]
                for fn in sorted(filenames):
                    if not fn.endswith(".py"):
                        continue
                    path = os.path.join(dirpath, fn)
                    rel = os.path.relpath(path, root).replace(os.sep, "/")
                    with open(path, encoding="utf-8") as f:
                        files[rel] = f.read()
        for fn in cls.PY_TOP:
            path = os.path.join(root, fn)
            if os.path.exists(path):
                with open(path, encoding="utf-8") as f:
                    files[fn] = f.read()
        readme = None
        rp = os.path.join(root, "README.md")
        if os.path.exists(rp):
            with open(rp, encoding="utf-8") as f:
                readme = f.read()
        return cls(files, readme)

    # -- selection -------------------------------------------------------

    def paths(self, prefix: str = "") -> list[str]:
        return sorted(p for p in self.files if p.startswith(prefix))

    def module(self, path: str) -> ast.AST | None:
        """Parsed AST (with parent links) or None on syntax error."""
        if path not in self._asts:
            try:
                tree = ast.parse(self.files[path], filename=path)
            except SyntaxError as e:
                self._asts[path] = None
                self.parse_errors.append((path, str(e)))
            else:
                attach_parents(tree)
                self._asts[path] = tree
        return self._asts[path]

    def modules(self, prefix: str = ""):
        """Yield ``(path, ast)`` for every parseable file under
        ``prefix``."""
        for p in self.paths(prefix):
            t = self.module(p)
            if t is not None:
                yield p, t


def attach_parents(tree: ast.AST) -> ast.AST:
    """Set ``node._tpq_parent`` on every node (None at the root)."""
    tree._tpq_parent = None  # type: ignore[attr-defined]
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            child._tpq_parent = node  # type: ignore[attr-defined]
    return tree


def ancestors(node: ast.AST):
    """Yield parents from the immediate one up to the module."""
    cur = getattr(node, "_tpq_parent", None)
    while cur is not None:
        yield cur
        cur = getattr(cur, "_tpq_parent", None)


def enclosing_function(node: ast.AST):
    """The nearest enclosing FunctionDef/AsyncFunctionDef, or None."""
    for a in ancestors(node):
        if isinstance(a, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return a
    return None


def call_name(call: ast.Call) -> str | None:
    """The bare callee name: ``f(...)`` -> "f", ``a.b.f(...)`` -> "f"."""
    f = call.func
    if isinstance(f, ast.Name):
        return f.id
    if isinstance(f, ast.Attribute):
        return f.attr
    return None


def const_str(node) -> str | None:
    """The literal string value of a Constant node, else None."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None
