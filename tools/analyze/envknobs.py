"""Env-knob pass: the ``TPQ_*`` catalog and the code cannot drift.

AST-level successor of the old source grep in
``tests/test_env_docs.py`` (which matched quoted literals and so
missed reads where the knob name arrives through a helper
parameter).  Evidence for "the source uses this knob", strongest
first:

* **direct reads/writes** — ``os.environ.get("TPQ_X")``,
  ``os.environ["TPQ_X"]``, ``os.getenv("TPQ_X")``, membership tests,
  ``setdefault``/``pop``/assignment;
* **indirect reads** — a call ``helper("TPQ_X", ...)`` where
  ``helper`` is any function in the tree whose matching *parameter*
  flows into an environ read in its body (``_env_budget``,
  ``_env_float``, ``_env_int``, and anything added later — detected
  structurally, not by name);
* **env-dict construction** — ``TPQ_X=...`` keyword arguments and
  ``{"TPQ_X": ...}`` dict keys (subprocess environments in the bench
  drivers);
* **bare literal** — any other ``"TPQ_X"`` string constant (the old
  grep's whole evidence class, kept as a fallback so nothing the
  grep caught goes dark).

The pass then proves catalog parity both ways against the README
"## Env knobs" section: every knob used in source is documented, and
every documented knob is still used.
"""

from __future__ import annotations

import ast
import re

from .astutil import Finding, RepoTree, const_str

PASS = "env-knobs"

_KNOB = re.compile(r"^TPQ_[A-Z0-9_]+$")
_DOCUMENTED = re.compile(r"`(TPQ_[A-Z0-9_]+)`")

#: roots whose knob usage the README must catalog (mirrors the old
#: grep: the library, the tools, and the bench driver; tests arm
#: knobs ad hoc and are exempt).  The analyzer's own sources are
#: excluded — its fixtures and pass logic *name* knobs as data.
ROOTS = ("tpuparquet/", "tools/", "bench.py")
EXCLUDE = ("tools/analyze/",)


def _is_environ(node) -> bool:
    """Does this expression denote ``os.environ``?"""
    return (isinstance(node, ast.Attribute) and node.attr == "environ") \
        or (isinstance(node, ast.Name) and node.id == "environ")


def _env_read_params(fn) -> set[int]:
    """Indices of ``fn`` parameters that flow into an environ read in
    its body (one level of indirection)."""
    params = [a.arg for a in fn.args.args]
    hits: set[int] = set()
    for node in ast.walk(fn):
        name = None
        if isinstance(node, ast.Call):
            f = node.func
            if isinstance(f, ast.Attribute) and _is_environ(f.value) \
                    and f.attr in ("get", "setdefault", "pop"):
                name = node.args[0] if node.args else None
            elif isinstance(f, ast.Attribute) and f.attr == "getenv":
                name = node.args[0] if node.args else None
            elif isinstance(f, ast.Name) and f.id == "getenv":
                name = node.args[0] if node.args else None
        elif isinstance(node, ast.Subscript) and _is_environ(node.value):
            name = node.slice
        if isinstance(name, ast.Name) and name.id in params:
            hits.add(params.index(name.id))
    return hits


def source_knobs(tree: RepoTree) -> dict[str, dict]:
    """knob -> {"evidence": kind, "file": path, "line": n} for every
    TPQ_ knob the configured roots use, with the strongest evidence
    kind retained (direct > indirect > envdict > literal)."""
    rank = {"direct": 0, "indirect": 1, "envdict": 2, "literal": 3}
    out: dict[str, dict] = {}

    def record(knob, kind, path, line):
        if knob is None or not _KNOB.match(knob):
            return
        prev = out.get(knob)
        if prev is None or rank[kind] < rank[prev["evidence"]]:
            out[knob] = {"evidence": kind, "file": path, "line": line}

    paths = [p for p in tree.files
             if any(p == r or p.startswith(r) for r in ROOTS)
             and not any(p.startswith(x) for x in EXCLUDE)]

    # pass 1: find helper functions with env-reading parameters
    helpers: dict[str, set[int]] = {}
    for path in sorted(paths):
        mod = tree.module(path)
        if mod is None:
            continue
        for node in ast.walk(mod):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                idx = _env_read_params(node)
                if idx:
                    helpers.setdefault(node.name, set()).update(idx)

    # pass 2: collect evidence
    for path in sorted(paths):
        mod = tree.module(path)
        if mod is None:
            continue
        for node in ast.walk(mod):
            if isinstance(node, ast.Call):
                f = node.func
                fname = f.attr if isinstance(f, ast.Attribute) \
                    else f.id if isinstance(f, ast.Name) else None
                if isinstance(f, ast.Attribute) \
                        and _is_environ(f.value) \
                        and f.attr in ("get", "setdefault", "pop") \
                        and node.args:
                    record(const_str(node.args[0]), "direct",
                           path, node.lineno)
                elif fname == "getenv" and node.args:
                    record(const_str(node.args[0]), "direct",
                           path, node.lineno)
                elif fname in helpers:
                    for i in helpers[fname]:
                        if i < len(node.args):
                            record(const_str(node.args[i]), "indirect",
                                   path, node.lineno)
                for kw in node.keywords:
                    if kw.arg and _KNOB.match(kw.arg):
                        record(kw.arg, "envdict", path, node.lineno)
            elif isinstance(node, ast.Subscript) \
                    and _is_environ(node.value):
                record(const_str(node.slice), "direct",
                       path, node.lineno)
            elif isinstance(node, ast.Compare) \
                    and any(_is_environ(c) for c in node.comparators):
                record(const_str(node.left), "direct",
                       path, node.lineno)
            elif isinstance(node, ast.Dict):
                for k in node.keys:
                    record(const_str(k), "envdict", path, node.lineno)
            else:
                s = const_str(node)
                if s is not None:
                    record(s, "literal", path, node.lineno)
    return out


def readme_knobs(tree: RepoTree) -> set[str]:
    """Knobs documented in the README "## Env knobs" section."""
    text = tree.readme or ""
    start = text.find("## Env knobs")
    if start < 0:
        return set()
    end = text.find("\n## ", start + 3)
    if end < 0:
        end = len(text)
    return set(_DOCUMENTED.findall(text[start:end]))


def run(tree: RepoTree) -> list[Finding]:
    findings: list[Finding] = []
    if tree.readme is None or "## Env knobs" not in tree.readme:
        findings.append(Finding(
            PASS, "README.md", 1, "catalog-missing", "Env knobs",
            "no '## Env knobs' section in the README — the knob "
            "catalog the source is checked against"))
        return findings
    src = source_knobs(tree)
    doc = readme_knobs(tree)
    for knob in sorted(set(src) - doc):
        ev = src[knob]
        findings.append(Finding(
            PASS, ev["file"], ev["line"], "undocumented-knob", knob,
            f"{knob} is used by the source ({ev['evidence']} evidence) "
            f"but has no row in the README 'Env knobs' catalog"))
    for knob in sorted(doc - set(src)):
        findings.append(Finding(
            PASS, "README.md", 1, "stale-doc-knob", knob,
            f"the README documents {knob} but no source under "
            f"{ROOTS} uses it anymore — drop the stale row"))
    return findings
