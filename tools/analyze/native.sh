#!/bin/bash
# Native sanitizer + static-analysis leg of tpq-analyze.
#
# The eight C codecs (delta.c, hybrid.c, intern.c, lz4raw.c, pack.c,
# page.c, plane.c, snappy.c) run with the GIL released on
# attacker-influenced bytes (and, on the write side, on whole column
# bodies);
# Python-level tests structurally cannot see a heap overrun that
# happens to land in mapped memory, or UB the optimizer hasn't
# punished yet.  This script:
#
#   1. rebuilds the extension instrumented with ASan+UBSan
#      (-fno-sanitize-recover: any report is fatal = nonzero exit)
#   2. runs the native test suite + the checked-in fuzz/crash corpus
#      against the instrumented build (TPQ_NATIVE_SO override +
#      LD_PRELOAD of the sanitizer runtimes, leak checking off —
#      the CPython interpreter "leaks" by design at exit)
#   3. runs a C static analyzer over the sources: clang --analyze
#      or cppcheck when available, else GCC's -fanalyzer
#
# Skips GRACEFULLY (exit 0, loud notice) when no sanitizer-capable
# compiler is on the box — CI images without clang/libasan still run
# the Python-side passes.  Force a failure on skip with
# TPQ_NATIVE_STRICT=1.
#
# Usage: bash tools/analyze/native.sh
set -u -o pipefail
cd "$(dirname "$0")/../.."

SRC_DIR=tpuparquet/native
SRCS=("$SRC_DIR"/delta.c "$SRC_DIR"/hybrid.c "$SRC_DIR"/intern.c \
      "$SRC_DIR"/lz4raw.c "$SRC_DIR"/pack.c "$SRC_DIR"/page.c \
      "$SRC_DIR"/plane.c "$SRC_DIR"/snappy.c)

# coverage check: the pinned SRCS list must name every native/*.c on
# disk — a codec added without updating this script would otherwise
# ship with zero sanitizer/static-analysis coverage, silently
for src in "$SRC_DIR"/*.c; do
  covered=0
  for s in "${SRCS[@]}"; do
    [ "$s" = "$src" ] && { covered=1; break; }
  done
  if [ "$covered" = 0 ]; then
    echo "native.sh: FAILED — $src exists on disk but is missing" >&2
    echo "native.sh: from SRCS; add it so the sanitizer + analyzer" >&2
    echo "native.sh: legs cover it" >&2
    exit 1
  fi
done
BUILD_DIR=${TMPDIR:-/tmp}/tpq-native-san.$$
SAN_SO="$BUILD_DIR/_tpq_native_san.so"
trap 'rm -rf "$BUILD_DIR"' EXIT
mkdir -p "$BUILD_DIR"

skip() {
  echo "native.sh: SKIPPED — $1" >&2
  echo "native.sh: the GIL-released C fast paths are NOT sanitizer-" >&2
  echo "native.sh: covered on this box; install clang or gcc+libasan" >&2
  if [ "${TPQ_NATIVE_STRICT:-0}" = "1" ]; then
    exit 1
  fi
  exit 0
}

fail() { echo "native.sh: FAILED at $1" >&2; exit 1; }

# ---- pick a sanitizer-capable compiler --------------------------------
CC=""
for cand in clang gcc cc; do
  command -v "$cand" >/dev/null 2>&1 || continue
  probe="$BUILD_DIR/probe"
  if echo 'int main(void){return 0;}' | "$cand" -x c - \
       -fsanitize=address,undefined -o "$probe" 2>/dev/null \
     && "$probe" >/dev/null 2>&1; then
    CC="$cand"
    break
  fi
done
[ -n "$CC" ] || skip "no compiler with a working ASan+UBSan runtime found"
echo "=== native leg 1/3: ASan+UBSan instrumented build ($CC) ==="

"$CC" -O1 -g -shared -fPIC \
  -fsanitize=address,undefined -fno-sanitize-recover=all \
  -o "$SAN_SO" "${SRCS[@]}" || fail "instrumented build"
echo "built $SAN_SO"

# sanitizer runtimes must be preloaded: python itself is not linked
# against them, only the .so is
PRELOAD=""
if [ "$CC" != clang ]; then
  for rt in libasan.so libubsan.so; do
    p=$("$CC" -print-file-name="$rt")
    [ "$p" != "$rt" ] && PRELOAD="$PRELOAD $p"
  done
else
  # clang links the combined runtime statically into the .so by
  # default only for executables; resolve its shared runtime —
  # name/layout varies by arch and clang version, so probe both forms
  arch=$(uname -m)
  for rt in "libclang_rt.asan-$arch.so" libclang_rt.asan.so; do
    p=$(clang -print-file-name="$rt" 2>/dev/null)
    if [ -n "$p" ] && [ "$p" != "$rt" ] && [ -e "$p" ]; then
      PRELOAD="$p"
      break
    fi
  done
fi
PRELOAD=${PRELOAD# }

echo "=== native leg 2/3: test suite + fuzz/crash corpus under ASan+UBSan ==="
# the strict-green set: native bindings, codec round-trips, the
# checked-in crash-corpus regressions, and the fuzz suite (Hypothesis
# legs self-skip when the dependency is absent; the corpus-driven
# mutation tests still run)
env JAX_PLATFORMS=cpu \
    TPQ_NATIVE_SO="$SAN_SO" \
    LD_PRELOAD="$PRELOAD" \
    ASAN_OPTIONS=detect_leaks=0:abort_on_error=1 \
    UBSAN_OPTIONS=halt_on_error=1:print_stacktrace=1 \
    timeout -k 10 600 python -m pytest \
      tests/test_native.py tests/test_codecs.py tests/test_compress.py \
      tests/test_fuzz.py tests/test_write_native.py \
      "tests/test_corpus.py::TestCrashRegressions" \
      -q -p no:cacheprovider \
  || fail "sanitized test run (a failure here that does not reproduce \
without native.sh is a sanitizer report — scroll up for the ASan/UBSan \
stack)"

echo "=== native leg 3/3: C static analysis ==="
ANALYZED=0
if command -v clang >/dev/null 2>&1; then
  # one file per invocation: the clang driver rejects -o (and can
  # interleave diagnostics) with multiple non-link inputs
  for src in "${SRCS[@]}"; do
    out=$(clang --analyze --analyzer-output text -Xclang \
          -analyzer-werror "$src" 2>&1) \
      || { echo "$out"; fail "clang --analyze ($src)"; }
    [ -n "$out" ] && { echo "$out"; fail "clang --analyze findings ($src)"; }
  done
  echo "clang --analyze: clean"; ANALYZED=1
fi
if command -v cppcheck >/dev/null 2>&1; then
  cppcheck --error-exitcode=1 --enable=warning,portability \
    --inline-suppr --quiet "${SRCS[@]}" || fail "cppcheck"
  echo "cppcheck: clean"; ANALYZED=1
fi
if [ "$ANALYZED" = 0 ]; then
  # neither clang nor cppcheck: GCC 10+'s -fanalyzer covers the
  # leak/overflow/UB-path classes on these sources
  out=$("$CC" -fanalyzer -fsyntax-only -Wall -Wextra \
        -Wno-unused-parameter "${SRCS[@]}" 2>&1) \
    || { echo "$out"; fail "$CC -fanalyzer"; }
  [ -n "$out" ] && { echo "$out"; fail "$CC -fanalyzer findings"; }
  echo "$CC -fanalyzer: clean"
fi

echo "native.sh: sanitizer + static-analysis leg PASSED"
