"""CLI entry: ``python -m tools.analyze``.

Exit 0 = gate passed (zero unsuppressed findings, no stale allowlist
entries); 1 = violations; 2 = usage error.  ``--json`` emits the
whole result as one machine-readable document (the same digest
``parquet-tool analyze --json`` prints).
"""

from __future__ import annotations

import argparse
import json
import sys

from . import (DEFAULT_ALLOWLIST, PASSES, Allowlist, RepoTree,
               repo_root, run_analysis)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="tpq-analyze",
        description="static invariant passes over the tpuparquet tree")
    p.add_argument("--root", default=None,
                   help="repo root (default: autodetected from this "
                        "file's location)")
    p.add_argument("--pass", dest="passes", action="append",
                   metavar="NAME", choices=sorted(PASSES),
                   help="run only this pass (repeatable; default all; "
                        "stale-allowlist checking needs the full run)")
    p.add_argument("--allowlist", default=DEFAULT_ALLOWLIST,
                   help="allowlist JSON path (default: the checked-in "
                        "tools/analyze/allowlist.json)")
    p.add_argument("--no-allowlist", action="store_true",
                   help="report raw findings with no suppression")
    p.add_argument("--json", action="store_true",
                   help="emit the full result as JSON on stdout")
    p.add_argument("--lock-graph", action="store_true",
                   help="print the whole-program static lock-order "
                        "graph (sites, edges, unresolved) and exit")
    p.add_argument("--verify-lockcheck", metavar="DUMP",
                   help="check a runtime lockcheck dump (JSON from "
                        "TPQ_LOCKCHECK_OUT) is violation-free and a "
                        "subgraph of the static graph, then exit")
    p.add_argument("--allowlist-audit", action="store_true",
                   help="list allowlist entries by age/pass and fail "
                        "on entries whose target file is gone")
    return p


def _lock_graph(args) -> int:
    from . import threads
    tree = RepoTree.from_disk(args.root or repo_root())
    g = threads.static_graph(tree)
    if args.json:
        json.dump(g, sys.stdout, indent=2, sort_keys=True)
        print()
    else:
        for site, info in sorted(g["sites"].items()):
            print(f"lock {site} [{info['kind']}] {info['label']}")
        for a, b in g["edges"]:
            print(f"edge {a} -> {b}")
        for u in g["unresolved"]:
            print(f"unresolved {u['file']}:{u['line']} "
                  f"{u['expr']} in {u['function']}()")
        print(f"lock-graph: {len(g['sites'])} site(s), "
              f"{len(g['edges'])} edge(s), "
              f"{len(g['unresolved'])} unresolved")
    return 1 if g["unresolved"] else 0


def _verify_lockcheck(args) -> int:
    from . import threads
    tree = RepoTree.from_disk(args.root or repo_root())
    with open(args.verify_lockcheck, encoding="utf-8") as f:
        recorded = json.load(f)
    problems = threads.verify_runtime_graph(tree, recorded)
    if args.json:
        json.dump({"problems": problems, "ok": not problems},
                  sys.stdout, indent=2, sort_keys=True)
        print()
    else:
        for pr in problems:
            print(f"lockcheck: {pr}")
        n_edges = len(recorded.get("edges") or [])
        print(f"verify-lockcheck: {n_edges} recorded edge(s), "
              f"{len(problems)} problem(s): "
              + ("PASSED" if not problems else "FAILED"))
    return 0 if not problems else 1


def _allowlist_audit(args) -> int:
    tree = RepoTree.from_disk(args.root or repo_root())
    report = Allowlist.load(args.allowlist).audit(tree)
    if args.json:
        json.dump(report, sys.stdout, indent=2, sort_keys=True)
        print()
    else:
        for e in report["entries"]:
            mark = " MISSING-TARGET" if not e["target_exists"] else ""
            print(f"{e['added']}  {e['pass']:20s} {e['file']}::"
                  f"{e['key']}{mark}")
        print(f"allowlist-audit: {len(report['entries'])} entr(y/ies),"
              f" {len(report['missing_target'])} with missing target "
              f"file: " + ("PASSED" if report["ok"] else "FAILED"))
    return 0 if report["ok"] else 1


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    if args.lock_graph:
        return _lock_graph(args)
    if args.verify_lockcheck:
        return _verify_lockcheck(args)
    if args.allowlist_audit:
        return _allowlist_audit(args)
    res = run_analysis(
        root=args.root, passes=args.passes,
        allowlist=None if args.no_allowlist else args.allowlist)
    if args.json:
        json.dump(res, sys.stdout, indent=2, sort_keys=True)
        print()
    else:
        for f in res["findings"]:
            print(f"{f['file']}:{f['line']}: [{f['pass']}/{f['code']}]"
                  f" {f['key']}: {f['why']}")
        for e in res["stale_allowlist"]:
            print(f"allowlist: stale entry ({e['pass']}, {e['file']}, "
                  f"{e['key']}) suppresses nothing — drop it "
                  f"(reason was: {e['reason']})")
        total = sum(res["counts"].values())
        print(f"tpq-analyze: {len(res['findings'])} finding(s) "
              f"({total} raw, {len(res['suppressed'])} allowlisted"
              f"{', ' + str(len(res['stale_allowlist'])) + ' stale allowlist entr(y/ies)' if res['stale_allowlist'] else ''}) "
              f"across {len(res['counts'])} pass(es): "
              + ("gate PASSED" if res["ok"] else "gate FAILED"))
    return 0 if res["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
