"""CLI entry: ``python -m tools.analyze``.

Exit 0 = gate passed (zero unsuppressed findings, no stale allowlist
entries); 1 = violations; 2 = usage error.  ``--json`` emits the
whole result as one machine-readable document (the same digest
``parquet-tool analyze --json`` prints).
"""

from __future__ import annotations

import argparse
import json
import sys

from . import DEFAULT_ALLOWLIST, PASSES, run_analysis


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="tpq-analyze",
        description="static invariant passes over the tpuparquet tree")
    p.add_argument("--root", default=None,
                   help="repo root (default: autodetected from this "
                        "file's location)")
    p.add_argument("--pass", dest="passes", action="append",
                   metavar="NAME", choices=sorted(PASSES),
                   help="run only this pass (repeatable; default all; "
                        "stale-allowlist checking needs the full run)")
    p.add_argument("--allowlist", default=DEFAULT_ALLOWLIST,
                   help="allowlist JSON path (default: the checked-in "
                        "tools/analyze/allowlist.json)")
    p.add_argument("--no-allowlist", action="store_true",
                   help="report raw findings with no suppression")
    p.add_argument("--json", action="store_true",
                   help="emit the full result as JSON on stdout")
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    res = run_analysis(
        root=args.root, passes=args.passes,
        allowlist=None if args.no_allowlist else args.allowlist)
    if args.json:
        json.dump(res, sys.stdout, indent=2, sort_keys=True)
        print()
    else:
        for f in res["findings"]:
            print(f"{f['file']}:{f['line']}: [{f['pass']}/{f['code']}]"
                  f" {f['key']}: {f['why']}")
        for e in res["stale_allowlist"]:
            print(f"allowlist: stale entry ({e['pass']}, {e['file']}, "
                  f"{e['key']}) suppresses nothing — drop it "
                  f"(reason was: {e['reason']})")
        total = sum(res["counts"].values())
        print(f"tpq-analyze: {len(res['findings'])} finding(s) "
              f"({total} raw, {len(res['suppressed'])} allowlisted"
              f"{', ' + str(len(res['stale_allowlist'])) + ' stale allowlist entr(y/ies)' if res['stale_allowlist'] else ''}) "
              f"across {len(res['counts'])} pass(es): "
              + ("gate PASSED" if res["ok"] else "gate FAILED"))
    return 0 if res["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
