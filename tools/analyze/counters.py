"""Counter-registry pass: every stats counter is declared, merged,
and alive.

The exactness story of ``tpuparquet/stats.py`` rests on three sets
staying equal by hand: the ``DecodeStats`` dataclass fields, the
``_MERGE_FIELDS`` tuple the worker/allgather fold iterates, and the
``st.<counter> += n`` bump sites scattered through the tree.  A
counter missing from ``_MERGE_FIELDS`` silently drops every count a
worker thread or remote host contributes; a bump on an undeclared
name raises only on the rare path that reaches it; a declared counter
nobody bumps is dead weight that ``as_dict``/Prometheus report as
forever-zero.  This pass proves the three-way equality statically.

Bump-site detection: any ``<name>.<field> += n`` where ``<field>`` is
a declared DecodeStats field counts (the repo's collector variables
are consistently st-like: ``st``/``_st``/``_cs``/``ws``); typo
protection additionally tracks variables assigned from
``current_stats()``/``worker_stats()``/``adopt_stats()`` and flags
AugAssigns on those receivers whose attribute is NOT a declared
field.  Dynamic bumps (``setattr(st, counter, ...)``) are credited by
the counter-name string literal, so ``retry_transient``'s
``counter="io_retries"`` contract keeps those counters alive.
"""

from __future__ import annotations

import ast

from .astutil import Finding, RepoTree, const_str, enclosing_function

PASS = "counters"

#: DecodeStats fields owned by the scope itself or merged specially —
#: everything else must ride _MERGE_FIELDS to survive the fold
SPECIAL_FIELDS = frozenset({"wall_s", "_t0", "hists", "events"})

#: names a collector variable is assigned from
_ST_FACTORIES = frozenset({"current_stats", "worker_stats",
                           "adopt_stats", "collect_stats"})

STATS_PATH = "tpuparquet/stats.py"


def _tuple_of_strs(node) -> list[str] | None:
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for e in node.elts:
            s = const_str(e)
            if s is None:
                return None
            out.append(s)
        return out
    return None


def read_registry(tree: RepoTree) -> dict | None:
    """Extract the declared/merged/fault-field sets from stats.py.
    Returns None (with a finding emitted by :func:`run`) when the
    module shape is unrecognizable."""
    mod = tree.module(STATS_PATH) if STATS_PATH in tree.files else None
    if mod is None:
        return None
    decl: dict[str, int] = {}
    merge: list[str] = []
    merge_line = 0
    fault: list[str] = []
    fault_line = 0
    for node in ast.walk(mod):
        if isinstance(node, ast.ClassDef) and node.name == "DecodeStats":
            for stmt in node.body:
                if isinstance(stmt, ast.AnnAssign) and \
                        isinstance(stmt.target, ast.Name):
                    decl[stmt.target.id] = stmt.lineno
                elif isinstance(stmt, ast.Assign):
                    for tgt in stmt.targets:
                        if isinstance(tgt, ast.Name) and \
                                tgt.id == "_MERGE_FIELDS":
                            merge = _tuple_of_strs(stmt.value) or []
                            merge_line = stmt.lineno
        elif isinstance(node, ast.Assign):
            for tgt in node.targets:
                if isinstance(tgt, ast.Name) and \
                        tgt.id == "_FAULT_OBSERVABILITY_FIELDS":
                    fault = _tuple_of_strs(node.value) or []
                    fault_line = node.lineno
    if not decl or not merge:
        return None
    return {"declared": decl, "merge": merge, "merge_line": merge_line,
            "fault": fault, "fault_line": fault_line}


def _st_like_vars(fn) -> set[str]:
    """Variable names in ``fn`` bound from a collector factory:
    ``st = current_stats()``, ``with worker_stats() as ws``."""
    out: set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign) and \
                isinstance(node.value, ast.Call):
            callee = node.value.func
            name = callee.attr if isinstance(callee, ast.Attribute) \
                else callee.id if isinstance(callee, ast.Name) else None
            if name in _ST_FACTORIES:
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        out.add(tgt.id)
        elif isinstance(node, ast.With):
            for item in node.items:
                ctx = item.context_expr
                if isinstance(ctx, ast.Call):
                    name = (ctx.func.attr
                            if isinstance(ctx.func, ast.Attribute)
                            else ctx.func.id
                            if isinstance(ctx.func, ast.Name) else None)
                    if name in _ST_FACTORIES and \
                            isinstance(item.optional_vars, ast.Name):
                        out.add(item.optional_vars.id)
    return out


def run(tree: RepoTree) -> list[Finding]:
    findings: list[Finding] = []
    reg = read_registry(tree)
    if reg is None:
        findings.append(Finding(
            PASS, STATS_PATH, 1, "registry-unreadable", "DecodeStats",
            "could not extract DecodeStats fields / _MERGE_FIELDS "
            "from stats.py — the pass has nothing to check against"))
        return findings
    declared = reg["declared"]
    counters = set(declared) - SPECIAL_FIELDS
    merge = reg["merge"]
    merge_set = set(merge)

    # 1) declared <-> merged equality
    for name in sorted(counters - merge_set):
        findings.append(Finding(
            PASS, STATS_PATH, declared[name], "unmerged-counter", name,
            f"DecodeStats.{name} is declared but missing from "
            f"_MERGE_FIELDS — worker-thread and cross-host folds "
            f"silently drop it"))
    for name in sorted(merge_set - set(declared)):
        findings.append(Finding(
            PASS, STATS_PATH, reg["merge_line"], "merge-of-undeclared",
            name,
            f"_MERGE_FIELDS names {name!r} which DecodeStats does not "
            f"declare — merge_from would raise AttributeError"))
    dupes = {n for n in merge if merge.count(n) > 1}
    for name in sorted(dupes):
        findings.append(Finding(
            PASS, STATS_PATH, reg["merge_line"], "merge-duplicate",
            name,
            f"_MERGE_FIELDS lists {name!r} more than once — the fold "
            f"would double-count it"))

    # 2) fault-observability fields must survive the merge fold
    for name in sorted(set(reg["fault"]) - merge_set):
        findings.append(Finding(
            PASS, STATS_PATH, reg["fault_line"], "fault-field-unmerged",
            name,
            f"_FAULT_OBSERVABILITY_FIELDS names {name!r} which is not "
            f"in _MERGE_FIELDS — failed-attempt folds would diverge "
            f"from successful ones"))

    # 3) bump sites across the library
    bumped: set[str] = set()
    literals: set[str] = set()
    for path, mod in tree.modules("tpuparquet/"):
        st_vars_cache: dict[int, set[str]] = {}
        for node in ast.walk(mod):
            if path != STATS_PATH:
                s = const_str(node)
                if s is not None and s in counters:
                    literals.add(s)
            if not isinstance(node, ast.AugAssign):
                continue
            tgt = node.target
            if not (isinstance(tgt, ast.Attribute)
                    and isinstance(tgt.value, ast.Name)
                    and tgt.value.id not in ("self", "cls")):
                continue
            attr = tgt.attr
            if attr in counters:
                bumped.add(attr)
                continue
            if attr in SPECIAL_FIELDS:
                findings.append(Finding(
                    PASS, path, node.lineno, "bump-of-special", attr,
                    f"augmented assignment to DecodeStats.{attr} — "
                    f"this field is owned by the scope/merge machinery "
                    f"and must never be bumped at a site"))
                continue
            # typo guard: only when the receiver provably came from a
            # collector factory in this function
            fn = enclosing_function(node)
            if fn is None:
                continue
            key = id(fn)
            if key not in st_vars_cache:
                st_vars_cache[key] = _st_like_vars(fn)
            if tgt.value.id in st_vars_cache[key]:
                findings.append(Finding(
                    PASS, path, node.lineno, "undeclared-counter-bump",
                    attr,
                    f"{tgt.value.id}.{attr} += ... bumps a field "
                    f"DecodeStats does not declare — a typo'd counter "
                    f"that only fails on the path that reaches it"))

    # 4) liveness: every merged counter has a bump site or a dynamic
    #    (string-literal) reference
    for name in sorted(merge_set & counters):
        if name not in bumped and name not in literals:
            findings.append(Finding(
                PASS, STATS_PATH, declared.get(name, reg["merge_line"]),
                "dead-counter", name,
                f"DecodeStats.{name} is declared and merged but no "
                f"site in tpuparquet/ ever bumps or names it — dead "
                f"weight reported as forever-zero"))
    return findings
