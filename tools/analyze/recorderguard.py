"""Recorder-guard pass: hot telemetry sites skip kwargs when off.

``obs.recorder.flight`` — and its causal-tracing sibling
``obs.trace.emit_span`` — are internally no-ops when their layer is
disabled, but the *call site* still evaluates and boxes its keyword
arguments first.  On per-page/per-chunk paths that cost is real, so
the repo's discipline (``obs/recorder.py`` and ``obs/trace.py``
docstrings) is to guard the call itself::

    if _flightrec._active is not None:
        _flightrec.flight("page", site=..., file=..., page=...)

    if _trace._active is not None:
        _trace.emit_span("read", t0, dt, file=..., column=...)

The longitudinal layer keeps the same discipline for its own emit
surfaces — ``obs.digest.observe`` (per-unit/per-scan latency
observations) and ``obs.alerts.emit_alert``::

    if _digest._active is not None:
        _digest.observe(label, "unit", us, trace=..., unit=...)

The round-20 sampling profiler adds two more: ``stage_begin`` (the
per-window stage hints at the write/transfer/dispatch/gather sites)
and ``wait_begin`` (the off-CPU IO markers in the chunk reader)::

    ptok = _profiler.stage_begin("write") \
        if _profiler._active is not None else None

Their ``stage_end``/``wait_end`` twins are exempt like
``close_span``: they take the instance-carrying token ``stage_begin``
returned (None when off) and build nothing.

This pass enforces the pattern structurally, for ALL vocabularies:

* every *module-qualified* call (``<alias>.flight(...)`` /
  ``<alias>.emit_span(...)`` / ``<alias>.open_span(...)`` /
  ``<alias>.observe(...)`` / ``<alias>.emit_alert(...)`` — the form
  hot sites use precisely so they can reach ``_active``) must sit
  under an ``if`` whose test checks ``_active is not None`` (or
  ``recorder()``/``tracer()``/``digests()``/``engine()`` is not
  None);
* every *bare* ``flight(...)``/``emit_span(...)`` call that lives
  inside a ``for``/``while`` loop is treated as hot and held to the
  same rule — unless it is on an exceptional path (inside an
  ``except`` handler), which is the cold-site idiom (faults,
  quarantines, retries fire rarely and keep the plain call).

``close_span``/``adopt``/``ctx_of`` are exempt: they take an
already-built handle (None when off) and build no kwargs — guarding
them would only duplicate the open-site guard.
"""

from __future__ import annotations

import ast

from .astutil import Finding, RepoTree, ancestors, enclosing_function

PASS = "recorder-guard"

EXCLUDE = ("tpuparquet/obs/recorder.py", "tpuparquet/obs/trace.py",
           "tpuparquet/obs/digest.py", "tpuparquet/obs/alerts.py",
           "tpuparquet/obs/profiler.py")

#: call names held to the guarded-hot-site rule (the kwargs-building
#: emit surfaces of the flight recorder, the causal tracer, the
#: latency digests, the alert engine, and the sampling profiler's
#: stage/wait markers)
HOT_NAMES = ("flight", "emit_span", "open_span", "observe",
             "emit_alert", "stage_begin", "wait_begin")

#: event KINDS (the first positional arg) that ride per-request /
#: per-range hot paths no matter where the call sits — the round-18
#: remote-store emulation fires on a modulo of EVERY request, the
#: disk-cache poison check runs per cache hit, and prefetch spans are
#: emitted once per prefetched range.  These must be guarded even
#: outside loops and even on exceptional paths (the kwargs build
#: happens before the raise).
HOT_KINDS = ("emu_fault", "cache_poison", "prefetch_span")


def _is_guard_test(test: ast.AST) -> bool:
    """Does this if-test (or any part of it) check the recorder gate?"""
    for node in ast.walk(test):
        if isinstance(node, ast.Attribute) and node.attr == "_active":
            return True
        if isinstance(node, ast.Name) and node.id == "_active":
            return True
        if isinstance(node, ast.Call):
            f = node.func
            name = f.attr if isinstance(f, ast.Attribute) \
                else f.id if isinstance(f, ast.Name) else None
            if name in ("recorder", "tracer", "digests", "engine",
                        "profiler"):
                return True
    return False


def _context(node, fn):
    """(guarded, in_loop, in_except) from the ancestor chain, scoped
    to the enclosing function."""
    guarded = in_loop = in_except = False
    prev = node
    for a in ancestors(node):
        if a is fn:
            break
        if isinstance(a, ast.If) and prev in a.body \
                and _is_guard_test(a.test):
            guarded = True
        # the expression form of the same idiom:
        #   h = _trace.open_span(...) if _trace._active is not None \
        #       else None
        if isinstance(a, ast.IfExp) and prev is a.body \
                and _is_guard_test(a.test):
            guarded = True
        if isinstance(a, (ast.For, ast.While)):
            in_loop = True
        if isinstance(a, ast.ExceptHandler):
            in_except = True
        prev = a
    return guarded, in_loop, in_except


def run(tree: RepoTree) -> list[Finding]:
    findings: list[Finding] = []
    for path, mod in tree.modules("tpuparquet/"):
        if path in EXCLUDE:
            continue
        for node in ast.walk(mod):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            qualified = isinstance(f, ast.Attribute) and \
                f.attr in HOT_NAMES
            bare = isinstance(f, ast.Name) and f.id in HOT_NAMES
            if not (qualified or bare):
                continue
            fn = enclosing_function(node)
            guarded, in_loop, in_except = _context(node, fn)
            if guarded:
                continue
            fname = fn.name if fn is not None else "<module>"
            called = f.attr if qualified else f.id
            kind = ""
            if node.args and isinstance(node.args[0], ast.Constant):
                kind = str(node.args[0].value)
            key = f"{fname}:{kind}" if kind else fname
            if kind in HOT_KINDS:
                findings.append(Finding(
                    PASS, path, node.lineno, "unguarded-hot-kind",
                    key,
                    f"{called}({kind!r}, ...) in {fname}() without "
                    f"the `_active is not None` guard — {kind} events "
                    f"fire on per-request/per-range paths, so the "
                    f"kwargs build must be skipped when the recorder "
                    f"is off, wherever the call sits"))
                continue
            if qualified:
                findings.append(Finding(
                    PASS, path, node.lineno, "unguarded-hot-flight",
                    key,
                    f"module-qualified {called}() call in {fname}() "
                    f"without the `_active is not None` guard — the "
                    f"qualified form exists exactly so hot sites can "
                    f"skip kwargs construction when the "
                    f"recorder/tracer is off"))
            elif in_loop and not in_except:
                findings.append(Finding(
                    PASS, path, node.lineno, "unguarded-hot-flight",
                    key,
                    f"{called}() call inside a loop in {fname}() "
                    f"constructs kwargs even with the recorder/tracer "
                    f"disabled — guard with `_active is not None` "
                    f"(hot) or move to an exceptional path (cold)"))
    return findings
