"""tpq-analyze: the repo's conventions as machine-checked contracts.

Eight AST invariant passes over the library (plus the native
sanitizer leg in ``tools/analyze/native.sh``) turn documented
disciplines — exact counter merges, registered fault sites, the
env-knob catalog, atomic durable writes, guarded flight-recorder hot
sites, lock-guarded module state with an acyclic whole-program lock
graph, released-on-all-paths resource lifecycles, and taxonomy-typed
raises — into a zero-findings CI gate.  Run::

    python -m tools.analyze [--json] [--pass NAME]

The gate is **zero findings, not zero noise**: real, justified
exceptions live in ``tools/analyze/allowlist.json`` with a reason
each, matched by ``(pass, file, key)`` where ``key`` is a stable
symbol/site/knob name (never a line number).  A stale allowlist entry
— one that matches nothing anymore — is itself a finding, so the
exception list can only shrink truthfully; ``--allowlist-audit``
additionally lists every entry by age and fails on entries whose
target file no longer exists.

The static thread-safety pass has a runtime twin: with
``TPQ_LOCKCHECK=1`` the library records its real lock-acquisition
graph (``tpuparquet/lockcheck.py``), and ``--verify-lockcheck DUMP``
checks that recording is cycle-free and a subgraph of the static
graph — each side validating the other.
"""

from __future__ import annotations

import json
import os
import time

from . import (atomicwrite, counters, envknobs, faultsites,
               lifecycle, raises, recorderguard, threads)
from .astutil import Finding, RepoTree

__all__ = ["PASSES", "RepoTree", "Finding", "Allowlist",
           "run_analysis", "repo_root", "DEFAULT_ALLOWLIST"]

#: registry of invariant passes, in report order
PASSES = {
    counters.PASS: counters.run,
    faultsites.PASS: faultsites.run,
    envknobs.PASS: envknobs.run,
    atomicwrite.PASS: atomicwrite.run,
    recorderguard.PASS: recorderguard.run,
    threads.PASS: threads.run,
    lifecycle.PASS: lifecycle.run,
    raises.PASS: raises.run,
}

_DIR = os.path.dirname(os.path.abspath(__file__))
DEFAULT_ALLOWLIST = os.path.join(_DIR, "allowlist.json")


def repo_root() -> str:
    """The repo root this analyzer ships in (tools/analyze/../..)."""
    return os.path.dirname(os.path.dirname(_DIR))


class Allowlist:
    """Justified exceptions: entries ``{pass, file, key, reason}``
    plus an optional ``added`` date (YYYY-MM-DD) the hygiene audit
    sorts by.

    Matching is exact on ``(pass, file, key)``; a ``reason`` is
    mandatory — an allowlist row without one is rejected at load so
    "TODO" exceptions can't accrete."""

    def __init__(self, entries: list[dict] | None = None):
        self.entries = list(entries or [])
        for e in self.entries:
            for field in ("pass", "file", "key", "reason"):
                if not e.get(field):
                    raise ValueError(
                        f"allowlist entry {e!r} missing {field!r} — "
                        f"every exception needs pass/file/key and a "
                        f"reason")
        self._used: set[int] = set()

    @classmethod
    def load(cls, path: str | None) -> "Allowlist":
        if not path or not os.path.exists(path):
            return cls([])
        with open(path, encoding="utf-8") as f:
            doc = json.load(f)
        return cls(doc.get("entries") or [])

    def suppresses(self, finding: Finding) -> bool:
        for i, e in enumerate(self.entries):
            if (e["pass"] == finding.pass_name
                    and e["file"] == finding.file
                    and e["key"] == finding.key):
                self._used.add(i)
                return True
        return False

    def stale_entries(self) -> list[dict]:
        """Entries that suppressed nothing in the last run."""
        return [e for i, e in enumerate(self.entries)
                if i not in self._used]

    def audit(self, tree: "RepoTree") -> dict:
        """Hygiene report: every entry by age/pass, plus the entries
        whose target FILE no longer exists in the tree (a stronger
        staleness than key-match — the justified code is gone
        entirely, so the exception must go with it)."""
        rows = []
        missing = []
        for e in self.entries:
            row = {
                "pass": e["pass"],
                "file": e["file"],
                "key": e["key"],
                "added": e.get("added") or "(pre-audit)",
                "reason": e["reason"],
                "target_exists": e["file"] in tree.files,
            }
            rows.append(row)
            if not row["target_exists"]:
                missing.append(row)
        rows.sort(key=lambda r: (r["added"], r["pass"], r["file"],
                                 r["key"]))
        return {"entries": rows, "missing_target": missing,
                "ok": not missing}


def run_analysis(root: str | None = None,
                 passes: list[str] | None = None,
                 allowlist: "Allowlist | str | None" = DEFAULT_ALLOWLIST,
                 tree: RepoTree | None = None) -> dict:
    """Run the selected passes and fold in the allowlist.

    Returns ``{"findings": [...], "suppressed": [...], "stale":
    [...], "counts": {...}, "timings_s": {...}, "ok": bool}`` —
    ``ok`` is the gate: no live findings, no parse errors, no stale
    allowlist entries.  ``timings_s`` carries per-pass wall time (the
    parsed-AST cache in :class:`RepoTree` is shared across passes, so
    the first pass pays the parse and the rest measure pure
    analysis)."""
    if tree is None:
        tree = RepoTree.from_disk(root or repo_root())
    if isinstance(allowlist, str) or allowlist is None:
        allowlist = Allowlist.load(allowlist)
    selected = passes or list(PASSES)
    unknown = [p for p in selected if p not in PASSES]
    if unknown:
        raise ValueError(f"unknown pass(es) {unknown}; "
                         f"have {sorted(PASSES)}")
    live: list[Finding] = []
    suppressed: list[Finding] = []
    counts: dict[str, int] = {}
    timings: dict[str, float] = {}
    for name in selected:
        t0 = time.monotonic()
        found = PASSES[name](tree)
        timings[name] = round(time.monotonic() - t0, 4)
        counts[name] = len(found)
        for f in found:
            (suppressed if allowlist.suppresses(f) else live).append(f)
    for path, err in tree.parse_errors:
        live.append(Finding("analyze", path, 1, "parse-error", path,
                            f"unparseable source: {err}"))
    # staleness is judged only for entries whose pass actually ran —
    # a --pass subset must not condemn the other passes' exceptions
    stale = [e for e in allowlist.stale_entries()
             if e["pass"] in selected]
    return {
        "findings": [f.as_dict() for f in live],
        "suppressed": [f.as_dict() for f in suppressed],
        "stale_allowlist": stale,
        "counts": counts,
        "timings_s": timings,
        "ok": not live and not stale,
    }
