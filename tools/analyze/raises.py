"""Exception-taxonomy pass: raises speak the repo's error language.

``tpuparquet/errors.py`` defines the structured taxonomy — ScanError
coordinates (file / row group / column / page), corrupt-vs-transient
classification, quarantine membership — and the repo's discipline is
"inner layers raise what they know; outer layers annotate":

* decode/validation internals raise the PLAIN vocabulary
  (``ValueError``/``EOFError``/``TypeError``…, which
  ``QUARANTINE_ERRORS`` classifies) or a taxonomy error;
* I/O and dispatch layers raise taxonomy errors CARRYING coordinates,
  so a quarantine report can name the exact page without re-reading;
* nothing raises the classes that defeat classification —
  bare ``Exception``, ``RuntimeError``, raw ``OSError`` and friends —
  because ``is_transient``/``QUARANTINE_ERRORS``/``on_error`` policy
  cannot route what they cannot type.

This pass walks every ``raise`` in ``tpuparquet/`` and requires:

* ``non-taxonomy-raise`` — the raised class is not a taxonomy error,
  not part of the plain quarantine/API vocabulary, and not a builtin
  with defined routing: justify it in the allowlist or retype it;
* ``taxonomy-no-coords`` — a ``ScanError``-family constructor call
  outside an ``except`` handler (the annotate path) that passes NO
  coordinate kwargs: the error will surface with nothing for the
  quarantine report to pinpoint;
* ``unknown-exception-class`` — a raise of a name the analyzer can
  see neither in builtins, the taxonomy, nor the repo.
"""

from __future__ import annotations

import ast
import builtins

from .astutil import Finding, RepoTree, ancestors, call_name, \
    enclosing_function

PASS = "exception-taxonomy"

_ERRORS_PATH = "tpuparquet/errors.py"
#: kwargs that count as coordinates (``offset`` is the footer
#: taxonomy's byte coordinate, same pinpointing role)
_COORD_KWARGS = ("file", "row_group", "column", "page", "offset")

#: the plain inner-layer vocabulary: QUARANTINE_ERRORS members plus
#: the API-misuse classes calling code is expected to let propagate
_ALLOWED_BUILTINS = frozenset({
    "ValueError", "TypeError", "KeyError", "IndexError", "EOFError",
    "NotImplementedError", "AssertionError", "StopIteration",
    "StopAsyncIteration", "AttributeError", "OverflowError",
    "ZeroDivisionError", "ArithmeticError", "LookupError",
    "UnicodeDecodeError", "UnicodeEncodeError", "MemoryError",
    "FileNotFoundError", "FileExistsError", "PermissionError",
    "IsADirectoryError", "NotADirectoryError", "ImportError",
    "ModuleNotFoundError", "KeyboardInterrupt", "SystemExit",
})

#: classes that defeat transient/quarantine classification
_FLAGGED = frozenset({
    "Exception", "BaseException", "RuntimeError", "SystemError",
    "OSError", "IOError", "EnvironmentError", "ConnectionError",
    "ConnectionResetError", "ConnectionAbortedError",
    "ConnectionRefusedError", "BrokenPipeError", "TimeoutError",
    "InterruptedError", "BlockingIOError",
})


def _taxonomy(tree: RepoTree):
    """(all taxonomy class names, the ScanError-family subset) from
    parsing errors.py — never from importing it."""
    mod = tree.module(_ERRORS_PATH) if _ERRORS_PATH in tree.files \
        else None
    if mod is None:
        return frozenset(), frozenset()
    bases: dict[str, list[str]] = {}
    for node in mod.body:
        if isinstance(node, ast.ClassDef):
            bases[node.name] = [b.id for b in node.bases
                                if isinstance(b, ast.Name)]

    def in_family(name: str, _seen=frozenset()) -> bool:
        if name == "ScanError":
            return True
        if name in _seen or name not in bases:
            return False
        return any(in_family(b, _seen | {name})
                   for b in bases[name])

    names = frozenset(n for n in bases if not n.startswith("_"))
    family = frozenset(n for n in names if in_family(n))
    return names, family


def _repo_bases(tree: RepoTree) -> dict:
    """name -> base names for every class defined in tpuparquet/."""
    out: dict[str, list[str]] = {}
    for path, mod in tree.modules("tpuparquet/"):
        for node in ast.walk(mod):
            if isinstance(node, ast.ClassDef):
                names = []
                for b in node.bases:
                    if isinstance(b, ast.Name):
                        names.append(b.id)
                    elif isinstance(b, ast.Attribute):
                        names.append(b.attr)
                out[node.name] = names
    return out


def _reaches(name: str, targets, bases: dict,
             _seen: frozenset = frozenset()) -> bool:
    """Does ``name``'s base closure reach any of ``targets``?"""
    if name in targets:
        return True
    if name in _seen or name not in bases:
        return False
    return any(_reaches(b, targets, bases, _seen | {name})
               for b in bases[name])


def _module_aliases(mod, known) -> dict:
    """Module-level ``NewName = KnownError`` re-exports (the
    footer.py ``FormatError = CorruptFooterError`` pattern)."""
    out: dict[str, str] = {}
    for node in mod.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and isinstance(node.value, ast.Name) \
                and node.value.id in known:
            out[node.targets[0].id] = node.value.id
    return out


def _raised_name(exc) -> str | None:
    if isinstance(exc, ast.Call):
        return call_name(exc)
    if isinstance(exc, ast.Name):
        return exc.id
    if isinstance(exc, ast.Attribute):
        return exc.attr
    return None


def _has_coords(call: ast.Call) -> bool:
    for kw in call.keywords:
        if kw.arg is None or kw.arg in _COORD_KWARGS:
            return True
    return False


def _in_except(node) -> bool:
    return any(isinstance(a, ast.ExceptHandler)
               for a in ancestors(node))


def _handler_names(handler: ast.ExceptHandler):
    t = handler.type
    elts = t.elts if isinstance(t, ast.Tuple) else [t]
    for e in elts:
        if isinstance(e, ast.Name):
            yield e.id
        elif isinstance(e, ast.Attribute):
            yield e.attr


def _annotated_on_exit(node, family) -> bool:
    """Is this raise inside a ``try`` whose handler catches the
    family (or a base wide enough to) and annotates on the way out?
    That is the chunk-reader discipline: inner raises are bare, the
    enclosing handler stamps column/page once for all of them."""
    catchers = set(family) | {"ScanError", "ValueError", "Exception"}
    for a in ancestors(node):
        if isinstance(a, ast.Try):
            for h in a.handlers:
                if h.type is not None and \
                        catchers.intersection(_handler_names(h)):
                    return True
    return False


def run(tree: RepoTree) -> list[Finding]:
    taxonomy, scan_family = _taxonomy(tree)
    repo_bases = _repo_bases(tree)
    known = taxonomy | frozenset(repo_bases)
    findings: list[Finding] = []
    # aliases declared in errors.py itself are taxonomy re-exports —
    # visible to every raising module, not just errors.py
    err_aliases: dict[str, str] = {}
    if _ERRORS_PATH in tree.files:
        err_aliases = _module_aliases(tree.module(_ERRORS_PATH), known)
    for path, mod in tree.modules("tpuparquet/"):
        aliases = dict(err_aliases)
        aliases.update(_module_aliases(mod, known))
        for node in ast.walk(mod):
            if not isinstance(node, ast.Raise) or node.exc is None:
                continue
            name = _raised_name(node.exc)
            if name is None:
                continue
            # lowercase name: a re-raise of a caught/boxed exception
            # object (``raise err``, ``raise errors.get(0)``) or an
            # exception-factory call (``raise error(...)``,
            # ``raise annotate(e, ...)``) — the class was typed where
            # the factory/box was filled, not here
            if not name[:1].isupper():
                continue
            name = aliases.get(name, name)
            fn = enclosing_function(node)
            fname = fn.name if fn is not None else "<module>"
            key = f"{fname}:{name}"
            in_family = name in scan_family or (
                name not in taxonomy
                and _reaches(name, ("ScanError",), repo_bases))
            if name in taxonomy or in_family:
                if in_family and \
                        isinstance(node.exc, ast.Call) and \
                        path != _ERRORS_PATH and \
                        not _in_except(node) and \
                        not _has_coords(node.exc) and \
                        not _annotated_on_exit(node, scan_family):
                    findings.append(Finding(
                        PASS, path, node.lineno, "taxonomy-no-coords",
                        key,
                        f"{name} raised in {fname}() with no "
                        f"coordinate kwargs (file/row_group/column/"
                        f"page/offset), outside an annotate path — "
                        f"the quarantine report will have nothing to "
                        f"pinpoint; pass what this layer knows"))
                continue
            if name in _FLAGGED or (
                    name in repo_bases
                    and _reaches(name, _FLAGGED, repo_bases)):
                findings.append(Finding(
                    PASS, path, node.lineno, "non-taxonomy-raise",
                    key,
                    f"raise {name} in {fname}() — is_transient/"
                    f"QUARANTINE_ERRORS/on_error policy cannot "
                    f"classify it; raise a taxonomy error from "
                    f"errors.py (or allowlist with the reason this "
                    f"path is outside scan/error routing)"))
                continue
            if name in _ALLOWED_BUILTINS:
                continue
            bi = getattr(builtins, name, None)
            if isinstance(bi, type) and \
                    issubclass(bi, BaseException):
                continue  # an un-flagged builtin: defined routing
            if name in repo_bases:
                # a repo class whose base closure reaches the plain
                # vocabulary (CompressionError(ValueError), ThriftError
                # (ValueError), …) IS classifiable — QUARANTINE_ERRORS
                # catches it by its builtin base
                if _reaches(name, _ALLOWED_BUILTINS, repo_bases):
                    continue
                findings.append(Finding(
                    PASS, path, node.lineno, "non-taxonomy-raise",
                    key,
                    f"raise {name} in {fname}() — a repo class "
                    f"outside the errors.py taxonomy with no "
                    f"classifiable builtin base; scan error routing "
                    f"cannot type it (move it into the taxonomy or "
                    f"allowlist with the reason)"))
                continue
            findings.append(Finding(
                PASS, path, node.lineno, "unknown-exception-class",
                key,
                f"raise {name} in {fname}() — a class the analyzer "
                f"finds neither in builtins, errors.py, nor the "
                f"repo; likely an unimported or misspelled name"))
    return findings
