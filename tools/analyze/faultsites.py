"""Fault-site pass: injection sites, registry, docs and tests agree.

The fault-injection harness (``tpuparquet/faults.py``) matches rules
to sites by *string equality* — a drifted site name doesn't error, it
just never fires, and the test that armed it silently tests nothing.
This pass pins four corners together:

* every ``fault_point("...")`` / ``filter_bytes("...", ...)``
  instrumentation site in the library is registered in
  ``faults.SITES``;
* every registered site is actually instrumented somewhere (no dead
  registry rows);
* every site a test arms (``inj.inject("site", "kind")``) exists, and
  the kind is one the site supports;
* the human table in the ``faults.py`` docstring lists exactly the
  registered sites (docs can't drift from the registry).
"""

from __future__ import annotations

import ast
import re

from .astutil import Finding, RepoTree, call_name, const_str

PASS = "fault-sites"

FAULTS_PATH = "tpuparquet/faults.py"

#: the instrumentation hooks whose first argument is a site name
_HOOKS = ("fault_point", "filter_bytes")

#: docstring table rows: a line opening with ``site.name`` (sites are
#: always dotted, which keeps kind words like ``hang`` out)
_DOC_SITE = re.compile(
    r"^``([a-z0-9_]+(?:\.[a-z0-9_]+)+)``", re.MULTILINE)


def read_sites(tree: RepoTree) -> dict[str, tuple] | None:
    """The ``SITES`` registry literal from faults.py, or None."""
    mod = tree.module(FAULTS_PATH) if FAULTS_PATH in tree.files else None
    if mod is None:
        return None
    for node in ast.walk(mod):
        if isinstance(node, (ast.Assign, ast.AnnAssign)):
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            for tgt in targets:
                if isinstance(tgt, ast.Name) and tgt.id == "SITES" \
                        and isinstance(node.value, ast.Dict):
                    out = {}
                    for k, v in zip(node.value.keys, node.value.values):
                        site = const_str(k)
                        if site is None:
                            return None
                        kinds = []
                        if isinstance(v, (ast.Tuple, ast.List, ast.Set)):
                            kinds = [const_str(e) for e in v.elts]
                        out[site] = tuple(x for x in kinds if x)
                    return out
    return None


def instrumented_sites(tree: RepoTree) -> dict[str, tuple[str, int]]:
    """site -> (file, line) of one instrumentation hook naming it."""
    out: dict[str, tuple[str, int]] = {}
    for path, mod in tree.modules("tpuparquet/"):
        if path == FAULTS_PATH:
            continue  # the hooks' own definitions/docs
        for node in ast.walk(mod):
            if isinstance(node, ast.Call) and \
                    call_name(node) in _HOOKS and node.args:
                site = const_str(node.args[0])
                if site is not None:
                    out.setdefault(site, (path, node.lineno))
    return out


def injected_sites(tree: RepoTree) -> list[tuple[str, str, str, int]]:
    """Every test-armed rule: (site, kind, file, line)."""
    out = []
    for path, mod in tree.modules("tests/"):
        for node in ast.walk(mod):
            if isinstance(node, ast.Call) and \
                    call_name(node) == "inject" and node.args:
                site = const_str(node.args[0])
                kind = const_str(node.args[1]) \
                    if len(node.args) > 1 else None
                if site is not None:
                    out.append((site, kind or "", path, node.lineno))
    return out


def docstring_sites(tree: RepoTree) -> set[str]:
    mod = tree.module(FAULTS_PATH) if FAULTS_PATH in tree.files else None
    if mod is None:
        return set()
    doc = ast.get_docstring(mod) or ""
    return set(_DOC_SITE.findall(doc))


def run(tree: RepoTree) -> list[Finding]:
    findings: list[Finding] = []
    sites = read_sites(tree)
    if sites is None:
        findings.append(Finding(
            PASS, FAULTS_PATH, 1, "registry-unreadable", "SITES",
            "no SITES = {...} literal in faults.py — the fault-site "
            "registry the harness and tests are checked against"))
        return findings

    hooked = instrumented_sites(tree)
    for site, (path, line) in sorted(hooked.items()):
        if site not in sites:
            findings.append(Finding(
                PASS, path, line, "unregistered-site", site,
                f"instrumentation names site {site!r} which "
                f"faults.SITES does not register — rules armed against "
                f"the registry can never fire here"))
    for site in sorted(set(sites) - set(hooked)):
        findings.append(Finding(
            PASS, FAULTS_PATH, 1, "dead-site", site,
            f"faults.SITES registers {site!r} but no fault_point/"
            f"filter_bytes hook in tpuparquet/ names it — a rule armed "
            f"there waits forever"))

    for site, kind, path, line in injected_sites(tree):
        if site not in sites:
            findings.append(Finding(
                PASS, path, line, "unknown-test-site", site,
                f"test arms fault site {site!r} which is not in "
                f"faults.SITES — the rule never fires and the test "
                f"exercises nothing"))
        elif kind and kind not in sites[site]:
            findings.append(Finding(
                PASS, path, line, "kind-mismatch", f"{site}:{kind}",
                f"test arms kind {kind!r} at {site!r} but the site "
                f"supports only {sorted(sites[site])}"))

    doc = docstring_sites(tree)
    if doc:  # fixtures without a docstring table skip the doc check
        for site in sorted(set(sites) - doc):
            findings.append(Finding(
                PASS, FAULTS_PATH, 1, "docstring-drift", site,
                f"site {site!r} is registered but missing from the "
                f"faults.py docstring table"))
        for site in sorted(doc - set(sites)):
            findings.append(Finding(
                PASS, FAULTS_PATH, 1, "docstring-drift", site,
                f"the faults.py docstring table lists {site!r} which "
                f"is not registered in SITES"))
    return findings
