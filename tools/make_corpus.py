"""Generate the checked-in cross-implementation corpus.

Writes small parquet files with **pyarrow** (the foreign writer) into
``tests/corpus/pyarrow/`` plus a ``manifest.json`` holding the expected
contents, so the corpus tests need no pyarrow at run time and keep
passing even if the generator's pyarrow version disappears.  The
reference's analogue is the impala-written file corpus its compat test
reads (``parquet_compatibility_test.go:76-87``).

Run from the repo root: ``python tools/make_corpus.py``.  Idempotent:
fixed seeds, fixed data.
"""

from __future__ import annotations

import json
import os
import sys

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq

OUT = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                   "tests", "corpus", "pyarrow")


def enc(v):
    """JSON-encode an expected value (bytes/str -> hex; exact floats)."""
    if v is None or isinstance(v, (bool, int, float)):
        return v
    if isinstance(v, str):
        return {"$b": v.encode().hex()}
    if isinstance(v, bytes):
        return {"$b": v.hex()}
    if isinstance(v, (list, tuple)):
        return [enc(x) for x in v]
    if isinstance(v, dict):
        return {"$struct": {k: enc(x) for k, x in v.items()}}
    raise TypeError(f"unhandled expected value type {type(v)}")


def expected_from_table(t: pa.Table) -> dict:
    out = {}
    for name in t.column_names:
        col = t.column(name)
        typ = col.type
        if pa.types.is_timestamp(typ) or pa.types.is_date(typ):
            # store raw encoded integers (our reader doesn't apply
            # logical conversions); ground truth still pyarrow-derived
            col = col.cast(pa.int64() if pa.types.is_timestamp(typ)
                           or typ == pa.date64() else pa.int32())
        out[name] = [enc(v) for v in col.to_pylist()]
    return out


def flat_table(n=151, seed=0):
    rng = np.random.default_rng(seed)
    i64 = rng.integers(-(2**60), 2**60, size=n)
    mask = rng.random(n) < 0.15
    vocab = ["", "a", "bb", "hello world", "日本語", "x" * 40]
    return pa.table({
        "i32": pa.array(rng.integers(-(2**31), 2**31, size=n),
                        pa.int32()),
        "i64": pa.array([None if m else int(v) for m, v in zip(mask, i64)],
                        pa.int64()),
        "d": pa.array(rng.random(n)),
        "f": pa.array(rng.random(n).astype(np.float32)),
        "flag": pa.array(rng.random(n) < 0.5),
        "s": pa.array([None if rng.random() < 0.1
                       else vocab[int(rng.integers(0, len(vocab)))]
                       for _ in range(n)]),
    })


def main():
    os.makedirs(OUT, exist_ok=True)
    manifest = {}

    def emit(name, table, **write_kw):
        path = os.path.join(OUT, name)
        pq.write_table(table, path, **write_kw)
        back = pq.read_table(path)  # what pyarrow itself sees
        manifest[name] = {
            "n_rows": back.num_rows,
            "write_kw": {k: str(v) for k, v in write_kw.items()},
            "columns": expected_from_table(back),
        }
        print(f"{name}: {back.num_rows} rows, "
              f"{os.path.getsize(path)} bytes")

    # codec x page-version ladder over the same flat data
    t = flat_table()
    emit("flat_none_v1.parquet", t, compression="none",
         data_page_version="1.0")
    emit("flat_snappy_v1.parquet", t, compression="snappy",
         data_page_version="1.0")
    emit("flat_gzip_v1.parquet", t, compression="gzip",
         data_page_version="1.0")
    emit("flat_snappy_v2.parquet", t, compression="snappy",
         data_page_version="2.0")
    emit("flat_zstd_v2.parquet", t, compression="zstd",
         data_page_version="2.0")

    # dictionary-encoded low-cardinality strings, multiple row groups
    rng = np.random.default_rng(1)
    n = 400
    t = pa.table({
        "cat": pa.array([f"cat-{int(i)%7}" for i in
                         rng.integers(0, 7, size=n)]),
        "v": pa.array(rng.integers(0, 1000, size=n), pa.int32()),
    })
    emit("dict_strings_v1.parquet", t, compression="snappy",
         use_dictionary=True, row_group_size=150)

    # delta encodings (dictionary off so the encodings actually appear)
    rng = np.random.default_rng(2)
    n = 300
    t = pa.table({
        "ts64": pa.array((1_600_000_000_000
                          + rng.integers(0, 10_000, size=n).cumsum())
                         .astype(np.int64)),
        "seq32": pa.array(rng.integers(0, 100, size=n).cumsum()
                          .astype(np.int32), pa.int32()),
    })
    emit("delta_ints_v1.parquet", t, compression="snappy",
         use_dictionary=False,
         column_encoding={"ts64": "DELTA_BINARY_PACKED",
                          "seq32": "DELTA_BINARY_PACKED"})

    words = [f"prefix-common-{i:04d}-suffix" for i in range(120)]
    t = pa.table({
        "dba": pa.array(words),
        "dlba": pa.array([w[::-1] for w in words]),
    })
    emit("delta_bytes_v1.parquet", t, compression="snappy",
         use_dictionary=False,
         column_encoding={"dba": "DELTA_BYTE_ARRAY",
                          "dlba": "DELTA_LENGTH_BYTE_ARRAY"})

    rng = np.random.default_rng(3)
    t = pa.table({
        "bf": pa.array(rng.random(200).astype(np.float32)),
        "bd": pa.array(rng.random(200)),
    })
    emit("byte_stream_split_v1.parquet", t, compression="snappy",
         use_dictionary=False,
         column_encoding={"bf": "BYTE_STREAM_SPLIT",
                          "bd": "BYTE_STREAM_SPLIT"})

    # nesting: list, list<struct>, map, struct
    t = pa.table({
        "l": pa.array([[1, 2], None, [], [3, None, 5], [7]],
                      pa.list_(pa.int64())),
        "ls": pa.array(
            [[{"k": "a", "n": 1}], [], None,
             [{"k": "b", "n": None}, {"k": "c", "n": 3}], [{"k": "", "n": 0}]],
            pa.list_(pa.struct([("k", pa.string()), ("n", pa.int64())]))),
    })
    emit("nested_list_snappy_v1.parquet", t, compression="snappy")

    t = pa.table({
        "m": pa.array([[("a", 1), ("b", 2)], None, [], [("c", None)]],
                      pa.map_(pa.string(), pa.int64())),
        "st": pa.array([{"x": 1, "y": "u"}, None, {"x": 3, "y": None},
                        {"x": None, "y": "w"}],
                       pa.struct([("x", pa.int64()), ("y", pa.string())])),
    })
    emit("map_struct_snappy_v2.parquet", t, compression="snappy",
         data_page_version="2.0")

    # decimal128 -> FIXED_LEN_BYTE_ARRAY: expected = unscaled big-endian
    from decimal import Decimal
    dec_vals = [Decimal("123456.789"), Decimal("-1.001"), None,
                Decimal("99999999999999999.999"), Decimal("0.000")]
    t = pa.table({"dec": pa.array(dec_vals, pa.decimal128(20, 3))})
    path = os.path.join(OUT, "decimal_flba_v1.parquet")
    pq.write_table(t, path, compression="snappy")
    byte_width = 9  # precision 20
    manifest["decimal_flba_v1.parquet"] = {
        "n_rows": len(dec_vals),
        "write_kw": {"compression": "snappy"},
        "columns": {"dec": [
            None if v is None else
            {"$b": int(v.scaleb(3)).to_bytes(byte_width, "big",
                                             signed=True).hex()}
            for v in dec_vals
        ]},
    }
    print(f"decimal_flba_v1.parquet: {len(dec_vals)} rows, "
          f"{os.path.getsize(path)} bytes")

    # INT96 timestamps (deprecated impala/hive layout)
    import datetime as dt
    stamps = [dt.datetime(2001, 1, 1, 12, 0, 0),
              dt.datetime(1969, 12, 31, 23, 59, 59, 999999),
              dt.datetime(2200, 1, 1, 0, 0, 1)]
    t = pa.table({"t96": pa.array(stamps, pa.timestamp("ns"))})
    path = os.path.join(OUT, "int96_v1.parquet")
    pq.write_table(t, path, compression="snappy",
                   use_deprecated_int96_timestamps=True)
    manifest["int96_v1.parquet"] = {
        "n_rows": len(stamps),
        "write_kw": {"use_deprecated_int96_timestamps": "True"},
        "columns": {"t96": [{"$iso": s.isoformat()} for s in stamps]},
    }
    print(f"int96_v1.parquet: {len(stamps)} rows, "
          f"{os.path.getsize(path)} bytes")

    # degenerate shapes
    emit("empty_v1.parquet", flat_table(0), compression="snappy")
    emit("one_row_v2.parquet", flat_table(1, seed=9), compression="snappy",
         data_page_version="2.0")

    with open(os.path.join(OUT, "manifest.json"), "w") as f:
        json.dump(manifest, f, indent=1, sort_keys=True)
    print(f"manifest: {len(manifest)} files")


if __name__ == "__main__":
    sys.exit(main())
