"""Host/device breakdown of the device decode path at scale.

Usage: python tools/profile_decode.py [n_rows] [n_groups]

Builds a NYC-Taxi-shaped file (config 2: snappy + dict) via the columnar
writer, then times each phase of read_row_group_device separately:
  plan      - page-header walk, decompress, run-table scans (host)
  transfer  - the one batched device_put
  dispatch  - jitted kernel dispatch (host side of finish())
  execute   - device execution tail (block_until_ready after dispatch)
Also reports the CPU-oracle time for the same row groups.
"""

from __future__ import annotations

import io
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def build_file(n_rows: int, n_groups: int) -> io.BytesIO:
    from tpuparquet import CompressionCodec, FileWriter

    rng = np.random.default_rng(42)
    buf = io.BytesIO()
    w = FileWriter(
        buf,
        """message taxi {
            required int64 pickup_ts;
            required int32 passenger_count;
            required int32 rate_code;
            required int64 trip_distance_mm;
            optional int32 payment_type;
        }""",
        codec=CompressionCodec.SNAPPY,
    )
    per = n_rows // n_groups
    base_ts = 1_700_000_000_000
    t0 = time.perf_counter()
    for g in range(n_groups):
        ts = base_ts + rng.integers(0, 3_600_000, size=per).cumsum()
        pay_mask = rng.random(per) >= 0.05
        w.write_columns(
            {
                "pickup_ts": ts,
                "passenger_count": rng.integers(1, 7, size=per,
                                                dtype=np.int32),
                "rate_code": rng.integers(1, 6, size=per, dtype=np.int32),
                "trip_distance_mm": rng.integers(100, 50_000, size=per),
                "payment_type": rng.integers(
                    0, 5, size=int(pay_mask.sum()), dtype=np.int32),
            },
            masks={"payment_type": pay_mask},
        )
    w.close()
    print(f"write: {time.perf_counter()-t0:.2f}s "
          f"({len(buf.getvalue())/1e6:.1f} MB)")
    buf.seek(0)
    return buf


def profile(reader, reps: int = 3):
    import jax

    from tpuparquet.kernels import device as D

    phases = {"plan": 0.0, "transfer": 0.0, "dispatch": 0.0, "execute": 0.0,
              "decompress": 0.0, "scan": 0.0}

    # sub-instrument decompress + scans inside plan
    import tpuparquet.compress as C
    import tpuparquet.cpu.hybrid as H
    orig_dec, orig_scan = C.decompress_block_into, H.scan_hybrid

    def timed_dec(*a, **k):
        t = time.perf_counter()
        r = orig_dec(*a, **k)
        phases["decompress"] += time.perf_counter() - t
        return r

    def timed_scan(*a, **k):
        t = time.perf_counter()
        r = orig_scan(*a, **k)
        phases["scan"] += time.perf_counter() - t
        return r

    best = None
    for rep in range(reps):
        for k in phases:
            phases[k] = 0.0
        t_total = time.perf_counter()
        outs = []
        for rg_index in range(reader.row_group_count()):
            rg = reader.meta.row_groups[rg_index]
            st = D._Stager()
            planned = []
            t = time.perf_counter()
            D.decompress_block_into = C.decompress_block_into = timed_dec
            D.scan_hybrid = H.scan_hybrid = timed_scan
            try:
                import tpuparquet.kernels.device as _d
                for path, node, cm, blob, start in \
                        reader.iter_selected_chunks(rg):
                    planned.append((path, D.plan_chunk_device(
                        memoryview(blob), cm, node, start, st)))
            finally:
                D.decompress_block_into = C.decompress_block_into = orig_dec
                D.scan_hybrid = H.scan_hybrid = orig_scan
            phases["plan"] += time.perf_counter() - t

            t = time.perf_counter()
            staged = st.put()
            jax.block_until_ready(staged)
            phases["transfer"] += time.perf_counter() - t

            t = time.perf_counter()
            out = {p: f(staged) for p, f in planned}
            phases["dispatch"] += time.perf_counter() - t
            outs.append(out)
        t = time.perf_counter()
        for out in outs:
            for c in out.values():
                c.block_until_ready()
        phases["execute"] += time.perf_counter() - t
        total = time.perf_counter() - t_total
        snap = dict(phases, total=total)
        if best is None or total < best["total"]:
            best = snap
    return best


def main():
    n_rows = int(sys.argv[1]) if len(sys.argv) > 1 else 2_000_000
    n_groups = int(sys.argv[2]) if len(sys.argv) > 2 else 4
    from tpuparquet import FileReader

    buf = build_file(n_rows, n_groups)
    reader = FileReader(buf)
    n_values = sum(cc.meta_data.num_values
                   for rg in reader.meta.row_groups for cc in rg.columns)
    print(f"n_values = {n_values/1e6:.1f}M")

    t0 = time.perf_counter()
    for rg in range(reader.row_group_count()):
        reader.read_row_group_arrays(rg)
    cpu1 = time.perf_counter() - t0
    t0 = time.perf_counter()
    for rg in range(reader.row_group_count()):
        reader.read_row_group_arrays(rg)
    cpu = min(cpu1, time.perf_counter() - t0)
    print(f"cpu oracle: {cpu:.3f}s  ({n_values/cpu/1e6:.1f} M vals/s)")

    profile(reader, reps=1)  # warm compile
    best = profile(reader, reps=3)
    # end-to-end via the real entry points (arena + per-rg sync included)
    from tpuparquet.kernels.device import (read_row_group_device,
                                           read_row_groups_device)
    e2e = []
    for _ in range(3):
        t0 = time.perf_counter()
        outs = [read_row_group_device(reader, rg)
                for rg in range(reader.row_group_count())]
        for o in outs:
            for c in o.values():
                c.block_until_ready()
        e2e.append(time.perf_counter() - t0)
    e2e_s = min(e2e)
    print(f"read_row_group_device e2e: {e2e_s:.3f}s "
          f"({n_values/e2e_s/1e6:.1f} M vals/s)  vs cpu {cpu/e2e_s:.2f}x")
    pipe = []
    for _ in range(3):
        t0 = time.perf_counter()
        outs = [out for _, out in read_row_groups_device(reader)]
        for o in outs:
            for c in o.values():
                c.block_until_ready()
        pipe.append(time.perf_counter() - t0)
    pipe_s = min(pipe)
    print(f"read_row_groups_device (pipelined) e2e: {pipe_s:.3f}s "
          f"({n_values/pipe_s/1e6:.1f} M vals/s)  vs cpu {cpu/pipe_s:.2f}x")
    print("device path breakdown (best of 3):")
    for k in ("plan", "decompress", "scan", "transfer", "dispatch",
              "execute", "total"):
        extra = ""
        if k in ("decompress", "scan"):
            extra = "   (inside plan)"
        print(f"  {k:10s} {best[k]*1e3:8.1f} ms{extra}")
    print(f"device: {best['total']:.3f}s  "
          f"({n_values/best['total']/1e6:.1f} M vals/s)  "
          f"vs cpu {cpu/best['total']:.2f}x")


if __name__ == "__main__":
    main()
