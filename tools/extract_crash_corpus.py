"""Extract the reference's embedded fuzz-crash inputs as binary fixtures.

The reference pins its go-fuzz crash findings as ``[]byte("...")``
literals inside ``TestFuzzCrash*`` functions (``chunk_reader_test.go:5``,
``deltabp_decoder_test.go:5,152``, ``schema_test.go:140,219``,
``type_bytearray_test.go:5``, ``type_dict_test.go:30``).  This script
parses those Go string literals (data, not code), unescapes them, and
writes each as ``tests/corpus/crash/<TestName>.bin`` so our regression
suite can assert every historical crasher fails *cleanly* in this
implementation too.

Run from the repo root with the reference checkout available:
``python tools/extract_crash_corpus.py``.
"""

from __future__ import annotations

import os
import re
import sys

REF = "/root/reference"
OUT = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                   "tests", "corpus", "crash")

FILES = [
    "chunk_reader_test.go",
    "deltabp_decoder_test.go",
    "schema_test.go",
    "type_bytearray_test.go",
    "type_dict_test.go",
    "page_v1_test.go",
]

_SIMPLE = {"a": 0x07, "b": 0x08, "f": 0x0C, "n": 0x0A, "r": 0x0D,
           "t": 0x09, "v": 0x0B, "\\": 0x5C, '"': 0x22, "'": 0x27}


def unescape_go(segment: str) -> bytes:
    """Decode one interpreted Go string literal body to bytes."""
    out = bytearray()
    i = 0
    while i < len(segment):
        c = segment[i]
        if c != "\\":
            out.extend(c.encode("utf-8"))
            i += 1
            continue
        e = segment[i + 1]
        if e in _SIMPLE:
            out.append(_SIMPLE[e])
            i += 2
        elif e == "x":
            out.append(int(segment[i + 2 : i + 4], 16))
            i += 4
        elif e == "u":
            out.extend(chr(int(segment[i + 2 : i + 6], 16)).encode("utf-8"))
            i += 6
        elif e.isdigit():  # octal \NNN
            out.append(int(segment[i + 1 : i + 4], 8))
            i += 4
        else:
            raise ValueError(f"unknown escape \\{e}")
    return bytes(out)


def extract(path: str) -> dict[str, bytes]:
    src = open(path, encoding="utf-8").read()
    found = {}
    for m in re.finditer(
        r"func (Test\w*Crash\w*)\(t \*testing\.T\) \{(.*?)\n\}",
        src, re.S,
    ):
        name, body = m.group(1), m.group(2)
        lit = re.search(r"\[\]byte\((.*?)\)\n", body, re.S)
        if lit is None:
            continue
        data = bytearray()
        for piece in re.findall(r'"((?:[^"\\]|\\.)*)"', lit.group(1)):
            data.extend(unescape_go(piece))
        found[name] = bytes(data)
    return found


def main():
    os.makedirs(OUT, exist_ok=True)
    total = 0
    for fn in FILES:
        path = os.path.join(REF, fn)
        if not os.path.exists(path):
            print(f"skip {fn}: not found")
            continue
        for name, data in extract(path).items():
            out = os.path.join(OUT, f"{name}.bin")
            with open(out, "wb") as f:
                f.write(data)
            print(f"{name}.bin: {len(data)} bytes (from {fn})")
            total += 1
    print(f"{total} crash inputs extracted")


if __name__ == "__main__":
    sys.exit(main())
