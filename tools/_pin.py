"""Shared CPU-backend pinning for tools/ scripts.

This image's sitecustomize force-registers the remote-TPU "axon"
backend via jax config, which OVERRIDES the ``JAX_PLATFORMS`` env var —
a script that relies on the env var alone wedges inside its first
device op whenever the tunnel is down (observed: an at-scale run stuck
at 3 MB RSS for 20+ minutes probing a dead tunnel).  Import this module
BEFORE anything that imports jax:

    sys.path.insert(0, <repo root>)
    from tools._pin import pin_cpu
    pin_cpu()            # or pin_cpu(devices=8) for a virtual mesh

Chip-facing tools (profile_decode, bench_wire, bench_pallas, the
check_* sweeps) must NOT use this — the tunnel is their target.
"""

import os


def pin_cpu(devices: int | None = None) -> None:
    if devices is not None:
        flag = f"--xla_force_host_platform_device_count={devices}"
        xf = os.environ.get("XLA_FLAGS", "")
        if "--xla_force_host_platform_device_count" not in xf:
            os.environ["XLA_FLAGS"] = f"{xf} {flag}".strip()
    import jax

    jax.config.update("jax_platforms", "cpu")
