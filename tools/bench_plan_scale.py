#!/usr/bin/env python
"""Plan-phase scaling micro-bench: the measured curve behind the
"wider plan parallelism" projection.

Two observables per ``TPQ_PLAN_THREADS`` point over the driver's
50M-value taxi shape (``bench.build_config2``):

* ``plan_wall_s`` — the MAKESPAN of planning every column task of
  every row group through a pool of that width, nothing else running.
  This is the clean plan-wall number the north-star model consumes
  (``wall ≈ plan_s + staged/BW``): on an N-core host it divides by
  workers; on a 1-core container it is honestly flat.
* ``pipelined_plan_s`` / ``e2e_wall_s`` — ``DecodeStats.plan_s`` and
  wall through the full pipelined device decode, the protocol of the
  round-5 record (its 1.10–1.16 s serial baseline is THIS metric).
  Per-task spans time-share against dispatch on a 1-core box, so this
  curve can inflate with thread count while e2e holds; both are
  recorded.

Then the footer-keyed plan cache's warm-re-read lever
(``TPQ_PLAN_CACHE_MB``) is measured plan-only (no dispatch noise) on
two shapes: the taxi file and the wide string/float shape (config 4).
Emits ``PLAN_SCALE_r06.json`` in the repo root (or ``--out``).
``TPQ_BENCH_TARGET`` scales the shapes down for smoke runs.

Usage: JAX_PLATFORMS=cpu python tools/bench_plan_scale.py [--out PATH]
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

THREADS = (1, 2, 4, 8)
REPS = int(os.environ.get("TPQ_PLAN_SCALE_REPS", 2))


def _plan_makespan(reader, threads: int):
    """Wall seconds to plan every column task of every row group with
    a ``threads``-wide pool (stats collected for counters/plan_s)."""
    from concurrent.futures import ThreadPoolExecutor

    import tpuparquet.kernels.device as D
    from tpuparquet.kernels.arena import lease_arena, return_arena
    from tpuparquet.stats import collect_stats

    # arenas are leased per task and returned as each task finishes —
    # plan-only never stages, so slabs recycle immediately (holding
    # every unit's slabs to the end measurably distorts a 50M sweep)
    def one(rgi, path, node, cm, like):
        a = lease_arena()
        try:
            return D._plan_column_task(reader, rgi, path, node, cm, a,
                                       like, False)
        finally:
            return_arena(a)

    tasks = []
    for rgi in range(reader.row_group_count()):
        rg = reader.meta.row_groups[rgi]
        for path, node, cm in reader.selected_chunks(rg):
            tasks.append((rgi, path, node, cm))
    with collect_stats() as st:
        t0 = time.perf_counter()
        if threads == 1:
            for rgi, path, node, cm in tasks:
                _, ws = one(rgi, path, node, cm, st)
                st.merge_from(ws)
        else:
            with ThreadPoolExecutor(max_workers=threads) as ex:
                futs = [ex.submit(one, rgi, path, node, cm, st)
                        for rgi, path, node, cm in tasks]
                for f in futs:
                    _, ws = f.result()
                    st.merge_from(ws)
        wall = time.perf_counter() - t0
    return wall, st


def _decode_once(reader):
    from tpuparquet.kernels.device import read_row_groups_device
    from tpuparquet.stats import collect_stats

    with collect_stats() as st:
        t0 = time.perf_counter()
        for _rg, cols in read_row_groups_device(reader):
            for c in cols.values():
                c.block_until_ready()
        wall = time.perf_counter() - t0
    return wall, st


def _cache_leg(reader):
    """Plan-only warm-cache measurement: no-cache re-read baseline,
    cold cached pass (store overhead included), warm best."""
    from tpuparquet.kernels.plancache import clear_plan_cache

    os.environ.pop("TPQ_PLAN_CACHE_MB", None)
    base = min(_plan_makespan(reader, 1)[0] for _ in range(REPS))
    os.environ["TPQ_PLAN_CACHE_MB"] = "256"
    clear_plan_cache()
    cold = _plan_makespan(reader, 1)[0]
    warm = None
    warm_st = None
    for _ in range(REPS):
        w, st = _plan_makespan(reader, 1)
        if warm is None or w < warm:
            warm, warm_st = w, st
    os.environ.pop("TPQ_PLAN_CACHE_MB", None)
    return {
        "budget_mb": 256,
        "no_cache_reread_plan_s": round(base, 4),
        "cold_plan_s": round(cold, 4),
        "warm_plan_s": round(warm, 4),
        "warm_reduction_vs_cold": round(1.0 - warm / cold, 4),
        "warm_reduction_vs_no_cache": round(1.0 - warm / base, 4),
        "hits": warm_st.plan_cache_hits,
        "misses": warm_st.plan_cache_misses,
    }


def main(argv=None) -> int:
    out_path = "PLAN_SCALE_r06.json"
    args = list(argv if argv is not None else sys.argv[1:])
    if "--out" in args:
        out_path = args[args.index("--out") + 1]

    import jax

    import bench
    from tpuparquet.io.reader import FileReader
    from tpuparquet.kernels.device import _usable_cpus

    target = bench.TARGET
    print(f"building taxi shape at {target:,} values ...",
          file=sys.stderr, flush=True)
    reader = FileReader(bench.build_config2())
    n_values = bench.total_values(reader)

    os.environ.pop("TPQ_PLAN_CACHE_MB", None)
    result = {
        "metric": "plan wall vs TPQ_PLAN_THREADS, 50M taxi shape",
        "n_values": n_values,
        "usable_cpus": _usable_cpus(),
        "backend": jax.default_backend(),
        "reps": REPS,
        "ts": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "sweep": [],
    }

    _decode_once(reader)  # warm compile: jit shapes off the clock
    for t in THREADS:
        os.environ["TPQ_PLAN_THREADS"] = str(t)
        mk = min(_plan_makespan(reader, t)[0] for _ in range(REPS))
        pipe = None
        for _ in range(REPS):
            wall, st = _decode_once(reader)
            if pipe is None or st.plan_s < pipe[0]:
                pipe = (st.plan_s, wall, st.bytes_staged)
        point = {"threads": t, "plan_wall_s": round(mk, 4),
                 "pipelined_plan_s": round(pipe[0], 4),
                 "e2e_wall_s": round(pipe[1], 4),
                 "bytes_staged": pipe[2]}
        result["sweep"].append(point)
        print(f"  threads={t}: plan_wall {point['plan_wall_s']}s  "
              f"pipelined plan_s {point['pipelined_plan_s']}s  "
              f"e2e {point['e2e_wall_s']}s", file=sys.stderr, flush=True)

    os.environ["TPQ_PLAN_THREADS"] = "1"
    result["plan_cache"] = {"taxi": _cache_leg(reader)}
    print(f"  cache/taxi: {result['plan_cache']['taxi']}",
          file=sys.stderr, flush=True)
    # epoch-shard shape: the same taxi schema at a realistic
    # per-shard-file size (2M values), where per-page DECISION work is
    # a large slice of the plan — the shape the cache's "re-read pays
    # transfer only" story is about (an epoch re-reads many such files)
    shard = FileReader(bench.build_config2(n_values=2_000_000,
                                           n_groups=8))
    _plan_makespan(shard, 1)
    result["plan_cache"]["taxi-2M-epoch-shard"] = _cache_leg(shard)
    print(f"  cache/shard: "
          f"{result['plan_cache']['taxi-2M-epoch-shard']}",
          file=sys.stderr, flush=True)
    print("building wide shape (config 4) ...", file=sys.stderr,
          flush=True)
    wide = FileReader(bench.build_config4())
    _plan_makespan(wide, 1)
    result["plan_cache"]["wide-string-float"] = _cache_leg(wide)
    print(f"  cache/wide: {result['plan_cache']['wide-string-float']}",
          file=sys.stderr, flush=True)
    os.environ.pop("TPQ_PLAN_THREADS", None)

    with open(out_path, "w") as f:
        json.dump(result, f, indent=1)
        f.write("\n")
    print(json.dumps(result))
    return 0


if __name__ == "__main__":
    sys.exit(main())
