"""rg x sp scaling curve for the sharded scan on the virtual CPU mesh
(round-4 verdict weak item 5 / next-round item 6).

Fixed total work, two experiments, phase-decomposed:

1. ShardedScan (the "rg" outer loop): same multi-row-group file scanned
   on 1/2/4/8-device meshes; phases = scan (host plan + stage + kernel
   dispatch per unit) and gather (the all-gather collective), plus the
   gather's padding waste (padded bytes shipped / true bytes).

2. The SPMD dict-decode step (sharded_dict_decode's internals, the
   "rg" x "sp" jitted step): phases = host plan (run-table scan), pad
   (stack_hybrid_plans bucket padding, with waste ratio), put (transfer
   to the sharded layout), step (compute + both all-gathers).

On virtual CPU devices every "device" is the same host, so absolute
speedup is meaningless — what this measures is where the orchestration
overhead lives and how it scales with the mesh, which IS transferable
to real chips (the phases are the same code).

    python tools/scan_scale_curve.py [out.json]
"""

import io
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from tools._pin import pin_cpu  # noqa: E402

pin_cpu(devices=8)

import jax  # noqa: E402
import numpy as np  # noqa: E402


def bench_sharded_scan(n_units=16, nv=1_000_000):
    from tpuparquet import CompressionCodec, FileWriter
    from tpuparquet.shard.mesh import make_mesh
    from tpuparquet.shard.scan import ShardedScan, gather_column

    rng = np.random.default_rng(6)
    buf = io.BytesIO()
    w = FileWriter(buf, "message m { required int64 v; }",
                   codec=CompressionCodec.SNAPPY)
    for _ in range(n_units):
        w.write_columns(
            {"v": rng.integers(0, 1 << 40, size=nv)})
    w.close()

    curve = []
    for nd in (1, 2, 4, 8):
        buf.seek(0)
        mesh = make_mesh(nd, sp=1)
        # warmup (compile) then measure best-of-2
        best = None
        for rep in range(3):
            buf.seek(0)
            scan = ShardedScan([buf], mesh=mesh)
            t0 = time.perf_counter()
            results = scan.run()
            for res in results:
                for c in res.values():
                    c.block_until_ready()
            t_scan = time.perf_counter() - t0
            t1 = time.perf_counter()
            vals, counts = gather_column(mesh, results, "v")
            t_gather = time.perf_counter() - t1
            if rep == 0:
                continue  # compile warmup
            if best is None or t_scan + t_gather < sum(best[:2]):
                true_bytes = int(counts.sum()) * 8
                padded_bytes = vals.size * 4  # u32 elements, all dims
                best = (t_scan, t_gather, padded_bytes / true_bytes)
        curve.append({
            "devices": nd,
            "scan_s": round(best[0], 3),
            "gather_s": round(best[1], 3),
            "values_per_sec": round(n_units * nv / (best[0] + best[1]), 1),
            "gather_pad_ratio": round(best[2], 3),
        })
    return {"n_units": n_units, "values_per_unit": nv, "curve": curve}


def bench_spmd_step(n_streams=32, nv=1_000_000, width=7, dict_size=100):
    """The rg x sp jitted decode step, phase-split."""
    from tpuparquet.cpu.hybrid import encode_hybrid
    from tpuparquet.kernels.hybrid import plan_hybrid
    from tpuparquet.shard.mesh import (
        decode_step_spmd, make_mesh, stack_hybrid_plans,
    )
    from jax.sharding import NamedSharding, PartitionSpec as P

    rng = np.random.default_rng(9)
    streams, counts = [], []
    for _ in range(n_streams):
        idx = rng.integers(0, dict_size, size=nv).astype(np.uint32)
        streams.append(encode_hybrid(idx, width))
        counts.append(nv)
    dictionary = rng.integers(0, 1 << 32, size=(dict_size, 2),
                              dtype=np.uint32)

    curve = []
    for nd, sp in ((1, 1), (2, 1), (2, 2), (4, 1), (4, 2), (8, 1),
                   (8, 2)):
        if nd % sp:
            continue
        mesh = make_mesh(nd, sp=sp)
        n_rg = mesh.shape["rg"]
        best = None
        for rep in range(3):
            t0 = time.perf_counter()
            plans = [plan_hybrid(s, c, width)
                     for s, c in zip(streams, counts)]
            t_plan = time.perf_counter() - t0

            t0 = time.perf_counter()
            n_units = ((len(plans) + n_rg - 1) // n_rg) * n_rg
            batch = stack_hybrid_plans(plans, n_units=n_units)
            count = batch.count
            if count % sp:
                count = (count + sp - 1) // sp * sp
                batch = stack_hybrid_plans(plans, n_units=n_units,
                                           count=count)
            t_pad = time.perf_counter() - t0
            pad_waste = (batch.count * batch.n_units) / float(
                sum(counts)) - 1.0

            t0 = time.perf_counter()
            unit_sh = NamedSharding(mesh, P("rg"))
            rep_sh = NamedSharding(mesh, P())
            args = [jax.device_put(a, unit_sh) for a in batch.arrays()]
            dict_dev = jax.device_put(dictionary, rep_sh)
            for a in args:
                a.block_until_ready()
            t_put = time.perf_counter() - t0

            step = decode_step_spmd(mesh, batch.count, batch.width,
                                    batch.n_bp, dictionary.shape[1])
            t0 = time.perf_counter()
            out = step(*args, dict_dev)
            out.block_until_ready()
            t_step = time.perf_counter() - t0
            if rep == 0:
                continue  # compile warmup
            tot = t_plan + t_pad + t_put + t_step
            if best is None or tot < best[0]:
                best = (tot, t_plan, t_pad, t_put, t_step, pad_waste)
        tot, t_plan, t_pad, t_put, t_step, pad_waste = best
        curve.append({
            "devices": nd, "sp": sp,
            "plan_s": round(t_plan, 3), "pad_s": round(t_pad, 3),
            "put_s": round(t_put, 3), "step_s": round(t_step, 3),
            "values_per_sec": round(n_streams * nv / tot, 1),
            "pad_waste": round(pad_waste, 4),
        })
    return {"n_streams": n_streams, "values_per_stream": nv,
            "width": width, "curve": curve}


def main():
    out_path = sys.argv[1] if len(sys.argv) > 1 else "SCAN_SCALE_r05.json"
    t0 = time.time()
    scan = bench_sharded_scan()
    spmd = bench_spmd_step()
    rec = {
        "backend": "cpu-virtual-8",
        "sharded_scan": scan,
        "spmd_step": spmd,
        "finding": (
            "plan+pad+put are <2% at every rg x sp point and bucket pad "
            "waste is 4.9%; the collective phase dominated and grew with "
            "device count because gather_column funneled every byte "
            "through one device before resharding — fixed by shard-major "
            "assembly (gather 3.25s -> 0.96s at 8 devices, throughput "
            "1.66M -> 5.26M values/s)"),
        "wall_s": round(time.time() - t0, 1),
    }
    print(json.dumps(rec, indent=1))
    with open(out_path, "w") as f:
        json.dump(rec, f, indent=1)


if __name__ == "__main__":
    main()
