#!/usr/bin/env python
"""Soak harness: N concurrent labeled scans under fault injection.

ROADMAP item 1's done-criterion made executable: drive ``--scans``
concurrent labeled ``ShardedScan`` tenants over a generated corpus
with DETERMINISTIC fault injection (``faults.py`` sites), record a
time-series ring, and assert the whole longitudinal observability
contract end to end:

* **alert coverage, zero false-negatives** — every injected fault
  class surfaces as its matching alert rule (CorruptPage → the
  corrupt tenant's ``units_quarantined`` threshold rule; the hang +
  unit-deadline combination → the deadline tenant's
  ``deadline_exceeded`` threshold rule; plus a burn-rate rule on the
  corrupt tenant's shredded error budget), and zero
  false-POSITIVES — the clean tenants' rules and the absence rule
  must stay silent;
* **digest conservation** — per-label unit-latency digests carry
  exactly one observation per driven unit and sum (exact
  bucket-wise merge) to the process totals;
* **ledger conservation** — per-label attribution ledgers sum
  counter-for-counter to the live registry totals (the round-16 pin,
  now under concurrent multi-tenant load with the ring feed on);
* **telemetry neutrality** — decoded output is byte-identical to a
  leg run with every telemetry surface off (live metrics, digests,
  ring);
* **remote equivalence** — one tenant reads through the ``emu://``
  object-store emulator under periodic 429 throttles
  (``TPQ_EMU_THROTTLE_EVERY``): the retry ladder must absorb every
  throttle (``remote_retry`` > 0, zero quarantines) and the decoded
  output must be byte-identical to a fault-free local control read
  of the same file.

Determinism under concurrency: fault rules target a tenant through
structure, not timing — the corrupt rule matches the column name
only tenant ``corrupt``'s schema has, the hang rule matches tenant
``deadline``'s file path, and both fire on EVERY matching call
(``times`` unbounded), so thread interleaving cannot reassign a
fault between legs.

Usage::

    JAX_PLATFORMS=cpu python -m tools.soak \
        [--scans 4] [--rows 120] [--units 4] [--json] [--keep DIR]

Exit 0 = every assertion held; nonzero prints what broke.  The CI
soak-smoke gate (``tools/ci.sh`` stage 13) runs exactly this at the
defaults.
"""

from __future__ import annotations

import argparse
import hashlib
import json
import os
import shutil
import sys
import tempfile
import threading
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

#: tenant roles by index: 1 eats corrupt pages, 2 eats hangs bounded
#: by a unit deadline, 3 reads through the ``emu://`` object-store
#: emulator under periodic 429 throttles (absorbed by the retry
#: ladder, so it must stay clean AND byte-identical to a local
#: control read), every other tenant must stay clean
CORRUPT_TENANT = 1
DEADLINE_TENANT = 2
REMOTE_TENANT = 3
REMOTE_THROTTLE_EVERY = "5"
UNIT_DEADLINE_S = 0.2
HANG_S = 5.0
#: the serve leg's per-job deadline budget — "no tenant starved"
#: means every tenant's job completes inside this
SERVE_SCAN_DEADLINE_S = 300.0


def tenant_label(i: int) -> str:
    return f"tenant_{i}"


def _tenant_schema(i: int) -> str:
    # the corrupt tenant's int column gets a UNIQUE name so the fault
    # rule can target it by structure (see module docstring)
    return (f"message soak {{ required int64 k{i}; "
            f"required double b; }}")


def build_corpus(root: str, scans: int, rows: int,
                 units: int) -> dict[str, list[str]]:
    """One file per tenant, ``units`` row groups each (each row group
    is one scan unit)."""
    from tpuparquet import FileWriter

    rg_rows = max(rows // units, 1)
    corpus: dict[str, list[str]] = {}
    for i in range(scans):
        path = os.path.join(root, f"tenant{i}.parquet")
        with open(path, "wb") as f:
            w = FileWriter(f, _tenant_schema(i),
                           max_row_group_size=rg_rows * 20)
            for j in range(rows):
                w.add_data({f"k{i}": i * 10_000 + j, "b": j * 0.5})
            w.close()
        corpus[tenant_label(i)] = [path]
    return corpus


def _output_digest(results) -> str:
    """Stable byte digest of a scan's decoded output: every unit's
    every column's numpy buffers, in order."""
    import numpy as np

    h = hashlib.sha256()
    for out in results:
        for name in sorted(out):
            for arr in out[name].to_numpy():
                if arr is not None:
                    h.update(np.ascontiguousarray(arr).tobytes())
    return h.hexdigest()


def _control_digest(paths: list[str]) -> str:
    """Fault-free LOCAL read of the remote tenant's file: the digest
    its ``emu://`` leg must reproduce byte-for-byte.  Runs before the
    legs; both legs reset every telemetry surface, so the control's
    counters never leak into the conservation checks."""
    from tpuparquet.shard.scan import ShardedScan

    return _output_digest(ShardedScan(paths).run())


def _arm_rules(inj, corpus: dict[str, list[str]]) -> None:
    """The deterministic fault plan (every matching call fires)."""
    inj.inject("kernels.device.page_payload", "corrupt",
               match={"column": f"k{CORRUPT_TENANT}"}, times=10**9)
    inj.inject("io.chunk.hang", "hang", seconds=HANG_S,
               match={"file": corpus[tenant_label(DEADLINE_TENANT)][0]},
               times=10**9)


def run_leg(corpus: dict[str, list[str]], *, telemetry: bool,
            ring_dir: str | None) -> dict:
    """One soak leg: every tenant scans concurrently under the fault
    plan.  Returns per-label output digests, quarantine counts, and
    the scans' own progress tallies."""
    from tpuparquet.faults import inject_faults
    from tpuparquet.obs import attribution, live
    from tpuparquet.obs import digest as _digest
    from tpuparquet.obs import timeseries as _timeseries
    from tpuparquet.shard.scan import ShardedScan
    from tpuparquet.stats import collect_stats

    live.reset_registry()
    attribution.reset_ledgers()
    _digest.set_digests(telemetry)
    _timeseries.set_ring_dir(ring_dir if telemetry else None)
    prev_live = os.environ.get("TPQ_LIVE_METRICS")
    if not telemetry:
        os.environ["TPQ_LIVE_METRICS"] = "0"
    results: dict[str, dict] = {}
    errors: list[BaseException] = []

    def drive(label: str, paths: list[str]) -> None:
        try:
            idx = int(label.rsplit("_", 1)[1])
            if idx == REMOTE_TENANT:
                # reroute through the object-store emulator; retries
                # (not quarantine) must absorb its throttles
                paths = ["emu://" + p for p in paths]
            scan = ShardedScan(
                paths, on_error="quarantine", retries=0,
                progress_label=label,
                unit_deadline=(UNIT_DEADLINE_S
                               if idx == DEADLINE_TENANT else None))
            with collect_stats() as st:
                out = scan.run()
            results[label] = {
                "digest": _output_digest(out),
                "units_done": scan.progress.units_done,
                "units_quarantined": scan.progress.units_quarantined,
                "quarantine": len(scan.quarantine),
                "remote_ranges_fetched": st.remote_ranges_fetched,
                "remote_retry": st.remote_retry,
            }
        except BaseException as e:  # surfaced by the main thread
            errors.append(e)

    try:
        with inject_faults() as inj:
            _arm_rules(inj, corpus)
            threads = [threading.Thread(target=drive, args=(lb, ps),
                                        name=f"soak-{lb}")
                       for lb, ps in sorted(corpus.items())]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
    finally:
        if not telemetry:
            if prev_live is None:
                os.environ.pop("TPQ_LIVE_METRICS", None)
            else:
                os.environ["TPQ_LIVE_METRICS"] = prev_live
    if errors:
        raise errors[0]
    return results


def run_http_leg(corpus: dict[str, list[str]]) -> dict:
    """The ``http(s)://`` tenant: the remote tenant's file served
    through the deterministic fault HTTP server (``tools/httpfault``)
    in two phases — a scripted 429/503/connection-reset storm, then a
    mid-scan ETag flip (the object "rewritten" under the reader) —
    both of which the retry ladder and the identity refresh must
    absorb without a single quarantined unit.  Returns per-phase
    digests and remote counters; the local control read is the byte
    oracle."""
    import threading as _threading

    from tools.httpfault import FaultHTTPServer, FaultPlan
    from tpuparquet.io.rangecache import reset_range_caches
    from tpuparquet.shard.scan import ShardedScan
    from tpuparquet.stats import collect_stats

    t_http = tenant_label(REMOTE_TENANT)
    path = corpus[t_http][0]
    srv = FaultHTTPServer(("127.0.0.1", 0), os.path.dirname(path))
    t = _threading.Thread(target=srv.serve_forever,
                          name="soak-httpfault", daemon=True)
    t.start()
    results: dict[str, dict] = {}
    try:
        url = srv.base_url + "/" + os.path.basename(path)

        def phase(name: str, plan: FaultPlan,
                  mid_scan_plan: FaultPlan | None = None) -> None:
            reset_range_caches()  # cold per phase: faults must land
            srv.plan = plan
            scan = ShardedScan([url], on_error="quarantine",
                               retries=0, progress_label=t_http)
            if mid_scan_plan is not None:
                # the scan's identity (HEAD + footer) was established
                # under ``plan``; the switch lands mid-scan
                srv.plan = mid_scan_plan
            with collect_stats() as st:
                out = scan.run()
            results[name] = {
                "digest": _output_digest(out),
                "units_done": scan.progress.units_done,
                "units_quarantined": scan.progress.units_quarantined,
                "remote_ranges_fetched": st.remote_ranges_fetched,
                "remote_retry": st.remote_retry,
            }

        phase("storm",
              FaultPlan(throttle_every=5, error_every=7,
                        reset_every=11, retry_after_s=0.005))
        # the object is "rewritten" under the open reader: every
        # request from here on serves the generation-2 ETag, so the
        # reader's conditional GETs keyed on the old tag answer 412,
        # the source refreshes its identity and refetches
        phase("flip", FaultPlan(),
              mid_scan_plan=FaultPlan(etag_flip_at=1))
    finally:
        srv.shutdown()
        srv.server_close()
        t.join(10.0)
        reset_range_caches()
    return results


def check_http(http: dict, on: dict,
               remote_control: str) -> list[str]:
    """The http-leg contract: byte-identical to the local control
    through both fault phases, faults absorbed by retries (never
    quarantine), exact unit accounting against the emu:// twin."""
    bad: list[str] = []
    t_http = tenant_label(REMOTE_TENANT)
    units = on[t_http]["units_done"]  # same file, same unit count
    for name, r in http.items():
        if r["digest"] != remote_control:
            bad.append(f"http[{name}]: output differs from the local "
                       f"control read")
        if r["units_quarantined"]:
            bad.append(f"http[{name}]: {r['units_quarantined']} "
                       f"units quarantined — scripted HTTP faults "
                       f"must be absorbed by the retry ladder")
        if r["units_done"] != units:
            bad.append(f"http[{name}]: {r['units_done']} units done, "
                       f"expected {units}")
        if not r["remote_ranges_fetched"]:
            bad.append(f"http[{name}]: no remote range fetches — the "
                       f"http:// reroute did not engage")
        if not r["remote_retry"]:
            bad.append(f"http[{name}]: no remote retries — the "
                       f"scripted fault plan did not fire")
    return bad


def run_serve_leg(corpus: dict[str, list[str]], *, ring_dir: str,
                  state_dir: str) -> tuple[dict, dict]:
    """The server-path leg: the SAME tenants, fault plan and
    telemetry surfaces as the raw telemetry-on leg, but every scan is
    submitted through a :class:`tpuparquet.serve.ScanServer` — shared
    arbiter, admission control, per-tenant queues, durable cursors.
    Returns ``(per-label results, server meta)``; the raw leg is the
    control its outputs must match byte-for-byte."""
    from tpuparquet.faults import inject_faults
    from tpuparquet.obs import attribution, live
    from tpuparquet.obs import digest as _digest
    from tpuparquet.obs import timeseries as _timeseries
    from tpuparquet.serve import ScanServer

    live.reset_registry()
    attribution.reset_ledgers()
    _digest.set_digests(True)
    _timeseries.set_ring_dir(ring_dir)
    results: dict[str, dict] = {}
    with inject_faults() as inj:
        _arm_rules(inj, corpus)
        server = ScanServer(state_dir=state_dir,
                            rebalance_interval=0.2)
        try:
            for lb in sorted(corpus):
                server.add_tenant(lb, error_rate_target=0.001,
                                  latency_target_ms=1000.0)
            jobs = {}
            for lb, paths in sorted(corpus.items()):
                idx = int(lb.rsplit("_", 1)[1])
                if idx == REMOTE_TENANT:
                    paths = ["emu://" + p for p in paths]
                jobs[lb] = server.submit(
                    lb, paths, job_id="soak",
                    unit_deadline=(UNIT_DEADLINE_S
                                   if idx == DEADLINE_TENANT
                                   else None),
                    scan_deadline=SERVE_SCAN_DEADLINE_S)
            for lb, job in jobs.items():
                if not job.wait(SERVE_SCAN_DEADLINE_S + 60):
                    raise RuntimeError(
                        f"serve leg: {lb} never reached a terminal "
                        f"state")
            meta = {"shares": server.status()["shares"],
                    "total_workers": server.status()["total_workers"]}
            for lb, job in jobs.items():
                st = job.stats
                out = [job.outputs[k] for k in sorted(job.outputs)]
                results[lb] = {
                    "digest": _output_digest(out),
                    "state": job.state,
                    "error": (repr(job.error)
                              if job.error is not None else None),
                    "units_done": job.units_done,
                    "units_quarantined": job.units_quarantined,
                    "quarantine": (len(job.quarantine)
                                   if job.quarantine is not None
                                   else 0),
                    "remote_ranges_fetched": (
                        st.remote_ranges_fetched if st else 0),
                    "remote_retry": st.remote_retry if st else 0,
                }
        finally:
            server.shutdown()
    return results, meta


def run_dataset_leg(corpus: dict[str, list[str]], *, root: str,
                    state_dir: str) -> tuple[dict, list[str]]:
    """The dataset-writing tenant: one tenant COMMITS a hive-
    partitioned dataset through the atomic manifest protocol while a
    scan tenant runs through the same server — concurrent scan+write
    admission under one arbiter (the writer's encode pool sizes from
    its tenant share via ``arbiter.write_budget()``).  The freshly
    committed dataset is then admitted back as a dataset job
    (:meth:`ScanServer.submit_dataset`) and the decoded ids must be
    complete and duplicate-free.  Returns ``(meta, failures)``."""
    import numpy as np

    from tpuparquet.dataset import DatasetWriter
    from tpuparquet.serve import ScanServer
    from tpuparquet.serve import arbiter as _arb

    ds_root = os.path.join(root, "dataset")
    n = 240
    failures: list[str] = []
    meta: dict = {}
    write_err: list[str] = []
    server = ScanServer(state_dir=state_dir, rebalance_interval=0.2)
    try:
        server.add_tenant("ds_scan")
        server.add_tenant("ds_writer")

        def write_ds():
            try:
                with _arb.tenant_scope("ds_writer"):
                    w = DatasetWriter(
                        ds_root,
                        "message rec { required int64 id; "
                        "required binary part (STRING); }",
                        ["part"])
                    step = n // 4
                    for batch in range(4):
                        seg = list(range(batch * step,
                                         (batch + 1) * step))
                        w.write_columns({
                            "id": np.asarray(seg, dtype=np.int64),
                            "part": [b"a" if i % 2 else b"b"
                                     for i in seg],
                        })
                    w.commit()
                    w._release()
            except BaseException as e:  # noqa: BLE001 — reported
                write_err.append(f"dataset: writer failed: {e!r}")

        # scan load + dataset write race through the same arbiter
        t = threading.Thread(target=write_ds, name="ds-writer")
        t.start()
        scan_job = server.submit(
            "ds_scan", corpus[tenant_label(0)], job_id="ds-bg-scan",
            scan_deadline=SERVE_SCAN_DEADLINE_S)
        if not scan_job.wait(SERVE_SCAN_DEADLINE_S + 60):
            failures.append("dataset: background scan never finished")
        elif scan_job.state != "done":
            failures.append(
                f"dataset: background scan ended {scan_job.state!r}")
        t.join(SERVE_SCAN_DEADLINE_S)
        failures += write_err
        if not failures:
            ds_job = server.submit_dataset(
                "ds_scan", ds_root, "id", job_id="ds-read",
                scan_deadline=SERVE_SCAN_DEADLINE_S)
            if not ds_job.wait(SERVE_SCAN_DEADLINE_S + 60):
                failures.append("dataset: read-back job never "
                                "finished")
            elif ds_job.state != "done":
                failures.append(
                    f"dataset: read-back ended {ds_job.state!r} "
                    f"({ds_job.error!r})")
            else:
                got: list[int] = []
                for k in sorted(ds_job.outputs):
                    vals, _rep, _dl = ds_job.outputs[k]["id"].to_numpy()
                    got.extend(int(v) for v in
                               np.asarray(vals).ravel())
                if sorted(got) != list(range(n)):
                    failures.append(
                        f"dataset: read-back ids not complete/"
                        f"duplicate-free ({len(got)} rows, "
                        f"{len(set(got))} distinct, want {n})")
                meta = {"est_bytes": ds_job.est_bytes,
                        "units": ds_job.units_total,
                        "rows": len(got)}
                if not ds_job.est_bytes:
                    failures.append(
                        "dataset: admission did not charge the "
                        "manifest byte estimate")
    finally:
        server.shutdown()
    return meta, failures


def _soak_rules(labels: list[str]) -> list:
    """The alert-coverage rule set both the raw and serve legs are
    held to: one rule per injected fault class, a burn-rate rule on
    the corrupt tenant, clean-tenant silence rules, and an absence
    rule that must stay quiet against a live ring."""
    from tpuparquet.obs.alerts import AlertRule

    t_corrupt = tenant_label(CORRUPT_TENANT)
    t_deadline = tenant_label(DEADLINE_TENANT)
    week = 7 * 24 * 3600.0
    rules = [
        AlertRule("corrupt_pages", "threshold", label=t_corrupt,
                  counter="units_quarantined", value=1, window_s=week),
        AlertRule("deadline_expiries", "threshold", label=t_deadline,
                  counter="deadline_exceeded", value=1, window_s=week),
        AlertRule("budget_burn", "burn_rate", label=t_corrupt,
                  error_rate_target=0.001, threshold=1.0),
        AlertRule("telemetry_absent", "absence", window_s=week),
    ]
    for lb in labels:
        if lb not in (t_corrupt, t_deadline):
            rules.append(AlertRule(
                f"clean_{lb}", "threshold", label=lb,
                counter="units_quarantined", value=1, window_s=week))
    return rules


def _alert_failures(labels: list[str], ring_dir: str,
                    alerts_path: str, leg: str) -> list[str]:
    """Alert coverage over one leg's ring: every fault class fires
    its rule, zero false alerts from the clean/absence rules."""
    from tpuparquet.obs.alerts import AlertEngine
    from tpuparquet.obs.timeseries import load_ring

    bad: list[str] = []
    frames = load_ring(ring_dir)
    if not frames:
        return [f"{leg}: time-series ring {ring_dir} is empty"]
    t_corrupt = tenant_label(CORRUPT_TENANT)
    t_deadline = tenant_label(DEADLINE_TENANT)
    engine = AlertEngine(_soak_rules(labels), record_path=alerts_path)
    firing = {a["name"] for a in engine.evaluate(frames)}
    for required in ("corrupt_pages", "deadline_expiries",
                     "budget_burn"):
        if required not in firing:
            bad.append(f"{leg}: fault class behind rule {required!r} "
                       f"did not fire its alert (false negative)")
    for lb in labels:
        if lb not in (t_corrupt, t_deadline) \
                and f"clean_{lb}" in firing:
            bad.append(f"{leg}: clean tenant {lb} fired a quarantine "
                       f"alert (false positive)")
    if "telemetry_absent" in firing:
        bad.append(f"{leg}: absence rule fired against a live ring "
                   f"(false positive)")
    return bad


def _conservation_failures(labels: list[str], units_done: dict,
                           leg: str) -> list[str]:
    """Digest + ledger conservation over the CURRENT process
    telemetry state: per-label unit digests carry exactly one
    observation per driven unit and merge to the process total, and
    per-label ledger counters sum to the registry totals exactly."""
    from tpuparquet.obs import attribution, live
    from tpuparquet.obs import digest as _digest
    from tpuparquet.obs.digest import QuantileDigest

    bad: list[str] = []
    reg = _digest.digests()
    snap = {} if reg is None else reg.snapshot()
    total = QuantileDigest()
    n_units = 0
    for lb in labels:
        g = snap.get((lb, "unit"))
        done = units_done[lb]
        n_units += done
        if g is None:
            bad.append(f"{leg}: no unit digest for {lb}")
            continue
        if g.n != done:
            bad.append(f"{leg}: unit digest of {lb} has n={g.n}, "
                       f"scan drove {done} units")
        total.merge_from(g)
    if total.n != n_units:
        bad.append(f"{leg}: merged per-label digests n={total.n} != "
                   f"process total {n_units}")
    counters = live.registry().snapshot()["counters"]
    led_sums: dict = {}
    for state in attribution.ledgers_state().values():
        for k, v in (state.get("counters") or {}).items():
            led_sums[k] = led_sums.get(k, 0) + v
    for key in ("row_groups", "pages", "values", "units_quarantined",
                "deadline_exceeded"):
        if led_sums.get(key, 0) != counters.get(key, 0):
            bad.append(f"{leg}: ledger sum of {key} "
                       f"({led_sums.get(key, 0)}) != registry total "
                       f"({counters.get(key, 0)})")
    return bad


def check_serve(corpus: dict[str, list[str]], serve: dict, meta: dict,
                on: dict, ring_dir: str, alerts_path: str,
                remote_control: str) -> list[str]:
    """The serve-leg contract: byte-identical to the raw control leg,
    no tenant starved, exact accounting, zero false alerts, fair
    shares."""
    bad: list[str] = []
    labels = sorted(corpus)
    t_remote = tenant_label(REMOTE_TENANT)

    # -- no tenant starved: every job completed within its deadline
    #    budget (a starved tenant fails its scan_deadline or never
    #    reaches "done") ------------------------------------------------
    for lb in labels:
        if serve[lb]["state"] != "done":
            bad.append(f"serve: tenant {lb} ended "
                       f"{serve[lb]['state']!r} "
                       f"({serve[lb].get('error')}) — starved or "
                       f"failed within its deadline budget")

    # -- server path is byte-identical to the raw control leg ----------
    for lb in labels:
        if serve[lb]["digest"] != on[lb]["digest"]:
            bad.append(f"serve: output of {lb} differs from the "
                       f"direct ShardedScan control leg")
        if serve[lb]["units_quarantined"] != on[lb]["units_quarantined"]:
            bad.append(f"serve: quarantine count of {lb} differs "
                       f"from the control leg (fault plan not "
                       f"deterministic through the server)")

    # -- remote tenant still equivalent through the server -------------
    if not serve[t_remote]["remote_retry"]:
        bad.append("serve: remote tenant saw no throttle retries — "
                   "the emulated-429 plan did not fire")
    if serve[t_remote]["digest"] != remote_control:
        bad.append("serve: remote tenant output differs from the "
                   "local control read")

    # -- fair shares: anti-starvation floors held ----------------------
    shares = meta.get("shares") or {}
    for lb in labels:
        if shares.get(lb, 0) < 1:
            bad.append(f"serve: tenant {lb} share is "
                       f"{shares.get(lb, 0)} — the anti-starvation "
                       f"floor (>= 1 worker) was violated")
    total = meta.get("total_workers") or 0
    if total >= len(labels) and sum(shares.values()) > total:
        bad.append(f"serve: shares {shares} oversubscribe the "
                   f"{total}-worker budget")

    bad += _alert_failures(labels, ring_dir, alerts_path, "serve")
    bad += _conservation_failures(
        labels, {lb: serve[lb]["units_done"] for lb in labels},
        "serve")
    return bad


def check_soak(corpus: dict[str, list[str]], on: dict, off: dict,
               ring_dir: str, alerts_path: str,
               remote_control: str) -> list[str]:
    """Every assertion of the soak contract; returns failure strings
    (empty = pass)."""
    from tpuparquet.obs import digest as _digest
    from tpuparquet.obs.digest import QuantileDigest
    from tpuparquet.obs.timeseries import load_ring

    bad: list[str] = []
    labels = sorted(corpus)
    t_corrupt = tenant_label(CORRUPT_TENANT)
    t_deadline = tenant_label(DEADLINE_TENANT)
    t_remote = tenant_label(REMOTE_TENANT)

    # -- telemetry neutrality: byte-identical outputs ------------------
    for lb in labels:
        if on[lb]["digest"] != off[lb]["digest"]:
            bad.append(f"output of {lb} differs between telemetry-on "
                       f"and telemetry-off legs")
        if on[lb]["units_quarantined"] != off[lb]["units_quarantined"]:
            bad.append(f"quarantine count of {lb} differs between "
                       f"legs (fault plan not deterministic)")

    # -- the faults actually landed ------------------------------------
    if not on[t_corrupt]["units_quarantined"]:
        bad.append("corrupt tenant saw no quarantined units — the "
                   "fault plan did not fire")
    if not on[t_deadline]["units_quarantined"]:
        bad.append("deadline tenant saw no quarantined units — the "
                   "hang/deadline plan did not fire")

    # -- remote tenant: emu:// engaged, throttles absorbed, bytes
    #    identical to the local control read --------------------------
    if not on[t_remote]["remote_ranges_fetched"]:
        bad.append("remote tenant issued no remote range fetches — "
                   "the emu:// reroute did not engage")
    if not on[t_remote]["remote_retry"]:
        bad.append("remote tenant saw no throttle retries — the "
                   "emulated-429 plan did not fire")
    if on[t_remote]["units_quarantined"]:
        bad.append("remote tenant quarantined units — throttles must "
                   "be absorbed by the retry ladder, not surfaced")
    if on[t_remote]["digest"] != remote_control:
        bad.append("remote tenant output differs from the local "
                   "control read of the same file (emu:// is not "
                   "byte-identical)")

    # -- alert coverage: one rule per fault class + clean/absence ------
    bad += _alert_failures(labels, ring_dir, alerts_path, "soak")

    # -- digest + ledger conservation under the ring feed --------------
    bad += _conservation_failures(
        labels, {lb: on[lb]["units_done"] for lb in labels}, "soak")

    # -- the last ring frame's digest state equals the in-process
    #    state bucket-for-bucket ----------------------------------------
    frames = load_ring(ring_dir)
    reg = _digest.digests()
    snap = {} if reg is None else reg.snapshot()
    last_digests = (frames[-1].get("digests") or {}) if frames else {}
    for lb in labels:
        g = snap.get((lb, "unit"))
        ring_d = (last_digests.get(lb) or {}).get("unit")
        if g is not None and ring_d is not None:
            rd = QuantileDigest.from_dict(ring_d)
            if rd.counts != g.counts or rd.n != g.n \
                    or rd.total != g.total:
                bad.append(f"ring-frame digest of {lb} differs from "
                           f"the in-process digest bucket-for-bucket")
    return bad


def _lockcheck_failures() -> list[str]:
    """When the runtime lock-order recorder is armed (TPQ_LOCKCHECK),
    the soak's concurrent legs are exactly the load it exists for:
    assert the recorded acquisition DAG is cycle-free and a subgraph
    of the static lock graph before declaring the soak green."""
    from tpuparquet import lockcheck

    if not lockcheck.installed():
        return []
    from tools.analyze import RepoTree, repo_root
    from tools.analyze import threads as _threads

    snap = lockcheck.snapshot()
    tree = RepoTree.from_disk(repo_root())
    return [f"lockcheck: {p}"
            for p in _threads.verify_runtime_graph(tree, snap)]


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--scans", type=int, default=4,
                    help="concurrent labeled scans (tenants); >= 4 "
                         "so the corrupt, deadline and remote "
                         "tenants exist beside a clean control")
    ap.add_argument("--rows", type=int, default=120,
                    help="rows per tenant file")
    ap.add_argument("--units", type=int, default=4,
                    help="row groups (scan units) per tenant file")
    ap.add_argument("--json", action="store_true",
                    help="emit the machine-readable result")
    ap.add_argument("--keep", metavar="DIR", default="",
                    help="run inside DIR and leave the corpus, ring "
                         "and alert records behind for inspection")
    ap.add_argument("--chaos-seed", type=int, default=None,
                    metavar="N",
                    help="run every leg under faults.chaos_scope(N): "
                         "seeded interleaving perturbation at each "
                         "fault site + an aggressive switch interval "
                         "(the assertions must hold unchanged)")
    ap.add_argument("--serve", action="store_true",
                    help="add a fourth leg that drives the same "
                         "tenant corpus through tpuparquet.serve."
                         "ScanServer and asserts the server path is "
                         "byte-identical to the direct-scan control, "
                         "no tenant starves, and the per-tenant "
                         "accounting stays exact")
    ap.add_argument("--http", action="store_true",
                    help="add an http(s):// leg: the remote tenant's "
                         "file is re-read through the deterministic "
                         "fault HTTP server under a scripted "
                         "429/503/reset storm and then a mid-scan "
                         "ETag flip; both must stay byte-identical "
                         "to the local control with zero quarantines")
    ap.add_argument("--dataset", action="store_true",
                    help="add a dataset leg: a writer tenant commits "
                         "a hive-partitioned dataset through the "
                         "atomic manifest protocol while a scan "
                         "tenant runs through the same server, then "
                         "the dataset is admitted back as a scan job "
                         "and must read back complete and "
                         "duplicate-free")
    args = ap.parse_args(argv)
    if args.scans < 4:
        print("soak: --scans must be >= 4 (corrupt + deadline + "
              "remote tenants + a clean control)", file=sys.stderr)
        return 2

    root = args.keep or tempfile.mkdtemp(prefix="tpq-soak-")
    os.makedirs(root, exist_ok=True)
    ring_dir = os.path.join(root, "ring")
    alerts_path = os.path.join(root, "alerts.json")
    t0 = time.time()
    prev_throttle = os.environ.get("TPQ_EMU_THROTTLE_EVERY")
    os.environ["TPQ_EMU_THROTTLE_EVERY"] = REMOTE_THROTTLE_EVERY
    try:
        import contextlib

        from tpuparquet.faults import chaos_scope

        def _scope():
            return (chaos_scope(args.chaos_seed)
                    if args.chaos_seed is not None
                    else contextlib.nullcontext())

        corpus = build_corpus(root, args.scans, args.rows, args.units)
        with _scope():
            remote_control = _control_digest(
                corpus[tenant_label(REMOTE_TENANT)])
            # telemetry-off leg FIRST: it must not see the ring/digest
            # state the on leg arms
            off = run_leg(corpus, telemetry=False, ring_dir=None)
            on = run_leg(corpus, telemetry=True, ring_dir=ring_dir)
        failures = check_soak(corpus, on, off, ring_dir, alerts_path,
                              remote_control)
        failures += _lockcheck_failures()
        serve = None
        smeta: dict = {}
        if args.serve:
            serve_ring = os.path.join(root, "ring-serve")
            serve_alerts = os.path.join(root, "alerts-serve.json")
            serve_state = os.path.join(root, "serve-state")
            # a fresh chaos scope: the serve leg must hold the same
            # contract under its own seeded interleaving
            with _scope():
                serve, smeta = run_serve_leg(
                    corpus, ring_dir=serve_ring, state_dir=serve_state)
            failures += check_serve(corpus, serve, smeta, on,
                                    serve_ring, serve_alerts,
                                    remote_control)
            failures += _lockcheck_failures()
        http = None
        if args.http:
            # its own chaos scope, like every other optional leg
            with _scope():
                http = run_http_leg(corpus)
            failures += check_http(http, on, remote_control)
            failures += _lockcheck_failures()
        dsmeta: dict = {}
        if args.dataset:
            ds_state = os.path.join(root, "dataset-state")
            with _scope():
                dsmeta, ds_failures = run_dataset_leg(
                    corpus, root=root, state_dir=ds_state)
            failures += ds_failures
            failures += _lockcheck_failures()
        result = {
            "scans": args.scans,
            "units_per_scan": args.units,
            "wall_s": round(time.time() - t0, 3),
            "tenants": {lb: {k: v for k, v in on[lb].items()
                             if k != "digest"} for lb in sorted(on)},
            "failures": failures,
            "ok": not failures,
        }
        if serve is not None:
            result["serve"] = {
                "shares": smeta.get("shares"),
                "total_workers": smeta.get("total_workers"),
                "tenants": {lb: {k: v for k, v in serve[lb].items()
                                 if k != "digest"}
                            for lb in sorted(serve)},
            }
        if http is not None:
            result["http"] = {
                name: {k: v for k, v in r.items() if k != "digest"}
                for name, r in http.items()}
        if args.dataset:
            result["dataset"] = dsmeta
        if args.json:
            print(json.dumps(result, sort_keys=True))
        else:
            for lb in sorted(on):
                r = on[lb]
                print(f"{lb}: {r['units_done']} units, "
                      f"{r['units_quarantined']} quarantined")
            if serve is not None:
                for lb in sorted(serve):
                    r = serve[lb]
                    print(f"serve {lb}: {r['state']}, "
                          f"{r['units_done']} units, share "
                          f"{(smeta.get('shares') or {}).get(lb)}")
            if http is not None:
                for name in sorted(http):
                    r = http[name]
                    print(f"http {name}: {r['units_done']} units, "
                          f"{r['units_quarantined']} quarantined, "
                          f"{r['remote_retry']} retries")
            for f in failures:
                print(f"FAIL: {f}", file=sys.stderr)
            print(f"soak {'PASS' if not failures else 'FAIL'} "
                  f"({args.scans} scans, {result['wall_s']}s)")
        return 0 if not failures else 1
    finally:
        from tpuparquet.obs import digest as _digest
        from tpuparquet.obs import timeseries as _timeseries

        if prev_throttle is None:
            os.environ.pop("TPQ_EMU_THROTTLE_EVERY", None)
        else:
            os.environ["TPQ_EMU_THROTTLE_EVERY"] = prev_throttle
        _digest.set_digests(_digest.digest_enabled_default())
        _timeseries.maybe_start_ring()
        if not args.keep:
            shutil.rmtree(root, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
