#!/usr/bin/env python
"""Schedule-chaos validator: seeded interleaving, invariant output.

The concurrency suites assert determinism under ONE interleaving per
run — whichever the OS happens to produce.  This harness re-runs them
under :func:`tpuparquet.faults.chaos_scope`: a seed-derived aggressive
``sys.setswitchinterval`` plus deterministic perturbations (GIL
yields, microsecond sleeps) at every registered fault site, which
double as named yield points on the hot paths.  Each suite runs once
WITHOUT chaos (the baseline) and once per ``--seeds`` entry, and every
chaos leg must reproduce the baseline exactly:

* **plan-parallel** — multi-threaded row-group planning
  (``TPQ_PLAN_THREADS``): byte-identical decoded output, exact
  ``row_groups``/``pages``/``values`` counters;
* **encode-ahead** — the writer's pipelined encode/compress pool
  (``TPQ_WRITE_THREADS``, multi-page columns): byte-identical FILE
  bytes — page order and framing must not depend on encode timing;
* **prefetch** — the remote fetch planner (coalesced parallel spans
  through ``emu://`` into a fresh disk cache): byte-identical decoded
  output, exact fetch/coalesce accounting;
* **soak-parity** — the multi-tenant soak leg (corrupt + deadline +
  remote + clean tenants under deterministic fault rules): per-tenant
  byte-identical output and exact quarantine counts.

A chaos leg that records zero perturbations is itself a failure — the
seed must actually have exercised the schedule, or the invariance it
"proves" is vacuous.

Usage::

    JAX_PLATFORMS=cpu python -m tools.chaos \
        [--seeds 101,202,303] [--suite NAME ...] [--json] [--keep DIR]

Exit 0 = every chaos leg reproduced its baseline; nonzero prints what
drifted.  ci.sh stage 15 runs the plan-parallel and soak-parity
suites at one seed; the full cross-seed sweep is
``tests/test_chaos.py``'s job.
"""

from __future__ import annotations

import argparse
import contextlib
import hashlib
import json
import os
import shutil
import sys
import tempfile
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

DEFAULT_SEEDS = (101, 202, 303)
ROWS = 240
UNITS = 4


@contextlib.contextmanager
def _env(**overrides):
    """Set env knobs for one leg, restoring the previous values."""
    prev = {k: os.environ.get(k) for k in overrides}
    for k, v in overrides.items():
        if v is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = str(v)
    try:
        yield
    finally:
        for k, v in prev.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def _output_digest(results) -> str:
    import numpy as np

    h = hashlib.sha256()
    for out in results:
        for name in sorted(out):
            for arr in out[name].to_numpy():
                if arr is not None:
                    h.update(np.ascontiguousarray(arr).tobytes())
    return h.hexdigest()


def _write_corpus_file(path: str, rows: int = ROWS,
                       units: int = UNITS) -> str:
    from tpuparquet import FileWriter

    rg_rows = max(rows // units, 1)
    with open(path, "wb") as f:
        w = FileWriter(f, "message chaos { required int64 k; "
                          "required double b; }",
                       max_row_group_size=rg_rows * 20)
        for j in range(rows):
            w.add_data({"k": j * 3 + 1, "b": j * 0.25})
        w.close()
    return path


# ----------------------------------------------------------------------
# Suites: each returns a dict that must be EXACTLY equal across legs
# ----------------------------------------------------------------------

def suite_plan_parallel(corpus: str, work: str) -> dict:
    from tpuparquet.shard.scan import ShardedScan
    from tpuparquet.stats import collect_stats

    with _env(TPQ_PLAN_THREADS="4"):
        with collect_stats() as st:
            out = ShardedScan([corpus]).run()
    return {
        "digest": _output_digest(out),
        "counters": {k: getattr(st, k)
                     for k in ("row_groups", "pages", "values")},
    }


def suite_encode_ahead(corpus: str, work: str) -> dict:
    from tpuparquet import FileWriter

    path = os.path.join(work, "encoded.parquet")
    with _env(TPQ_WRITE_THREADS="4", TPQ_PAGE_ROWS="16"):
        with open(path, "wb") as f:
            w = FileWriter(f, "message chaos { required int64 k; "
                              "required double b; }",
                           max_row_group_size=1200)
            for j in range(ROWS):
                w.add_data({"k": j * 3 + 1, "b": j * 0.25})
            w.close()
    with open(path, "rb") as f:
        return {"digest": hashlib.sha256(f.read()).hexdigest()}


def suite_prefetch(corpus: str, work: str) -> dict:
    from tpuparquet.shard.scan import ShardedScan
    from tpuparquet.stats import collect_stats

    dcache = os.path.join(work, "dcache")
    os.makedirs(dcache, exist_ok=True)
    # mem tier off: it is a process-global singleton that would carry
    # baseline-leg hits into the chaos legs (fewer remote fetches in
    # later legs — state drift, not schedule drift); the per-leg disk
    # dir keeps the disk tier cold each time
    with _env(TPQ_PLAN_THREADS="4", TPQ_CACHE_DISK_DIR=dcache,
              TPQ_CACHE_DISK_MB="64", TPQ_CACHE_MEM_MB="0",
              TPQ_RANGE_COALESCE_GAP="4096"):
        with collect_stats() as st:
            out = ShardedScan(["emu://" + corpus]).run()
    return {
        "digest": _output_digest(out),
        "counters": {k: getattr(st, k)
                     for k in ("row_groups", "pages", "values",
                               "remote_ranges_fetched",
                               "ranges_coalesced", "remote_bytes")},
    }


def suite_soak_parity(corpus: str, work: str) -> dict:
    from tools import soak

    soak_corpus = json.loads(corpus)  # {label: [paths]} built once
    with _env(TPQ_EMU_THROTTLE_EVERY=soak.REMOTE_THROTTLE_EVERY):
        legs = soak.run_leg(soak_corpus, telemetry=False,
                            ring_dir=None)
    return {lb: {"digest": r["digest"],
                 "units_done": r["units_done"],
                 "units_quarantined": r["units_quarantined"],
                 "quarantine": r["quarantine"]}
            for lb, r in sorted(legs.items())}


SUITES = {
    "plan-parallel": suite_plan_parallel,
    "encode-ahead": suite_encode_ahead,
    "prefetch": suite_prefetch,
    "soak-parity": suite_soak_parity,
}


def run_chaos(root: str, suites: list[str],
              seeds: list[int]) -> dict:
    """Run each suite at baseline + every seed; compare exactly."""
    from tools import soak as _soak
    from tpuparquet.faults import chaos_scope

    corpus = _write_corpus_file(os.path.join(root, "chaos.parquet"))
    suite_input = {name: corpus for name in SUITES}
    if "soak-parity" in suites:
        sroot = os.path.join(root, "soak")
        os.makedirs(sroot, exist_ok=True)
        suite_input["soak-parity"] = json.dumps(
            _soak.build_corpus(sroot, 4, 120, UNITS))

    failures: list[str] = []
    report: dict = {}
    for name in suites:
        fn = SUITES[name]
        legs: dict = {}
        base_dir = os.path.join(root, f"{name}-baseline")
        os.makedirs(base_dir, exist_ok=True)
        baseline = fn(suite_input[name], base_dir)
        legs["baseline"] = baseline
        for seed in seeds:
            work = os.path.join(root, f"{name}-seed{seed}")
            os.makedirs(work, exist_ok=True)
            with chaos_scope(seed) as sched:
                got = fn(suite_input[name], work)
            legs[f"seed{seed}"] = got
            if sched.perturbations == 0:
                failures.append(
                    f"{name} seed {seed}: zero perturbations — the "
                    f"chaos schedule never fired, invariance is "
                    f"vacuous")
            if got != baseline:
                diffs = _diff(baseline, got)
                failures.append(
                    f"{name} seed {seed} drifted from baseline: "
                    f"{'; '.join(diffs) or 'structural difference'}")
        report[name] = {
            "seeds": seeds,
            "perturbed": True,
            "digest": str(baseline)[:120],
        }
    return {"failures": failures, "suites": report,
            "ok": not failures}


def _diff(a, b, prefix="") -> list[str]:
    out: list[str] = []
    if isinstance(a, dict) and isinstance(b, dict):
        for k in sorted(set(a) | set(b)):
            out.extend(_diff(a.get(k), b.get(k), f"{prefix}{k}."))
    elif a != b:
        out.append(f"{prefix.rstrip('.')}: {a!r} != {b!r}")
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--seeds", default=",".join(
        str(s) for s in DEFAULT_SEEDS),
        help="comma-separated chaos seeds (default: "
             f"{','.join(str(s) for s in DEFAULT_SEEDS)})")
    ap.add_argument("--suite", dest="suites", action="append",
                    choices=sorted(SUITES), metavar="NAME",
                    help="run only this suite (repeatable; "
                         "default all)")
    ap.add_argument("--json", action="store_true",
                    help="emit the machine-readable result")
    ap.add_argument("--keep", metavar="DIR", default="",
                    help="run inside DIR and leave artifacts behind")
    args = ap.parse_args(argv)
    seeds = [int(s) for s in args.seeds.split(",") if s.strip()]
    suites = args.suites or list(SUITES)

    root = args.keep or tempfile.mkdtemp(prefix="tpq-chaos-")
    os.makedirs(root, exist_ok=True)
    t0 = time.time()
    try:
        res = run_chaos(root, suites, seeds)
        res["wall_s"] = round(time.time() - t0, 3)
        if args.json:
            print(json.dumps(res, sort_keys=True))
        else:
            for f in res["failures"]:
                print(f"FAIL: {f}", file=sys.stderr)
            print(f"chaos {'PASS' if res['ok'] else 'FAIL'} "
                  f"({len(suites)} suite(s) x {len(seeds)} seed(s) + "
                  f"baseline, {res['wall_s']}s)")
        return 0 if res["ok"] else 1
    finally:
        if not args.keep:
            shutil.rmtree(root, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
