#!/bin/bash
# In-repo CI gate (counterpart of the reference's .circleci/config.yml,
# which pins go versions and runs `go test ./...` + the compatibility
# corpus per commit).  Twenty stages, pinned env:
#
#   1. tier-1 suite   — the ROADMAP.md verify command, gated on a PASS
#                       FLOOR rather than rc: optional deps (zstandard,
#                       hypothesis) are absent from some images and
#                       their tests fail/error there by design; the
#                       floor catches regressions without pinning the
#                       image.  Override with CI_PASS_FLOOR.
#   2. smoke bench    — the full bench ladder at tiny scale on the CPU
#                       backend (every config builder + parity gate +
#                       JSON contract; catches harness bugs off-chip)
#   3. crash corpus + fault matrix — strict (rc=0): these are green in
#                       every image; run standalone so a hang or flake
#                       here is attributable
#   4. salvage gate    — strict (rc=0): truncation sweep (every page
#                       boundary + mid-page), strict metadata
#                       validation over the pyarrow + crash corpora,
#                       torn-fixture corpus, rescue round trip
#   5. time/crash gate — strict (rc=0): hang-injection matrix
#                       (watchdog deadlines, hedged reads over
#                       replicas) and the SIGKILL/resume durable-
#                       checkpoint sweep
#   6. plan matrix     — strict (rc=0): the column-parallel planner's
#                       serial/parallel parity pin run under BOTH
#                       TPQ_PLAN_THREADS=1 and the default pool, and
#                       the plan-cache suite with the cache ON — the
#                       serial path and the cache-off path can never
#                       silently rot
#   7. live obs gate   — strict (rc=0): the always-on telemetry layer
#                       (metrics registry / flight recorder / progress
#                       / post-mortems) + the env-knob catalog test,
#                       then the overhead guard: bench_obs.py asserts
#                       the always-on default stays within a
#                       noise-proof bound of the all-off hot path
#                       (measured ~1-3%, bound 25%; the structural
#                       zero-cost pin lives in the pytest half)
#   8. pruning parity  — strict (rc=0): predicate pushdown's bit-exact
#                       contract — filtered scan output identical to
#                       unfiltered-scan-then-post-filter on randomized
#                       corpora, serial AND parallel plans, with fault
#                       injection and salvage=True, plus the
#                       corrupt-index degrade-to-no-pruning pin
#   9. static analysis — strict (rc=0): the tpq-analyze v2 invariant
#                       passes (counters / fault sites / env knobs /
#                       atomic writes / recorder guards / whole-
#                       program thread-safety + lock graph / resource
#                       lifecycle / exception taxonomy) must report
#                       ZERO unsuppressed findings, the analyzer's
#                       own seeded-bug suite must pass, and the
#                       native ASan+UBSan + C-static-analysis leg
#                       runs (skipping loudly when no sanitizer-
#                       capable compiler is on the box)
#  10. gather parity    — strict (rc=0): consumer-aligned output
#                       placement must stay byte-identical to the
#                       replicated gather across the hard scan paths
#                       (filter pruning, quarantine, salvage, cursor
#                       resume, multi-host), then the whole placement
#                       suite re-runs under TPQ_GATHER_TO=0 (every
#                       scan's default placement armed) — the env
#                       knob cannot change values or leak into the
#                       free functions
#  11. write parity     — strict (rc=0): the native write pipeline's
#                       bit-exact contract — the full
#                       tests/test_write_native.py suite (native-on
#                       vs native-off byte identity across thread
#                       budgets and page splits, CRC/page-index/bloom
#                       semantics, pyarrow interop both ways, fault
#                       fallback, counter conservation), then the
#                       same suite re-run under TPQ_WRITE_NATIVE=0 so
#                       the pure path (and its parity pins) can never
#                       silently rot
#  12. tracing + sentinel — strict (rc=0): the causal-tracing /
#                       attribution suite (span-tree connectivity,
#                       adversity propagation, ledger conservation,
#                       doctor goldens), the scan suites re-run with
#                       TPQ_TRACE=1 (armed tracing must not change a
#                       byte), and the bench sentinel in check mode
#                       against the committed noise-aware baseline
#  13. soak smoke       — strict (rc=0): tools/soak.py at the small
#                       default (4 concurrent labeled scans, corrupt-
#                       page + hang/deadline fault plans): every
#                       injected fault class must fire its matching
#                       alert rule with zero false-negatives (and the
#                       clean tenants'/absence rules zero false-
#                       positives), per-label digests and ledgers
#                       must sum exactly to process totals, and the
#                       decoded output must be byte-identical to a
#                       telemetry-off leg
#  14. remote emulator  — strict (rc=0): the remote byte-range path.
#                       The dedicated suite (tests/test_remote.py:
#                       coalescer properties, tiered-cache
#                       conservation, poisoning, torn-cache restart,
#                       emu parity legs cache-on AND cache-off), then
#                       the scan/prune/checkpoint suites re-run
#                       UNMODIFIED with TPQ_SOURCE=emu rerouting every
#                       bare-path open through the emulated object
#                       store — with a mild deterministic fault plan
#                       (every 23rd request throttled, every 41st
#                       reset) and the disk cache armed — so the whole
#                       scan stack (filter pushdown, cursor resume,
#                       quarantine, gather) proves byte-identical over
#                       an unreliable remote store
#  15. concurrency validator — strict (rc=0): the runtime half of the
#                       tpq-analyze v2 concurrency contract.  One
#                       chaos-seed leg of the plan-parallel and
#                       soak-parity suites (tools/chaos.py: seeded
#                       schedule perturbation must reproduce the
#                       unperturbed baseline byte-for-byte with exact
#                       counter conservation), then a soak leg under
#                       TPQ_LOCKCHECK=1 — the recorded lock-order
#                       graph must be cycle-free and a subgraph of
#                       the static lock graph (the full cross-seed
#                       sweep and the recorder unit suite run in
#                       tier-1 via tests/test_chaos.py and
#                       tests/test_lockcheck.py)
#  16. sampling profiler — strict (rc=0): the round-20 profiler gate.
#                       The profiler suite + scan suite re-run under
#                       TPQ_PROFILE=1 (armed sampling must not change
#                       a byte of scan output), then a CLI smoke over
#                       freshly captured profiles: flame renders a
#                       native-write capture, flame --diff localizes
#                       the native-on vs TPQ_WRITE_NATIVE=0 delta,
#                       and doctor --profile joins a profiled+traced
#                       scan's samples to its span-derived stage walls
#                       with zero consistency warnings
#  17. scan server      — strict (rc=0): the tpuparquet.serve gate.
#                       The soak's --serve leg (same tenants + fault
#                       plan through ScanServer: byte identity vs the
#                       direct-scan control, no starvation, exact
#                       per-tenant accounting, zero false alerts)
#                       under TPQ_LOCKCHECK=strict across three chaos
#                       seeds, the serve suite (arbiter shares /
#                       admission / drain-resume sweep), and a
#                       legacy-knob leg proving direct scans under
#                       TPQ_PLAN_THREADS/TPQ_WRITE_THREADS are
#                       untouched by the arbiter's existence
#  18. datasets         — strict (rc=0): the partitioned-dataset gate.
#                       The full dataset suite INCLUDING the slow
#                       kill/resume chaos legs (SIGKILL at every
#                       commit-protocol step: reader sees prior
#                       snapshot or nothing, resume_from= converges
#                       bit-exact/duplicate-free on the uninterrupted
#                       oracle; resumed under chaos seeds 101/202/303
#                       with TPQ_LOCKCHECK=strict and zero findings),
#                       then the soak's --dataset leg: a writer
#                       tenant commits through the atomic manifest
#                       protocol while a scan tenant runs under the
#                       same arbiter, and the dataset reads back
#                       complete and duplicate-free through
#                       submit_dataset admission
#  19. http(s) backend  — strict (rc=0): the HTTP range-backend gate.
#                       The http-source suite (Range/ETag/If-Match
#                       protocol, status taxonomy, retry ladder over
#                       scripted 429/503/reset/short faults) and the
#                       cross-process shared-disk-cache suite (two
#                       concurrent scanners over one cache dir under
#                       chaos seeds: byte identity, exact counter
#                       conservation, kill/resume at arbitrary
#                       offsets, fleet-visible poison eviction), then
#                       a remote-equivalence leg: the scan/prune/
#                       checkpoint suites re-run with TPQ_SOURCE=http
#                       rerouted through a live tools/httpfault
#                       server (root /, mild throttle+reset plan) and
#                       must pass unmodified, then the soak's --http
#                       leg (429/503/reset storm + mid-scan ETag
#                       flip, zero quarantines, byte identity to the
#                       local control) under TPQ_LOCKCHECK=strict
#                       across three chaos seeds
#  20. codec parity     — strict (rc=0): the round-24 codec-matrix
#                       gate.  The block-codec suite
#                       (tests/test_compress.py), re-run under
#                       TPQ_WRITE_NATIVE=0 and under
#                       TPQ_NATIVE_CODECS=0 (pure fallbacks can never
#                       silently rot), then a whole-file equivalence
#                       sweep over every registered codec: native-on
#                       vs native-off files byte-identical where the
#                       two sides are pinned deterministic
#                       (uncompressed always; lz4_raw via the
#                       pure==C mirror; gzip when the runtime probe
#                       shows the bound zlib matches the stdlib
#                       byte-for-byte) and decoded-identical
#                       elsewhere, plus 1-thread vs N-thread
#                       block-split writes decoded-identical under
#                       chaos seeds with TPQ_LOCKCHECK=strict
#
# Usage: bash tools/ci.sh            (exit 0 = gate passed)
# The tier-1 stage mirrors ROADMAP.md exactly — if you change one,
# change both.
set -u -o pipefail
cd "$(dirname "$0")/.."

# pinned environment: CPU backend, virtual 8-device mesh (conftest.py
# re-pins too; exporting here covers the non-pytest stages), stable
# hashing, CRC write+verify on (the defaults, pinned against drift)
export JAX_PLATFORMS=cpu
export PYTHONHASHSEED=0
export TPQ_PAGE_CRC=1
export TPQ_PAGE_CRC_VERIFY=1

# floor history: 860 (r7-r10) -> 1000 (r11: suite grew to ~1041-1087
# passing depending on optional deps; keep ~40-80 of headroom for
# image variance, not 200+)
CI_PASS_FLOOR=${CI_PASS_FLOOR:-1000}

fail() { echo "ci.sh: FAILED at stage $1" >&2; exit 1; }

echo "=== stage 1/20: tier-1 suite (pass floor $CI_PASS_FLOOR) ==="
rm -f /tmp/_t1.log
timeout -k 10 870 python -m pytest tests/ -q -m 'not slow' \
  --continue-on-collection-errors -p no:cacheprovider -p no:xdist \
  -p no:randomly 2>&1 | tee /tmp/_t1.log
# progress chars: . pass, F fail, E error, s skip, x xfail, X xpass —
# 'X' included so one xpass doesn't silently drop its whole line of
# dots from the count
passed=$(grep -aE '^[.FEsxX]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log \
  | tr -cd . | wc -c)
echo "DOTS_PASSED=$passed"
[ "$passed" -ge "$CI_PASS_FLOOR" ] \
  || fail "tier-1 ($passed passed < floor $CI_PASS_FLOOR)"

echo "=== stage 2/20: smoke bench (CPU backend, tiny target) ==="
TPQ_BENCH_TARGET=60000 TPQ_BENCH_CPU=1 timeout -k 10 600 \
  python bench.py > /tmp/_ci_bench.json || fail "smoke bench"
tail -1 /tmp/_ci_bench.json

echo "=== stage 3/20: crash corpus + fault-injection matrix (strict) ==="
timeout -k 10 600 python -m pytest \
  "tests/test_corpus.py::TestCrashRegressions" tests/test_faults.py \
  -q -p no:cacheprovider || fail "corpus/faults"

echo "=== stage 4/20: salvage + strict metadata (strict) ==="
timeout -k 10 600 python -m pytest tests/test_salvage.py \
  -q -p no:cacheprovider || fail "salvage"

echo "=== stage 5/20: deadlines/hedging + kill-resume checkpoints (strict) ==="
timeout -k 10 600 python -m pytest tests/test_deadline.py \
  tests/test_checkpoint.py -q -p no:cacheprovider || fail "time/crash"

echo "=== stage 6/20: plan matrix: serial vs parallel, cache on (strict) ==="
# leg A: pinned-serial planning (the TPQ_PLAN_THREADS=1 reference path)
TPQ_PLAN_THREADS=1 timeout -k 10 600 python -m pytest \
  tests/test_plan_parallel.py tests/test_plan_cache.py \
  -q -p no:cacheprovider || fail "plan matrix (serial leg)"
# leg B: default pool width + the footer-keyed plan cache enabled for
# the whole fallback-matrix routing pin (hints must not change routing)
TPQ_PLAN_CACHE_MB=64 timeout -k 10 600 python -m pytest \
  tests/test_plan_parallel.py tests/test_fallback_matrix.py \
  -q -p no:cacheprovider || fail "plan matrix (cache-on leg)"

echo "=== stage 7/20: live obs gate + overhead guard (strict) ==="
timeout -k 10 600 python -m pytest tests/test_live_obs.py \
  tests/test_env_docs.py -q -p no:cacheprovider || fail "live obs"
# overhead guard: the always-on default must stay within a generous
# noise-proof bound of the all-off hot path (the structural zero-cost
# pin already ran above; this catches a per-value hook sneaking in)
timeout -k 10 600 python tools/bench_obs.py --values 2000000 \
  --reps 2 --assert-overhead 25 > /tmp/_ci_obs.json \
  || fail "obs overhead guard"
tail -5 /tmp/_ci_obs.json

echo "=== stage 8/20: pruning parity gate (strict) ==="
# leg A: the whole pushdown suite (write/read page index + bloom,
# verdicts, late materialization, counter exactness, corrupt-index
# degrade, pyarrow interop) on the default pool width
timeout -k 10 600 python -m pytest tests/test_prune.py \
  -q -p no:cacheprovider || fail "pruning parity"
# leg B: pinned-serial planning + prune disabled must still be exact
# (TPQ_PRUNE=0 is the parity escape hatch — filters evaluate over a
# full decode and the results must not change)
TPQ_PLAN_THREADS=1 TPQ_PRUNE=0 timeout -k 10 600 python -m pytest \
  "tests/test_prune.py::TestParity" \
  -q -p no:cacheprovider || fail "pruning parity (prune-off leg)"

echo "=== stage 9/20: tpq-analyze invariant passes + sanitizer leg (strict) ==="
timeout -k 10 300 python -m tools.analyze || fail "tpq-analyze"
timeout -k 10 600 python -m pytest tests/test_analyze.py \
  -q -p no:cacheprovider || fail "analyzer self-test"
timeout -k 10 900 bash tools/analyze/native.sh || fail "native sanitizers"

echo "=== stage 10/20: gather placement parity gate (strict) ==="
# leg A: the placement suite — byte parity placed vs replicated across
# filter/quarantine/salvage/resume/multi-host, placement + counter pins,
# mesh-mismatch errors
timeout -k 10 600 python -m pytest tests/test_gather_placement.py \
  -q -p no:cacheprovider || fail "gather placement"
# leg B: the same suite with the env default armed on every scan —
# values must not change, and the knob must not leak into the free
# functions' ndarray contract
TPQ_GATHER_TO=0 timeout -k 10 600 python -m pytest \
  tests/test_gather_placement.py \
  -q -p no:cacheprovider || fail "gather placement (env leg)"

echo "=== stage 11/20: write-pipeline parity gate (strict) ==="
# leg A: the whole native-write suite on the default knobs
timeout -k 10 600 python -m pytest tests/test_write_native.py \
  -q -p no:cacheprovider || fail "write parity"
# leg B: pure-path pin — the native gate off, every byte-parity and
# semantics test must still hold (this is the leg that catches a
# change to the PURE writer that the native path didn't mirror)
TPQ_WRITE_NATIVE=0 timeout -k 10 600 python -m pytest \
  tests/test_write_native.py -q -p no:cacheprovider \
  || fail "write parity (native-off leg)"

echo "=== stage 12/20: causal tracing + attribution + bench sentinel (strict) ==="
# leg A: the trace/attribution suite on the default (trace-off) env —
# span-tree connectivity, adversity-matrix propagation, ledger
# conservation, doctor goldens
timeout -k 10 600 python -m pytest tests/test_trace.py \
  -q -p no:cacheprovider || fail "trace suite"
# leg B: trace-ENABLED scan paths — the scan/gather/write suites run
# with TPQ_TRACE=1 so armed tracing can never change results (the
# byte-parity pins inside these suites now also hold under tracing),
# and the attribution/ledger exactness tests re-verify with spans on
TPQ_TRACE=1 timeout -k 10 900 python -m pytest \
  tests/test_trace.py tests/test_shard.py tests/test_live_obs.py \
  tests/test_gather_placement.py \
  -q -p no:cacheprovider || fail "trace-enabled leg"
# leg C: perf regression sentinel — fresh micro-runs vs the committed
# noise-aware baseline (SENTINEL_BASELINE.json); box-independent
# ratio pins (prune >= floor) enforced even on a different box
timeout -k 10 600 python tools/bench_sentinel.py --check \
  || fail "bench sentinel"

echo "=== stage 13/20: soak smoke: faults -> alerts, exact sums, byte identity (strict) ==="
# N=4 concurrent labeled scans with the deterministic fault plan
# (CorruptPage on one tenant's unique column, hang + unit deadline on
# another tenant's file).  Asserts the whole longitudinal contract:
# alert coverage without false negatives OR false positives, digest/
# ledger conservation to process totals, telemetry-off byte identity.
timeout -k 10 600 python -m tools.soak --scans 4 \
  || fail "soak smoke"

echo "=== stage 14/20: remote emulator: parity over an unreliable store (strict) ==="
# leg A: the dedicated remote suite — URI routing, coalescer property
# sweep, tiered-cache conservation + poisoning + torn-file restart,
# emu parity with the cache on AND off, hedged slow replicas
timeout -k 10 600 python -m pytest tests/test_remote.py \
  -q -p no:cacheprovider || fail "remote suite"
# leg B: the scan/prune/checkpoint suites rerouted through the
# emulated store (TPQ_SOURCE=emu: bare paths keep their names, so the
# suites run unmodified), under a mild deterministic fault plan and
# with the disk tier armed — the full scan stack must be byte-exact
# over a throttling, resetting remote
_CI_EMU_CACHE=$(mktemp -d)
TPQ_SOURCE=emu TPQ_EMU_THROTTLE_EVERY=23 TPQ_EMU_RESET_EVERY=41 \
  TPQ_CACHE_DISK_DIR="$_CI_EMU_CACHE" timeout -k 10 900 \
  python -m pytest tests/test_shard.py tests/test_prune.py \
  tests/test_checkpoint.py -q -p no:cacheprovider \
  || fail "remote emulator (cache-on leg)"
rm -rf "$_CI_EMU_CACHE"
# leg C: cache-off parity — the same reroute with both cache tiers
# disabled; results may not depend on the cache's existence
TPQ_SOURCE=emu TPQ_CACHE_DISK_MB=0 TPQ_CACHE_MEM_MB=0 \
  timeout -k 10 900 python -m pytest tests/test_shard.py \
  tests/test_checkpoint.py -q -p no:cacheprovider \
  || fail "remote emulator (cache-off leg)"

echo "=== stage 15/20: schedule chaos + runtime lock-order validation (strict) ==="
# leg A: one chaos seed over the plan-parallel and soak-parity suites
# — the seeded schedule perturbation must reproduce the unperturbed
# baseline exactly (tests/test_chaos.py runs the full 3-seed sweep in
# tier-1; this leg keeps the harness itself on the strict path)
timeout -k 10 600 python -m tools.chaos --seeds 101 \
  --suite plan-parallel --suite soak-parity || fail "chaos leg"
# leg B: the soak workload under the runtime lock-order recorder with
# a chaos seed — any lock-cycle, or any recorded edge the static
# analysis failed to model, fails the soak's own gate
TPQ_LOCKCHECK=1 timeout -k 10 600 python -m tools.soak --scans 4 \
  --chaos-seed 101 || fail "lockcheck soak leg"

echo "=== stage 16/20: sampling profiler: armed parity + flame/doctor smoke (strict) ==="
# leg A: profiler-ENABLED scan paths — the real sampler thread walks
# sys._current_frames() through the whole scan suite and must not
# change a byte of output (the byte-parity pins inside these suites
# now also hold under armed sampling)
TPQ_PROFILE=1 timeout -k 10 900 python -m pytest \
  tests/test_profiler.py tests/test_shard.py \
  -q -p no:cacheprovider || fail "profile-enabled leg"
# leg B: CLI smoke over freshly captured profiles — capture the
# native and pure write pipelines plus one traced+profiled scan, then
# flame / flame --diff / doctor --profile must all render (and the
# doctor's samples-vs-stage-wall consistency check must stay quiet)
_CI_PROF=$(mktemp -d)
timeout -k 10 600 python - "$_CI_PROF" <<'PYEOF' || fail "profile capture"
import os
import sys

root = sys.argv[1]
import jax

jax.config.update("jax_platforms", "cpu")
import numpy as np

from tpuparquet import FileWriter
from tpuparquet.obs import profiler as prof
from tpuparquet.obs import trace
from tpuparquet.obs.profiler import write_profile_file
from tpuparquet.shard.scan import ShardedScan

N, STEP = 400_000, 50_000
ts = np.arange(N, dtype=np.int64) * 3
fare = (ts % 977).astype("float64") * 0.5
SCHEMA = "message t { required int64 ts; required double fare; }"


def write_once(path):
    with open(path, "wb") as f:
        w = FileWriter(f, SCHEMA)
        for a in range(0, N, STEP):
            w.write_columns({"ts": ts[a:a + STEP],
                             "fare": fare[a:a + STEP]})
        w.close()


def capture(fn):
    p = prof.set_profiling(True, hz=500)
    try:
        fn()
    finally:
        state = p.to_state()
        prof.set_profiling(False)
    return state

sa = capture(lambda: write_once(os.path.join(root, "native.parquet")))
os.environ["TPQ_WRITE_NATIVE"] = "0"
sb = capture(lambda: write_once(os.path.join(root, "pure.parquet")))
del os.environ["TPQ_WRITE_NATIVE"]
assert sa["counters"]["profile_samples"], "no samples (native write)"
assert sb["counters"]["profile_samples"], "no samples (pure write)"
write_profile_file(sa, os.path.join(root, "native.prof"))
write_profile_file(sb, os.path.join(root, "pure.prof"))

# one traced + profiled scan: the scan driver exports both files
os.environ["TPQ_PROFILE_EXPORT"] = os.path.join(root, "scan.prof")
os.environ["TPQ_TRACE_EXPORT"] = os.path.join(root, "scan.trace")
trace.set_tracing(True)
prof.set_profiling(True, hz=500)
try:
    for _k, cols in ShardedScan(
            [os.path.join(root, "native.parquet")]).run_iter():
        for c in cols.values():
            c.block_until_ready()
finally:
    prof.set_profiling(False)
    trace.set_tracing(False)
    for k in ("TPQ_PROFILE_EXPORT", "TPQ_TRACE_EXPORT"):
        del os.environ[k]
assert os.path.exists(os.path.join(root, "scan.prof")), "no scan export"
assert os.path.exists(os.path.join(root, "scan.trace")), "no trace export"
PYEOF
timeout -k 10 120 python -m tpuparquet.cli.parquet_tool flame \
  "$_CI_PROF/native.prof" > /dev/null || fail "flame smoke"
timeout -k 10 120 python -m tpuparquet.cli.parquet_tool flame \
  --diff "$_CI_PROF/native.prof" "$_CI_PROF/pure.prof" > /dev/null \
  || fail "flame --diff smoke"
_CI_DOC=$(timeout -k 10 120 python -m tpuparquet.cli.parquet_tool \
  doctor --profile "$_CI_PROF/scan.prof" "$_CI_PROF/scan.trace") \
  || fail "doctor --profile smoke"
echo "$_CI_DOC" | grep -q "profile: top frames" \
  || fail "doctor --profile (no profile section)"
echo "$_CI_DOC" | grep -q "WARNING" \
  && fail "doctor --profile (consistency warning)"
rm -rf "$_CI_PROF"

echo "=== stage 17/20: scan server: arbiter + admission + drain (strict) ==="
# leg A: the serve suite — arbiter apportionment (anti-starvation
# floors, bounded boosts), admission load-shedding, the in-process
# server path, and the SIGTERM/SIGKILL drain-resume sweep
timeout -k 10 600 python -m pytest tests/test_serve.py \
  -q -p no:cacheprovider || fail "serve suite"
# leg B: the soak's server leg under the runtime lock-order recorder,
# across three chaos seeds: the same tenant corpus + fault plan
# multiplexed through one ScanServer must be byte-identical to the
# direct-scan control with exact per-tenant accounting, no starved
# tenant and zero false alerts — and the serve locks must join the
# whole-program acquisition graph acyclically
for _ci_seed in 101 202 303; do
  TPQ_LOCKCHECK=strict timeout -k 10 600 python -m tools.soak \
    --serve --scans 4 --chaos-seed "$_ci_seed" \
    || fail "serve soak leg (seed $_ci_seed)"
done
# leg C: legacy-knob leg — direct scans with the per-pool env knobs
# set behave exactly as before the arbiter existed (the knobs only
# warn when they jointly oversubscribe the box; no server = no
# arbiter = no behavior change)
TPQ_PLAN_THREADS=2 TPQ_WRITE_THREADS=2 timeout -k 10 600 \
  python -m pytest tests/test_shard.py tests/test_plan_parallel.py \
  -q -p no:cacheprovider || fail "legacy-knob leg"

echo "=== stage 18/20: partitioned datasets: atomic commits + kill sweep (strict) ==="
# leg A: the dataset suite with the slow marker INCLUDED — the
# kill-at-every-step sweep, the first-commit snapshot-or-nothing pin,
# pruning/quarantine/compaction/interop, and the chaos kill/resume
# legs (seeds 101/202/303 baked into the parametrize) where the
# resume runs under TPQ_LOCKCHECK=strict and must post zero lock
# findings with exact counter conservation vs the unperturbed oracle
timeout -k 10 600 python -m pytest tests/test_dataset.py \
  -q -p no:cacheprovider || fail "dataset suite + kill sweep"
# leg B: concurrent scan+write admission under one arbiter — the
# soak's dataset leg across the same three chaos seeds: the writer
# tenant's commit must survive seeded interleaving perturbation and
# read back complete and duplicate-free through submit_dataset
for _ci_seed in 101 202 303; do
  TPQ_LOCKCHECK=strict timeout -k 10 600 python -m tools.soak \
    --dataset --scans 4 --chaos-seed "$_ci_seed" \
    || fail "dataset soak leg (seed $_ci_seed)"
done

echo "=== stage 19/20: http(s) backend: fault server + shared cache (strict) ==="
# leg A: the dedicated suites — the HTTP range source against the
# deterministic fault server (status taxonomy, retry ladder, ETag
# flips, bounded pool) and the cross-process shared disk cache (two
# concurrent scanners, chaos seeds, kill/resume sweep, poison
# eviction, fleet origin economy)
timeout -k 10 900 python -m pytest tests/test_http_source.py \
  tests/test_shared_cache.py -q -p no:cacheprovider \
  || fail "http/shared-cache suites"
# leg B: remote equivalence — the scan/prune/checkpoint suites re-run
# with every bare-path open rerouted through a LIVE fault HTTP server
# (TPQ_SOURCE=http + TPQ_HTTP_BASE; the server roots at / so rerouted
# absolute paths resolve) under a mild deterministic fault plan; the
# whole scan stack must be byte-exact over a throttling, resetting
# HTTP origin, exactly like the emu:// leg of stage 14
_CI_HTTP_DIR=$(mktemp -d)
python -m tools.httpfault --root / --throttle-every 23 \
  --reset-every 41 --url-file "$_CI_HTTP_DIR/url" \
  > /dev/null 2>&1 &
_CI_HTTP_PID=$!
for _i in $(seq 1 50); do
  [ -s "$_CI_HTTP_DIR/url" ] && break
  sleep 0.1
done
[ -s "$_CI_HTTP_DIR/url" ] || { kill "$_CI_HTTP_PID" 2>/dev/null;
  fail "httpfault server did not start"; }
TPQ_SOURCE=http TPQ_HTTP_BASE=$(cat "$_CI_HTTP_DIR/url") \
  timeout -k 10 900 python -m pytest tests/test_shard.py \
  tests/test_prune.py tests/test_checkpoint.py -q \
  -p no:cacheprovider
_ci_http_rc=$?
kill "$_CI_HTTP_PID" 2>/dev/null
wait "$_CI_HTTP_PID" 2>/dev/null
rm -rf "$_CI_HTTP_DIR"
[ "$_ci_http_rc" -eq 0 ] || fail "http remote-equivalence leg"
# leg C: the soak's http leg — scripted 429/503/reset storm, then a
# mid-scan ETag flip, both byte-identical to the local control with
# zero quarantined units — under the runtime lock-order recorder
# across three chaos seeds
for _ci_seed in 101 202 303; do
  TPQ_LOCKCHECK=strict timeout -k 10 600 python -m tools.soak \
    --http --scans 4 --chaos-seed "$_ci_seed" \
    || fail "http soak leg (seed $_ci_seed)"
done

echo "=== stage 20/20: codec parity: native matrix + fallbacks + file equivalence (strict) ==="
# leg A: the block-codec suite on the default knobs — cross-impl
# oracles (pyarrow), the LZ4 pure==C byte-parity pin, malformed-frame
# fuzz, block-split determinism, multi-member/multi-frame decode
timeout -k 10 600 python -m pytest tests/test_compress.py \
  -q -p no:cacheprovider || fail "codec suite"
# leg B: the same suite under the page-pipeline native gate off AND
# under the codec native gate off — both pure paths must keep every
# semantics and parity pin (the cross-impl oracles catch a pure-side
# format drift the native path would have masked)
TPQ_WRITE_NATIVE=0 timeout -k 10 600 python -m pytest \
  tests/test_compress.py -q -p no:cacheprovider \
  || fail "codec suite (TPQ_WRITE_NATIVE=0 leg)"
TPQ_NATIVE_CODECS=0 timeout -k 10 600 python -m pytest \
  tests/test_compress.py -q -p no:cacheprovider \
  || fail "codec suite (TPQ_NATIVE_CODECS=0 leg)"
# leg C: whole-file equivalence sweep — for every registered codec:
# native-on vs native-off writes byte-identical where deterministic
# (uncompressed always; lz4_raw via the pure==C mirror pin; gzip when
# the runtime probe shows bound-zlib == stdlib-zlib bytes) and
# decoded-identical elsewhere; then 1-thread vs N-thread block-split
# writes decoded-identical under chaos seeds with the lock-order
# recorder armed
TPQ_LOCKCHECK=strict timeout -k 10 600 python - <<'PYEOF' \
  || fail "codec file-equivalence sweep"
import io
import os

import jax

jax.config.update("jax_platforms", "cpu")
import numpy as np

from tpuparquet import CompressionCodec, FileReader, FileWriter
from tpuparquet.compress import registered_codecs
from tpuparquet.faults import chaos_scope

SCHEMA = ("message m { required int64 a; required double x; "
          "optional binary s (STRING); }")
N = 200_000
rng = np.random.default_rng(24)
A = rng.integers(0, 1 << 40, N)
X = (A % 9973) * 0.25
MASK = rng.random(N) >= 0.1
VOCAB = [f"city-{i:03d}".encode() for i in range(180)]
S = [VOCAB[i] for i in rng.integers(0, len(VOCAB), int(MASK.sum()))]


def write_file():
    from tpuparquet.cpu.plain import ByteArrayColumn

    buf = io.BytesIO()
    w = FileWriter(buf, SCHEMA, codec=CODEC)
    w.write_columns(
        {"a": A, "x": X, "s": ByteArrayColumn.from_list(S)},
        masks={"s": MASK})
    w.close()
    return buf.getvalue()


def decoded(blob):
    out = []
    with FileReader(io.BytesIO(blob)) as r:
        for rg in range(r.row_group_count()):
            for path, cd in sorted(r.read_row_group_arrays(rg).items()):
                v = cd.values
                out.append(v if isinstance(v, (bytes, list)) else
                           np.asarray(v).tobytes())
                out.append(np.asarray(cd.def_levels).tobytes()
                           if cd.def_levels is not None else b"")
    return out


def gzip_deterministic():
    """True when the bound zlib emits the same bytes as the stdlib
    module (same vendored zlib: the common case, but not guaranteed
    across e.g. zlib-ng boxes)."""
    import zlib

    from tpuparquet.native.syslibs import zlib_native

    nat = zlib_native()
    if nat is None:
        return False
    probe = bytes(range(256)) * 64
    co = zlib.compressobj(wbits=31)
    return nat.compress(probe) == co.compress(probe) + co.flush()


for CODEC in sorted(registered_codecs()):
    if CODEC == CompressionCodec.LZO:
        continue  # test-registered plugins have no writer contract
    name = CompressionCodec(CODEC).name
    base = write_file()
    base_dec = decoded(base)

    # native-off leg (zstd without the wheel has no fallback: skip)
    os.environ["TPQ_NATIVE_CODECS"] = "0"
    try:
        pure = write_file()
    except Exception:
        pure = None
    finally:
        del os.environ["TPQ_NATIVE_CODECS"]
    if pure is not None:
        byte_pinned = (
            CODEC == CompressionCodec.UNCOMPRESSED
            or CODEC == CompressionCodec.LZ4_RAW
            or (CODEC == CompressionCodec.GZIP and gzip_deterministic()))
        if byte_pinned:
            assert pure == base, f"{name}: native-off bytes diverged"
        assert decoded(pure) == base_dec, f"{name}: native-off decode"

    # 1-thread vs N-thread block-split writes under chaos seeds: the
    # split must stay deterministic in block size, and every width
    # must decode identically to the serial file
    os.environ["TPQ_COMPRESS_BLOCK_KB"] = "64"
    os.environ["TPQ_WRITE_THREADS"] = "1"
    try:
        one = write_file()
        assert decoded(one) == base_dec, f"{name}: 1-thread decode"
        wide = {}
        for seed in (101, 202, 303):
            os.environ["TPQ_WRITE_THREADS"] = "4"
            with chaos_scope(seed):
                blob = write_file()
            wide[seed] = blob
            assert decoded(blob) == base_dec, \
                f"{name}: 4-thread decode (seed {seed})"
        assert len({wide[s] for s in wide}) == 1, \
            f"{name}: multi-thread bytes vary across chaos seeds"
    finally:
        del os.environ["TPQ_COMPRESS_BLOCK_KB"]
        del os.environ["TPQ_WRITE_THREADS"]
    print(f"codec parity OK: {name}")
PYEOF

echo "ci.sh: gate PASSED"
