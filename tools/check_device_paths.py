"""On-chip parity check of EVERY device decode branch on small files.

One minute of tunnel time validates what the CPU-backend test suite
can't: that each branch's kernels compile and run bit-exactly on real
hardware (the Mosaic straddle miscompile showed interpret-mode parity
is not sufficient).  Builds one small file per encoding family and
runs the `parquet-tool verify` comparison (CPU oracle vs device path,
bitwise).

Usage: python tools/check_device_paths.py [--events]
(exit 0 = all bit-exact; --events additionally asserts PER-PAGE
transport decisions against the aggregate counters and prints the
exact page a gate regression demoted)
"""

from __future__ import annotations

import io
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _files():
    from tpuparquet import CompressionCodec, Encoding, FileWriter
    from tpuparquet.cpu.plain import ByteArrayColumn

    rng = np.random.default_rng(5)
    n = 4000

    def build(name, schema, cols, masks=None, offsets=None,
              expect=None, **kw):
        buf = io.BytesIO()
        w = FileWriter(buf, schema, **kw)
        w.write_columns(cols, masks=masks, offsets=offsets)
        w.close()
        buf.seek(0)
        return name, buf, expect

    m = rng.random(n) >= 0.2
    yield build(
        "plain+dict+snappy (v1)",
        "message m { required int64 a; optional int32 b; "
        "required binary s (STRING); }",
        {"a": rng.integers(-(2**60), 2**60, size=n),
         "b": rng.integers(0, 9, size=int(m.sum()), dtype=np.int32),
         "s": ByteArrayColumn.from_list(
             [b"cat-%d" % (i % 17) for i in range(n)])},
        masks={"b": m}, codec=CompressionCodec.SNAPPY)
    yield build(
        "plain fixed v2 + device snappy path",
        "message m { required int64 a; required double d; }",
        {"a": np.arange(n, dtype=np.int64) % 13,  # compressible
         "d": rng.random(n)},
        codec=CompressionCodec.SNAPPY, data_page_v2=True)
    yield build(
        "delta int64 + int32",
        "message m { required int64 t; required int32 k; }",
        {"t": 1_700_000_000_000 + rng.integers(0, 9000, n).cumsum(),
         "k": rng.integers(-999, 999, size=n, dtype=np.int32)},
        column_encodings={"t": Encoding.DELTA_BINARY_PACKED,
                          "k": Encoding.DELTA_BINARY_PACKED},
        allow_dict=False)
    yield build(
        "byte_stream_split + boolean RLE",
        "message m { required double x; required float y; "
        "required boolean f; }",
        {"x": rng.random(n) * 1e6, "y": rng.random(n).astype(np.float32),
         "f": rng.random(n) >= 0.5},
        column_encodings={"x": Encoding.BYTE_STREAM_SPLIT,
                          "y": Encoding.BYTE_STREAM_SPLIT,
                          "f": Encoding.RLE},
        allow_dict=False)
    yield build(
        "delta_length + delta_byte_array (front-coded)",
        "message m { required binary u; required binary v; }",
        {"u": ByteArrayColumn.from_list(
            [b"val-%d" % (i % 23) for i in range(n)]),
         "v": ByteArrayColumn.from_list(
            [("warehouse/region-3/shelf-%04d/item-%07d"
              % (i // 40, i)).encode() for i in range(n)])},
        column_encodings={"u": Encoding.DELTA_LENGTH_BYTE_ARRAY,
                          "v": Encoding.DELTA_BYTE_ARRAY},
        allow_dict=False)
    yield build(
        "nested list + levels",
        "message m { optional group l (LIST) { repeated group list { "
        "optional int64 element; } } }",
        {"l": rng.integers(0, 10**9, size=3 * n)},
        offsets={"l": np.arange(0, 3 * n + 1, 3, dtype=np.int64)})
    # -- round-4 wire transports -----------------------------------------
    big = 50_000  # large enough to clear the transports' savings gates
    yield build(
        "lane-RLE transport (timestamp i64 uncompressed)",
        "message m { required int64 t; }",
        {"t": 1_700_000_000_000
         + rng.integers(0, 3_600_000, size=big).cumsum()},
        allow_dict=False)
    yield build(
        "byte-plane descent (small-range i32) + V1 optional levels",
        "message m { optional int32 k; }",
        {"k": rng.integers(0, 1000, size=big - big // 10,
                           dtype=np.int32)},
        masks={"k": np.arange(big) % 10 != 0},
        codec=CompressionCodec.SNAPPY, allow_dict=False)
    yield build(
        "PLAIN byte-array token+gather (compressible strings)",
        "message m { required binary s (STRING); }",
        {"s": ByteArrayColumn.from_list(
            [b"the-quick-brown-fox-%d" % (i % 97) for i in range(big)])},
        codec=CompressionCodec.SNAPPY, allow_dict=False)
    # -- round-5 transports / kernels ------------------------------------
    # (the uncompressed-timestamp case above now rides DELTA lanes; these
    # pin the remaining new paths on real silicon)
    flba_rows = rng.integers(0, 256, (n, 16)).astype(np.uint8)
    flba_rows[:, :12] = 7  # shared prefixes -> expanding front coding
    yield build(
        "FLBA delta_byte_array (device copy-token expansion -> lanes)",
        "message m { required fixed_len_byte_array(16) k; }",
        {"k": flba_rows},
        column_encodings={"k": Encoding.DELTA_BYTE_ARRAY},
        allow_dict=False, codec=CompressionCodec.SNAPPY,
        expect={"pages_host_values": 0})
    yield build(
        "delta-lane w=0 (arithmetic sequence ships in 8 bytes)",
        "message m { required int64 t; }",
        {"t": np.arange(big, dtype=np.int64) * 12345},
        allow_dict=False, expect={"pages_device_delta_lanes": 1})
    yield build(
        "byte planes on doubles (delta-ineligible type)",
        "message m { required double d; }",
        {"d": rng.integers(0, 255, size=big).astype(np.float64)},
        allow_dict=False, codec=CompressionCodec.SNAPPY,
        expect={"pages_device_planes": 1})


def _device_pages(st):
    """Device-path page events (the CPU-oracle half of verify emits
    transport="cpu" events; those are not routing decisions)."""
    return [e for e in st.events.pages if e.transport != "cpu"]


def main() -> int:
    import jax

    from tpuparquet.cli.parquet_tool import cmd_verify

    from tpuparquet.stats import collect_stats

    # --events: assert PER-PAGE transport decisions, not just aggregate
    # counters — a gate regression is then localized to the exact page
    # (column, page ordinal, gate numbers) on real silicon
    events_mode = "--events" in sys.argv[1:]
    print(f"backend={jax.default_backend()}"
          + (" (per-page events mode)" if events_mode else ""))
    failures = 0
    for name, buf, expect in _files():
        class _A:
            file = buf

        out = io.StringIO()
        with collect_stats(events=events_mode) as st:
            rc = cmd_verify(_A, out=out)
        detail = out.getvalue().strip().splitlines()[-1]
        if rc == 0 and events_mode:
            from tpuparquet.obs import TRANSPORT_COUNTER, counter_counts

            # counter/event agreement for EVERY transport counter: each
            # counted page must have exactly one event claiming that
            # transport (the event log and the counters cannot drift)
            d = st.as_dict()
            ev_counts = counter_counts(_device_pages(st))
            for counter in sorted(set(TRANSPORT_COUNTER.values())):
                if d.get(counter, 0) != ev_counts.get(counter, 0):
                    rc = 1
                    detail = (
                        f"event/counter drift: {counter}="
                        f"{d.get(counter, 0)} but "
                        f"{ev_counts.get(counter, 0)} page events")
                    break
        # transport pinning: bit-exactness alone is vacuous for the
        # cases whose point is WHICH path ran (a gate regression that
        # demotes the transport must fail here, not pass silently)
        if rc == 0 and expect:
            d = st.as_dict()
            for key, want in expect.items():
                if d.get(key, 0) < want:
                    rc = 1
                    detail = (f"transport regression: {key}={d.get(key)}"
                              f" < {want} (decode was bit-exact)")
                    if events_mode:
                        # the per-page log names the page that demoted
                        # and what the gate saw
                        detail += "".join(
                            f"\n    {e.column}[{e.page}] {e.encoding} "
                            f"-> {e.transport}"
                            + (f" ({e.reason})" if e.reason else "")
                            for e in _device_pages(st))
                    break
        status = "OK" if rc == 0 else "FAIL"
        print(f"[{status}] {name}: {detail}")
        failures += rc
    print("ALL DEVICE PATHS BIT-EXACT" if not failures
          else f"{failures} FAILURES")
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
