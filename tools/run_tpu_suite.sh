#!/bin/bash
# One tunnel window, everything measured: official bench ladder first
# (the number that matters), then the scale sweep, then the Pallas A/B.
# Usage: bash tools/run_tpu_suite.sh [outdir]
set -u
cd "$(dirname "$0")/.."
OUT=$(realpath -m "${1:-/tmp/tpu_suite}")
mkdir -p "$OUT"

echo "=== bench.py (official ladder) ==="
timeout 2400 python bench.py > "$OUT/bench.out" 2> "$OUT/bench.err"
echo "rc=$?" | tee -a "$OUT/bench.err"
tail -1 "$OUT/bench.out"

echo "=== unpack hardware parity sweep (catches Mosaic regressions) ==="
timeout 900 python tools/check_unpack_hw.py 200000 \
  > "$OUT/unpack_hw.out" 2>&1
echo "rc=$?"
tail -1 "$OUT/unpack_hw.out"

echo "=== every device decode branch, bit-exact on chip ==="
timeout 900 python tools/check_device_paths.py \
  > "$OUT/device_paths.out" 2>&1
echo "rc=$?"
tail -1 "$OUT/device_paths.out"

echo "=== profile_decode scale sweep ==="
for rows in 2000000 4000000 10000000; do
  timeout 900 python tools/profile_decode.py $rows 8 \
    > "$OUT/profile_${rows}.out" 2>&1
  echo "rows=$rows rc=$?"
  grep -E "e2e|device:" "$OUT/profile_${rows}.out" | head -4
done

echo "=== wire transport A/B (planes/tokens on vs off) ==="
timeout 1800 python tools/bench_wire.py > "$OUT/wire.out" 2>&1
echo "rc=$?"
cat "$OUT/wire.out"

echo "=== pallas vs xla unpack A/B ==="
timeout 1200 python tools/bench_pallas.py 50000000 \
  > "$OUT/pallas.out" 2>&1
echo "rc=$?"
tail -10 "$OUT/pallas.out"
