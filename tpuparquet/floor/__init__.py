"""floor — the object mapper (``/root/reference/floor/``), Python-native.

Write dataclasses (or anything with ``marshal_parquet``) straight to
Parquet and scan rows back into typed objects.
"""

from .reader import Reader, new_file_reader  # noqa: F401
from .reflect import field_name, from_row, schema_of, to_row  # noqa: F401
from .time import (  # noqa: F401
    Time,
    time_from_microseconds,
    time_from_milliseconds,
    time_from_nanoseconds,
)
from .writer import Writer, new_file_writer  # noqa: F401
