"""floor.Reader: read Parquet rows back into Python objects.

Parity with ``floor.NewFileReader``/``Reader.Next``/``Scan``
(``/root/reference/floor/reader.go:18-91``): iterate rows and fill
dataclass instances, honoring an ``unmarshal_parquet(row)`` hook when
the target provides one.
"""

from __future__ import annotations

from ..io.reader import FileReader
from .reflect import decode_row, from_row

__all__ = ["Reader", "new_file_reader"]


class Reader:
    """Typed row iteration over a low-level :class:`FileReader`."""

    def __init__(self, fr: FileReader, cls=None):
        self._fr = fr
        self._cls = cls
        self._row = None

    @property
    def file_reader(self) -> FileReader:
        return self._fr

    def next(self) -> bool:
        """Advance to the next row; False at end of file
        (``floor/reader.go:65-78``)."""
        try:
            self._row = self._fr.next_row()
            return True
        except EOFError:
            self._row = None
            return False

    def scan(self, target=None):
        """Deserialize the current row.

        * ``target`` with an ``unmarshal_parquet(row)`` method: the hook
          receives the raw row (``floor/reader.go:84-87``), returns target.
        * ``target`` a dataclass type (or the reader's bound ``cls``):
          returns a new instance via reflection.
        * no target: returns a logical-type-decoded plain dict.
        """
        if self._row is None:
            raise RuntimeError("scan before next(), or past end of file")
        if (target is not None and not isinstance(target, type)
                and callable(getattr(target, "unmarshal_parquet", None))):
            target.unmarshal_parquet(self._row)
            return target
        cls = target or self._cls
        if cls is None:
            return decode_row(self._row, self._fr.schema)
        return from_row(self._row, cls, self._fr.schema)

    def read_columns(self, rg_index: int, cls=None) -> list:
        """Bulk-materialize one row group's objects: columnar decode +
        per-leaf conversion, no per-row record assembly.  Flat, STRUCT
        (nested dataclass), MAP (dict), and list-of-primitive fields;
        same objects as iterating that row group."""
        from .reflect import objects_from_columns

        cls = cls or self._cls
        if cls is None:
            raise TypeError("read_columns needs a dataclass (bind cls "
                            "or pass one)")
        return objects_from_columns(
            self._fr.read_row_group_arrays(rg_index), cls,
            self._fr.schema,
            n_rows=self._fr.meta.row_groups[rg_index].num_rows)

    def __iter__(self):
        while self.next():
            yield self.scan()

    def close(self) -> None:
        self._fr.close()

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        self.close()


def new_file_reader(path, cls=None, *columns: str) -> Reader:
    """Open ``path`` for object reading (``floor.NewFileReader``)."""
    return Reader(FileReader(path, *columns), cls=cls)
