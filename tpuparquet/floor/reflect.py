"""Dataclass <-> row-dict reflection against a Parquet schema tree.

The TPU-build equivalent of floor's reflection marshaller/unmarshaller
(``/root/reference/floor/writer.go:99-294``,
``/root/reference/floor/reader.go:117-388``): instead of Go reflect over
struct tags, we walk dataclass fields with ``typing`` hints. The schema
element (logical/converted type) drives value conversion exactly as in
the reference — strings, DATE/TIME/TIMESTAMP, UUID, LIST/MAP
conventions — so objects round-trip through the low-level row shape the
file layer expects.

``schema_of`` additionally derives a schema definition from a dataclass
(no reference analogue; floor always takes an explicit schema — kept as
a convenience, with explicit schemas still fully supported).
"""

from __future__ import annotations

import dataclasses
import datetime
import types
import typing
import uuid

from ..format.dsl import _unit_name
from ..format.metadata import ConvertedType, Type
from ..format.schema import SchemaNode
from ..int96_time import datetime_to_int96, int96_to_datetime
from .time import (
    Time,
    time_from_microseconds,
    time_from_milliseconds,
    time_from_nanoseconds,
)

__all__ = ["field_name", "schema_of", "to_row", "from_row",
           "objects_to_columns", "objects_from_columns"]


def field_name(f: dataclasses.Field) -> str:
    """Parquet column name for a dataclass field: ``metadata['parquet']``
    else the lowercased field name (``floor/fieldname.go:10-19``)."""
    return f.metadata.get("parquet", f.name.lower())


# ----------------------------------------------------------------------
# Schema introspection helpers
# ----------------------------------------------------------------------

_CONVERTED_TO_LOGICAL = {
    ConvertedType.UTF8: ("STRING", None),
    ConvertedType.DATE: ("DATE", None),
    ConvertedType.MAP: ("MAP", None),
    ConvertedType.LIST: ("LIST", None),
    ConvertedType.ENUM: ("ENUM", None),
    ConvertedType.JSON: ("JSON", None),
    ConvertedType.BSON: ("BSON", None),
    ConvertedType.TIME_MILLIS: ("TIME", "MILLIS"),
    ConvertedType.TIME_MICROS: ("TIME", "MICROS"),
    ConvertedType.TIMESTAMP_MILLIS: ("TIMESTAMP", "MILLIS"),
    ConvertedType.TIMESTAMP_MICROS: ("TIMESTAMP", "MICROS"),
}


def _logical(node: SchemaNode) -> tuple[str | None, str | None]:
    """(logical type name, time unit name) for a schema node, merging the
    new-style logical type and the legacy converted type.  Cached on the
    node — schema trees are immutable for the life of a file."""
    cached = getattr(node, "_floor_logical", None)
    if cached is not None:
        return cached
    out = _logical_uncached(node)
    try:
        node._floor_logical = out
    except AttributeError:
        pass  # slotted node: just recompute
    return out


def _logical_uncached(node: SchemaNode) -> tuple[str | None, str | None]:
    el = node.element
    lt = getattr(el, "logicalType", None)
    if lt is not None:
        name, val = lt.set_member()
        if name in ("TIME", "TIMESTAMP") and val is not None:
            return name, _unit_name(val.unit)
        if name is not None:
            return name, None
    ct = getattr(el, "converted_type", None)
    if ct is not None:
        return _CONVERTED_TO_LOGICAL.get(ConvertedType(ct), (None, None))
    return None, None


def _is_list_group(node: SchemaNode) -> bool:
    return (not node.is_leaf and _logical(node)[0] == "LIST"
            and len(node.children) == 1 and node.children[0].is_repeated
            and not node.is_repeated)


def _is_map_group(node: SchemaNode) -> bool:
    return (not node.is_leaf and _logical(node)[0] == "MAP"
            and len(node.children) == 1 and node.children[0].is_repeated
            and len(node.children[0].children) == 2
            and not node.is_repeated)


# ----------------------------------------------------------------------
# Schema derivation from a dataclass
# ----------------------------------------------------------------------

_LEAF_DSL = {
    bool: "boolean {name}",
    int: "int64 {name}",
    float: "double {name}",
    bytes: "binary {name}",
    str: "binary {name} (STRING)",
    datetime.date: "int32 {name} (DATE)",
    datetime.datetime: "int64 {name} (TIMESTAMP(MICROS, true))",
    datetime.time: "int64 {name} (TIME(MICROS, true))",
    Time: "int64 {name} (TIME(MICROS, true))",
    uuid.UUID: "fixed_len_byte_array(16) {name} (UUID)",
}


def _unwrap_optional(hint):
    """(inner_type, is_optional) for Optional[...] / ``T | None`` hints."""
    origin = typing.get_origin(hint)
    if origin is typing.Union or origin is types.UnionType:
        args = [a for a in typing.get_args(hint) if a is not type(None)]
        if len(args) == 1 and len(typing.get_args(hint)) == 2:
            return args[0], True
    return hint, False


def _field_dsl(name: str, hint, required: bool, indent: str) -> str:
    hint, opt = _unwrap_optional(hint)
    rep = "required" if required and not opt else "optional"
    origin = typing.get_origin(hint)
    if origin in (list, tuple):
        (elem,) = typing.get_args(hint)[:1]
        inner = _field_dsl("element", elem, False, indent + "    ")
        return (f"{indent}{rep} group {name} (LIST) {{\n"
                f"{indent}  repeated group list {{\n"
                f"{indent}    {inner.strip()}\n"
                f"{indent}  }}\n{indent}}}")
    if origin is dict:
        k, v = typing.get_args(hint)
        kd = _field_dsl("key", k, True, indent + "    ")
        vd = _field_dsl("value", v, False, indent + "    ")
        return (f"{indent}{rep} group {name} (MAP) {{\n"
                f"{indent}  repeated group key_value {{\n"
                f"{indent}    {kd.strip()}\n"
                f"{indent}    {vd.strip()}\n"
                f"{indent}  }}\n{indent}}}")
    if dataclasses.is_dataclass(hint):
        body = "".join(
            _field_dsl(field_name(f), h, True, indent + "  ") + "\n"
            for f, h in _dc_fields(hint)
        )
        return f"{indent}{rep} group {name} {{\n{body}{indent}}}"
    for t, tmpl in _LEAF_DSL.items():
        if hint is t:
            return indent + rep + " " + tmpl.format(name=name) + ";"
    raise TypeError(f"cannot derive a Parquet type for field "
                    f"{name!r} with hint {hint!r}")


def _dc_fields(cls):
    hints = typing.get_type_hints(cls)
    return [(f, hints[f.name]) for f in dataclasses.fields(cls)]


def schema_of(cls, name: str = "msg") -> str:
    """Derive a schema-definition DSL string from a dataclass."""
    if not dataclasses.is_dataclass(cls):
        raise TypeError(f"{cls!r} is not a dataclass")
    body = "".join(
        _field_dsl(field_name(f), h, True, "  ") + "\n"
        for f, h in _dc_fields(cls)
    )
    return f"message {name} {{\n{body}}}"


# ----------------------------------------------------------------------
# Object -> row (marshalling; ``floor/writer.go decodeValue``)
# ----------------------------------------------------------------------

def to_row(obj, schema) -> dict:
    """Marshal a dataclass instance (or mapping) into the low-level
    nested-dict row shape for ``FileWriter.add_data``."""
    return {
        child.name: _encode(_get_member(obj, child.name), child)
        for child in schema.root.children
        if _has_member(obj, child.name)
    }


def _bulk_list_leaf(schema, leaf) -> "SchemaNode | None":
    """If ``leaf`` sits under a top-level column the bulk columnar paths
    can handle as a Python list — a bare repeated leaf, a 2-level legacy
    list, or the canonical 3-level LIST of a primitive/string — return
    the top-level node; None for shapes the row path must handle
    (multi-leaf groups, maps, deeper nesting)."""
    if leaf.max_rep_level != 1:
        return None
    top = _child_named(schema.root, leaf.path[0])
    if top is None:
        return None
    if top is leaf:  # bare repeated leaf
        return top
    if _is_list_group(top):
        mid = top.children[0]
        if mid is leaf:  # 2-level legacy: repeated leaf is the element
            return top
        if len(mid.children) == 1 and mid.children[0] is leaf:
            return top  # canonical 3-level
    return None


def _bulk_struct_list(schema, top_name: str):
    """If the top-level column ``top_name`` is a list of structs the
    bulk paths can marshal — element leaves are direct, non-repeated
    children of the element group — return ``(top, rep_node, elem_node,
    leaves)``; None otherwise.

    Covered shapes: canonical 3-level LIST whose element is a group,
    and a bare ``repeated group`` element (2-level legacy).  An
    optional element group ships a group-null mask (one def level below
    null fields)."""
    top = _child_named(schema.root, top_name)
    if top is None or top.is_leaf:
        return None
    if _is_list_group(top):
        mid = top.children[0]
        if mid.is_leaf or len(mid.children) != 1:
            return None
        elem = mid.children[0]
        if elem.is_leaf:
            return None
        rep_node = mid
    elif top.is_repeated:  # bare repeated group: the element itself
        elem = top
        rep_node = top
    else:
        return None
    if not elem.children or any(not c.is_leaf for c in elem.children):
        return None
    if any(c.max_rep_level != 1 for c in elem.children):
        return None
    return top, rep_node, elem, list(elem.children)


def objects_to_columns(objs, schema):
    """Bulk columnar extraction: dataclasses/mappings ->
    ``(columns, masks, offsets, element_masks)`` for
    ``FileWriter.write_columns``.

    Skips the per-row dict building + shredding machinery while
    applying the SAME leaf conversions as :func:`to_row`
    (strings, date/time/timestamp units, UUID) — decoded contents are
    identical to the row path; the columnar call writes one row group.
    Flat leaves, STRUCT columns (nested dataclasses/mappings over
    non-repeated groups, emitted as dotted leaf columns + per-group
    masks), MAP columns (dict fields -> (keys, values) per-leaf arrays
    sharing slot offsets), and LIST-of-primitive columns (bare repeated
    leaves, 2-level legacy, canonical 3-level — the shapes the
    reference's reflection shreds at ``floor/writer.go:241-294``) are
    supported, as are LIST-of-struct columns (``list[dataclass]``
    fields over a single-repeated-level element group, including
    optional elements via a group-null mask)."""
    leaves = schema.leaves
    list_tops = {}
    struct_leaves = set()
    map_tops = {}  # map top node -> (key leaf, value leaf)
    struct_list_tops = {}  # name -> (top, rep_node, elem, leaves)
    for leaf in leaves:
        if len(leaf.path) == 1 and not leaf.max_rep_level:
            continue
        if not leaf.max_rep_level:
            struct_leaves.add(leaf)  # nested non-repeated groups
            continue
        top = _child_named(schema.root, leaf.path[0])
        if (top is not None and _is_map_group(top)
                and leaf.max_rep_level == 1
                and top.children[0].children[0].is_leaf
                and top.children[0].children[1].is_leaf
                # key must be required: _maps_from_chunks pairs one key
                # per slot; an optional key leaf would misalign streams
                and top.children[0].children[0].is_required):
            kv = top.children[0]
            map_tops[top] = (kv.children[0], kv.children[1])
            continue
        top = _bulk_list_leaf(schema, leaf)
        if top is None:
            sl = _bulk_struct_list(schema, leaf.path[0])
            if sl is not None:
                struct_list_tops[sl[0].name] = sl
                continue
            raise ValueError(
                f"objects_to_columns supports flat schemas, STRUCT, "
                f"MAP, LIST-of-primitive, and LIST-of-struct columns; "
                f"{leaf.flat_name!r} is nested (use write/write_many)")
        list_tops[leaf] = top
    objs = list(objs)
    # per-class parquet-name -> attribute map, computed once (the row
    # path's per-access field scan would cost O(fields) per value here)
    attr_maps: dict = {}

    def getter(o, name):
        if isinstance(o, dict):
            return o.get(name)
        cls = type(o)
        m = attr_maps.get(cls)
        if m is None:
            if not dataclasses.is_dataclass(o):
                raise TypeError(
                    f"cannot marshal {cls.__name__}: expected a "
                    "dataclass or mapping")
            m = {field_name(f): f.name for f in dataclasses.fields(o)}
            attr_maps[cls] = m
        attr = m.get(name)
        return getattr(o, attr) if attr is not None else None

    import numpy as _np

    columns: dict = {}
    masks: dict = {}
    offsets: dict = {}
    element_masks: dict = {}
    # resolved sub-objects per group prefix, shared across the group's
    # leaves so sibling columns see one traversal (and one mask)
    prefix_objs: dict = {}

    def resolve(parts):
        key = ".".join(parts)
        cached = prefix_objs.get(key)
        if cached is not None:
            return cached
        if len(parts) == 1:
            vals = [getter(o, parts[0]) for o in objs]
        else:
            parent = resolve(parts[:-1])
            name = parts[-1]
            vals = [None if p is None else getter(p, name)
                    for p in parent]
        prefix_objs[key] = vals
        return vals

    map_top_by_name = {t.name: t for t in map_tops}
    done_maps: set = set()
    for leaf in leaves:
        sl = (struct_list_tops.get(leaf.path[0])
              if leaf.max_rep_level else None)
        if sl is not None:
            if leaf.path[0] in done_maps:
                continue  # all element leaves marshal together
            done_maps.add(leaf.path[0])
            top, rep_node, elem, elem_leaves = sl
            name = top.name
            elem_optional = elem is not rep_node and not elem.is_required
            pl_vals = {lf.name: [] for lf in elem_leaves}
            pl_mask = {lf.name: [] for lf in elem_leaves}
            enull: list = []  # True = the element group itself is null
            offs = _np.zeros(len(objs) + 1, dtype=_np.int64)
            mask = None
            for i, o in enumerate(objs):
                v = getter(o, name)
                if v is None:
                    # a bare repeated group has no null state: absent
                    # means empty, matching the row path
                    if top is not rep_node and not top.is_required:
                        if mask is None:
                            mask = _np.ones(len(objs), dtype=bool)
                        mask[i] = False
                    elif top is not rep_node:
                        raise ValueError(
                            f"column {name!r} is required but object "
                            f"{i} has no value")
                    offs[i + 1] = offs[i]
                    continue
                offs[i + 1] = offs[i] + len(v)
                for e in v:
                    if e is None:
                        if not elem_optional:
                            raise ValueError(
                                f"column {name!r} element is required "
                                f"but object {i} contains None")
                        enull.append(True)
                        for lf in elem_leaves:
                            # True keeps required-leaf masks all-true
                            # (never emitted); the group-null mask
                            # excludes the slot either way
                            pl_mask[lf.name].append(lf.is_required)
                        continue
                    enull.append(False)
                    for lf in elem_leaves:
                        fv = getter(e, lf.name)
                        if fv is None:
                            if lf.is_required:
                                raise ValueError(
                                    f"{lf.flat_name!r} is required but "
                                    f"an element of object {i} has no "
                                    "value")
                            pl_mask[lf.name].append(False)
                        else:
                            pl_mask[lf.name].append(True)
                            pl_vals[lf.name].append(
                                _encode_leaf(fv, lf))
            columns[name] = tuple(pl_vals[lf.name] for lf in elem_leaves)
            offsets[name] = offs
            if mask is not None:
                masks[name] = mask
            emd = {lf.flat_name: _np.asarray(pl_mask[lf.name],
                                             dtype=bool)
                   for lf in elem_leaves if not all(pl_mask[lf.name])}
            if any(enull):
                emd[elem.flat_name] = _np.asarray(enull, dtype=bool)
            if emd:
                element_masks[name] = emd
            continue
        mtop = (map_top_by_name.get(leaf.path[0])
                if leaf.max_rep_level else None)
        if mtop is not None:
            if mtop.name in done_maps:
                continue  # key and value leaves marshal together
            done_maps.add(mtop.name)
            key_leaf, val_leaf = map_tops[mtop]
            name = mtop.name
            val_optional = not val_leaf.is_required
            keys: list = []
            vals_v: list = []
            vmask: list = []
            offs = _np.zeros(len(objs) + 1, dtype=_np.int64)
            mask = None
            for i, o in enumerate(objs):
                v = getter(o, name)
                if v is None:
                    if not mtop.is_required:
                        if mask is None:
                            mask = _np.ones(len(objs), dtype=bool)
                        mask[i] = False
                    else:
                        raise ValueError(
                            f"column {name!r} is required but object "
                            f"{i} has no value")
                    offs[i + 1] = offs[i]
                    continue
                offs[i + 1] = offs[i] + len(v)
                for k, val in v.items():
                    keys.append(_encode_leaf(k, key_leaf))
                    if val is None:
                        if not val_optional:
                            raise ValueError(
                                f"column {name!r} value is required "
                                f"but object {i} contains None")
                        vmask.append(False)
                    else:
                        vmask.append(True)
                        vals_v.append(_encode_leaf(val, val_leaf))
            columns[name] = (keys, vals_v)
            offsets[name] = offs
            if mask is not None:
                masks[name] = mask
            if not all(vmask):
                element_masks[name] = {
                    val_leaf.flat_name: _np.asarray(vmask, dtype=bool)}
            continue
        top = list_tops.get(leaf)
        if top is not None:
            name = top.name
            elem_optional = not leaf.is_required and not leaf.is_repeated
            vals = []
            offs = _np.zeros(len(objs) + 1, dtype=_np.int64)
            mask = None
            emask = []
            for i, o in enumerate(objs):
                v = getter(o, name)
                if v is None:
                    # a bare repeated leaf has no null state — an absent
                    # value is an empty list, matching the row path
                    if top is not leaf and not top.is_required:
                        if mask is None:
                            mask = _np.ones(len(objs), dtype=bool)
                        mask[i] = False
                    elif top is not leaf:
                        raise ValueError(
                            f"column {name!r} is required but object "
                            f"{i} has no value")
                    offs[i + 1] = offs[i]
                    continue
                offs[i + 1] = offs[i] + len(v)
                for e in v:
                    if e is None:
                        if not elem_optional:
                            raise ValueError(
                                f"column {name!r} element is required "
                                f"but object {i} contains None")
                        emask.append(False)
                    else:
                        emask.append(True)
                        vals.append(_encode_leaf(e, leaf))
            columns[name] = vals
            offsets[name] = offs
            if mask is not None:
                masks[name] = mask
            if not all(emask):
                element_masks[name] = _np.asarray(emask, dtype=bool)
            continue
        if leaf in struct_leaves:
            chain = []
            node = leaf
            while node is not None and node.parent is not None:
                chain.append(node)
                node = node.parent
            chain.reverse()
            # group prefix masks (optional groups only — a required
            # group that is None under a present parent is an error,
            # matching the row-path shredder)
            for depth in range(1, len(chain)):
                gnode = chain[depth - 1]
                parts = [n.name for n in chain[:depth]]
                key = ".".join(parts)
                vals_g = resolve(parts)
                parent_vals = resolve(parts[:-1]) if depth > 1 else None
                if gnode.is_required:
                    for i, v in enumerate(vals_g):
                        if v is None and (parent_vals is None
                                          or parent_vals[i] is not None):
                            raise ValueError(
                                f"group {key!r} is required but object "
                                f"{i} has no value")
                elif key not in masks:
                    masks[key] = _np.fromiter(
                        (v is not None for v in vals_g), dtype=bool,
                        count=len(vals_g))
            parent_vals = resolve([n.name for n in chain[:-1]])
            vals = []
            lmask = _np.ones(len(objs), dtype=bool)
            for i, p in enumerate(parent_vals):
                v = None if p is None else getter(p, leaf.name)
                if v is None:
                    if p is not None and leaf.is_required:
                        raise ValueError(
                            f"column {leaf.flat_name!r} is required but "
                            f"object {i} has no value")
                    lmask[i] = False
                else:
                    vals.append(_encode_leaf(v, leaf))
            columns[leaf.flat_name] = vals
            if not leaf.is_required:
                masks[leaf.flat_name] = lmask
            continue
        name = leaf.name
        vals = []
        mask = None
        for i, o in enumerate(objs):
            v = getter(o, name)
            if v is None:
                if not leaf.max_def_level:
                    raise ValueError(
                        f"column {name!r} is required but object {i} "
                        "has no value")
                if mask is None:
                    mask = _np.ones(len(objs), dtype=bool)
                mask[i] = False
            else:
                vals.append(_encode_leaf(v, leaf))
        columns[name] = vals
        if mask is not None:
            masks[name] = mask
    return columns, masks, offsets, element_masks


def objects_from_columns(columns, cls, schema, n_rows=None) -> list:
    """Bulk inverse of :func:`objects_to_columns`: the
    ``{name: ChunkData}`` output of ``FileReader.read_row_group_arrays``
    -> ``list[cls]``, with the same leaf conversions as
    :func:`from_row` (strings, date/time/timestamp units, UUID) —
    but no per-row record assembly.  Flat, STRUCT (nested dataclass
    fields), MAP (dict fields), LIST-of-primitive, and LIST-of-struct
    columns are supported.  ``n_rows``
    is required when no dataclass field matches a file column (there
    is then no column to infer the row count from)."""
    if not dataclasses.is_dataclass(cls):
        raise TypeError(f"{cls!r} is not a dataclass")
    list_leaves = {}
    struct_tops = set()
    map_tops = {}
    struct_list_tops = {}
    for leaf in schema.leaves:
        if len(leaf.path) == 1 and not leaf.max_rep_level:
            continue
        if not leaf.max_rep_level:
            struct_tops.add(leaf.path[0])
            continue
        top = _child_named(schema.root, leaf.path[0])
        if (top is not None and _is_map_group(top)
                and leaf.max_rep_level == 1
                and top.children[0].children[0].is_leaf
                and top.children[0].children[1].is_leaf
                # key must be required: _maps_from_chunks pairs one key
                # per slot; an optional key leaf would misalign streams
                and top.children[0].children[0].is_required):
            kv = top.children[0]
            map_tops[top.name] = (top, kv.children[0], kv.children[1])
            continue
        top = _bulk_list_leaf(schema, leaf)
        if top is None:
            sl = _bulk_struct_list(schema, leaf.path[0])
            if sl is not None:
                struct_list_tops[sl[0].name] = sl
                continue
            raise ValueError(
                f"objects_from_columns supports flat schemas, STRUCT, "
                f"MAP, LIST-of-primitive, and LIST-of-struct columns; "
                f"{leaf.flat_name!r} is nested (use iteration/scan)")
        list_leaves[top.name] = leaf
    field_cols: list = []
    for f, hint in _dc_fields(cls):
        name = field_name(f)
        node = _child_named(schema.root, name)
        if node is not None and name in struct_list_tops:
            top, rep_node, elem, elem_leaves = struct_list_tops[name]
            cds = {lf.name: columns.get(lf.flat_name)
                   for lf in elem_leaves}
            if all(cd is None for cd in cds.values()):
                field_cols.append((f.name, None))
                continue
            hint_u = _unwrap_optional(hint)[0] if hint is not None \
                else None
            args = typing.get_args(hint_u) if hint_u else ()
            ehint = _unwrap_optional(args[0])[0] if args else None
            out = _struct_lists_from_chunks(
                cds, top, rep_node, elem, elem_leaves, ehint)
            if n_rows is None:
                n_rows = len(out)
            elif n_rows != len(out):
                raise ValueError(
                    f"column {name!r} has {len(out)} rows, "
                    f"expected {n_rows}")
            field_cols.append((f.name, out))
            continue
        if node is not None and name in map_tops:
            top, key_leaf, val_leaf = map_tops[name]
            cd_k = columns.get(key_leaf.flat_name)
            cd_v = columns.get(val_leaf.flat_name)
            if cd_k is None or cd_v is None:
                field_cols.append((f.name, None))
                continue
            hint_u = _unwrap_optional(hint)[0] if hint is not None \
                else None
            args = typing.get_args(hint_u) if hint_u else ()
            kh = _unwrap_optional(args[0])[0] if args else None
            vh = (_unwrap_optional(args[1])[0]
                  if len(args) > 1 else None)
            out = _maps_from_chunks(cd_k, cd_v, top, key_leaf,
                                    val_leaf, kh, vh)
            if n_rows is None:
                n_rows = len(out)
            elif n_rows != len(out):
                raise ValueError(
                    f"column {name!r} has {len(out)} rows, "
                    f"expected {n_rows}")
            field_cols.append((f.name, out))
            continue
        if node is not None and name in struct_tops:
            hint_u = _unwrap_optional(hint)[0] if hint is not None else None
            out = _structs_from_chunks(columns, node, hint_u)
            if out is None:
                field_cols.append((f.name, None))
                continue
            if n_rows is None:
                n_rows = len(out)
            elif n_rows != len(out):
                raise ValueError(
                    f"column {name!r} has {len(out)} rows, "
                    f"expected {n_rows}")
            field_cols.append((f.name, out))
            continue
        if node is not None and name in list_leaves:
            leaf = list_leaves[name]
            cd = columns.get(leaf.flat_name)
            if cd is None:
                field_cols.append((f.name, None))
                continue
            hint_u = _unwrap_optional(hint)[0] if hint is not None else None
            ehint = (typing.get_args(hint_u)[0]
                     if hint_u and typing.get_args(hint_u) else None)
            # list[Optional[T]]: the row path decodes against T
            ehint = _unwrap_optional(ehint)[0] if ehint is not None else None
            out = _lists_from_chunk(cd, node, leaf, ehint)
            if n_rows is None:
                n_rows = len(out)
            elif n_rows != len(out):
                raise ValueError(
                    f"column {name!r} has {len(out)} rows, "
                    f"expected {n_rows}")
            field_cols.append((f.name, out))
            continue
        if node is None or name not in columns:
            field_cols.append((f.name, None))
            continue
        cd = columns[name]
        hint_u = _unwrap_optional(hint)[0] if hint is not None else None
        # the row path's materialization (io/store.py): unsigned
        # re-views, FLBA/INT96 -> bytes, np scalars -> Python values
        out = _leaf_col_from_chunk(cd, node, hint_u)
        if n_rows is None:
            n_rows = len(out)
        elif n_rows != len(out):
            raise ValueError(
                f"column {name!r} has {len(out)} rows, expected {n_rows}")
        field_cols.append((f.name, out))
    n_rows = n_rows or 0
    return [
        cls(**{attr: (col[i] if col is not None else None)
               for attr, col in field_cols})
        for i in range(n_rows)
    ]


def _leaf_col_from_chunk(cd, node: SchemaNode, hint) -> list:
    """Per-row Python values (None for nulls) from one non-repeated
    leaf's ChunkData, with the row path's leaf conversions."""
    from ..io.values import handler_for

    vals = handler_for(node.element).to_pylist(cd.values)
    # one C-level conversion: iterating the np array would box an
    # np.int32 per row in this bulk path
    dl = cd.def_levels.tolist()
    md = node.max_def_level
    out = []
    k = 0
    for lvl in dl:
        if md and lvl != md:
            out.append(None)
        else:
            out.append(_decode_leaf(vals[k], node, hint))
            k += 1
    return out


def _structs_from_chunks(columns, node: SchemaNode, hint):
    """Reconstruct per-row nested objects for one STRUCT subtree from
    leaf ChunkData — presence at each group level comes from the def
    levels the row path would walk one record at a time.  Returns
    ``list[instance | None]``, or None when projection dropped every
    leaf of the subtree."""
    if hint is None or not dataclasses.is_dataclass(hint):
        raise ValueError(
            f"STRUCT column {node.name!r} needs a dataclass field type "
            "in the bulk path (use iteration/scan for dict rows)")
    import numpy as _np

    cd0 = None
    stack = [node]
    while stack and cd0 is None:
        c = stack.pop()
        if c.is_leaf:
            cd0 = columns.get(c.flat_name)
        else:
            stack.extend(c.children)
    if cd0 is None:
        return None
    gd = node.max_def_level
    dl0 = _np.asarray(cd0.def_levels)
    n = len(dl0)
    present = (dl0 >= gd) if gd else _np.ones(n, dtype=bool)
    child_cols: list = []
    for f, h in _dc_fields(hint):
        child = _child_named(node, field_name(f))
        if child is None:
            child_cols.append((f.name, None))
            continue
        h_u = _unwrap_optional(h)[0] if h is not None else None
        if child.is_leaf and not child.is_repeated:
            cd = columns.get(child.flat_name)
            child_cols.append(
                (f.name,
                 None if cd is None
                 else _leaf_col_from_chunk(cd, child, h_u)))
        elif (not child.is_leaf and not child.is_repeated
              and not _is_list_group(child) and not _is_map_group(child)):
            child_cols.append(
                (f.name, _structs_from_chunks(columns, child, h_u)))
        else:
            raise ValueError(
                f"{child.flat_name!r}: lists/maps inside STRUCT columns "
                "are not supported by the bulk path (use iteration/scan)")
    return [
        hint(**{attr: (col[i] if col is not None else None)
                for attr, col in child_cols})
        if present[i] else None
        for i in range(n)
    ]


def _struct_lists_from_chunks(cds, top: SchemaNode, rep_node: SchemaNode,
                              elem: SchemaNode, elem_leaves, ehint):
    """Reconstruct per-row ``list[dataclass]`` values from the element
    leaves' ChunkData — all leaf streams share rep levels and slot
    structure; the first available stream drives the walk and each
    leaf's own def levels say whether its field is set per slot."""
    if ehint is None or not dataclasses.is_dataclass(ehint):
        raise ValueError(
            f"LIST-of-struct column {top.name!r} needs a list[dataclass] "
            "field type in the bulk path (use iteration/scan)")
    from ..io.values import handler_for

    drive_name, drive = next(
        (n, cd) for n, cd in cds.items() if cd is not None)
    rep = drive.rep_levels.tolist()
    streams = {}
    for lf in elem_leaves:
        cd = cds[lf.name]
        if cd is None:
            continue
        streams[lf.name] = (
            handler_for(lf.element).to_pylist(cd.values),
            cd.def_levels.tolist(), lf, [0])
    drive_dl = streams[drive_name][1]
    # dataclass attr per leaf name
    attr_of = {field_name(f): f.name for f in dataclasses.fields(ehint)}
    hints = {field_name(f): _unwrap_optional(h)[0] if h is not None
             else None for f, h in _dc_fields(ehint)}
    # projection dropped these leaves: their attrs fill with None,
    # matching the flat path's behavior for unmatched columns
    absent = [attr_of[lf.name] for lf in elem_leaves
              if lf.name not in streams and lf.name in attr_of]
    slot_def = rep_node.max_def_level  # list holds an entry at >= this
    elem_def = elem.max_def_level      # ... a non-null element at >= this
    row_nullable = top is not rep_node and not top.is_required
    def_t = top.max_def_level
    out = []
    _no_row = object()
    row = _no_row
    for slot, (r, d) in enumerate(zip(rep, drive_dl)):
        if r == 0:
            if row is not _no_row:
                out.append(row)
            row = []
        if d >= slot_def:
            if d < elem_def:
                row.append(None)  # null element (optional elem group)
            else:
                kwargs = {attr: None for attr in absent}
                for lname, (vals, dl, lf, k) in streams.items():
                    attr = attr_of.get(lname)
                    if dl[slot] == lf.max_def_level:
                        v = _decode_leaf(vals[k[0]], lf,
                                         hints.get(lname))
                        k[0] += 1
                        if attr is not None:
                            kwargs[attr] = v
                    elif attr is not None:
                        kwargs[attr] = None
                row.append(ehint(**kwargs))
        elif row_nullable and d < def_t:
            row = None
    if row is not _no_row:
        out.append(row)
    return out


def _maps_from_chunks(cd_k, cd_v, top: SchemaNode, key_leaf: SchemaNode,
                      val_leaf: SchemaNode, khint, vhint):
    """Reconstruct per-row Python dicts from a MAP column's key and
    value ChunkData — the two leaf streams share rep levels and slot
    structure (Dremel with one repeated level), so one walk over the
    key stream drives both."""
    from ..io.values import handler_for

    keys = handler_for(key_leaf.element).to_pylist(cd_k.values)
    vals = handler_for(val_leaf.element).to_pylist(cd_v.values)
    rep = cd_k.rep_levels.tolist()
    dl = cd_k.def_levels.tolist()
    vdl = cd_v.def_levels.tolist()
    kv = top.children[0]
    def_m = kv.max_def_level       # slot holds an entry at def >= this
    def_v = val_leaf.max_def_level  # ... with a non-null value at this
    row_nullable = not top.is_required
    def_t = top.max_def_level
    out = []
    _no_row = object()
    row = _no_row
    ki = vi = 0
    for slot, (r, d) in enumerate(zip(rep, dl)):
        if r == 0:
            if row is not _no_row:
                out.append(row)
            row = {}
        if d >= def_m:
            k = _decode_leaf(keys[ki], key_leaf, khint)
            ki += 1
            if vdl[slot] == def_v:
                row[k] = _decode_leaf(vals[vi], val_leaf, vhint)
                vi += 1
            else:
                row[k] = None
        elif row_nullable and d < def_t:
            row = None
    if row is not _no_row:
        out.append(row)
    return out


def _lists_from_chunk(cd, top: SchemaNode, leaf: SchemaNode, ehint):
    """Reconstruct per-row Python lists from one repeated leaf's
    ChunkData — the bulk inverse of the single-level list shredding
    (Dremel with one repeated level: ``rep==0`` starts a row; ``def``
    distinguishes null row / empty list / null element / element)."""
    from ..io.values import handler_for

    vals = handler_for(leaf.element).to_pylist(cd.values)
    rep = cd.rep_levels.tolist()
    dl = cd.def_levels.tolist()
    # the repeated node on the path (the leaf itself for bare/2-level)
    mid = top if top is leaf else top.children[0]
    def_m = mid.max_def_level      # slot holds an element at def >= this
    def_l = leaf.max_def_level     # ... a non-null element at exactly this
    row_nullable = top is not leaf and not top.is_required
    def_t = top.max_def_level      # row defined (possibly empty) at >= this
    out = []
    _no_row = object()
    row = _no_row
    k = 0
    for r, d in zip(rep, dl):
        if r == 0:
            if row is not _no_row:
                out.append(row)
            row = []
        if d >= def_m:
            if d == def_l:
                row.append(_decode_leaf(vals[k], leaf, ehint))
                k += 1
            else:
                row.append(None)
        elif row_nullable and d < def_t:
            row = None
    if row is not _no_row:
        out.append(row)
    return out


def _get_member(obj, name: str):
    if isinstance(obj, dict):
        return obj.get(name)
    if dataclasses.is_dataclass(obj):
        for f in dataclasses.fields(obj):
            if field_name(f) == name:
                return getattr(obj, f.name)
        return None
    raise TypeError(f"cannot marshal {type(obj).__name__}: expected a "
                    "dataclass or mapping")


def _has_member(obj, name: str) -> bool:
    if isinstance(obj, dict):
        return name in obj
    return any(field_name(f) == name for f in dataclasses.fields(obj))


def _encode(v, node: SchemaNode):
    if v is None:
        return None
    if not node.is_leaf:
        if _is_list_group(node):
            # Use the schema's actual names — 3-level compliant files say
            # list/element, legacy layouts (bag/item, 2-level) vary.
            mid = node.children[0]
            if mid.is_leaf:  # 2-level legacy: repeated leaf IS the element
                return {mid.name: [_encode_leaf(e, mid) for e in v]}
            if len(mid.children) == 1:
                elem = mid.children[0]
                return {mid.name: [
                    {} if e is None else {elem.name: _encode(e, elem)}
                    for e in v
                ]}
            # 2-level legacy: repeated group is itself the element struct
            return {mid.name: [_group_dict(e, mid) for e in v]}
        if _is_map_group(node):
            kv = node.children[0]
            knode = kv.children[0]
            vnode = kv.children[1]
            return {kv.name: [
                {knode.name: _encode(k, knode),
                 vnode.name: _encode(val, vnode)}
                for k, val in v.items()
            ]}
        if node.is_repeated:
            return [_group_dict(e, node) for e in v]
        return _group_dict(v, node)
    if node.is_repeated:
        return [_encode_leaf(e, node) for e in v]
    return _encode_leaf(v, node)


def _group_dict(v, node: SchemaNode) -> dict:
    return {
        child.name: _encode(_get_member(v, child.name), child)
        for child in node.children
        if _has_member(v, child.name)
    }


def _encode_leaf(v, node: SchemaNode):
    el = node.element
    logical, unit = _logical(node)
    if el.type == Type.INT96:
        if isinstance(v, datetime.datetime):
            return datetime_to_int96(v)
        return v
    if isinstance(v, str):
        return v.encode("utf-8")
    if isinstance(v, uuid.UUID):
        if el.type_length not in (None, 16):
            raise ValueError("UUID requires fixed_len_byte_array(16)")
        return v.bytes
    if isinstance(v, Time) or isinstance(v, datetime.time):
        if isinstance(v, datetime.time):
            v = Time.from_datetime_time(v)
        if logical != "TIME":
            raise TypeError(f"{node.flat_name!r}: Time value on a "
                            "non-TIME column")
        if unit == "MILLIS":
            return v.milliseconds()
        if unit == "MICROS":
            return v.microseconds()
        return v.nanoseconds()
    if isinstance(v, datetime.datetime):  # before date: datetime is a date
        if logical == "TIMESTAMP":
            if v.tzinfo is not None:
                v = v.astimezone(datetime.timezone.utc).replace(tzinfo=None)
            delta = v - datetime.datetime(1970, 1, 1)
            us = (delta.days * 86_400_000_000
                  + delta.seconds * 1_000_000 + delta.microseconds)
            if unit == "MILLIS":
                return us // 1000
            if unit == "MICROS":
                return us
            return us * 1000
        raise TypeError(f"{node.flat_name!r}: datetime value on a "
                        "non-TIMESTAMP column")
    if isinstance(v, datetime.date):
        if logical != "DATE":
            raise TypeError(f"{node.flat_name!r}: date value on a "
                            "non-DATE column")
        return (v - datetime.date(1970, 1, 1)).days
    return v


# ----------------------------------------------------------------------
# Row -> object (unmarshalling; ``floor/reader.go fillValue``)
# ----------------------------------------------------------------------

def from_row(row: dict, cls, schema):
    """Build ``cls`` (a dataclass) from a low-level assembled row."""
    if not dataclasses.is_dataclass(cls):
        raise TypeError(f"{cls!r} is not a dataclass")
    kwargs = {}
    for f, hint in _dc_fields(cls):
        name = field_name(f)
        node = _child_named(schema.root, name)
        raw = row.get(name)
        if node is None:
            kwargs[f.name] = raw
            continue
        kwargs[f.name] = _decode(raw, node, hint)
    return cls(**kwargs)


def decode_row(row: dict, schema) -> dict:
    """Logical-type-aware plain-dict view of a row (str/date/datetime/
    Time/UUID restored, list/map conventions flattened)."""
    return {
        child.name: _decode(row.get(child.name), child, None)
        for child in schema.root.children
        if child.name in row
    }


def _child_named(node: SchemaNode, name: str) -> SchemaNode | None:
    for c in node.children:
        if c.name == name:
            return c
    return None


def _decode(raw, node: SchemaNode, hint):
    if raw is None:
        return None
    hint, _ = _unwrap_optional(hint) if hint is not None else (None, False)
    if not node.is_leaf:
        if _is_list_group(node):
            mid = node.children[0]
            inner = (typing.get_args(hint)[0]
                     if hint and typing.get_args(hint) else None)
            entries = raw.get(mid.name, [])
            if mid.is_leaf:  # 2-level legacy: repeated leaf
                return [_decode_leaf(e, mid, inner) for e in entries]
            if len(mid.children) == 1:
                elem = mid.children[0]
                return [
                    _decode(e.get(elem.name), elem, inner)
                    for e in entries
                ]
            return [_decode_group(e, mid, inner) for e in entries]
        if _is_map_group(node):
            kv = node.children[0]
            knode, vnode = kv.children[0], kv.children[1]
            args = typing.get_args(hint) if hint else ()
            kh = args[0] if args else None
            vh = args[1] if len(args) > 1 else None
            return {
                _decode(e.get(knode.name), knode, kh):
                    _decode(e.get(vnode.name), vnode, vh)
                for e in raw.get(kv.name, [])
            }
        if node.is_repeated:
            inner = (typing.get_args(hint)[0]
                     if hint and typing.get_args(hint) else None)
            return [_decode_group(e, node, inner) for e in raw]
        return _decode_group(raw, node, hint)
    if node.is_repeated:
        inner = (typing.get_args(hint)[0]
                 if hint and typing.get_args(hint) else None)
        return [_decode_leaf(e, node, inner) for e in raw]
    return _decode_leaf(raw, node, hint)


def _decode_group(raw: dict, node: SchemaNode, hint):
    if hint is not None and dataclasses.is_dataclass(hint):
        kwargs = {}
        for f, h in _dc_fields(hint):
            child = _child_named(node, field_name(f))
            if child is None:
                kwargs[f.name] = raw.get(field_name(f))
            else:
                kwargs[f.name] = _decode(raw.get(child.name), child, h)
        return hint(**kwargs)
    return {
        c.name: _decode(raw.get(c.name), c, None)
        for c in node.children if c.name in raw
    }


def _decode_leaf(raw, node: SchemaNode, hint):
    el = node.element
    logical, unit = _logical(node)
    if el.type == Type.INT96 and (hint is datetime.datetime or hint is None):
        return int96_to_datetime(raw)
    if logical in ("STRING", "ENUM", "JSON") and (hint is not bytes):
        return raw.decode("utf-8") if isinstance(raw, bytes) else raw
    if logical == "DATE" and hint is not int:
        return datetime.date(1970, 1, 1) + datetime.timedelta(days=raw)
    if logical == "TIMESTAMP" and hint is not int:
        scale = {"MILLIS": 1000, "MICROS": 1, None: 1}.get(unit)
        if scale is None:  # NANOS
            us, rem = divmod(raw, 1000)
        else:
            us, rem = raw * scale, 0
        del rem
        return (datetime.datetime(1970, 1, 1)
                + datetime.timedelta(microseconds=us))
    if logical == "TIME" and hint is not int:
        t = {"MILLIS": time_from_milliseconds,
             "MICROS": time_from_microseconds}.get(unit,
                                                   time_from_nanoseconds)(raw)
        return t.to_datetime_time() if hint is datetime.time else t
    if logical == "UUID" and hint is not bytes:
        return uuid.UUID(bytes=raw)
    if hint is str and isinstance(raw, bytes):
        return raw.decode("utf-8")
    return raw
