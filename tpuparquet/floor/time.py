"""Time-of-day type for TIME logical columns.

Parity with ``floor.Time`` (``/root/reference/floor/time.go``):
nanoseconds since midnight plus a UTC-adjusted flag, with unit
conversions used by the writer/reader for TIME(MILLIS|MICROS|NANOS).
"""

from __future__ import annotations

import datetime

__all__ = [
    "Time",
    "time_from_milliseconds",
    "time_from_microseconds",
    "time_from_nanoseconds",
]

_NS_PER_SEC = 1_000_000_000
_NS_PER_DAY = 86_400 * _NS_PER_SEC


class Time:
    """A time of day, independent of any date or timezone.

    ``Time(hours, minutes, seconds, nanoseconds)`` validates each
    component range (``floor/time.go:26-43``).
    """

    __slots__ = ("_ns", "utc")

    def __init__(self, hours: int = 0, minutes: int = 0, seconds: int = 0,
                 nanoseconds: int = 0, *, utc: bool = True):
        if not 0 <= hours < 24:
            raise ValueError(f"hours out of range: {hours}")
        if not 0 <= minutes < 60:
            raise ValueError(f"minutes out of range: {minutes}")
        if not 0 <= seconds < 60:
            raise ValueError(f"seconds out of range: {seconds}")
        if not 0 <= nanoseconds < _NS_PER_SEC:
            raise ValueError(f"nanoseconds out of range: {nanoseconds}")
        self._ns = ((hours * 3600 + minutes * 60 + seconds) * _NS_PER_SEC
                    + nanoseconds)
        self.utc = utc

    # -- accessors ---------------------------------------------------------

    @property
    def hour(self) -> int:
        return self._ns // (3600 * _NS_PER_SEC)

    @property
    def minute(self) -> int:
        return self._ns // (60 * _NS_PER_SEC) % 60

    @property
    def second(self) -> int:
        return self._ns // _NS_PER_SEC % 60

    @property
    def nanosecond(self) -> int:
        return self._ns % _NS_PER_SEC

    def milliseconds(self) -> int:
        """Since midnight — the TIME_MILLIS int32 column value."""
        return self._ns // 1_000_000

    def microseconds(self) -> int:
        """Since midnight — the TIME_MICROS int64 column value."""
        return self._ns // 1_000

    def nanoseconds(self) -> int:
        """Since midnight — the TIME(NANOS) int64 column value."""
        return self._ns

    # -- conversions -------------------------------------------------------

    def to_datetime_time(self) -> datetime.time:
        return datetime.time(self.hour, self.minute, self.second,
                             self.nanosecond // 1000)

    @classmethod
    def from_datetime_time(cls, t: datetime.time, *, utc: bool = True):
        return cls(t.hour, t.minute, t.second, t.microsecond * 1000, utc=utc)

    def utc_adjusted(self, utc: bool = True) -> "Time":
        out = Time.__new__(Time)
        out._ns = self._ns
        out.utc = utc
        return out

    # -- dunder ------------------------------------------------------------

    def __eq__(self, other) -> bool:
        return isinstance(other, Time) and self._ns == other._ns

    def __hash__(self) -> int:
        return hash(("floor.Time", self._ns))

    def __repr__(self) -> str:
        return (f"Time({self.hour:02d}:{self.minute:02d}:{self.second:02d}"
                f".{self.nanosecond:09d}, utc={self.utc})")


def _from_ns(ns: int, utc: bool) -> Time:
    if not 0 <= ns < _NS_PER_DAY:
        raise ValueError(f"nanoseconds since midnight out of range: {ns}")
    out = Time.__new__(Time)
    out._ns = ns
    out.utc = utc
    return out


def time_from_milliseconds(ms: int, *, utc: bool = True) -> Time:
    return _from_ns(ms * 1_000_000, utc)


def time_from_microseconds(us: int, *, utc: bool = True) -> Time:
    return _from_ns(us * 1_000, utc)


def time_from_nanoseconds(ns: int, *, utc: bool = True) -> Time:
    return _from_ns(ns, utc)
