"""floor.Writer: write Python objects (dataclasses) to Parquet.

Parity with ``floor.NewFileWriter``/``floor.Writer``
(``/root/reference/floor/writer.go:19-67``): a thin wrapper over the
low-level :class:`~tpuparquet.io.FileWriter` that marshals objects via a
``marshal_parquet`` hook when present, else dataclass reflection.
"""

from __future__ import annotations

import dataclasses

from ..io.writer import FileWriter
from .reflect import objects_to_columns, schema_of, to_row

__all__ = ["Writer", "new_file_writer"]


class Writer:
    """Wraps a low-level :class:`FileWriter` (``floor.NewWriter``)."""

    def __init__(self, fw: FileWriter, _owned_file=None):
        self._fw = fw
        self._owned_file = _owned_file

    @property
    def file_writer(self) -> FileWriter:
        return self._fw

    def write(self, obj) -> None:
        """Write one object as a row.

        Marshalling order (``floor/writer.go:51-67``): an object with a
        ``marshal_parquet() -> dict`` method supplies the low-level row
        itself; otherwise dataclass/mapping reflection against the
        schema converts field values (strings, date/time/timestamp,
        UUID, LIST/MAP conventions).
        """
        m = getattr(obj, "marshal_parquet", None)
        if callable(m):
            row = m()
        else:
            row = to_row(obj, self._fw.schema)
        self._fw.add_data(row)

    def write_many(self, objs) -> None:
        for o in objs:
            self.write(o)

    def write_columns(self, objs, **flush_kw) -> None:
        """Bulk columnar write of objects: one row group per call, same
        decoded contents as :meth:`write_many` but without per-row dict
        building and shredding.  Flat fields, nested-dataclass STRUCT
        fields, dict MAP fields (primitive keys/values), and
        list-of-primitive fields (``list[int]``, ``list[str]``, ...)
        are supported; objects with a ``marshal_parquet`` hook, lists
        of structs, and maps with struct values need the row path
        (``write``/``write_many``)."""
        objs = list(objs)
        if not objs:
            return  # match write_many([]): no empty row group
        for o in objs:
            if callable(getattr(o, "marshal_parquet", None)):
                # the hook supplies custom rows that reflection would
                # silently diverge from — refuse loudly
                raise TypeError(
                    f"{type(o).__name__} defines marshal_parquet; the "
                    "columnar path reflects raw attributes — use "
                    "write/write_many")
        cols, masks, offs, emasks = objects_to_columns(
            objs, self._fw.schema)
        self._fw.write_columns(
            cols, masks=masks or None, offsets=offs or None,
            element_masks=emasks or None, **flush_kw)

    def flush_row_group(self, **kw) -> None:
        self._fw.flush_row_group(**kw)

    def close(self) -> None:
        try:
            self._fw.close()
        finally:
            if self._owned_file is not None:
                self._owned_file.close()
                self._owned_file = None

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        self.close()


def new_file_writer(path, schema=None, *, cls=None, **options) -> Writer:
    """Open ``path`` for object writing (``floor.NewFileWriter``).

    ``schema`` may be any form :class:`FileWriter` accepts; or pass
    ``cls`` (a dataclass) to derive the schema via :func:`schema_of`.
    """
    if schema is None:
        if cls is None or not dataclasses.is_dataclass(cls):
            raise TypeError("new_file_writer needs a schema or a "
                            "dataclass cls to derive one from")
        schema = schema_of(cls)
    if isinstance(path, str):
        f = open(path, "wb")
        try:
            return Writer(FileWriter(f, schema, **options), _owned_file=f)
        except BaseException:
            f.close()
            raise
    return Writer(FileWriter(path, schema, **options))
