"""Shared LEB128 varint + zigzag primitives.

Single home for the wire-level integer coding used by both the thrift
compact protocol (:mod:`tpuparquet.format.compact`) and the data codecs
(hybrid RLE, DELTA_BINARY_PACKED headers).
"""

from __future__ import annotations

__all__ = [
    "read_uvarint",
    "write_uvarint",
    "zigzag_encode",
    "zigzag_decode",
    "read_zigzag",
    "write_zigzag",
]


def read_uvarint(buf, pos: int) -> tuple[int, int]:
    """Return (value, new_pos); raises ValueError on truncation/overlength."""
    result = 0
    shift = 0
    n = len(buf)
    while True:
        if pos >= n:
            raise ValueError("truncated uvarint")
        b = int(buf[pos])  # int(): numpy buffers yield uint8 scalars
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7
        if shift > 70:
            raise ValueError("uvarint too long")


def write_uvarint(out: bytearray, n: int) -> None:
    if n < 0:
        raise ValueError("uvarint must be non-negative")
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return


def zigzag_encode(n: int) -> int:
    return (n << 1) ^ (n >> 63) if n < 0 else n << 1


def zigzag_decode(u: int) -> int:
    return (u >> 1) ^ -(u & 1)


def read_zigzag(buf, pos: int) -> tuple[int, int]:
    u, pos = read_uvarint(buf, pos)
    return zigzag_decode(u), pos


def write_zigzag(out: bytearray, n: int) -> None:
    write_uvarint(out, zigzag_encode(n))
