"""Byte-range sources: the pluggable object-store read abstraction.

Production scan fleets read S3/GCS-style object stores, not local
filesystems.  This module gives the reader a narrow, swappable contract
for that regime — :class:`ByteRangeSource` with ``get_range``/
``get_ranges``/``size`` — plus two concrete backends:

* :class:`LocalByteRangeSource` (``file://``) — a plain local file
  served through the range contract, so the remote-tuned read path
  (coalescing, tiered caching, per-request retry) can be exercised and
  parity-tested against the classic ``open()`` path byte-for-byte.

* :class:`EmulatedStoreSource` (``emu://``) — a local-dir *emulator*
  that models object-store failure behavior deterministically:
  per-request latency, HTTP-429-style throttling, connection resets,
  slow replicas, and truncated/short range responses, each driven by a
  per-instance request counter (no wall-clock or RNG), so a fault plan
  replays identically run to run.

* :class:`HttpByteRangeSource` (``http://`` / ``https://``) — a real
  HTTP range client on the stdlib: conditional Range GETs
  (``If-Match`` keyed on the served ETag, so a concurrent object
  rewrite surfaces as 412 → cache invalidation + retry, never stale
  bytes), keep-alive connection reuse through a bounded per-source
  pool (``TPQ_HTTP_CONNS``), per-request socket deadlines
  (``TPQ_HTTP_TIMEOUT_S``), and classification of
  416/412/429/5xx/short-body/reset into the existing error taxonomy —
  so retry/backoff (``Retry-After``-aware), hedged mirrors, failover
  and quarantine all compose unchanged.  ``tools/httpfault.py`` is
  its deterministic in-repo test server.

Every range read also traverses the registered fault sites
``io.remote.open`` / ``io.remote.throttle`` / ``io.remote.range``, so
the :mod:`tpuparquet.faults` harness can inject the same failure
taxonomy into *any* backend, not just the emulator.

Short responses are never returned to callers: a range that comes back
with fewer bytes than requested raises :class:`TransientIOError` (the
client-detects-and-refetches model), so truncation can never silently
corrupt a decode.

:func:`open_byte_source` resolves source strings: explicit URIs
(``file://``, ``emu://``) always resolve; bare paths resolve only when
``TPQ_SOURCE`` names a scheme — and keep their plain path as the
display name, so cursors, quarantine records, and fault-plan ``file=``
matches stay stable when a whole suite is rerouted through the
emulator.

:func:`coalesce_ranges` is the remote-tuned planner primitive: merge
adjacent chunk reads under a gap threshold (``TPQ_RANGE_COALESCE_GAP``)
— the inverse of the seek-happy local path, where every extra request
is a round trip.
"""

from __future__ import annotations

import http.client
import os
import threading
import time
import urllib.parse

from ..errors import TransientIOError
from ..faults import fault_point, filter_bytes, retry_transient
from ..obs import recorder as _flightrec

__all__ = [
    "ByteRangeSource",
    "LocalByteRangeSource",
    "EmulatedStoreSource",
    "HttpByteRangeSource",
    "RangeSourceFile",
    "coalesce_ranges",
    "coalesce_gap_default",
    "http_conns_default",
    "http_timeout_default",
    "open_byte_source",
    "parse_source_uri",
]

_SCHEMES = ("file", "emu", "http", "https")


def parse_source_uri(src):
    """``"emu:///data/f.parquet"`` -> ``("emu", "/data/f.parquet")``;
    ``None`` for a bare path; :class:`ValueError` for a scheme this
    build does not know (a typo'd scheme must fail loudly at open, not
    fall through to ``open()`` and produce ENOENT noise)."""
    if not isinstance(src, str):
        return None
    head, sep, rest = src.partition("://")
    if not sep:
        return None
    if head not in _SCHEMES:
        raise ValueError(f"unsupported source scheme {head!r} in {src!r} "
                         f"(known: {', '.join(_SCHEMES)})")
    return head, rest


def open_byte_source(src):
    """Resolve a source string to a :class:`ByteRangeSource`, or
    ``None`` when the classic local-``open()`` path should be used.

    Explicit ``scheme://`` URIs always resolve.  Bare paths resolve
    only when ``TPQ_SOURCE`` names a scheme (``file`` or ``emu``) —
    the reroute keeps the bare path as the source's display name so
    every path-keyed artifact (cursors, quarantine entries, fault-plan
    matches) is byte-identical to a local run.
    """
    parsed = parse_source_uri(src)
    if parsed is not None:
        scheme, path = parsed
        uri = src
    else:
        if not isinstance(src, str):
            return None
        scheme = os.environ.get("TPQ_SOURCE", "").strip().lower()
        if not scheme:
            return None
        if scheme not in _SCHEMES:
            raise ValueError(
                f"TPQ_SOURCE={scheme!r} is not a known scheme "
                f"(known: {', '.join(_SCHEMES)})")
        path = src
        uri = src  # bare path stays the display name (see docstring)
    if scheme in ("http", "https"):
        if parsed is not None:
            return HttpByteRangeSource(src, uri=uri)
        base = os.environ.get("TPQ_HTTP_BASE", "").strip()
        if not base:
            raise ValueError(
                "TPQ_SOURCE=http(s) reroutes bare paths and needs "
                "TPQ_HTTP_BASE (e.g. http://127.0.0.1:8080) to build "
                "the request URL")
        return HttpByteRangeSource(base.rstrip("/") + path, uri=uri)
    if scheme == "emu":
        return EmulatedStoreSource(path, uri=uri)
    return LocalByteRangeSource(path, uri=uri)


def coalesce_gap_default() -> int:
    """``TPQ_RANGE_COALESCE_GAP`` — merge two requested ranges into one
    fetch when the hole between them is at most this many bytes
    (default 256 KiB: on an object store a round trip costs far more
    than shipping a quarter-megabyte of dead bytes)."""
    v = os.environ.get("TPQ_RANGE_COALESCE_GAP")
    if not v:
        return 256 * 1024
    return max(0, int(v))


def coalesce_ranges(ranges, gap: int = 0):
    """Merge ``[(start, size), ...]`` into fetch spans under a gap
    threshold.

    Returns ``[(start, size, members), ...]`` where ``members`` lists
    the indices of the requested ranges served by that span.  Spans are
    disjoint and sorted, every requested byte is covered by exactly one
    span (overlapping requests are never double-fetched), and a
    requested range is always a contiguous slice of its span —
    ``data[rs - start : rs - start + rn]`` recovers it.
    """
    if gap < 0:
        raise ValueError(f"gap must be >= 0, got {gap}")
    order = sorted(range(len(ranges)),
                   key=lambda i: (ranges[i][0], ranges[i][1]))
    merged = []  # [start, end, [member indices]]
    for i in order:
        s, n = ranges[i]
        if s < 0 or n < 0:
            raise ValueError(f"bad range {(s, n)!r}")
        if merged and s <= merged[-1][1] + gap:
            m = merged[-1]
            m[1] = max(m[1], s + n)
            m[2].append(i)
        else:
            merged.append([s, s + n, [i]])
    return [(s, e - s, mem) for s, e, mem in merged]


class ByteRangeSource:
    """The object-store read contract: exact byte ranges by offset.

    Subclasses implement ``_read_raw(start, size)`` and set ``path``,
    ``uri``, ``_size`` and ``_etag`` in ``__init__``.  ``get_range``
    wraps every read with the registered remote fault sites and the
    short-response check; ``get_ranges`` is the multi-range batch hook
    (base implementation: sequential — a real S3/GCS backend would
    issue them concurrently; the reader's prefetch layer already
    parallelizes above this call).
    """

    scheme = "?"

    # -- subclass surface -------------------------------------------------
    def _read_raw(self, start: int, size: int) -> bytes:
        raise NotImplementedError

    def close(self) -> None:
        pass

    def reopen(self) -> "ByteRangeSource":
        """A fresh, independent source for the same object — used by
        handle un-poisoning and mirror opens."""
        raise NotImplementedError

    # -- contract ---------------------------------------------------------
    def size(self) -> int:
        return self._size

    def etag(self):
        """Cache identity: ``(path, size, mtime_ns)``.  Any rewrite of
        the object changes it, so stale cache entries can never serve a
        new file's reads."""
        return self._etag

    def get_range(self, start: int, size: int) -> bytes:
        """Exactly ``size`` bytes at ``start``.  A short response —
        injected, emulated, or real (EOF race with a concurrent
        truncate) — raises :class:`TransientIOError` so the retry
        ladder refetches; callers never see silently truncated data."""
        fault_point("io.remote.throttle", file=self.uri)
        fault_point("io.remote.range", file=self.uri,
                    start=start, size=size)
        data = self._read_raw(start, size)
        data = filter_bytes("io.remote.range", data, file=self.uri,
                            start=start, size=size)
        if len(data) != size:
            raise TransientIOError(
                f"short range response from {self.uri}: "
                f"{len(data)}/{size} bytes at offset {start}",
                file=self.uri)
        return data

    def get_ranges(self, ranges):
        """Batch fetch: ``[(start, size), ...] -> [bytes, ...]``."""
        return [self.get_range(s, n) for s, n in ranges]


class LocalByteRangeSource(ByteRangeSource):
    """``file://`` — a local file behind the range contract."""

    scheme = "file"

    def __init__(self, path: str, uri: str | None = None):
        self.path = path
        self.uri = uri if uri is not None else f"file://{path}"
        fault_point("io.remote.open", file=self.uri)
        self._f = open(path, "rb")
        try:
            self._lock = threading.Lock()  # serializes seek+read pairs
            self._closed = False
            st = os.fstat(self._f.fileno())
            self._size = st.st_size
            self._etag = (path, st.st_size, st.st_mtime_ns)
        except BaseException:
            # a failed __init__ returns no instance for anyone to
            # close: release the fd before the raise escapes
            self._f.close()
            raise

    def _read_raw(self, start: int, size: int) -> bytes:
        with self._lock:
            self._f.seek(start)
            return self._f.read(size)

    def close(self) -> None:
        with self._lock:
            if not self._closed:
                self._closed = True
                self._f.close()

    def reopen(self) -> "LocalByteRangeSource":
        return type(self)(self.path, uri=self.uri)


class EmulatedStoreSource(LocalByteRangeSource):
    """``emu://`` — object-store behavior modeled over a local file.

    Deterministic by construction: every fault fires on a per-instance
    request counter (throttle/reset/short on every Nth request), never
    on wall-clock or RNG, so a failing run replays exactly.  Knobs come
    from the constructor or their ``TPQ_EMU_*`` env defaults:

    * ``latency_ms`` / ``TPQ_EMU_LATENCY_MS`` — fixed per-request pause
      (the round-trip cost the coalescer exists to amortize).
    * ``throttle_every`` / ``TPQ_EMU_THROTTLE_EVERY`` — every Nth
      request fails like an HTTP 429 (:class:`TransientIOError`).
    * ``reset_every`` / ``TPQ_EMU_RESET_EVERY`` — every Nth request
      dies mid-flight (:class:`ConnectionResetError`).
    * ``short_every`` / ``TPQ_EMU_SHORT_EVERY`` — every Nth response
      returns half the requested bytes (detected upstream and retried).
    * ``slow_match`` + ``slow_ms`` / ``TPQ_EMU_SLOW_MATCH`` +
      ``TPQ_EMU_SLOW_MS`` — replicas whose path contains the substring
      pay an extra pause per request: the tail-latency replica the
      hedging machinery exists to route around.

    ``0`` / empty disables a knob.  Every injected fault is announced
    on the flight recorder (``emu_fault``) before it fires — no silent
    failures, per the no-silent-retries observability contract.
    """

    scheme = "emu"

    def __init__(self, path: str, uri: str | None = None, *,
                 latency_ms: float | None = None,
                 throttle_every: int | None = None,
                 reset_every: int | None = None,
                 short_every: int | None = None,
                 slow_match: str | None = None,
                 slow_ms: float | None = None):
        def _f(v, env, dflt):
            return float(os.environ.get(env) or dflt) if v is None else v

        def _i(v, env):
            return int(os.environ.get(env) or 0) if v is None else v

        self._latency_s = _f(latency_ms, "TPQ_EMU_LATENCY_MS", 0.0) / 1e3
        self._throttle_every = _i(throttle_every, "TPQ_EMU_THROTTLE_EVERY")
        self._reset_every = _i(reset_every, "TPQ_EMU_RESET_EVERY")
        self._short_every = _i(short_every, "TPQ_EMU_SHORT_EVERY")
        self._slow_match = (os.environ.get("TPQ_EMU_SLOW_MATCH", "")
                            if slow_match is None else slow_match)
        self._slow_s = _f(slow_ms, "TPQ_EMU_SLOW_MS", 50.0) / 1e3
        self._requests = 0  # guarded by _req_lock
        self._req_lock = threading.Lock()
        super().__init__(path, uri=uri if uri is not None
                         else f"emu://{path}")

    def _knobs(self) -> dict:
        return {
            "latency_ms": self._latency_s * 1e3,
            "throttle_every": self._throttle_every,
            "reset_every": self._reset_every,
            "short_every": self._short_every,
            "slow_match": self._slow_match,
            "slow_ms": self._slow_s * 1e3,
        }

    def reopen(self) -> "EmulatedStoreSource":
        return type(self)(self.path, uri=self.uri, **self._knobs())

    def _read_raw(self, start: int, size: int) -> bytes:
        with self._req_lock:
            self._requests += 1
            n = self._requests
        if self._latency_s > 0:
            time.sleep(self._latency_s)
        if self._slow_match and self._slow_match in self.path:
            time.sleep(self._slow_s)
        if self._throttle_every and n % self._throttle_every == 0:
            if _flightrec._active is not None:
                _flightrec.flight(
                    "emu_fault", site="io.remote.throttle",
                    fault="throttle", file=self.uri, request=n)
            raise TransientIOError(
                f"429 throttled (emulated, request {n}) on {self.uri}",
                file=self.uri)
        if self._reset_every and n % self._reset_every == 0:
            if _flightrec._active is not None:
                _flightrec.flight(
                    "emu_fault", site="io.remote.range", fault="reset",
                    file=self.uri, request=n)
            raise ConnectionResetError(
                f"connection reset (emulated, request {n}) on {self.uri}")
        data = super()._read_raw(start, size)
        if self._short_every and n % self._short_every == 0 and len(data) > 1:
            if _flightrec._active is not None:
                _flightrec.flight(
                    "emu_fault", site="io.remote.range", fault="short",
                    file=self.uri, request=n)
            return data[:len(data) // 2]
        return data


def http_conns_default() -> int:
    """``TPQ_HTTP_CONNS`` — bound on live keep-alive connections per
    source (default 4: enough for the prefetch pool to overlap spans
    without stampeding one origin host)."""
    v = os.environ.get("TPQ_HTTP_CONNS")
    return max(1, int(v)) if v else 4


def http_timeout_default() -> float:
    """``TPQ_HTTP_TIMEOUT_S`` — per-request socket deadline (connect
    and each read) on HTTP sources, default 30s.  A hung origin
    surfaces as a retryable :class:`TimeoutError`, never a stuck
    scan."""
    v = os.environ.get("TPQ_HTTP_TIMEOUT_S")
    return float(v) if v else 30.0


class _HttpConnPool:
    """Bounded keep-alive connection pool for one origin host.

    ``acquire`` hands out an idle connection or dials a new one while
    under the bound; past the bound it waits (bounded by the request
    timeout) for a release.  Network I/O always happens OUTSIDE the
    pool lock.  A connection that saw a protocol error or an
    unconsumed body is closed and discarded on release instead of
    being reused."""

    def __init__(self, host: str, port, tls: bool, timeout: float,
                 bound: int):
        self._host = host
        self._port = port
        self._tls = tls
        self._timeout = timeout
        self._bound = max(1, bound)
        self._cv = threading.Condition(threading.Lock())
        self._idle: list = []  # guarded by _cv
        self._total = 0        # guarded by _cv
        self._closed = False   # guarded by _cv

    def _connect(self):
        cls = (http.client.HTTPSConnection if self._tls
               else http.client.HTTPConnection)
        return cls(self._host, self._port, timeout=self._timeout)

    def acquire(self):
        deadline = time.monotonic() + self._timeout
        with self._cv:
            while True:
                if self._closed:
                    raise ValueError("connection pool is closed")
                if self._idle:
                    return self._idle.pop()
                if self._total < self._bound:
                    self._total += 1
                    break
                left = deadline - time.monotonic()
                if left <= 0 or not self._cv.wait(left):
                    raise TransientIOError(
                        f"connection pool exhausted: {self._bound} "
                        f"connections busy for {self._timeout:g}s",
                        file=self._host)
        try:
            return self._connect()
        except BaseException:
            with self._cv:
                self._total -= 1
                self._cv.notify()
            raise

    def release(self, conn, *, reusable: bool) -> None:
        with self._cv:
            if reusable and not self._closed:
                self._idle.append(conn)
                self._cv.notify()
                return
            self._total -= 1
            self._cv.notify()
        try:
            conn.close()
        except OSError:
            pass

    def close(self) -> None:
        with self._cv:
            self._closed = True
            drop, self._idle = self._idle, []
            self._total -= len(drop)
            self._cv.notify_all()
        for conn in drop:
            try:
                conn.close()
            except OSError:
                pass


class HttpByteRangeSource(ByteRangeSource):
    """``http://`` / ``https://`` — a real HTTP range client.

    Opens with a HEAD (size + served ``ETag``); every range read is a
    conditional GET (``Range`` + ``If-Match``), so a concurrent
    rewrite of the object surfaces as 412 — the handler refreshes the
    identity, invalidates both cache tiers for this source, and
    raises :class:`TransientIOError` for the retry ladder to refetch
    under the NEW identity; stale bytes can never serve a read.

    Status classification into the existing taxonomy (everything the
    scan stack above already knows how to absorb):

    * 206/200 — bytes (200 is sliced; a short slice trips the base
      class's short-response check).
    * 412/416 — identity/size stale → refresh + invalidate +
      :class:`TransientIOError`.
    * 429/503 — :class:`TransientIOError` carrying the parsed
      ``Retry-After`` hint (``retry_after_s``), which
      :func:`tpuparquet.faults.retry_transient` honors.
    * other 5xx — :class:`TransientIOError`.
    * 404 — :class:`FileNotFoundError`; 401/403 —
      :class:`PermissionError`; other 4xx — :class:`OSError`
      (permanent: quarantine, don't retry).
    * resets / remote disconnects propagate as
      :class:`ConnectionError` (transient); short/incomplete bodies
      return their partial bytes and trip the short-response check.
    """

    scheme = "http"

    def __init__(self, url: str, uri: str | None = None, *,
                 timeout_s: float | None = None,
                 conns: int | None = None):
        split = urllib.parse.urlsplit(url)
        if split.scheme not in ("http", "https") or not split.hostname:
            raise ValueError(f"not an http(s) URL: {url!r}")
        self._url = url
        self.uri = uri if uri is not None else url
        self.path = self.uri
        self.scheme = split.scheme
        self._target = split.path or "/"
        if split.query:
            self._target += "?" + split.query
        self._timeout = (timeout_s if timeout_s is not None
                         else http_timeout_default())
        self._conns = conns if conns is not None else http_conns_default()
        self._pool = _HttpConnPool(
            split.hostname, split.port, split.scheme == "https",
            self._timeout, self._conns)
        self._id_lock = threading.Lock()  # guards the etag identity
        self._closed = False
        fault_point("io.remote.open", file=self.uri)
        try:
            size, tag = retry_transient(self._head)
        except BaseException:
            self._pool.close()
            raise
        self._size = size
        self._etag_header = tag
        self._etag = (self.path, size, tag)

    # -- identity ---------------------------------------------------------
    def _head(self):
        """HEAD the object: (size, etag-header-or-empty)."""
        conn = self._pool.acquire()
        reusable = False
        try:
            conn.request("HEAD", self._target)
            resp = conn.getresponse()
            resp.read()
            if resp.status == 200:
                reusable = True
                n = resp.getheader("Content-Length")
                if n is None:
                    # protocol violation from origin/proxy: let the
                    # retry ladder take a few swings, then quarantine
                    raise TransientIOError(
                        f"HEAD {self.uri}: origin sent no "
                        f"Content-Length", file=self.uri)
                return int(n), (resp.getheader("ETag") or "").strip()
            raise self._status_error(resp, verb="HEAD")
        except (ConnectionError, TimeoutError):
            raise
        except http.client.HTTPException as e:
            raise TransientIOError(
                f"HEAD {self.uri}: {e!r}", file=self.uri) from e
        finally:
            self._pool.release(conn, reusable=reusable)

    def _refresh_identity(self) -> None:
        """Re-HEAD after a 412/416: adopt the new (size, etag) and
        drop every cached range for this source — all before the
        transient raise hands control to the retry ladder."""
        size, tag = retry_transient(self._head)
        with self._id_lock:
            self._size = size
            self._etag_header = tag
            self._etag = (self.path, size, tag)
        from .rangecache import invalidate_source_caches

        invalidate_source_caches(self.uri)

    def _status_error(self, resp, *, verb: str = "GET",
                      start: int | None = None) -> BaseException:
        """Map a non-2xx response to the error taxonomy (the caller
        raises); transient errors carry a ``retry_after_s`` hint when
        the origin sent one."""
        status = resp.status
        at = "" if start is None else f" at offset {start}"
        msg = f"{verb} {self.uri}{at}: HTTP {status}"
        if status in (429, 503) or status >= 500:
            err = TransientIOError(msg, file=self.uri)
            hint = _parse_retry_after(resp.getheader("Retry-After"))
            if hint is not None:
                err.retry_after_s = hint
            return err
        if status == 404:
            return FileNotFoundError(msg)
        if status in (401, 403):
            return PermissionError(msg)
        return OSError(msg)

    # -- reads ------------------------------------------------------------
    def _read_raw(self, start: int, size: int) -> bytes:
        conn = self._pool.acquire()
        reusable = False
        try:
            with self._id_lock:
                tag = self._etag_header
            headers = {"Range": f"bytes={start}-{start + size - 1}"}
            if tag:
                headers["If-Match"] = tag
            conn.request("GET", self._target, headers=headers)
            resp = conn.getresponse()
            short = False
            try:
                body = resp.read()
            except (http.client.IncompleteRead,) as e:
                body, short = e.partial, True
            if resp.status == 206:
                reusable = not short
                return body  # short bodies trip the base length check
            if resp.status == 200:
                reusable = not short
                return body[start:start + size]
            if resp.status in (412, 416):
                self._refresh_identity()
                what = ("object changed under us (etag mismatch)"
                        if resp.status == 412 else
                        "range not satisfiable (stale size)")
                raise TransientIOError(
                    f"GET {self.uri} at offset {start}: HTTP "
                    f"{resp.status} — {what}; identity refreshed, "
                    f"caches invalidated", file=self.uri)
            raise self._status_error(resp, start=start)
        except (ConnectionError, TimeoutError):
            raise  # already transient in the taxonomy
        except http.client.HTTPException as e:
            raise TransientIOError(
                f"GET {self.uri} at offset {start}: {e!r}",
                file=self.uri) from e
        finally:
            self._pool.release(conn, reusable=reusable)

    # -- lifecycle --------------------------------------------------------
    def close(self) -> None:
        if not self._closed:
            self._closed = True
            self._pool.close()

    def reopen(self) -> "HttpByteRangeSource":
        return type(self)(self._url, uri=self.uri,
                          timeout_s=self._timeout, conns=self._conns)


def _parse_retry_after(value):
    """``Retry-After`` header -> seconds (or None): delta-seconds or
    an HTTP-date, clamped to >= 0."""
    if not value:
        return None
    value = value.strip()
    try:
        return max(0.0, float(value))
    except ValueError:
        pass
    try:
        import email.utils

        when = email.utils.parsedate_to_datetime(value)
    except (TypeError, ValueError):
        return None
    if when is None:
        return None
    return max(0.0, when.timestamp() - time.time())


class RangeSourceFile:
    """Seekable file-object facade over a :class:`ByteRangeSource`.

    Lets the entire existing reader stack — footer framing, fingerprint
    hashing, salvage scans, hedged/deadline-bounded chunk reads via
    ``_IoHandle`` — run unchanged against a remote source: every
    ``seek``+``read`` pair becomes one exact range request.  Position
    state is per-facade; concurrency control stays where it already
    lives (the reader's handle lock).
    """

    def __init__(self, source: ByteRangeSource):
        self.source = source
        self.name = source.uri
        self._pos = 0

    def read(self, size: int = -1) -> bytes:
        end = self.source.size()
        if size is None or size < 0:
            size = max(0, end - self._pos)
        else:
            size = min(size, max(0, end - self._pos))
        if size == 0:
            return b""
        data = self.source.get_range(self._pos, size)
        self._pos += size
        return data

    def seek(self, offset: int, whence: int = os.SEEK_SET) -> int:
        if whence == os.SEEK_SET:
            self._pos = offset
        elif whence == os.SEEK_CUR:
            self._pos += offset
        elif whence == os.SEEK_END:
            self._pos = self.source.size() + offset
        else:
            raise ValueError(f"bad whence {whence}")
        return self._pos

    def tell(self) -> int:
        return self._pos

    def readable(self) -> bool:
        return True

    def seekable(self) -> bool:
        return True

    def close(self) -> None:
        self.source.close()

    @property
    def closed(self) -> bool:
        return getattr(self.source, "_closed", False)
