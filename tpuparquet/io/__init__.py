"""File I/O layer: reader/writer, pages, chunks, Dremel store."""

from .chunk import ChunkData, read_chunk, write_chunk  # noqa: F401
from .reader import FileReader  # noqa: F401
from .store import (  # noqa: F401
    ColumnStore,
    assemble_record,
    attach_stores,
    shred_record,
)
from .writer import FileWriter  # noqa: F401
