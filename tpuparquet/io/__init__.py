"""File I/O layer: reader/writer, pages, chunks, Dremel store."""

from .chunk import ChunkData, read_chunk, write_chunk  # noqa: F401
from .rangecache import (  # noqa: F401
    invalidate_source_caches,
    reset_range_caches,
)
from .reader import FileReader  # noqa: F401
from .source import (  # noqa: F401
    ByteRangeSource,
    EmulatedStoreSource,
    LocalByteRangeSource,
    coalesce_ranges,
    open_byte_source,
    parse_source_uri,
)
from .store import (  # noqa: F401
    ColumnStore,
    assemble_record,
    attach_stores,
    shred_record,
)
from .writer import FileWriter  # noqa: F401
