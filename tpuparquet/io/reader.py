"""FileReader: row iteration, projection, and the columnar batch API.

API parity with the reference's ``FileReader`` (``file_reader.go:27-134``):
``next_row``/``rows`` iterate assembled records row-group-at-a-time with
lazy loading (``advanceIfNeeded``), ``skip_row_group``/``pre_load`` control
loading, ``metadata``/``column_meta_data`` expose the footer, and column
projection restricts decoding to selected columns (unselected chunks are
never decompressed — ``skipChunk``, ``chunk_reader.go:286``).

TPU-first addition: :meth:`read_row_group_arrays` returns decoded columns
in codec-layer form (ndarray/ByteArrayColumn + level arrays) without row
assembly — the shape the device path and columnar consumers want.
"""

from __future__ import annotations

import io
import time

import numpy as np

from ..deadline import (
    call_with_deadline,
    hedge_delay_default,
    hedged_call,
    read_deadline_default,
    read_latency,
)
from ..errors import CorruptChunkError, CorruptPageError, \
    ScanError
from ..faults import fault_point, filter_bytes, retry_transient
from ..obs import profiler as _profiler
from ..obs import recorder as _flightrec
from ..obs import trace as _trace
from ..obs.recorder import flight
from ..format.footer import read_file_metadata
from ..format.metadata import ColumnMetaData, FileMetaData
from ..format.schema import Schema
from .chunk import ChunkData, read_chunk
from .source import ByteRangeSource, RangeSourceFile, open_byte_source
from .store import assemble_record, attach_stores

__all__ = ["FileReader"]

from ..format.footer import _file_size as _source_size  # noqa: E402


_FP_UNSET = object()  # plan_fingerprint not computed yet


class _IoHandle:
    """One seekable handle + its serialization lock + an in-flight
    read count.  The count lets ``close()`` and the un-poisoning path
    distinguish an idle handle (safe to close) from one an abandoned
    deadline/hedge worker may still be blocked inside (must be LEAKED
    — closing an fd under a blocked read is undefined on some
    platforms, and a buffered file's ``close()`` blocks on the
    internal lock the hung reader holds)."""

    __slots__ = ("f", "lock", "owns", "name", "inflight")

    def __init__(self, f: "RangeSourceFile | io.BufferedIOBase",
                 owns: bool, name=None):
        import threading

        self.f = f
        self.lock = threading.Lock()
        self.owns = owns
        self.name = name
        self.inflight = 0   # guarded by the reader's _count_lock


class _MetaRangeFile:
    """Footer-resolution view of a byte-range source: every read is an
    absolute range served through the MEMORY cache tier (keyed by the
    source's etag), so reopens of the same object — fingerprint
    hashing, handle un-poisoning, replica opens — skip the remote
    round trips entirely.  Misses fetch with per-request retry
    (``remote_retry``) and count toward ``remote_ranges_fetched`` /
    ``remote_bytes``.  Position state is local to this view; safe to
    construct per use."""

    def __init__(self, source):
        self.source = source
        self.name = source.uri
        self._pos = 0

    def read(self, size: int = -1) -> bytes:
        from ..faults import retry_transient
        from ..stats import current_stats
        from .rangecache import mem_cache

        end = self.source.size()
        if size is None or size < 0:
            size = max(0, end - self._pos)
        else:
            size = min(size, max(0, end - self._pos))
        if size == 0:
            return b""
        start = self._pos
        key = self.source.etag() + (start, size)
        mc = mem_cache()
        data = None if mc is None else mc.get(key)
        if data is None:
            data = retry_transient(
                lambda: self.source.get_range(start, size),
                counter="remote_retry")
            st = current_stats()
            if st is not None:
                st.remote_ranges_fetched += 1
                st.remote_bytes += size
            if mc is not None:
                mc.put(key, data)
        self._pos += size
        return data

    def seek(self, offset: int, whence: int = 0) -> int:
        import os as _os

        if whence == _os.SEEK_SET:
            self._pos = offset
        elif whence == _os.SEEK_CUR:
            self._pos += offset
        elif whence == _os.SEEK_END:
            self._pos = self.source.size() + offset
        else:
            raise ValueError(f"bad whence {whence}")
        return self._pos

    def tell(self) -> int:
        return self._pos


class FileReader:
    """Reads a seekable binary file object (or a path).

    ``verify_crc`` gates page CRC32 verification for headers that
    carry one (None = env default ``TPQ_PAGE_CRC_VERIFY``, on).
    Transient I/O failures on chunk reads are retried with bounded
    exponential backoff (:func:`tpuparquet.faults.retry_transient`).

    Time-domain knobs (deadline/hedging round, ``deadline.py``):

    * ``read_deadline`` — per chunk-read budget in seconds (None = env
      ``TPQ_READ_DEADLINE_S``, off).  A read that runs past it raises
      :class:`~tpuparquet.errors.DeadlineExceededError` (a
      ``TransientIOError``, so the retry ladder handles it) instead of
      hanging the scan.
    * ``mirrors`` — replica sources holding byte-identical copies
      (paths or file objects, opened lazily on first use).  Chunk
      reads are *hedged*: if the primary hasn't answered after
      ``hedge_delay`` seconds (None = env ``TPQ_HEDGE_DELAY_S``, else
      the rolling p95 of observed read latency), the read is
      duplicated against the next mirror and the first success wins.
      Replicas must be bit-identical; the page CRC path rejects a
      mirror that diverges exactly like corruption.

    Untrusted-metadata knobs (file-level robustness round):

    * ``strict_metadata`` — validate the whole footer against the file
      before trusting it (``format/validate.py``); error findings raise
      :class:`~tpuparquet.errors.CorruptFooterError` carrying them.
      None = env default ``TPQ_STRICT_METADATA`` (off).
    * ``salvage`` — when the footer is torn/truncated or fails
      validation, recover the readable row-group prefix instead of
      raising (``format/recover.py``).  The reader is then flagged
      :attr:`salvaged` with a :attr:`salvage_report`, and the partial
      metadata carries a ``tpq.salvaged`` key-value marker.  Recovered
      data is bit-exact or absent — never wrong.
    * ``salvage_like`` — schema/codec donor for salvage of files with
      no embedded salvage hint: a sibling path, reader, or
      ``FileMetaData``.
    """

    def __init__(self, source, *columns: str,
                 verify_crc: bool | None = None,
                 strict_metadata: bool | None = None,
                 salvage: bool = False,
                 salvage_like=None,
                 mirrors=(),
                 hedge_delay: float | None = None,
                 read_deadline: float | None = None):
        import threading

        # byte-range sources (io/source.py): explicit scheme://
        # URIs, TPQ_SOURCE-rerouted bare paths, or a ByteRangeSource
        # instance.  The source rides behind a RangeSourceFile facade
        # so the whole handle/hedge/deadline machinery below works
        # unchanged; _source non-None switches on the remote-tuned
        # read path (tiered cache, coalesced prefetch, remote_retry
        # accounting).
        self._source = (source if isinstance(source, ByteRangeSource)
                        else open_byte_source(source)
                        if isinstance(source, str) else None)
        if self._source is not None:
            self._f = RangeSourceFile(self._source)
            self._owns = True
            self.name = self._source.uri
        elif isinstance(source, (str, bytes)) \
                and not hasattr(source, "read"):
            self._f = open(source, "rb")
            self._owns = True
            self.name = source if isinstance(source, str) else None
        else:
            self._f = source
            self._owns = False
            self.name = getattr(source, "name", None)
        self._verify_crc = verify_crc
        self._mirrors = list(mirrors)
        # (fileobj, lock, name, owns) per mirror, opened lazily — a
        # scan that never hedges never touches its mirrors
        self._mirror_handles = [None] * len(self._mirrors)
        self._mirror_lock = threading.Lock()
        self._hedge_delay = hedge_delay
        self._read_deadline = (read_deadline if read_deadline is not None
                               else read_deadline_default())
        # seek+read pairs must be atomic: the pipelined device reader
        # plans row group N+1 on a worker thread while the caller may
        # still use this reader from the main thread.  The fd + its
        # lock travel as ONE handle object: a deadline expiry may swap
        # in a fresh one (_reopen_after_expiry) while other plan
        # threads are mid-read on the old
        self._io = _IoHandle(self._f, self._owns, self.name)
        self._io_lock = self._io.lock
        self._count_lock = threading.Lock()  # inflight + hedge streak
        self._hedge_losses = 0  # consecutive mirror wins, no primary
        self._buf = None
        self.salvaged = False
        self.salvage_report = None
        self.metadata_findings = None
        try:
            fault_point("io.reader.open", file=self.name)
            self.meta: FileMetaData = self._resolve_metadata(
                strict_metadata, salvage, salvage_like)
            # In-memory sources serve chunk blobs as zero-copy views (the
            # read() copy was ~25% of the 50M-value plan phase).  Taken
            # only after the footer parses (a raised export would pin the
            # caller's BytesIO), read-only (blob-derived arrays must not
            # alias the file writably); pins the BytesIO against resize
            # while open.
            if isinstance(self._f, io.BytesIO):
                self._buf = self._f.getbuffer().toreadonly()
            self.schema = Schema.from_elements(self.meta.schema)
            attach_stores(self.schema)
            if columns:
                self.schema.set_selected_columns(*columns)
        except BaseException:
            # a rejected open must not leak the fd it opened (nor pin
            # an in-memory source via the exported buffer)
            if self._buf is not None:
                self._buf.release()
                self._buf = None
            if self._owns:
                self._f.close()
            raise
        # footer fingerprint: the plan-cache key for this file identity
        # (kernels/plancache.py), computed LAZILY on first access so
        # cache-off opens never pay the extra footer read.  None for
        # salvaged files — recovered metadata must never populate or
        # hit the cache — and when the source cannot be fingerprinted.
        # A rewritten file gets a new footer and therefore a new
        # fingerprint, so stale plans age out.
        self._plan_fp = _FP_UNSET
        # page-index / bloom caches (predicate pushdown): parsed once
        # per (rg, column); a corrupt index parses to None = no pruning
        self._pageindex_cache: dict = {}
        self._bloom_cache: dict = {}
        self.pageindex_findings: list = []
        self._rg_pos = 0          # next row group to load
        self._loaded = False      # current row group loaded into stores
        self._current_rg = 0      # last loaded (or next) row group index
        self._current_record = 0
        self._rg_records = 0

    def _resolve_metadata(self, strict_metadata, salvage,
                          salvage_like) -> FileMetaData:
        """Footer read + optional strict validation + optional salvage.
        All paths annotate raised errors with the file name and count
        the salvage/reject observables on the active collector."""
        from ..errors import CorruptFooterError
        from ..format.validate import (
            strict_metadata_default,
            validate_metadata,
            raise_on_errors,
        )

        if strict_metadata is None:
            strict_metadata = strict_metadata_default()
        # remote sources resolve the footer through the memory cache
        # tier (hot footers: a reopen costs zero round trips); the
        # salvage forward-scan below stays on the plain facade — bulk
        # page reads must not churn the small-range tier
        mf = (self._f if self._source is None
              else _MetaRangeFile(self._source))
        try:
            meta = read_file_metadata(mf)
        except CorruptFooterError as e:
            if not salvage:
                raise e.annotate(file=self.name)
            # footer unusable: rebuild from the pages (forward scan)
            from ..format.recover import recover_file_metadata

            meta, report = recover_file_metadata(
                self._f, like=salvage_like,
                verify_crc=(self._verify_crc
                            if self._verify_crc is not None else True))
            report["footer_error"] = str(e)
            self._mark_salvaged(meta, report)
            return meta
        if not (strict_metadata or salvage):
            return meta
        size = _source_size(mf)
        findings = validate_metadata(meta, size)
        self.metadata_findings = findings
        if not any(f.is_error for f in findings):
            return meta
        if salvage:
            # footer decodes but lies.  Two independent salvage routes:
            # trim to the validated row-group prefix (keeps the richer
            # footer metadata), or rebuild from the pages themselves
            # (donor schema / the file's own embedded hint — a lying
            # footer over INTACT pages loses nothing that way).  Take
            # whichever recovers more row groups; tie goes to the trim.
            from ..format.recover import (
                recover_file_metadata,
                salvage_valid_prefix,
            )

            trimmed = salvage_valid_prefix(meta, size,
                                           findings=findings)
            if trimmed is not None and len(trimmed[0].row_groups) \
                    == len(meta.row_groups):
                # the trim kept everything (repairable file-level lie
                # only): page recovery cannot beat it, skip the scan
                meta, report = trimmed
                self._mark_salvaged(meta, report)
                return meta
            try:
                rebuilt = recover_file_metadata(
                    self._f, like=salvage_like,
                    verify_crc=(self._verify_crc
                                if self._verify_crc is not None
                                else True))
            except CorruptFooterError:
                rebuilt = None  # no donor and no hint
            best = None
            if trimmed is not None and (
                    rebuilt is None
                    or len(trimmed[0].row_groups)
                    >= len(rebuilt[0].row_groups)):
                best = trimmed
            elif rebuilt is not None:
                best = rebuilt
            if best is not None:
                meta, report = best
                self._mark_salvaged(meta, report)
                return meta
            # neither route usable: fall through to the strict reject
        from ..stats import current_stats

        flight("metadata_reject", site="io.reader.footer",
               file=self.name)
        st = current_stats()
        if st is not None:
            st.metadata_rejects += 1
            if st.events is not None:
                st.events.fault(site="io.reader.footer",
                                kind="metadata_reject", file=self.name)
        try:
            raise_on_errors(findings, file=self.name)
        except CorruptFooterError as e:
            raise e.annotate(file=self.name)
        return meta

    @property
    def plan_fingerprint(self):
        """The plan-cache file identity (lazy; a benign compute race
        between plan workers yields identical values)."""
        if self._plan_fp is _FP_UNSET:
            self._plan_fp = self._compute_fingerprint()
        return self._plan_fp

    def _compute_fingerprint(self):
        """CRC32 of the footer thrift blob + file size + footer length,
        as a hashable triple.  Lazy first access can come from a plan
        worker while siblings run chunk reads, so the fd path holds the
        SAME handle lock the chunk reads serialize on (an unlocked seek
        here would move the fd position under a concurrent locked
        seek+read pair)."""
        import os as _os
        import struct as _struct
        import zlib

        if self.salvaged:
            return None
        try:
            if self._buf is not None:
                size = len(self._buf)
                if size < 12:
                    return None
                tail = bytes(self._buf[size - 8 : size - 4])
                (flen,) = _struct.unpack("<I", tail)
                if flen <= 0 or size - 8 - flen < 4:
                    return None
                crc = zlib.crc32(self._buf[size - 8 - flen : size - 8])
            elif self._source is not None:
                # memory-tier view: the footer ranges were cached at
                # open, so the lazy fingerprint costs no round trips
                # (and needs no handle lock — the view is independent
                # of the chunk-read handles)
                mf = _MetaRangeFile(self._source)
                size = mf.seek(0, _os.SEEK_END)
                if size < 12:
                    return None
                mf.seek(size - 8)
                # full 8-byte tail: the same range the footer read
                # cached, so this is a guaranteed memory hit
                tail = mf.read(8)
                (flen,) = _struct.unpack("<I", tail[:4])
                if flen <= 0 or size - 8 - flen < 4:
                    return None
                mf.seek(size - 8 - flen)
                crc = zlib.crc32(mf.read(flen))
            else:
                with self._count_lock:
                    h = self._io
                    h.inflight += 1
                try:
                    with h.lock:
                        f = h.f
                        pos = f.tell()
                        try:
                            size = f.seek(0, _os.SEEK_END)
                            if size < 12:
                                return None
                            f.seek(size - 8)
                            tail = f.read(4)
                            (flen,) = _struct.unpack("<I", tail)
                            if flen <= 0 or size - 8 - flen < 4:
                                return None
                            f.seek(size - 8 - flen)
                            crc = zlib.crc32(f.read(flen))
                        finally:
                            f.seek(pos)
                finally:
                    with self._count_lock:
                        h.inflight -= 1
        except (OSError, ValueError, _struct.error):
            return None
        return (crc, size, flen)

    def cached_plan_fingerprint(self):
        """The fingerprint IF already computed, else None — for cleanup
        paths (quarantine invalidation) that must never trigger fresh
        footer I/O on a possibly-wedged handle."""
        return None if self._plan_fp is _FP_UNSET else self._plan_fp

    def _mark_salvaged(self, meta: FileMetaData, report: dict) -> None:
        from ..stats import current_stats

        flight("salvaged", site="io.reader.footer", file=self.name,
               row_groups=len(meta.row_groups or []),
               stop_reason=report.get("stop_reason"))
        self.salvaged = True
        self.salvage_report = report
        st = current_stats()
        if st is not None:
            st.files_salvaged += 1
            st.row_groups_recovered += len(meta.row_groups or [])
            if st.events is not None:
                st.events.fault(
                    site="io.reader.footer", kind="salvaged",
                    file=self.name,
                    row_groups=len(meta.row_groups or []),
                    stop_reason=report.get("stop_reason"))

    # -- metadata accessors ------------------------------------------------

    @property
    def num_rows(self) -> int:
        return self.meta.num_rows

    def row_group_count(self) -> int:
        return len(self.meta.row_groups)

    def metadata(self) -> FileMetaData:
        return self.meta

    def key_value_metadata(self) -> dict:
        return {
            kv.key: kv.value for kv in (self.meta.key_value_metadata or [])
        }

    def column_meta_data(self, column: str) -> tuple[dict, ColumnMetaData]:
        """Per-row-group metadata for a column of the *current* row group
        (≙ ``ColumnMetaData``, ``file_reader.go:127``)."""
        rg = self.meta.row_groups[self._current_rg]
        for cc in rg.columns:
            if ".".join(cc.meta_data.path_in_schema) == column:
                return self.key_value_metadata(), cc.meta_data
        raise KeyError(f"no such column {column!r}")

    def current_row_group(self):
        return self.meta.row_groups[self._current_rg]

    def get_schema_definition(self):
        return self.schema.definition()

    # -- predicate pushdown: page index, bloom filters, prune verdicts ----

    def _read_range(self, start: int, size: int) -> bytes:
        """Small absolute-range read off the primary handle (page-index
        and bloom blobs); zero-copy for in-memory sources.  Raises
        ``ValueError`` when the range escapes the file."""
        if start < 0 or size <= 0:
            raise ValueError(f"bad byte range [{start}, {start + size})")
        if self._buf is not None:
            if start + size > len(self._buf):
                raise ValueError("byte range overruns the file")
            return bytes(self._buf[start : start + size])
        if self._source is not None:
            # plan hints (page index / bloom blobs) live in the memory
            # tier: small, hot, re-read per (rg, column) across reopens
            from ..stats import current_stats
            from .rangecache import mem_cache

            if start + size > self._source.size():
                raise ValueError("byte range overruns the file")
            key = self._source.etag() + (start, size)
            mc = mem_cache()
            data = None if mc is None else mc.get(key)
            if data is None:
                data = self._source.get_range(start, size)
                st = current_stats()
                if st is not None:
                    st.remote_ranges_fetched += 1
                    st.remote_bytes += size
                if mc is not None:
                    mc.put(key, data)
            return data
        with self._count_lock:
            h = self._io
            h.inflight += 1
        try:
            with h.lock:
                h.f.seek(start)
                out = h.f.read(size)
        finally:
            with self._count_lock:
                h.inflight -= 1
        if len(out) != size:
            raise ValueError(
                f"short read: {len(out)}/{size} bytes at {start}")
        return out

    def page_index(self, rg_index: int, columns=None) -> dict:
        """Parsed page index of one row group: ``{column: pages}`` where
        ``pages`` is a list of ``(row_start, row_end, min, max,
        null_count, null_page)`` per data page (bounds decoded to
        LOGICAL values) — exactly the shape
        :func:`tpuparquet.filter.candidate_mask` consumes.  Columns
        without an index (or whose index fails validation — fault site
        ``format.pageindex``) are absent: conservative "no pruning".
        Results cache per reader, and in the footer-keyed plan cache
        (``TPQ_PLAN_CACHE_MB``) across reopens of the same file."""
        from ..faults import fault_point, filter_bytes
        from ..format.compact import ThriftError
        from ..format.metadata import ColumnIndex, OffsetIndex
        from ..format.validate import validate_page_index
        from ..kernels.plancache import plan_cache
        from .values import handler_for

        want = None if columns is None else set(columns)

        def _view(parsed: dict) -> dict:
            return ({k: v for k, v in parsed.items() if k in want}
                    if want is not None else dict(parsed))

        cached = self._pageindex_cache.get(rg_index)
        if cached is not None:
            return _view(cached)

        pc = plan_cache()
        pc_key = None
        if pc is not None and self.plan_fingerprint is not None:
            pc_key = (self.plan_fingerprint, rg_index, "__pageindex__")
            got = pc.lookup(pc_key)
            if got is not None:
                out = {col: pages for col, pages in got
                       if pages is not None}
                self._pageindex_cache[rg_index] = out
                return _view(out)

        from ..errors import TransientIOError

        rg = self.meta.row_groups[rg_index]
        size = _source_size(self._f) if self._buf is None \
            else len(self._buf)
        out: dict = {}
        absent: set = set()
        transient = False
        for cc in rg.columns:
            cm = cc.meta_data
            path = ".".join(cm.path_in_schema)
            if cc.column_index_offset is None \
                    or cc.column_index_length is None \
                    or cc.offset_index_offset is None \
                    or cc.offset_index_length is None:
                absent.add(path)
                continue
            node = self.schema.leaf(path)
            try:
                fault_point("format.pageindex", file=self.name,
                            column=path)
                # same retry policy as chunk reads: a flaky-store blip
                # must not masquerade as a corrupt index
                ci_blob = filter_bytes(
                    "format.pageindex",
                    retry_transient(lambda: self._read_range(
                        cc.column_index_offset,
                        cc.column_index_length),
                        counter=self._retry_counter),
                    column=path)
                oi_blob = retry_transient(lambda: self._read_range(
                    cc.offset_index_offset, cc.offset_index_length),
                    counter=self._retry_counter)
                ci = ColumnIndex.from_bytes(ci_blob)
                oi = OffsetIndex.from_bytes(oi_blob)
                findings = validate_page_index(
                    ci, oi, cm, rg.num_rows, size,
                    element=None if node is None else node.element,
                    row_group=rg_index)
                if any(f.is_error for f in findings):
                    self.pageindex_findings.extend(findings)
                    raise ValueError(
                        f"page index failed validation: "
                        f"{[f for f in findings if f.is_error][0]}")
                handler = (handler_for(node.element)
                           if node is not None else None)
                if handler is not None \
                        and not handler.stats_bytewise_comparable():
                    handler = None  # bounds unusable: rows kept
                locs = oi.page_locations
                pages = []
                for i, loc in enumerate(locs):
                    r0 = loc.first_row_index
                    r1 = (locs[i + 1].first_row_index
                          if i + 1 < len(locs) else rg.num_rows)
                    null_page = bool(ci.null_pages[i])
                    if null_page or handler is None:
                        mn = mx = None
                    else:
                        mn = handler.decode_stat_logical(
                            ci.min_values[i])
                        mx = handler.decode_stat_logical(
                            ci.max_values[i])
                    nulls = (ci.null_counts[i]
                             if ci.null_counts is not None else None)
                    pages.append((r0, r1, mn, mx, nulls, null_page))
                out[path] = pages
            except (ScanError, OSError, ValueError, ThriftError,
                    IndexError, KeyError, TypeError,
                    OverflowError) as e:
                # corrupt/lying index: degrade this COLUMN to
                # "no pruning" — results stay exact, only efficiency
                # is lost.  The incident is observable: flight record
                # + fault event with coordinates.  A TRANSIENT failure
                # that outlived its retries degrades this scan the
                # same way, but must not be remembered as
                # "index absent" by the cross-reopen plan cache.
                if isinstance(e, (TransientIOError, OSError)) \
                        and not isinstance(e, ValueError):
                    transient = True
                absent.add(path)
                flight("pageindex_reject", site="format.pageindex",
                       file=self.name, row_group=rg_index, column=path,
                       error=type(e).__name__)
                from ..stats import current_stats

                st = current_stats()
                if st is not None and st.events is not None:
                    st.events.fault(site="format.pageindex",
                                    kind="pageindex_reject",
                                    file=self.name, row_group=rg_index,
                                    column=path,
                                    error=type(e).__name__)
        if not transient:
            self._pageindex_cache[rg_index] = out
        if pc_key is not None and not transient:
            from ..kernels.plancache import plan_cache_budget

            record = [(col, out.get(col)) for col in
                      sorted(out.keys() | absent)]
            pc.store(pc_key, record, plan_cache_budget())
        return _view(out)

    def bloom_filter(self, rg_index: int, column: str):
        """The split-block bloom filter of one column chunk, or None
        (absent / corrupt — fault site ``format.pageindex`` covers the
        whole index family).  Cached per reader."""
        from ..format.bloom import SplitBlockBloom
        from ..format.compact import CompactReader, ThriftError
        from ..format.metadata import BloomFilterHeader, decode_struct
        from ..faults import fault_point, filter_bytes

        from ..errors import TransientIOError

        key = (rg_index, column)
        if key in self._bloom_cache:
            return self._bloom_cache[key]
        got = None
        transient = False
        rg = self.meta.row_groups[rg_index]
        for cc in rg.columns:
            cm = cc.meta_data
            if ".".join(cm.path_in_schema) != column:
                continue
            if cm.bloom_filter_offset is None:
                break
            try:
                fault_point("format.pageindex", file=self.name,
                            column=column)

                def _read():
                    if cm.bloom_filter_length is not None:
                        return self._read_range(cm.bloom_filter_offset,
                                                cm.bloom_filter_length)
                    # no length in the footer (older writers): read the
                    # header window first, then exactly the bitset
                    head = self._read_range(
                        cm.bloom_filter_offset,
                        min(256, _source_size(self._f)
                            - cm.bloom_filter_offset
                            if self._buf is None
                            else len(self._buf)
                            - cm.bloom_filter_offset))
                    r = CompactReader(head)
                    header = decode_struct(BloomFilterHeader, r)
                    nb = header.numBytes or 0
                    return self._read_range(cm.bloom_filter_offset,
                                            r.pos + nb)

                blob = filter_bytes("format.pageindex",
                                    retry_transient(
                                        _read,
                                        counter=self._retry_counter),
                                    column=column)
                got = SplitBlockBloom.from_bytes(blob)
            except (ScanError, OSError, ValueError, ThriftError,
                    IndexError, KeyError, TypeError,
                    OverflowError) as e:
                if isinstance(e, (TransientIOError, OSError)) \
                        and not isinstance(e, ValueError):
                    transient = True  # don't cache a flaky-store miss
                flight("bloom_reject", site="format.pageindex",
                       file=self.name, row_group=rg_index,
                       column=column, error=type(e).__name__)
                got = None
            break
        if not transient:
            self._bloom_cache[key] = got
        return got

    def prune_row_group(self, f, rg_index: int, *, pages: bool = True):
        """Static pruning verdict of one row group against a bound
        filter: chunk ``Statistics``, then bloom filters (``==``/``IN``
        refutation, counted as ``bloom_hits``), then the page index's
        candidate row mask.  Conservative by construction — ``skip``
        only when NO row can match.  With pruning disabled
        (``TPQ_PRUNE=0``) returns an all-rows verdict."""
        from ..filter import (
            PruneVerdict,
            _walk_leaves,
            bind_filter,
            candidate_mask,
            may_match_stats,
            prune_enabled,
            row_group_stats,
        )

        bind_filter(f, self.schema)
        if not prune_enabled():
            return PruneVerdict()
        rg = self.meta.row_groups[rg_index]
        wanted = f.columns()
        stats = row_group_stats(rg, self.schema, wanted)
        hits = [0]

        def bloom_probe(column, probes):
            b = self.bloom_filter(rg_index, column)
            if b is None:
                return True
            h = None
            for leaf, _neg in _walk_leaves(f):
                if leaf.column == column \
                        and getattr(leaf, "_h", None) is not None:
                    h = leaf._h
                    break
            if h is None:
                return True
            for v in probes:
                try:
                    enc = h.encode_stat_value(v)
                except (TypeError, ValueError, OverflowError):
                    return True
                if enc is None or b.check(enc):
                    return True
            hits[0] += 1
            return False

        # bloom_hits ride the VERDICT, not the collector: the scan
        # drivers prune at construction time (often before any
        # collector opens) and fold verdict counters at run start, so
        # counting here too would double-count under an active
        # collector
        ok = may_match_stats(f, stats, bloom_probe)
        if not ok:
            return PruneVerdict(skip=True,
                                reason="bloom" if hits[0] else "stats",
                                bloom_hits=hits[0])
        if not pages:
            return PruneVerdict(bloom_hits=hits[0])
        pages_by_col = self.page_index(rg_index, columns=wanted)
        if not pages_by_col:
            return PruneVerdict(bloom_hits=hits[0])
        cand = candidate_mask(f, pages_by_col, rg.num_rows)
        if not cand.any():
            return PruneVerdict(skip=True, reason="pages",
                                pages_by_col=pages_by_col,
                                bloom_hits=hits[0])
        if cand.all():
            cand = None  # all rows are candidates: no static narrowing
        return PruneVerdict(candidate=cand, pages_by_col=pages_by_col,
                            bloom_hits=hits[0])

    # -- row-group loading -------------------------------------------------

    def read_row_group_arrays(self, rg_index: int,
                              filter=None) -> dict[str, ChunkData]:
        """Decode the selected columns of one row group into codec-layer
        arrays (no row assembly).  Only selected chunks are read from the
        file at all — projection skips both I/O and decode (≙ skipChunk,
        ``chunk_reader.go:286``).

        ``filter`` (a :mod:`tpuparquet.filter` expression) switches to
        the late-materialized predicate-pushdown path: row groups /
        pages the metadata proves empty are never decoded, the filter
        columns decode first, and the returned chunks hold exactly the
        surviving rows — bit-identical to a full decode followed by a
        post-filter."""
        if not 0 <= rg_index < len(self.meta.row_groups):
            raise IndexError(
                f"row group {rg_index} out of range "
                f"(file has {len(self.meta.row_groups)})"
            )
        from ..stats import current_stats

        st = current_stats()
        if st is not None:
            st.row_groups += 1
        if filter is not None:
            from ..filter import read_row_group_filtered

            try:
                chunks, _rows = read_row_group_filtered(
                    self, rg_index, filter)
            except ScanError as e:
                raise e.annotate(row_group=rg_index, file=self.name)
            return chunks
        rg = self.meta.row_groups[rg_index]
        out = {}
        # phase span for the Perfetto export; nothing runs (and nothing
        # allocates) on this path without an event-carrying collector
        ev = None if st is None else st.events
        t0 = time.perf_counter() if ev is not None else 0.0
        try:
            for path, node, cm, blob, start in self.iter_selected_chunks(rg):
                out[path] = read_chunk(memoryview(blob),
                                       _rebase(cm, start), node,
                                       verify_crc=self._verify_crc)
        except ScanError as e:
            if isinstance(e, (CorruptPageError, CorruptChunkError)):
                # the file's bytes no longer match the footer's claims:
                # cached plans under this fingerprint are unsafe to
                # trust.  Transient/deadline errors do NOT invalidate —
                # the bytes are fine, the link was slow (matching the
                # device path's policy in kernels/device.py).
                from ..kernels.plancache import invalidate_fingerprint

                # only the ALREADY-COMPUTED fingerprint can have
                # cache entries under it; never compute one here
                if self._plan_fp is not _FP_UNSET:
                    invalidate_fingerprint(self._plan_fp)
                if self._source is not None:
                    # the bad bytes may have been SERVED from the range
                    # cache: evict both tiers so a retry of this unit
                    # refetches from the store, not the poison
                    from .rangecache import invalidate_source_caches

                    invalidate_source_caches(self._source.uri)
            raise e.annotate(row_group=rg_index, file=self.name)
        if ev is not None:
            import threading

            ev.span("read_row_group", "cpu-decode", t0,
                    time.perf_counter(), tid=threading.get_ident(),
                    rg=rg_index, columns=len(out))
        return out

    def selected_chunks(self, rg):
        """``[(path, node, cm)]`` for the selected columns of a row
        group — metadata only, no I/O.  The device path turns each
        entry into an independent column plan task."""
        out = []
        for cc in rg.columns:
            cm = cc.meta_data
            path = ".".join(cm.path_in_schema)
            node = self.schema.leaf(path)
            if node is None:
                raise ValueError(f"column {path!r} not in schema")
            if not self.schema.is_selected(node):
                continue
            out.append((path, node, cm))
        return out

    def chunk_blob(self, cm, path: str):
        """One selected chunk's bytes: ``(blob, start_offset)``.
        Zero-copy view for in-memory sources; the full time-domain read
        policy (retry/hedge/deadline) otherwise.  Thread-safe — the
        column-parallel planner calls this from pool workers."""
        from ..stats import current_stats

        start = cm.data_page_offset
        if cm.dictionary_page_offset is not None:
            start = min(start, cm.dictionary_page_offset)
        t0 = time.perf_counter()
        # off-CPU marker: a thread sampled inside the fetch (fault
        # hangs, remote stalls, retry/hedge/deadline waits) is
        # wait-on-IO, not on-CPU work in this frame
        ptok = _profiler.wait_begin("io", "io.reader.chunk_read") \
            if _profiler._active is not None else None
        try:
            if self._buf is not None:
                # explicit bounds: negative offsets would WRAP on a
                # memoryview slice (the old seek() raised instead)
                if (start < 0 or cm.total_compressed_size < 0
                        or start + cm.total_compressed_size
                        > len(self._buf)):
                    raise CorruptChunkError(
                        "column chunk overruns file",
                        column=path, file=self.name)
                fault_point("io.reader.chunk_read", column=path)
                fault_point("io.chunk.hang", file=self.name,
                            column=path)
                blob = self._buf[start : start + cm.total_compressed_size]
            else:
                # remote path: column-chunk ranges live in the DISK
                # cache tier (CRC-framed files, rangecache.py); a hit
                # skips the fetch entirely, a miss fetches through the
                # full retry/hedge/deadline ladder and back-fills the
                # tier
                dcache = None
                ckey = None
                blob = None
                if self._source is not None:
                    from .rangecache import disk_cache

                    dcache = disk_cache()
                    if dcache is not None:
                        ckey = self._source.etag() + (
                            start, cm.total_compressed_size)
                        blob = dcache.get(ckey)
                if blob is None:
                    blob = self._read_chunk_bytes(
                        start, cm.total_compressed_size, path)
                    if len(blob) < cm.total_compressed_size:
                        raise CorruptChunkError(
                            f"column chunk short read: {len(blob)}/"
                            f"{cm.total_compressed_size} bytes",
                            column=path, file=self.name)
                    if self._source is not None:
                        st = current_stats()
                        if st is not None:
                            st.remote_ranges_fetched += 1
                            st.remote_bytes += len(blob)
                        if dcache is not None:
                            dcache.put(ckey, blob)
        finally:
            if ptok is not None:
                _profiler.wait_end(ptok)
        blob = filter_bytes("io.reader.chunk_read", blob, column=path)
        dt = time.perf_counter() - t0
        st = current_stats()
        if st is not None:
            # the read-side attribution pair: wall spent fetching
            # (retry/hedge/deadline wait included) and bytes fetched
            st.read_s += dt
            st.bytes_read += len(blob)
        # flight recorder: one record per chunk read (file/column
        # coordinates are exactly what a post-mortem wants trailing;
        # guarded so the disabled path skips the kwargs build)
        if _flightrec._active is not None:
            _flightrec.flight("chunk_read", site="io.reader",
                              file=self.name, column=path,
                              bytes=cm.total_compressed_size)
        # causal trace: the read span of this chunk's unit/plan chain
        if _trace._active is not None:
            _trace.emit_span("read", t0, dt, file=self.name,
                             column=path,
                             bytes=cm.total_compressed_size)
        return blob, start

    def iter_selected_chunks(self, rg):
        """Yield (path, node, cm, chunk_bytes, start_offset) for each
        selected chunk of a row group — the shared slurp used by both the
        CPU and device decode paths.  Remote sources batch-prefetch the
        row group's chunk ranges first (coalesced, parallel) so the
        per-chunk loop below is all cache hits."""
        chunks = self.selected_chunks(rg)
        if self._source is not None:
            self.prefetch_ranges([
                (self._chunk_start(cm), cm.total_compressed_size, path)
                for path, node, cm in chunks])
        for path, node, cm in chunks:
            blob, start = self.chunk_blob(cm, path)
            yield path, node, cm, blob, start

    @staticmethod
    def _chunk_start(cm) -> int:
        start = cm.data_page_offset
        if cm.dictionary_page_offset is not None:
            start = min(start, cm.dictionary_page_offset)
        return start

    def prefetch_chunks(self, rg) -> None:
        """Batch-prefetch the selected chunk ranges of one row group
        into the disk tier (no-op for local/in-memory sources)."""
        if self._source is None or self._buf is not None:
            return
        self.prefetch_ranges([
            (self._chunk_start(cm), cm.total_compressed_size, path)
            for path, node, cm in self.selected_chunks(rg)])

    def prefetch_ranges(self, entries) -> None:
        """The remote-tuned fetch planner: coalesce ``(start, size,
        path)`` requests under ``TPQ_RANGE_COALESCE_GAP`` — the inverse
        of the seek-happy local path, where every request is a round
        trip — and fetch the merged spans in parallel under the shared
        ``TPQ_PLAN_THREADS`` budget, populating the disk tier.

        Only ranges not already cached are fetched.  Accounting is
        exact: ``remote_ranges_fetched`` counts merged spans issued,
        ``ranges_coalesced`` counts requests saved by merging, and
        ``remote_bytes`` sums span payloads (gap bytes included —
        that's the trade).  Spans retry/deadline individually; a span
        that exhausts its retries is simply not cached, and the
        per-chunk read path surfaces the error with full coordinates.
        """
        from ..stats import current_stats
        from .rangecache import disk_cache
        from .source import coalesce_gap_default, coalesce_ranges

        if self._source is None or not entries:
            return
        dcache = disk_cache()
        if dcache is None:
            return
        etag = self._source.etag()
        missing = [(s, n) for s, n, _p in entries
                   if not dcache.contains(etag + (s, n))]
        if not missing:
            return
        spans = coalesce_ranges(missing, coalesce_gap_default())

        def _fetch_span(start, size):
            def _one():
                if self._read_deadline:
                    return call_with_deadline(
                        lambda: self._source.get_range(start, size),
                        self._read_deadline, site="io.remote.range",
                        file=self.name)
                return self._source.get_range(start, size)
            try:
                return retry_transient(_one, counter="remote_retry")
            except (ScanError, OSError):
                return None  # per-chunk path re-reads and surfaces it

        n_workers = min(self._prefetch_threads(), len(spans))
        if n_workers <= 1:
            fetched = [_fetch_span(s, n) for s, n, _m in spans]
        else:
            from concurrent.futures import ThreadPoolExecutor

            from ..stats import merge_worker_stats, worker_stats

            like = current_stats()

            def _task(start, size):
                # per-thread collector, merged after join — the
                # exactness discipline stats.py documents
                with worker_stats(like=like) as ws:
                    out = _fetch_span(start, size)
                return out, ws

            with ThreadPoolExecutor(max_workers=n_workers) as ex:
                futs = [ex.submit(_task, s, n) for s, n, _m in spans]
                fetched = []
                for fu in futs:
                    out, ws = fu.result()
                    merge_worker_stats(like, ws, failed=out is None)
                    fetched.append(out)
        st = current_stats()
        for (start, size, members), data in zip(spans, fetched):
            if data is None:
                continue
            # flight recorder: one record per fetched span so a ring
            # dump shows what the planner coalesced and actually
            # pulled (guarded — this fires per prefetched range)
            if _flightrec._active is not None:
                _flightrec.flight(
                    "prefetch_span", site="io.reader", file=self.name,
                    start=start, size=size, members=len(members))
            if st is not None:
                st.remote_ranges_fetched += 1
                st.remote_bytes += size
                st.ranges_coalesced += len(members) - 1
            for mi in members:
                ms, mn = missing[mi]
                dcache.put(etag + (ms, mn),
                           bytes(data[ms - start : ms - start + mn]))

    def _prefetch_threads(self) -> int:
        """Shared thread budget: the serve-arbiter tenant share when
        the calling thread is bound, else ``TPQ_PLAN_THREADS`` when
        set, else usable cores (mirrors ``kernels/device.
        _plan_threads`` without importing the device stack on the
        pure-CPU path)."""
        import os as _os

        from ..serve import arbiter as _arbiter

        share = _arbiter.plan_budget()
        if share is not None:
            return share
        v = _os.environ.get("TPQ_PLAN_THREADS")
        if v is not None:
            try:
                return max(int(v), 1)
            except ValueError:
                pass
        try:
            return len(_os.sched_getaffinity(0)) or 1
        except (AttributeError, OSError):
            return _os.cpu_count() or 1

    # -- timed / hedged / deadline-bounded chunk reads ---------------------

    def _read_chunk_bytes(self, start: int, size: int, path: str):
        """One chunk's bytes with the full time-domain policy: retry
        with backoff (transient errors AND deadline expiries), hedge
        against mirrors after the hedge delay, bound each read by
        ``read_deadline``.

        With ``read_deadline`` set each read runs on a disposable
        watchdog worker (~100µs of thread overhead per chunk read —
        pennies next to a real I/O-bound read; leave the knob off for
        in-memory or local-SSD sources)."""
        import time as _time

        from ..errors import DeadlineExceededError

        def _read_primary(start=start, size=size, path=path):
            # the fault points sit INSIDE the retried callable: an
            # injected fault exercises the same ladder a flaky store
            # would.  The hang site sits OUTSIDE the io lock — an
            # injected hang models a slow read without pinning the
            # lock that retry/hedge siblings need (a REAL hang pins
            # it; _reopen_after_expiry un-poisons the reader then).
            fault_point("io.reader.chunk_read", column=path)
            fault_point("io.chunk.hang", file=self.name, column=path)
            # capture + increment under ONE lock: the closers check
            # inflight under the same lock before closing, so a handle
            # can never be closed between capture and first use
            with self._count_lock:
                h = self._io
                h.inflight += 1
            try:
                with h.lock:
                    h.f.seek(start)
                    out = h.f.read(size)
            finally:
                with self._count_lock:
                    h.inflight -= 1
            # a COMPLETING primary read — even on an already-abandoned
            # branch — proves the handle is alive: reset the
            # hedge-loss streak (_note_hedge_win)
            with self._count_lock:
                self._hedge_losses = 0
            return out

        if self._mirrors:
            branches = [_read_primary] + [
                (lambda mi=mi: self._mirror_read(mi, start, size, path))
                for mi in range(len(self._mirrors))
            ]

            def _hedged():
                try:
                    return hedged_call(
                        branches, delay=self._resolve_hedge_delay(),
                        site="io.reader.chunk_read",
                        budget=self._read_deadline,
                        tracker=read_latency,
                        on_win=self._note_hedge_win,
                        file=self.name, column=path)
                except DeadlineExceededError:
                    self._reopen_after_expiry()
                    raise

            return retry_transient(_hedged,
                                   counter=self._retry_counter)
        if self._read_deadline:
            def _bounded():
                try:
                    return call_with_deadline(
                        _read_primary, self._read_deadline,
                        site="io.reader.chunk_read",
                        file=self.name, column=path)
                except DeadlineExceededError:
                    self._reopen_after_expiry()
                    raise
            fn = _bounded
        else:
            fn = _read_primary

        def _timed():
            t0 = _time.monotonic()
            out = fn()
            # successful reads feed the rolling p95 the adaptive hedge
            # delay is derived from
            read_latency.record(_time.monotonic() - t0)
            return out

        return retry_transient(_timed,
                               counter=self._retry_counter)

    def _note_hedge_win(self, i: int) -> None:
        """Hedge outcome feedback: a mirror win means the primary lost
        (slow OR hung — indistinguishable at win time).  A primary
        read that completes resets the streak, even on an abandoned
        branch; two consecutive mirror wins with NO primary completion
        means the primary handle looks wedged (dead mount with no
        ``read_deadline`` configured to expire it), so swap it out —
        otherwise every later read queues behind the corpse at
        +hedge_delay each, and ``close()`` would block on it."""
        if i == 0:
            return
        with self._count_lock:
            self._hedge_losses += 1
            wedged = self._hedge_losses >= 2
            if wedged:
                self._hedge_losses = 0
        if wedged:
            self._reopen_after_expiry()

    def _reopen_after_expiry(self) -> None:
        """Un-poison the reader after an abandoned read: a worker hung
        INSIDE ``fd.read()`` holds its io lock forever, so every later
        read of this file would queue behind it and burn its own full
        deadline.  Swap in a fresh fd + lock for the primary, and drop
        the cached mirror handles so the next hedge reopens fresh ones
        too (a hedge branch may have been the hung party).  Path-backed
        handles only; caller-owned file objects cannot be reopened.
        A dropped handle is closed only when idle — one an abandoned
        worker may still be inside is leaked to that worker instead."""
        with self._mirror_lock:
            for i, h in enumerate(self._mirror_handles):
                if h is not None and h.owns:  # we opened: re-openable
                    # idle-check + close under _count_lock: readers
                    # capture + increment inflight under the same lock,
                    # so an idle verdict cannot race a fresh capture
                    with self._count_lock:
                        idle = h.inflight == 0
                    if idle:
                        h.f.close()
                    self._mirror_handles[i] = None
        if not (self._owns and self.name):
            return  # caller-owned file object: nothing we can reopen
        try:
            if self._source is not None:
                ns = self._source.reopen()
                f = RangeSourceFile(ns)
                self._source = ns
            else:
                f = open(self.name, "rb")
        except OSError:
            return  # keep the old handle; the retry ladder decides
        nh = _IoHandle(f, True, self.name)
        with self._count_lock:
            # swap + idle-check atomically vs capture/increment: after
            # the swap no new reader can capture `old`, and any that
            # did has already incremented inflight
            old = self._io
            self._f = f
            self._io = nh
            self._io_lock = nh.lock
            idle = old.inflight == 0
        if idle:
            old.f.close()

    def _mirror_handle(self, mi: int) -> _IoHandle:
        h = self._mirror_handles[mi]
        if h is not None:
            return h
        # the (blocking) open happens OUTSIDE the shared lock: a hung
        # mount must never wedge _reopen_after_expiry or sibling hedge
        # branches behind _mirror_lock, which only guards the list
        src = self._mirrors[mi]
        if hasattr(src, "read"):
            nh = _IoHandle(src, False, getattr(src, "name", None))
        else:
            bs = (src if isinstance(src, ByteRangeSource)
                  else open_byte_source(src) if isinstance(src, str)
                  else None)
            if bs is not None:
                nh = _IoHandle(RangeSourceFile(bs), True, bs.uri)
            else:
                nh = _IoHandle(open(src, "rb"), True,
                               src if isinstance(src, str) else None)
        with self._mirror_lock:
            cur = self._mirror_handles[mi]
            if cur is None:
                self._mirror_handles[mi] = nh
                return nh
        if nh.owns:  # lost the init race: discard ours
            nh.f.close()
        return cur

    def _mirror_read(self, mi: int, start: int, size: int, path: str):
        # capture + increment with the handle re-validated under
        # _mirror_lock: the closers drop a handle from the list under
        # that lock BEFORE closing it, so a handle that is still listed
        # cannot be mid-close, and once inflight > 0 it stays open
        while True:
            h = self._mirror_handle(mi)
            with self._mirror_lock:
                if self._mirror_handles[mi] is h:
                    with self._count_lock:
                        h.inflight += 1
                    break
        try:
            # fault points inside the guarded region: an injected raise
            # must still decrement inflight or the handle leaks forever
            fault_point("io.reader.chunk_read", column=path)
            fault_point("io.chunk.hang", file=h.name, column=path)
            with h.lock:
                h.f.seek(start)
                return h.f.read(size)
        finally:
            with self._count_lock:
                h.inflight -= 1

    @property
    def _retry_counter(self) -> str:
        """Which DecodeStats counter the retry ladder bumps: remote
        sources account separately (``remote_retry``) so fleet
        dashboards can tell a flaky store from a flaky local disk."""
        return "remote_retry" if self._source is not None else "io_retries"

    def _resolve_hedge_delay(self) -> float:
        if self._hedge_delay is not None:
            return self._hedge_delay
        env = hedge_delay_default()
        return env if env is not None else read_latency.hedge_delay()

    def pre_load(self) -> None:
        """Eagerly load the next row group (≙ ``PreLoad``)."""
        if not self._loaded:
            self._load_next()

    def skip_row_group(self) -> None:
        """Skip the remainder of the current/next row group."""
        if self._loaded:
            self._loaded = False
        else:
            self._rg_pos += 1

    def _load_next(self) -> None:
        if self._rg_pos >= len(self.meta.row_groups):
            raise EOFError("no more row groups")
        idx = self._rg_pos
        data = self.read_row_group_arrays(idx)
        rg = self.meta.row_groups[idx]
        for leaf in self.schema.leaves:
            cd = data.get(leaf.flat_name)
            if cd is None:
                leaf.store.mark_skipped()
            else:
                leaf.store.load_decoded(
                    cd.values, cd.rep_levels, cd.def_levels
                )
        self._current_rg = idx
        self._rg_pos += 1
        self._loaded = True
        self._current_record = 0
        self._rg_records = rg.num_rows

    # -- row iteration -----------------------------------------------------

    def next_row(self) -> dict:
        """Next assembled record; raises EOFError at end of file
        (≙ ``NextRow`` returning io.EOF)."""
        while True:
            if not self._loaded:
                self._load_next()  # raises EOFError when exhausted
            if self._current_record < self._rg_records:
                self._current_record += 1
                if self._current_record >= self._rg_records:
                    self._loaded = False  # advance on the next call
                return assemble_record(self.schema)
            self._loaded = False

    def rows(self):
        """Iterate every remaining record."""
        while True:
            try:
                yield self.next_row()
            except EOFError:
                return

    # -- cleanup -----------------------------------------------------------

    def close(self) -> None:
        if self._buf is not None:
            # release the exported buffer or BytesIO.close() raises
            self._buf.release()
            self._buf = None
        # close only IDLE handles we own: one with a reader still in
        # flight (an abandoned hedge/deadline worker hung inside
        # read()) is leaked to that worker — a buffered close() would
        # block on the internal lock the hung reader holds, turning
        # cleanup into exactly the unbounded stall this round removes
        # drop the slots under _mirror_lock FIRST, close after: the
        # _mirror_read capture loop re-validates against the list under
        # that lock, so a handle it can still validate is never
        # mid-close (a hedge branch racing close() instead sees the
        # emptied slot and, per the r09 policy, is leaked its handle)
        with self._mirror_lock:
            dropped = list(self._mirror_handles)
            for i in range(len(self._mirror_handles)):
                self._mirror_handles[i] = None
        for h in dropped:
            if h is not None and h.owns:
                with self._count_lock:
                    idle = h.inflight == 0
                if idle:
                    h.f.close()
        if self._owns:
            with self._count_lock:
                h = self._io
                idle = h.inflight == 0
            if idle:
                h.f.close()

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        self.close()


def _rebase(cm: ColumnMetaData, base: int) -> ColumnMetaData:
    """Shift a chunk's offsets to be relative to a sliced byte range."""
    out = ColumnMetaData(**{
        name: getattr(cm, name) for name in cm._NAMES
    })
    out.data_page_offset = cm.data_page_offset - base
    if cm.dictionary_page_offset is not None:
        out.dictionary_page_offset = cm.dictionary_page_offset - base
    return out
