"""FileReader: row iteration, projection, and the columnar batch API.

API parity with the reference's ``FileReader`` (``file_reader.go:27-134``):
``next_row``/``rows`` iterate assembled records row-group-at-a-time with
lazy loading (``advanceIfNeeded``), ``skip_row_group``/``pre_load`` control
loading, ``metadata``/``column_meta_data`` expose the footer, and column
projection restricts decoding to selected columns (unselected chunks are
never decompressed — ``skipChunk``, ``chunk_reader.go:286``).

TPU-first addition: :meth:`read_row_group_arrays` returns decoded columns
in codec-layer form (ndarray/ByteArrayColumn + level arrays) without row
assembly — the shape the device path and columnar consumers want.
"""

from __future__ import annotations

import io
import time

import numpy as np

from ..errors import CorruptChunkError, ScanError
from ..faults import fault_point, filter_bytes, retry_transient
from ..format.footer import read_file_metadata
from ..format.metadata import ColumnMetaData, FileMetaData
from ..format.schema import Schema
from .chunk import ChunkData, read_chunk
from .store import assemble_record, attach_stores

__all__ = ["FileReader"]

from ..format.footer import _file_size as _source_size  # noqa: E402


class FileReader:
    """Reads a seekable binary file object (or a path).

    ``verify_crc`` gates page CRC32 verification for headers that
    carry one (None = env default ``TPQ_PAGE_CRC_VERIFY``, on).
    Transient I/O failures on chunk reads are retried with bounded
    exponential backoff (:func:`tpuparquet.faults.retry_transient`).

    Untrusted-metadata knobs (file-level robustness round):

    * ``strict_metadata`` — validate the whole footer against the file
      before trusting it (``format/validate.py``); error findings raise
      :class:`~tpuparquet.errors.CorruptFooterError` carrying them.
      None = env default ``TPQ_STRICT_METADATA`` (off).
    * ``salvage`` — when the footer is torn/truncated or fails
      validation, recover the readable row-group prefix instead of
      raising (``format/recover.py``).  The reader is then flagged
      :attr:`salvaged` with a :attr:`salvage_report`, and the partial
      metadata carries a ``tpq.salvaged`` key-value marker.  Recovered
      data is bit-exact or absent — never wrong.
    * ``salvage_like`` — schema/codec donor for salvage of files with
      no embedded salvage hint: a sibling path, reader, or
      ``FileMetaData``.
    """

    def __init__(self, source, *columns: str,
                 verify_crc: bool | None = None,
                 strict_metadata: bool | None = None,
                 salvage: bool = False,
                 salvage_like=None):
        import threading

        if isinstance(source, (str, bytes)) and not hasattr(source, "read"):
            self._f = open(source, "rb")
            self._owns = True
            self.name = source if isinstance(source, str) else None
        else:
            self._f = source
            self._owns = False
            self.name = getattr(source, "name", None)
        self._verify_crc = verify_crc
        # seek+read pairs must be atomic: the pipelined device reader
        # plans row group N+1 on a worker thread while the caller may
        # still use this reader from the main thread
        self._io_lock = threading.Lock()
        self._buf = None
        self.salvaged = False
        self.salvage_report = None
        self.metadata_findings = None
        try:
            fault_point("io.reader.open", file=self.name)
            self.meta: FileMetaData = self._resolve_metadata(
                strict_metadata, salvage, salvage_like)
            # In-memory sources serve chunk blobs as zero-copy views (the
            # read() copy was ~25% of the 50M-value plan phase).  Taken
            # only after the footer parses (a raised export would pin the
            # caller's BytesIO), read-only (blob-derived arrays must not
            # alias the file writably); pins the BytesIO against resize
            # while open.
            if isinstance(self._f, io.BytesIO):
                self._buf = self._f.getbuffer().toreadonly()
            self.schema = Schema.from_elements(self.meta.schema)
            attach_stores(self.schema)
            if columns:
                self.schema.set_selected_columns(*columns)
        except BaseException:
            # a rejected open must not leak the fd it opened (nor pin
            # an in-memory source via the exported buffer)
            if self._buf is not None:
                self._buf.release()
                self._buf = None
            if self._owns:
                self._f.close()
            raise
        self._rg_pos = 0          # next row group to load
        self._loaded = False      # current row group loaded into stores
        self._current_rg = 0      # last loaded (or next) row group index
        self._current_record = 0
        self._rg_records = 0

    def _resolve_metadata(self, strict_metadata, salvage,
                          salvage_like) -> FileMetaData:
        """Footer read + optional strict validation + optional salvage.
        All paths annotate raised errors with the file name and count
        the salvage/reject observables on the active collector."""
        from ..errors import CorruptFooterError
        from ..format.validate import (
            strict_metadata_default,
            validate_metadata,
            raise_on_errors,
        )

        if strict_metadata is None:
            strict_metadata = strict_metadata_default()
        try:
            meta = read_file_metadata(self._f)
        except CorruptFooterError as e:
            if not salvage:
                raise e.annotate(file=self.name)
            # footer unusable: rebuild from the pages (forward scan)
            from ..format.recover import recover_file_metadata

            meta, report = recover_file_metadata(
                self._f, like=salvage_like,
                verify_crc=(self._verify_crc
                            if self._verify_crc is not None else True))
            report["footer_error"] = str(e)
            self._mark_salvaged(meta, report)
            return meta
        if not (strict_metadata or salvage):
            return meta
        size = _source_size(self._f)
        findings = validate_metadata(meta, size)
        self.metadata_findings = findings
        if not any(f.is_error for f in findings):
            return meta
        if salvage:
            # footer decodes but lies.  Two independent salvage routes:
            # trim to the validated row-group prefix (keeps the richer
            # footer metadata), or rebuild from the pages themselves
            # (donor schema / the file's own embedded hint — a lying
            # footer over INTACT pages loses nothing that way).  Take
            # whichever recovers more row groups; tie goes to the trim.
            from ..format.recover import (
                recover_file_metadata,
                salvage_valid_prefix,
            )

            trimmed = salvage_valid_prefix(meta, size,
                                           findings=findings)
            if trimmed is not None and len(trimmed[0].row_groups) \
                    == len(meta.row_groups):
                # the trim kept everything (repairable file-level lie
                # only): page recovery cannot beat it, skip the scan
                meta, report = trimmed
                self._mark_salvaged(meta, report)
                return meta
            try:
                rebuilt = recover_file_metadata(
                    self._f, like=salvage_like,
                    verify_crc=(self._verify_crc
                                if self._verify_crc is not None
                                else True))
            except CorruptFooterError:
                rebuilt = None  # no donor and no hint
            best = None
            if trimmed is not None and (
                    rebuilt is None
                    or len(trimmed[0].row_groups)
                    >= len(rebuilt[0].row_groups)):
                best = trimmed
            elif rebuilt is not None:
                best = rebuilt
            if best is not None:
                meta, report = best
                self._mark_salvaged(meta, report)
                return meta
            # neither route usable: fall through to the strict reject
        from ..stats import current_stats

        st = current_stats()
        if st is not None:
            st.metadata_rejects += 1
            if st.events is not None:
                st.events.fault(site="io.reader.footer",
                                kind="metadata_reject", file=self.name)
        try:
            raise_on_errors(findings, file=self.name)
        except CorruptFooterError as e:
            raise e.annotate(file=self.name)
        return meta

    def _mark_salvaged(self, meta: FileMetaData, report: dict) -> None:
        from ..stats import current_stats

        self.salvaged = True
        self.salvage_report = report
        st = current_stats()
        if st is not None:
            st.files_salvaged += 1
            st.row_groups_recovered += len(meta.row_groups or [])
            if st.events is not None:
                st.events.fault(
                    site="io.reader.footer", kind="salvaged",
                    file=self.name,
                    row_groups=len(meta.row_groups or []),
                    stop_reason=report.get("stop_reason"))

    # -- metadata accessors ------------------------------------------------

    @property
    def num_rows(self) -> int:
        return self.meta.num_rows

    def row_group_count(self) -> int:
        return len(self.meta.row_groups)

    def metadata(self) -> FileMetaData:
        return self.meta

    def key_value_metadata(self) -> dict:
        return {
            kv.key: kv.value for kv in (self.meta.key_value_metadata or [])
        }

    def column_meta_data(self, column: str) -> tuple[dict, ColumnMetaData]:
        """Per-row-group metadata for a column of the *current* row group
        (≙ ``ColumnMetaData``, ``file_reader.go:127``)."""
        rg = self.meta.row_groups[self._current_rg]
        for cc in rg.columns:
            if ".".join(cc.meta_data.path_in_schema) == column:
                return self.key_value_metadata(), cc.meta_data
        raise KeyError(f"no such column {column!r}")

    def current_row_group(self):
        return self.meta.row_groups[self._current_rg]

    def get_schema_definition(self):
        return self.schema.definition()

    # -- row-group loading -------------------------------------------------

    def read_row_group_arrays(self, rg_index: int) -> dict[str, ChunkData]:
        """Decode the selected columns of one row group into codec-layer
        arrays (no row assembly).  Only selected chunks are read from the
        file at all — projection skips both I/O and decode (≙ skipChunk,
        ``chunk_reader.go:286``)."""
        if not 0 <= rg_index < len(self.meta.row_groups):
            raise IndexError(
                f"row group {rg_index} out of range "
                f"(file has {len(self.meta.row_groups)})"
            )
        from ..stats import current_stats

        st = current_stats()
        if st is not None:
            st.row_groups += 1
        rg = self.meta.row_groups[rg_index]
        out = {}
        # phase span for the Perfetto export; nothing runs (and nothing
        # allocates) on this path without an event-carrying collector
        ev = None if st is None else st.events
        t0 = time.perf_counter() if ev is not None else 0.0
        try:
            for path, node, cm, blob, start in self.iter_selected_chunks(rg):
                out[path] = read_chunk(memoryview(blob),
                                       _rebase(cm, start), node,
                                       verify_crc=self._verify_crc)
        except ScanError as e:
            raise e.annotate(row_group=rg_index, file=self.name)
        if ev is not None:
            import threading

            ev.span("read_row_group", "cpu-decode", t0,
                    time.perf_counter(), tid=threading.get_ident(),
                    rg=rg_index, columns=len(out))
        return out

    def iter_selected_chunks(self, rg):
        """Yield (path, node, cm, chunk_bytes, start_offset) for each
        selected chunk of a row group — the shared slurp used by both the
        CPU and device decode paths."""
        for cc in rg.columns:
            cm = cc.meta_data
            path = ".".join(cm.path_in_schema)
            node = self.schema.leaf(path)
            if node is None:
                raise ValueError(f"column {path!r} not in schema")
            if not self.schema.is_selected(node):
                continue
            start = cm.data_page_offset
            if cm.dictionary_page_offset is not None:
                start = min(start, cm.dictionary_page_offset)
            if self._buf is not None:
                # explicit bounds: negative offsets would WRAP on a
                # memoryview slice (the old seek() raised instead)
                if (start < 0 or cm.total_compressed_size < 0
                        or start + cm.total_compressed_size
                        > len(self._buf)):
                    raise CorruptChunkError("column chunk overruns file",
                                            column=path, file=self.name)
                fault_point("io.reader.chunk_read", column=path)
                blob = self._buf[start : start + cm.total_compressed_size]
            else:
                def _read(start=start, size=cm.total_compressed_size):
                    # the fault point sits INSIDE the retried callable:
                    # an injected transient fault exercises the same
                    # backoff loop a flaky filesystem would
                    fault_point("io.reader.chunk_read", column=path)
                    with self._io_lock:
                        self._f.seek(start)
                        return self._f.read(size)

                blob = retry_transient(_read)
                if len(blob) < cm.total_compressed_size:
                    raise CorruptChunkError(
                        f"column chunk short read: {len(blob)}/"
                        f"{cm.total_compressed_size} bytes",
                        column=path, file=self.name)
            blob = filter_bytes("io.reader.chunk_read", blob, column=path)
            yield path, node, cm, blob, start

    def pre_load(self) -> None:
        """Eagerly load the next row group (≙ ``PreLoad``)."""
        if not self._loaded:
            self._load_next()

    def skip_row_group(self) -> None:
        """Skip the remainder of the current/next row group."""
        if self._loaded:
            self._loaded = False
        else:
            self._rg_pos += 1

    def _load_next(self) -> None:
        if self._rg_pos >= len(self.meta.row_groups):
            raise EOFError("no more row groups")
        idx = self._rg_pos
        data = self.read_row_group_arrays(idx)
        rg = self.meta.row_groups[idx]
        for leaf in self.schema.leaves:
            cd = data.get(leaf.flat_name)
            if cd is None:
                leaf.store.mark_skipped()
            else:
                leaf.store.load_decoded(
                    cd.values, cd.rep_levels, cd.def_levels
                )
        self._current_rg = idx
        self._rg_pos += 1
        self._loaded = True
        self._current_record = 0
        self._rg_records = rg.num_rows

    # -- row iteration -----------------------------------------------------

    def next_row(self) -> dict:
        """Next assembled record; raises EOFError at end of file
        (≙ ``NextRow`` returning io.EOF)."""
        while True:
            if not self._loaded:
                self._load_next()  # raises EOFError when exhausted
            if self._current_record < self._rg_records:
                self._current_record += 1
                if self._current_record >= self._rg_records:
                    self._loaded = False  # advance on the next call
                return assemble_record(self.schema)
            self._loaded = False

    def rows(self):
        """Iterate every remaining record."""
        while True:
            try:
                yield self.next_row()
            except EOFError:
                return

    # -- cleanup -----------------------------------------------------------

    def close(self) -> None:
        if self._buf is not None:
            # release the exported buffer or BytesIO.close() raises
            self._buf.release()
            self._buf = None
        if self._owns:
            self._f.close()

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        self.close()


def _rebase(cm: ColumnMetaData, base: int) -> ColumnMetaData:
    """Shift a chunk's offsets to be relative to a sliced byte range."""
    out = ColumnMetaData(**{
        name: getattr(cm, name) for name in cm._NAMES
    })
    out.data_page_offset = cm.data_page_offset - base
    if cm.dictionary_page_offset is not None:
        out.dictionary_page_offset = cm.dictionary_page_offset - base
    return out
