"""Tiered range cache for remote byte-range sources.

Two tiers, two access costs:

* **Memory** (:class:`MemRangeCache`, ``TPQ_CACHE_MEM_MB``) — hot small
  ranges: footer framing bytes, metadata blobs, page-index/bloom
  sections.  These are re-read on every reopen (fingerprint hashing,
  handle un-poisoning, mirror opens), and on an object store each
  re-read is a full round trip.  Same byte-budgeted LRU discipline as
  ``kernels/plancache.py``, keyed by the source's *etag* — ``(path,
  size, mtime_ns)`` — plus the range, so a rewritten object can never
  be served stale bytes.

* **Disk** (:class:`DiskRangeCache`, ``TPQ_CACHE_DISK_DIR`` +
  ``TPQ_CACHE_DISK_MB``) — recently fetched column-chunk ranges.  One
  file per entry, written atomically (tmp + ``os.replace``) and
  CRC-verified on every read.  A torn file (process killed mid-write)
  or a bit-rotted payload can therefore never reach a decoder: torn
  framing self-heals silently (unlink + miss), while a CRC mismatch on
  well-formed framing is treated as *poisoning* — the entry is
  evicted, a ``cache_poison`` flight record and post-mortem incident
  are emitted, and the key is marked so the direct refetch is NOT
  immediately re-cached (degrade to uncached: if the payload keeps
  arriving corrupt, the cache must not amplify it).

Both tiers bump the exactly-merging ``cache_{hits,misses,evictions}_
{mem,disk}`` counters on the calling thread's collector, so
``cache_hits + cache_misses == lookups`` holds per tier by
construction.  :func:`invalidate_source_caches` drops both tiers for a
path — wired to the corruption/quarantine/salvage hooks.
"""

from __future__ import annotations

import json
import os
import threading
import zlib
from collections import OrderedDict

from ..obs import recorder as _flightrec
from .source import parse_source_uri

__all__ = [
    "MemRangeCache",
    "DiskRangeCache",
    "mem_cache",
    "disk_cache",
    "invalidate_source_caches",
    "reset_range_caches",
]

_MAGIC = b"TPQC1"
_SUFFIX = ".tpqc"
# magic + crc32(u32) + payload_len(u64) + key_len(u16), big-endian
_HDR = len(_MAGIC) + 4 + 8 + 2


def _bump(field: str, n: int = 1) -> None:
    from ..stats import current_stats

    st = current_stats()
    if st is not None:
        setattr(st, field, getattr(st, field) + n)


def _norm_path(src: str) -> str:
    """Cache keys store the backing *path*; accept either a path or a
    ``scheme://path`` URI at the invalidation hooks."""
    parsed = parse_source_uri(src)
    return parsed[1] if parsed is not None else src


def mem_cache_budget() -> int:
    """``TPQ_CACHE_MEM_MB`` in bytes (default 16 MiB; ``0`` disables).
    Read per call so tests and operators can flip it live."""
    v = os.environ.get("TPQ_CACHE_MEM_MB")
    if v is None or v == "":
        return 16 * (1 << 20)
    return max(0, int(float(v) * (1 << 20)))


def disk_cache_dir() -> str | None:
    return os.environ.get("TPQ_CACHE_DISK_DIR") or None


def disk_cache_budget() -> int:
    """``TPQ_CACHE_DISK_MB`` in bytes (default 256 MiB; ``0`` disables
    the disk tier even when a directory is configured)."""
    v = os.environ.get("TPQ_CACHE_DISK_MB")
    if v is None or v == "":
        return 256 * (1 << 20)
    return max(0, int(float(v) * (1 << 20)))


class MemRangeCache:
    """Byte-budgeted LRU of ``key -> bytes`` (self-synchronized)."""

    def __init__(self, budget: int):
        self._budget = budget
        self._lock = threading.Lock()
        self._entries: OrderedDict = OrderedDict()
        self._bytes = 0

    def get(self, key):
        with self._lock:
            data = self._entries.get(key)
            if data is None:
                _bump("cache_misses_mem")
                return None
            self._entries.move_to_end(key)
        _bump("cache_hits_mem")
        return data

    def put(self, key, data: bytes) -> None:
        n = len(data)
        if n > self._budget:
            return
        evicted = 0
        with self._lock:
            old = self._entries.pop(key, None)
            if old is not None:
                self._bytes -= len(old)
            self._entries[key] = data
            self._bytes += n
            while self._bytes > self._budget and len(self._entries) > 1:
                _, dropped = self._entries.popitem(last=False)
                self._bytes -= len(dropped)
                evicted += 1
        if evicted:
            _bump("cache_evictions_mem", evicted)

    def invalidate_path(self, path: str) -> int:
        with self._lock:
            doomed = [k for k in self._entries if k[0] == path]
            for k in doomed:
                self._bytes -= len(self._entries.pop(k))
        if doomed:
            _bump("cache_evictions_mem", len(doomed))
        return len(doomed)

    def stats(self) -> dict:
        with self._lock:
            return {"entries": len(self._entries), "bytes": self._bytes,
                    "budget": self._budget}


class DiskRangeCache:
    """One CRC-framed file per cached range, LRU by entry mtime.

    Entry layout: ``TPQC1 | crc32(payload) u32 | payload_len u64 |
    key_len u16 | key json | payload`` (big-endian).  Writes go to a
    ``.tmp`` sibling and ``os.replace`` in, so a crash leaves either
    the old entry, the new entry, or a ``.tmp`` straggler that the next
    startup sweep removes — never a half-entry under the real name.
    """

    def __init__(self, directory: str, budget: int):
        self._dir = directory
        self._budget = budget
        self._lock = threading.Lock()
        # key -> [fname, total_file_bytes]; insertion order = LRU order
        self._index: OrderedDict = OrderedDict()
        self._bytes = 0
        self._no_recache: set = set()  # poisoned keys: skip next put
        os.makedirs(directory, exist_ok=True)
        self._sweep()

    # -- startup recovery -------------------------------------------------
    def _sweep(self) -> None:
        """Rebuild the index from disk: drop ``.tmp`` stragglers and
        entries whose framing no longer parses (torn by a crash)."""
        found = []
        for fn in os.listdir(self._dir):
            fp = os.path.join(self._dir, fn)
            if fn.endswith(".tmp"):
                _unlink_quiet(fp)
                continue
            if not fn.endswith(_SUFFIX):
                continue
            key = self._parse_header(fp)
            if key is None:
                _unlink_quiet(fp)  # torn entry: self-heal
                continue
            try:
                st = os.stat(fp)
            except OSError:
                continue
            found.append((st.st_mtime_ns, key, fn, st.st_size))
        for _, key, fn, nbytes in sorted(found):
            self._index[key] = [fn, nbytes]
            self._bytes += nbytes

    @staticmethod
    def _parse_header(fp: str):
        """Key tuple from an entry's header, or None if malformed.
        Validates framing only — payload CRC is checked at ``get``."""
        try:
            with open(fp, "rb") as f:
                hdr = f.read(_HDR)
                if len(hdr) < _HDR or hdr[:len(_MAGIC)] != _MAGIC:
                    return None
                o = len(_MAGIC) + 4
                plen = int.from_bytes(hdr[o:o + 8], "big")
                klen = int.from_bytes(hdr[o + 8:o + 10], "big")
                kraw = f.read(klen)
                if len(kraw) < klen:
                    return None
                if os.fstat(f.fileno()).st_size != _HDR + klen + plen:
                    return None
                return tuple(json.loads(kraw.decode()))
        except (OSError, ValueError):
            return None

    # -- entry naming -----------------------------------------------------
    @staticmethod
    def _fname(key) -> str:
        import hashlib

        raw = json.dumps(list(key)).encode()
        return hashlib.sha256(raw).hexdigest()[:40] + _SUFFIX

    # -- contract ---------------------------------------------------------
    def get(self, key):
        with self._lock:
            ent = self._index.get(key)
            if ent is not None:
                self._index.move_to_end(key)
        if ent is None:
            _bump("cache_misses_disk")
            return None
        fp = os.path.join(self._dir, ent[0])
        data, poisoned = self._read_entry(fp, key)
        if data is not None:
            _bump("cache_hits_disk")
            try:
                os.utime(fp)  # LRU persists across restarts
            except OSError:
                pass
            return data
        # unreadable entry: evict; on CRC poison also pin the key so
        # the direct refetch ships uncached (see module docstring)
        with self._lock:
            dropped = self._index.pop(key, None)
            if dropped is not None:
                self._bytes -= dropped[1]
            if poisoned:
                self._no_recache.add(key)
        _unlink_quiet(fp)
        _bump("cache_misses_disk")
        _bump("cache_evictions_disk")
        if poisoned:
            if _flightrec._active is not None:
                _flightrec.flight(
                    "cache_poison", site="io.remote.range",
                    file=key[0], start=key[3], size=key[4])
            from ..obs.postmortem import postmortem_path_for, \
                record_incident

            record_incident(postmortem_path_for(None), {
                "kind": "cache_poison", "file": key[0],
                "start": key[3], "size": key[4], "entry": fp,
            })
        return None

    def contains(self, key) -> bool:
        """Counter-free index peek for the prefetch planner.  No
        hit/miss bump: conservation (hits + misses == lookups) is
        pinned on ``get`` alone, and prefetch consults this before
        deciding what to fetch — it is not a lookup."""
        with self._lock:
            return key in self._index

    def _read_entry(self, fp: str, key):
        """(payload, poisoned): payload None when unreadable; poisoned
        True only for a CRC mismatch inside intact framing."""
        try:
            with open(fp, "rb") as f:
                blob = f.read()
        except OSError:
            return None, False
        if len(blob) < _HDR or blob[:len(_MAGIC)] != _MAGIC:
            return None, False
        o = len(_MAGIC)
        crc = int.from_bytes(blob[o:o + 4], "big")
        plen = int.from_bytes(blob[o + 4:o + 12], "big")
        klen = int.from_bytes(blob[o + 12:o + 14], "big")
        if len(blob) != _HDR + klen + plen:
            return None, False
        try:
            stored = tuple(json.loads(blob[_HDR:_HDR + klen].decode()))
        except ValueError:
            return None, False
        if stored != tuple(key):
            return None, False
        payload = blob[_HDR + klen:]
        if zlib.crc32(payload) & 0xFFFFFFFF != crc:
            return None, True  # bit rot: the poisoning case
        return payload, False

    def put(self, key, data: bytes) -> None:
        with self._lock:
            if key in self._no_recache:
                self._no_recache.discard(key)
                return
        kraw = json.dumps(list(key)).encode()
        total = _HDR + len(kraw) + len(data)
        if total > self._budget:
            return
        fn = self._fname(key)
        fp = os.path.join(self._dir, fn)
        tmp = f"{fp}.{os.getpid()}.{threading.get_ident()}.tmp"
        hdr = (_MAGIC
               + (zlib.crc32(data) & 0xFFFFFFFF).to_bytes(4, "big")
               + len(data).to_bytes(8, "big")
               + len(kraw).to_bytes(2, "big"))
        try:
            with open(tmp, "wb") as f:
                f.write(hdr)
                f.write(kraw)
                f.write(data)
            os.replace(tmp, fp)
        except OSError:
            _unlink_quiet(tmp)
            return
        evict = []
        with self._lock:
            old = self._index.pop(key, None)
            if old is not None:
                self._bytes -= old[1]
            self._index[key] = [fn, total]
            self._bytes += total
            while self._bytes > self._budget and len(self._index) > 1:
                k, (efn, ebytes) = self._index.popitem(last=False)
                self._bytes -= ebytes
                evict.append(efn)
        for efn in evict:
            _unlink_quiet(os.path.join(self._dir, efn))
        if evict:
            _bump("cache_evictions_disk", len(evict))

    def invalidate_path(self, path: str) -> int:
        with self._lock:
            doomed = [(k, ent) for k, ent in self._index.items()
                      if k[0] == path]
            for k, ent in doomed:
                self._index.pop(k, None)
                self._bytes -= ent[1]
        for _, ent in doomed:
            _unlink_quiet(os.path.join(self._dir, ent[0]))
        if doomed:
            _bump("cache_evictions_disk", len(doomed))
        return len(doomed)

    def stats(self) -> dict:
        with self._lock:
            return {"entries": len(self._index), "bytes": self._bytes,
                    "budget": self._budget, "dir": self._dir}


def _unlink_quiet(fp: str) -> None:
    try:
        os.unlink(fp)
    except OSError:
        pass


# -- process-wide tier singletons (env-keyed, rebuilt when config
# changes; mutated only under the module lock) --------------------------
_LOCK = threading.Lock()
_MEM: tuple | None = None   # (budget, MemRangeCache)
_DISK: tuple | None = None  # ((dir, budget), DiskRangeCache)


def mem_cache() -> MemRangeCache | None:
    global _MEM
    budget = mem_cache_budget()
    if budget <= 0:
        return None
    with _LOCK:
        if _MEM is None or _MEM[0] != budget:
            _MEM = (budget, MemRangeCache(budget))
        return _MEM[1]


def disk_cache() -> DiskRangeCache | None:
    global _DISK
    d = disk_cache_dir()
    if d is None:
        return None
    budget = disk_cache_budget()
    if budget <= 0:
        return None
    with _LOCK:
        if _DISK is None or _DISK[0] != (d, budget):
            _DISK = ((d, budget), DiskRangeCache(d, budget))
        return _DISK[1]


def invalidate_source_caches(src: str) -> int:
    """Drop every cached range for a source from BOTH tiers — the
    corruption/quarantine/salvage invalidation hook.  Accepts a bare
    path or a ``scheme://`` URI; returns entries dropped."""
    path = _norm_path(src)
    n = 0
    m = mem_cache()
    if m is not None:
        n += m.invalidate_path(path)
    d = disk_cache()
    if d is not None:
        n += d.invalidate_path(path)
    return n


def reset_range_caches() -> None:
    """Test hook: forget both tier singletons (the next lookup rebuilds
    from the current env)."""
    global _MEM, _DISK
    with _LOCK:
        _MEM = None
        _DISK = None
