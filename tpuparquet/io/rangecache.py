"""Tiered range cache for remote byte-range sources.

Two tiers, two access costs:

* **Memory** (:class:`MemRangeCache`, ``TPQ_CACHE_MEM_MB``) — hot small
  ranges: footer framing bytes, metadata blobs, page-index/bloom
  sections.  These are re-read on every reopen (fingerprint hashing,
  handle un-poisoning, mirror opens), and on an object store each
  re-read is a full round trip.  Same byte-budgeted LRU discipline as
  ``kernels/plancache.py``, keyed by the source's *etag* — ``(path,
  size, mtime_ns)`` — plus the range, so a rewritten object can never
  be served stale bytes.

* **Disk** (:class:`DiskRangeCache`, ``TPQ_CACHE_DISK_DIR`` +
  ``TPQ_CACHE_DISK_MB``) — recently fetched column-chunk ranges.  One
  file per entry, written atomically (tmp + ``os.replace``) and
  CRC-verified on every read.  A torn file (process killed mid-write)
  or a bit-rotted payload can therefore never reach a decoder: torn
  framing self-heals silently (unlink + miss), while a CRC mismatch on
  well-formed framing is treated as *poisoning* — the entry is
  evicted, a ``cache_poison`` flight record and post-mortem incident
  are emitted, and the key is marked so the direct refetch is NOT
  immediately re-cached (degrade to uncached: if the payload keeps
  arriving corrupt, the cache must not amplify it).

Both tiers bump the exactly-merging ``cache_{hits,misses,evictions}_
{mem,disk}`` counters on the calling thread's collector, so
``cache_hits + cache_misses == lookups`` holds per tier by
construction.  :func:`invalidate_source_caches` drops both tiers for a
path — wired to the corruption/quarantine/salvage hooks.

**Cross-process sharing** (``TPQ_CACHE_DISK_SHARED=1``,
:class:`SharedDiskRangeCache`): N server processes over ONE cache
directory, so a fleet hits origin approximately once per span.  The
single-process tier already publishes entries atomically; sharing adds
the coordination the multi-writer regime needs:

* a **CRC-framed journaled index** (``index.tpqj``) — every publish/
  evict/poison appends a framed record under the directory lock; each
  process replays new records into its in-memory mirror, so eviction
  decisions (and poison pins) are visible fleet-wide without rescans.
  A torn tail (kill mid-append) is data-end for readers and is
  truncated by the next lock holder before it appends.
* a **lock file** (``index.lock``) with dead-holder recovery — the
  holder's pid rides in the file; a contender that finds a dead pid
  renames the stale lock aside (exactly one wins the rename) and
  retakes it, so a SIGKILL inside the critical section never wedges
  the fleet.
* **generation-stamped entries** — a publish never overwrites a live
  entry file in place; each publish gets a fresh
  ``<keyhash>.<pid>-<seq>.tpqc`` name, so a concurrent reader holding
  the OLD name sees either the complete old frame or ENOENT (a clean
  miss) — never a frame mid-replacement.
* **init self-heal** — a process joining (or restarting after a kill
  at ANY byte) takes the lock, truncates a torn journal tail, drops
  journal entries whose files are gone/torn, unlinks orphan files the
  journal never published, and compacts the journal when it has grown
  past its live set.

Only the process that journals an eviction bumps
``cache_evictions_disk`` (replaying processes just update their
mirror), so summing counters across the fleet stays exact — no
phantom evictions.
"""

from __future__ import annotations

import itertools
import json
import os
import threading
import time
import zlib
from collections import OrderedDict

from ..obs import recorder as _flightrec
from .source import parse_source_uri

__all__ = [
    "MemRangeCache",
    "DiskRangeCache",
    "SharedDiskRangeCache",
    "mem_cache",
    "disk_cache",
    "disk_cache_shared",
    "invalidate_source_caches",
    "reset_range_caches",
]

_MAGIC = b"TPQC1"
_SUFFIX = ".tpqc"
# magic + crc32(u32) + payload_len(u64) + key_len(u16), big-endian
_HDR = len(_MAGIC) + 4 + 8 + 2

# shared-index journal framing: magic + crc32(payload) u32 + len u32
_JMAGIC = b"TPQJ"
_JHDR = len(_JMAGIC) + 4 + 4
_JOURNAL = "index.tpqj"
_LOCKFILE = "index.lock"


def _bump(field: str, n: int = 1) -> None:
    from ..stats import current_stats

    st = current_stats()
    if st is not None:
        setattr(st, field, getattr(st, field) + n)


def _norm_path(src: str) -> str:
    """Cache keys store the backing *path*; accept either a path or a
    ``scheme://path`` URI at the invalidation hooks.  HTTP sources key
    on the full URL (there is no local backing path to strip to)."""
    parsed = parse_source_uri(src)
    if parsed is None or parsed[0] in ("http", "https"):
        return src
    return parsed[1]


def mem_cache_budget() -> int:
    """``TPQ_CACHE_MEM_MB`` in bytes (default 16 MiB; ``0`` disables).
    Read per call so tests and operators can flip it live."""
    v = os.environ.get("TPQ_CACHE_MEM_MB")
    if v is None or v == "":
        return 16 * (1 << 20)
    return max(0, int(float(v) * (1 << 20)))


def disk_cache_dir() -> str | None:
    return os.environ.get("TPQ_CACHE_DISK_DIR") or None


def disk_cache_budget() -> int:
    """``TPQ_CACHE_DISK_MB`` in bytes (default 256 MiB; ``0`` disables
    the disk tier even when a directory is configured)."""
    v = os.environ.get("TPQ_CACHE_DISK_MB")
    if v is None or v == "":
        return 256 * (1 << 20)
    return max(0, int(float(v) * (1 << 20)))


def disk_cache_shared() -> bool:
    """``TPQ_CACHE_DISK_SHARED=1`` — coordinate the disk tier across
    processes (journaled index + directory lock; see module
    docstring).  Off by default: a private cache dir needs none of
    the coordination cost."""
    return os.environ.get("TPQ_CACHE_DISK_SHARED", "") == "1"


class MemRangeCache:
    """Byte-budgeted LRU of ``key -> bytes`` (self-synchronized)."""

    def __init__(self, budget: int):
        self._budget = budget
        self._lock = threading.Lock()
        self._entries: OrderedDict = OrderedDict()
        self._bytes = 0

    def get(self, key):
        with self._lock:
            data = self._entries.get(key)
            if data is None:
                _bump("cache_misses_mem")
                return None
            self._entries.move_to_end(key)
        _bump("cache_hits_mem")
        return data

    def put(self, key, data: bytes) -> None:
        n = len(data)
        if n > self._budget:
            return
        evicted = 0
        with self._lock:
            old = self._entries.pop(key, None)
            if old is not None:
                self._bytes -= len(old)
            self._entries[key] = data
            self._bytes += n
            while self._bytes > self._budget and len(self._entries) > 1:
                _, dropped = self._entries.popitem(last=False)
                self._bytes -= len(dropped)
                evicted += 1
        if evicted:
            _bump("cache_evictions_mem", evicted)

    def invalidate_path(self, path: str) -> int:
        with self._lock:
            doomed = [k for k in self._entries if k[0] == path]
            for k in doomed:
                self._bytes -= len(self._entries.pop(k))
        if doomed:
            _bump("cache_evictions_mem", len(doomed))
        return len(doomed)

    def stats(self) -> dict:
        with self._lock:
            return {"entries": len(self._entries), "bytes": self._bytes,
                    "budget": self._budget}


class DiskRangeCache:
    """One CRC-framed file per cached range, LRU by entry mtime.

    Entry layout: ``TPQC1 | crc32(payload) u32 | payload_len u64 |
    key_len u16 | key json | payload`` (big-endian).  Writes go to a
    ``.tmp`` sibling and ``os.replace`` in, so a crash leaves either
    the old entry, the new entry, or a ``.tmp`` straggler that the next
    startup sweep removes — never a half-entry under the real name.
    """

    def __init__(self, directory: str, budget: int):
        self._dir = directory
        self._budget = budget
        self._lock = threading.Lock()
        # key -> [fname, total_file_bytes]; insertion order = LRU order
        self._index: OrderedDict = OrderedDict()
        self._bytes = 0
        self._no_recache: set = set()  # poisoned keys: skip next put
        os.makedirs(directory, exist_ok=True)
        self._sweep()

    # -- startup recovery -------------------------------------------------
    def _sweep(self) -> None:
        """Rebuild the index from disk: drop ``.tmp`` stragglers and
        entries whose framing no longer parses (torn by a crash)."""
        found = []
        for fn in os.listdir(self._dir):
            fp = os.path.join(self._dir, fn)
            if fn.endswith(".tmp"):
                _unlink_quiet(fp)
                continue
            if not fn.endswith(_SUFFIX):
                continue
            key = self._parse_header(fp)
            if key is None:
                _unlink_quiet(fp)  # torn entry: self-heal
                continue
            try:
                st = os.stat(fp)
            except OSError:
                continue
            found.append((st.st_mtime_ns, key, fn, st.st_size))
        for _, key, fn, nbytes in sorted(found):
            self._index[key] = [fn, nbytes]
            self._bytes += nbytes

    @staticmethod
    def _parse_header(fp: str):
        """Key tuple from an entry's header, or None if malformed.
        Validates framing only — payload CRC is checked at ``get``."""
        try:
            with open(fp, "rb") as f:
                hdr = f.read(_HDR)
                if len(hdr) < _HDR or hdr[:len(_MAGIC)] != _MAGIC:
                    return None
                o = len(_MAGIC) + 4
                plen = int.from_bytes(hdr[o:o + 8], "big")
                klen = int.from_bytes(hdr[o + 8:o + 10], "big")
                kraw = f.read(klen)
                if len(kraw) < klen:
                    return None
                if os.fstat(f.fileno()).st_size != _HDR + klen + plen:
                    return None
                return tuple(json.loads(kraw.decode()))
        except (OSError, ValueError):
            return None

    # -- entry naming -----------------------------------------------------
    @staticmethod
    def _fname(key) -> str:
        import hashlib

        raw = json.dumps(list(key)).encode()
        return hashlib.sha256(raw).hexdigest()[:40] + _SUFFIX

    # -- contract ---------------------------------------------------------
    def get(self, key):
        with self._lock:
            ent = self._index.get(key)
            if ent is not None:
                self._index.move_to_end(key)
        if ent is None:
            _bump("cache_misses_disk")
            return None
        fp = os.path.join(self._dir, ent[0])
        data, poisoned = self._read_entry(fp, key)
        if data is not None:
            _bump("cache_hits_disk")
            try:
                os.utime(fp)  # LRU persists across restarts
            except OSError:
                pass
            return data
        # unreadable entry: evict; on CRC poison also pin the key so
        # the direct refetch ships uncached (see module docstring)
        with self._lock:
            dropped = self._index.pop(key, None)
            if dropped is not None:
                self._bytes -= dropped[1]
            if poisoned:
                self._no_recache.add(key)
        _unlink_quiet(fp)
        _bump("cache_misses_disk")
        _bump("cache_evictions_disk")
        if poisoned:
            if _flightrec._active is not None:
                _flightrec.flight(
                    "cache_poison", site="io.remote.range",
                    file=key[0], start=key[3], size=key[4])
            from ..obs.postmortem import postmortem_path_for, \
                record_incident

            record_incident(postmortem_path_for(None), {
                "kind": "cache_poison", "file": key[0],
                "start": key[3], "size": key[4], "entry": fp,
            })
        return None

    def contains(self, key) -> bool:
        """Counter-free index peek for the prefetch planner.  No
        hit/miss bump: conservation (hits + misses == lookups) is
        pinned on ``get`` alone, and prefetch consults this before
        deciding what to fetch — it is not a lookup."""
        with self._lock:
            return key in self._index

    def _read_entry(self, fp: str, key):
        """(payload, poisoned): payload None when unreadable; poisoned
        True only for a CRC mismatch inside intact framing."""
        try:
            with open(fp, "rb") as f:
                blob = f.read()
        except OSError:
            return None, False
        if len(blob) < _HDR or blob[:len(_MAGIC)] != _MAGIC:
            return None, False
        o = len(_MAGIC)
        crc = int.from_bytes(blob[o:o + 4], "big")
        plen = int.from_bytes(blob[o + 4:o + 12], "big")
        klen = int.from_bytes(blob[o + 12:o + 14], "big")
        if len(blob) != _HDR + klen + plen:
            return None, False
        try:
            stored = tuple(json.loads(blob[_HDR:_HDR + klen].decode()))
        except ValueError:
            return None, False
        if stored != tuple(key):
            return None, False
        payload = blob[_HDR + klen:]
        if zlib.crc32(payload) & 0xFFFFFFFF != crc:
            return None, True  # bit rot: the poisoning case
        return payload, False

    def put(self, key, data: bytes) -> None:
        with self._lock:
            if key in self._no_recache:
                self._no_recache.discard(key)
                return
        kraw = json.dumps(list(key)).encode()
        total = _HDR + len(kraw) + len(data)
        if total > self._budget:
            return
        fn = self._fname(key)
        fp = os.path.join(self._dir, fn)
        tmp = f"{fp}.{os.getpid()}.{threading.get_ident()}.tmp"
        hdr = (_MAGIC
               + (zlib.crc32(data) & 0xFFFFFFFF).to_bytes(4, "big")
               + len(data).to_bytes(8, "big")
               + len(kraw).to_bytes(2, "big"))
        try:
            with open(tmp, "wb") as f:
                f.write(hdr)
                f.write(kraw)
                f.write(data)
            os.replace(tmp, fp)
        except OSError:
            _unlink_quiet(tmp)
            return
        evict = []
        with self._lock:
            old = self._index.pop(key, None)
            if old is not None:
                self._bytes -= old[1]
            self._index[key] = [fn, total]
            self._bytes += total
            while self._bytes > self._budget and len(self._index) > 1:
                k, (efn, ebytes) = self._index.popitem(last=False)
                self._bytes -= ebytes
                evict.append(efn)
        for efn in evict:
            _unlink_quiet(os.path.join(self._dir, efn))
        if evict:
            _bump("cache_evictions_disk", len(evict))

    def invalidate_path(self, path: str) -> int:
        with self._lock:
            doomed = [(k, ent) for k, ent in self._index.items()
                      if k[0] == path]
            for k, ent in doomed:
                self._index.pop(k, None)
                self._bytes -= ent[1]
        for _, ent in doomed:
            _unlink_quiet(os.path.join(self._dir, ent[0]))
        if doomed:
            _bump("cache_evictions_disk", len(doomed))
        return len(doomed)

    def stats(self) -> dict:
        with self._lock:
            return {"entries": len(self._index), "bytes": self._bytes,
                    "budget": self._budget, "dir": self._dir}


def _unlink_quiet(fp: str) -> None:
    try:
        os.unlink(fp)
    except OSError:
        pass


def _pid_alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    except OSError:
        pass  # EPERM etc: someone owns it — treat as alive
    return True


class _DirLock:
    """Cross-process mutex on the cache directory: an ``O_EXCL`` lock
    file carrying the holder's pid, with dead-holder recovery — a
    contender that finds the recorded pid dead renames the stale lock
    aside (exactly one contender wins the rename) and retakes it.

    File-only on purpose: in-process contenders must already be
    serialized by the owning cache's ``_jlock`` (a plain ``with``-held
    threading lock the lock-graph analyzer can see), so this class
    never touches threading primitives and the file only ever
    arbitrates between processes."""

    def __init__(self, directory: str):
        self._path = os.path.join(directory, _LOCKFILE)
        self._seq = itertools.count(1)  # stale-rename uniqifier

    def acquire(self, timeout: float = 30.0) -> bool:
        deadline = time.monotonic() + timeout
        delay = 0.0005
        while True:
            try:
                fd = os.open(self._path,
                             os.O_CREAT | os.O_EXCL | os.O_WRONLY)
            except FileExistsError:
                self._recover_if_stale()
                if time.monotonic() >= deadline:
                    return False
                time.sleep(delay)
                delay = min(delay * 2, 0.05)
                continue
            except OSError:
                return False
            try:
                os.write(fd, json.dumps(
                    {"pid": os.getpid()}).encode())
            finally:
                os.close(fd)
            return True

    def _recover_if_stale(self) -> None:
        try:
            with open(self._path, "rb") as f:
                holder = json.loads(f.read().decode() or "{}")
        except (OSError, ValueError):
            return  # mid-create or already recovered: retry the open
        pid = holder.get("pid")
        if not isinstance(pid, int) or _pid_alive(pid):
            return
        # dead holder: exactly one contender wins this rename; losers
        # see ENOENT and simply retry the O_EXCL create
        stale = (f"{self._path}.stale-{os.getpid()}"
                 f"-{threading.get_ident():x}-{next(self._seq)}")
        try:
            os.rename(self._path, stale)
        except OSError:
            return
        _unlink_quiet(stale)

    def release(self) -> None:
        _unlink_quiet(self._path)


def _jframe(record: dict) -> bytes:
    payload = json.dumps(record, sort_keys=True).encode()
    return (_JMAGIC
            + (zlib.crc32(payload) & 0xFFFFFFFF).to_bytes(4, "big")
            + len(payload).to_bytes(4, "big")
            + payload)


def _jparse(blob: bytes, offset: int = 0):
    """Parse journal frames from ``offset``; returns
    ``(records, end_offset)`` — ``end_offset`` stops at the first
    torn/corrupt frame (a kill mid-append), which readers treat as
    end-of-journal and the next lock holder truncates."""
    records = []
    pos = offset
    n = len(blob)
    while pos + _JHDR <= n:
        if blob[pos:pos + len(_JMAGIC)] != _JMAGIC:
            break
        o = pos + len(_JMAGIC)
        crc = int.from_bytes(blob[o:o + 4], "big")
        plen = int.from_bytes(blob[o + 4:o + 8], "big")
        end = pos + _JHDR + plen
        if end > n:
            break
        payload = blob[pos + _JHDR:end]
        if zlib.crc32(payload) & 0xFFFFFFFF != crc:
            break
        try:
            records.append(json.loads(payload.decode()))
        except ValueError:
            break
        pos = end
    return records, pos


class SharedDiskRangeCache(DiskRangeCache):
    """The disk tier, safe for N concurrent processes over one
    directory (``TPQ_CACHE_DISK_SHARED=1``; see module docstring for
    the journal / lock / generation design).

    Lock order: ``_jlock`` (serializes this process's directory-lock
    critical sections; the ``index.lock`` file is taken and dropped
    strictly inside it) is always OUTERMOST; the in-memory mirror
    lock (``_lock``) nests inside it or stands alone; ``_gen_lock``
    is a leaf.  ``__init__``/``_sweep`` take no threading locks at
    all — construction happens before the instance is published (the
    tier singleton builds it under the module lock), and keeping the
    constructor lock-free keeps the runtime lock graph identical to
    the statically provable one."""

    def __init__(self, directory: str, budget: int):
        self._dirlock = _DirLock(directory)
        self._jlock = threading.Lock()
        self._gen_lock = threading.Lock()
        self._gen = 0          # guarded by _gen_lock
        self._joff = 0         # journal replay offset; guarded by _lock
        self._jino = -1        # journal inode at last replay; _lock
        os.makedirs(directory, exist_ok=True)
        self._jpath = os.path.join(directory, _JOURNAL)
        super().__init__(directory, budget)

    # -- naming -----------------------------------------------------------
    def _next_fname(self, key) -> str:
        """Generation-stamped entry name: publishes never reuse a live
        name, so a reader on the old name gets the complete old frame
        or a clean ENOENT — never a torn replacement."""
        base = DiskRangeCache._fname(key)[: -len(_SUFFIX)]
        with self._gen_lock:
            self._gen += 1
            gen = self._gen
        return f"{base}.{os.getpid():x}-{gen:x}{_SUFFIX}"

    # -- init self-heal ----------------------------------------------------
    def _sweep(self) -> None:
        """Join (or rejoin after a kill at any byte): under the
        directory lock, truncate a torn journal tail, reconcile the
        journal with the directory, and compact when the journal has
        outgrown its live set.  Init-only, pre-publication: mutates
        the mirror and replay offsets directly, no threading locks
        (see the class docstring)."""
        if not self._dirlock.acquire():
            from ..errors import TransientIOError

            raise TransientIOError(
                f"shared-cache lock in {self._dir} not acquired "
                f"(held by a live process for too long)",
                file=os.path.join(self._dir, _LOCKFILE))
        try:
            records, end, ino = self._read_journal_file()
            self._joff, self._jino = end, ino
            live: OrderedDict = OrderedDict()
            for rec in records:
                self._apply_record(rec, live, None)
            # drop journal entries whose file is gone or torn
            doomed = []
            for key, (fn, _nb) in list(live.items()):
                fp = os.path.join(self._dir, fn)
                if self._parse_header(fp) != key:
                    doomed.append((key, fn))
            for key, fn in doomed:
                live.pop(key, None)
                _unlink_quiet(os.path.join(self._dir, fn))
            # unlink orphans: entry files the journal does not own
            # (kill between publish and journal append, or between an
            # eviction record and its unlink) and .tmp stragglers
            owned = {fn for fn, _nb in live.values()}
            for fn in os.listdir(self._dir):
                if fn.endswith(".tmp"):
                    _unlink_quiet(os.path.join(self._dir, fn))
                elif fn.endswith(_SUFFIX) and fn not in owned:
                    _unlink_quiet(os.path.join(self._dir, fn))
            if doomed or len(records) > max(64, 4 * len(live)):
                self._compact_init(live)
            self._index = OrderedDict(live)
            self._bytes = sum(nb for _fn, nb in live.values())
        finally:
            self._dirlock.release()

    def _read_journal_file(self):
        """Read the whole journal and truncate a torn tail so appends
        always extend a well-formed file — MUST hold the directory
        lock.  Returns ``(records, end_offset, inode)``; storing the
        offsets is the caller's job (init writes the attributes
        directly, runtime callers update them under ``_lock``)."""
        try:
            with open(self._jpath, "rb") as f:
                blob = f.read()
                ino = os.fstat(f.fileno()).st_ino
        except OSError:
            return [], 0, -1
        records, end = _jparse(blob)
        if end < len(blob):
            try:
                with open(self._jpath, "r+b") as f:
                    f.truncate(end)
            except OSError:
                pass
        return records, end, ino

    def _compact_init(self, live: OrderedDict) -> None:
        """Rewrite the journal as one ``put`` per live entry (tmp +
        replace; concurrent replayers detect the inode change and
        rebuild their mirror from scratch).  Init-only, under the
        directory lock — offsets are written directly."""
        tmp = f"{self._jpath}.{os.getpid()}.tmp"
        try:
            with open(tmp, "wb") as f:
                for key, (fn, nb) in live.items():
                    f.write(_jframe({"op": "put", "key": list(key),
                                     "fn": fn, "bytes": nb}))
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self._jpath)
        except OSError:
            _unlink_quiet(tmp)
            return
        try:
            st = os.stat(self._jpath)
        except OSError:
            return
        self._joff = st.st_size
        self._jino = st.st_ino

    # -- journal replay ----------------------------------------------------
    @staticmethod
    def _apply_record(rec: dict, index: OrderedDict,
                      pins: set | None) -> None:
        op = rec.get("op")
        key = tuple(rec.get("key") or ())
        if not key:
            return
        if op == "put":
            index.pop(key, None)
            index[key] = [rec.get("fn"), int(rec.get("bytes") or 0)]
        elif op in ("evict", "poison"):
            index.pop(key, None)
            if op == "poison" and pins is not None:
                pins.add(key)

    def _replay(self) -> None:
        """Fold journal records appended by OTHER processes into the
        in-memory mirror (no counters: the journaling process already
        accounted its own operation — replay is bookkeeping, not an
        event)."""
        with self._lock:
            off, ino = self._joff, self._jino
        try:
            st = os.stat(self._jpath)
        except OSError:
            return
        if st.st_ino == ino and st.st_size <= off:
            return
        try:
            with open(self._jpath, "rb") as f:
                cur_ino = os.fstat(f.fileno()).st_ino
                if cur_ino != ino or st.st_size < off:
                    blob = f.read()  # compacted underneath us: rebuild
                    records, end = _jparse(blob)
                    with self._lock:
                        fresh: OrderedDict = OrderedDict()
                        for rec in records:
                            self._apply_record(rec, fresh,
                                               self._no_recache)
                        self._index = fresh
                        self._bytes = sum(nb for _fn, nb
                                          in fresh.values())
                        self._joff = end
                        self._jino = cur_ino
                    return
                f.seek(off)
                blob = f.read()
        except OSError:
            return
        records, end = _jparse(blob)
        if not records:
            return
        with self._lock:
            if self._jino != ino or self._joff != off:
                return  # another thread replayed first
            for rec in records:
                self._apply_record(rec, self._index, self._no_recache)
            self._bytes = sum(nb for _fn, nb in self._index.values())
            self._joff = off + end
            self._jino = ino

    def _append_locked(self, records: list[dict]) -> None:
        """Append records — MUST hold ``_jlock`` + the directory
        lock.  First replays to the journal's true end (truncating a
        torn tail a killed process left), so the mirror is current
        before the new records land and our own records are consumed
        here, not by a later replay."""
        try:
            st = os.stat(self._jpath)
        except OSError:
            st = None
        with self._lock:
            stale = (st is None or st.st_ino != self._jino
                     or st.st_size != self._joff)
        if stale:
            recs, end, ino = self._read_journal_file()
            with self._lock:
                fresh: OrderedDict = OrderedDict()
                for rec in recs:
                    self._apply_record(rec, fresh, self._no_recache)
                self._index = fresh
                self._bytes = sum(nb for _fn, nb in fresh.values())
                self._joff, self._jino = end, ino
        blob = b"".join(_jframe(r) for r in records)
        try:
            with open(self._jpath, "ab") as f:
                f.write(blob)
                f.flush()
                os.fsync(f.fileno())
        except OSError:
            return
        with self._lock:
            for rec in records:
                self._apply_record(rec, self._index, None)
            self._bytes = sum(nb for _fn, nb in self._index.values())
            self._joff += len(blob)

    # -- contract ---------------------------------------------------------
    def get(self, key):
        self._replay()
        with self._lock:
            ent = self._index.get(key)
            if ent is not None:
                self._index.move_to_end(key)
        if ent is None:
            _bump("cache_misses_disk")
            return None
        fp = os.path.join(self._dir, ent[0])
        data, poisoned = self._read_entry(fp, key)
        if data is not None:
            _bump("cache_hits_disk")
            try:
                os.utime(fp)  # cross-process LRU signal
            except OSError:
                pass
            return data
        if not poisoned and not os.path.exists(fp):
            # a concurrent evictor won the race between our mirror
            # peek and the open: their journal record carries the
            # eviction — for us this is a plain miss (or a hit on the
            # replacement generation, one replay later)
            self._replay()
            with self._lock:
                ent2 = self._index.get(key)
            if ent2 is not None and ent2[0] != ent[0]:
                data2, _p = self._read_entry(
                    os.path.join(self._dir, ent2[0]), key)
                if data2 is not None:
                    _bump("cache_hits_disk")
                    return data2
            _bump("cache_misses_disk")
            return None
        # torn or poisoned entry: evict fleet-wide through the journal
        with self._jlock:
            held = self._dirlock.acquire()
            if held:
                try:
                    self._append_locked([{
                        "op": "poison" if poisoned else "evict",
                        "key": list(key), "fn": ent[0]}])
                    _unlink_quiet(fp)
                finally:
                    self._dirlock.release()
        if not held:
            with self._lock:
                if poisoned:
                    self._no_recache.add(key)
            _bump("cache_misses_disk")
            return None
        with self._lock:
            if poisoned:
                self._no_recache.add(key)
        _bump("cache_misses_disk")
        _bump("cache_evictions_disk")
        if poisoned:
            if _flightrec._active is not None:
                _flightrec.flight(
                    "cache_poison", site="io.remote.range",
                    file=key[0], start=key[3], size=key[4])
            from ..obs.postmortem import postmortem_path_for, \
                record_incident

            record_incident(postmortem_path_for(None), {
                "kind": "cache_poison", "file": key[0],
                "start": key[3], "size": key[4], "entry": fp,
            })
        return None

    def contains(self, key) -> bool:
        self._replay()
        with self._lock:
            return key in self._index

    def put(self, key, data: bytes) -> None:
        with self._lock:
            if key in self._no_recache:
                self._no_recache.discard(key)
                return
        kraw = json.dumps(list(key)).encode()
        total = _HDR + len(kraw) + len(data)
        if total > self._budget:
            return
        fn = self._next_fname(key)
        fp = os.path.join(self._dir, fn)
        tmp = f"{fp}.{os.getpid()}.{threading.get_ident()}.tmp"
        hdr = (_MAGIC
               + (zlib.crc32(data) & 0xFFFFFFFF).to_bytes(4, "big")
               + len(data).to_bytes(8, "big")
               + len(kraw).to_bytes(2, "big"))
        try:
            with open(tmp, "wb") as f:
                f.write(hdr)
                f.write(kraw)
                f.write(data)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, fp)
        except OSError:
            _unlink_quiet(tmp)
            return
        evict: list[str] = []
        n_evicted = 0
        with self._jlock:
            if not self._dirlock.acquire():
                _unlink_quiet(fp)  # degrade to uncached, not stale
                return
            try:
                # _append_locked would fold remote records in anyway,
                # but peek first: when another process already
                # published this key, keep ITS entry (first publisher
                # wins — that is the once-per-span fleet economy) and
                # drop ours
                self._replay()
                with self._lock:
                    existing = self._index.get(key)
                if existing is not None:
                    _unlink_quiet(fp)
                    return
                self._append_locked([{"op": "put", "key": list(key),
                                      "fn": fn, "bytes": total}])
                # budget eviction, decided under the directory lock
                # by cross-process LRU (entry mtime; hits os.utime
                # theirs)
                with self._lock:
                    over = self._bytes > self._budget \
                        and len(self._index) > 1
                while over:
                    victim = self._oldest_entry(exclude=key)
                    if victim is None:
                        break
                    vkey, vfn = victim
                    self._append_locked([
                        {"op": "evict",
                         "key": list(vkey), "fn": vfn}])
                    evict.append(vfn)
                    n_evicted += 1
                    with self._lock:
                        over = self._bytes > self._budget \
                            and len(self._index) > 1
            finally:
                self._dirlock.release()
        for efn in evict:
            _unlink_quiet(os.path.join(self._dir, efn))
        if n_evicted:
            _bump("cache_evictions_disk", n_evicted)

    def _oldest_entry(self, exclude=None):
        """LRU victim by entry-file mtime (the cross-process signal
        ``get`` refreshes); mirror order breaks ties.  Returns
        ``(key, fname)`` or None."""
        with self._lock:
            candidates = [(k, fn) for k, (fn, _nb)
                          in self._index.items() if k != exclude]
        best = None
        best_m = None
        for k, fn in candidates:
            try:
                m = os.stat(os.path.join(self._dir, fn)).st_mtime_ns
            except OSError:
                return k, fn  # file already gone: reap the record
            if best_m is None or m < best_m:
                best, best_m = (k, fn), m
        return best

    def invalidate_path(self, path: str) -> int:
        self._replay()
        with self._lock:
            doomed = [(k, ent[0]) for k, ent in self._index.items()
                      if k[0] == path]
        if not doomed:
            return 0
        with self._jlock:
            if not self._dirlock.acquire():
                return 0
            try:
                self._append_locked([
                    {"op": "evict", "key": list(k), "fn": fn}
                    for k, fn in doomed])
            finally:
                self._dirlock.release()
        for _k, fn in doomed:
            _unlink_quiet(os.path.join(self._dir, fn))
        _bump("cache_evictions_disk", len(doomed))
        return len(doomed)

    def stats(self) -> dict:
        self._replay()
        d = super().stats()
        d["shared"] = True
        return d


# -- process-wide tier singletons (env-keyed, rebuilt when config
# changes; mutated only under the module lock) --------------------------
_LOCK = threading.Lock()
_MEM: tuple | None = None   # (budget, MemRangeCache)
_DISK: tuple | None = None  # ((dir, budget, shared), DiskRangeCache)


def mem_cache() -> MemRangeCache | None:
    global _MEM
    budget = mem_cache_budget()
    if budget <= 0:
        return None
    with _LOCK:
        if _MEM is None or _MEM[0] != budget:
            _MEM = (budget, MemRangeCache(budget))
        return _MEM[1]


def disk_cache() -> DiskRangeCache | None:
    global _DISK
    d = disk_cache_dir()
    if d is None:
        return None
    budget = disk_cache_budget()
    if budget <= 0:
        return None
    shared = disk_cache_shared()
    with _LOCK:
        if _DISK is None or _DISK[0] != (d, budget, shared):
            cls = SharedDiskRangeCache if shared else DiskRangeCache
            _DISK = ((d, budget, shared), cls(d, budget))
        return _DISK[1]


def invalidate_source_caches(src: str) -> int:
    """Drop every cached range for a source from BOTH tiers — the
    corruption/quarantine/salvage invalidation hook.  Accepts a bare
    path or a ``scheme://`` URI; returns entries dropped."""
    path = _norm_path(src)
    n = 0
    m = mem_cache()
    if m is not None:
        n += m.invalidate_path(path)
    d = disk_cache()
    if d is not None:
        n += d.invalidate_path(path)
    return n


def reset_range_caches() -> None:
    """Test hook: forget both tier singletons (the next lookup rebuilds
    from the current env)."""
    global _MEM, _DISK
    with _LOCK:
        _MEM = None
        _DISK = None
