"""Dremel record shredding and assembly.

Write side: :func:`shred_record` walks the schema tree with a nested-dict
record and appends (value, rep, def) triples to each leaf's
:class:`ColumnStore` — the algorithm of ``recursiveAddColumnData`` /
``recursiveAddColumnNil`` (``/root/reference/schema.go:714-786``) and
``ColumnStore.add`` (``data_store.go:86-126``).

Read side: :func:`assemble_record` rebuilds one nested-dict record from
per-leaf cursors — ``Column.getData``/``getNextData``/``getFirstRDLevel``
(``schema.go:171-264``) and ``ColumnStore.get`` (``data_store.go:158-203``).

Levels semantics (Dremel):

* ``def`` counts how many non-REQUIRED ancestors (incl. self) are present;
  a null at def < max_def tells *which* ancestor was absent.
* ``rep`` is 0 for the first value of a record, else the rep level of the
  repeated ancestor at which the new value attaches.
"""

from __future__ import annotations

import numpy as np

from ..format.schema import Schema, SchemaNode
from .values import ValueHandler, handler_for

__all__ = ["ColumnStore", "shred_record", "assemble_record", "attach_stores"]


class ColumnStore:
    """Per-leaf write buffer + read cursor.

    On the write path ``values`` is a Python list (appended per record); on
    the read path it is the decoded codec-layer column plus materialized
    Python values for assembly.
    """

    __slots__ = (
        "node", "handler", "values", "rep_levels", "def_levels",
        "null_count", "_read_values", "_read_pos", "_val_pos", "skipped",
    )

    def __init__(self, node: SchemaNode):
        self.node = node
        self.handler: ValueHandler = handler_for(node.element)
        self.reset()

    def reset(self) -> None:
        self.values = []
        self.rep_levels: list[int] = []
        self.def_levels: list[int] = []
        self.null_count = 0
        self._read_values = None
        self._read_pos = 0
        self._val_pos = 0
        self.skipped = False

    # ------------------------------------------------------------------
    # write path (shredding)
    # ------------------------------------------------------------------

    def add(self, v, def_level: int, max_rep: int, rep_level: int) -> None:
        """``ColumnStore.add`` semantics (``data_store.go:86-126``)."""
        if self.node.is_repeated:
            max_rep += 1
        rep_level = min(rep_level, max_rep)

        if v is None:
            self.rep_levels.append(rep_level)
            self.def_levels.append(def_level)
            self.null_count += 1
            return
        vals = self.handler.get_values(v, repeated=self.node.is_repeated)
        if not vals:  # empty repeated list records a null at this def level
            self.add(None, def_level, max_rep, rep_level)
            return
        d = def_level + (0 if self.node.is_required else 1)
        for i, item in enumerate(vals):
            self.values.append(item)
            self.rep_levels.append(rep_level if i == 0 else max_rep)
            self.def_levels.append(d)

    def num_records_levels(self) -> tuple[np.ndarray, np.ndarray]:
        return (
            np.asarray(self.rep_levels, dtype=np.int32),
            np.asarray(self.def_levels, dtype=np.int32),
        )

    # ------------------------------------------------------------------
    # read path (assembly)
    # ------------------------------------------------------------------

    def load_decoded(self, column, rep_levels, def_levels) -> None:
        """Install decoded chunk data for row assembly."""
        self.values = column
        self.rep_levels = np.asarray(rep_levels, dtype=np.int32)
        self.def_levels = np.asarray(def_levels, dtype=np.int32)
        self._read_values = self.handler.to_pylist(column) if column is not None else []
        self._read_pos = 0
        self._val_pos = 0
        self.skipped = False

    def mark_skipped(self) -> None:
        self.skipped = True
        self.values = None
        self.rep_levels = np.empty(0, dtype=np.int32)
        self.def_levels = np.empty(0, dtype=np.int32)
        self._read_values = []
        self._read_pos = 0
        self._val_pos = 0

    def rd_level_at(self, pos: int | None = None):
        """(rep, def, exhausted) at ``pos`` (default: cursor)."""
        if pos is None:
            pos = self._read_pos
        if pos >= len(self.rep_levels):
            return 0, 0, True
        return int(self.rep_levels[pos]), int(self.def_levels[pos]), False

    def get(self, max_def: int, max_rep: int):
        """Read the next value (or repeated group of values) for one record
        slot; returns (value, def_level) — ``data_store.go:158-203``."""
        if self.skipped:
            return None, 0
        _, dl, last = self.rd_level_at()
        if last:
            # Exhaustion here means the file's row count overstates the
            # level streams — corruption, not normal end-of-data (which the
            # reader detects from row-group metadata before assembling).
            raise ValueError(
                f"column store {self.node.flat_name!r} exhausted mid-record"
            )
        if dl < max_def:
            self._read_pos += 1
            return None, dl
        v = self._read_values[self._val_pos]
        self._val_pos += 1
        if not self.node.is_repeated:
            self._read_pos += 1
            return v, max_def
        ret = [v]
        while True:
            self._read_pos += 1
            rl, _, last = self.rd_level_at()
            if last or rl < max_rep:
                return ret, max_def
            ret.append(self._read_values[self._val_pos])
            self._val_pos += 1

    @property
    def exhausted(self) -> bool:
        return self._read_pos >= len(self.rep_levels)


def attach_stores(schema: Schema) -> None:
    for leaf in schema.leaves:
        if leaf.store is None:
            leaf.store = ColumnStore(leaf)


# ----------------------------------------------------------------------
# Shredding
# ----------------------------------------------------------------------

def shred_record(schema: Schema, record: dict) -> None:
    """Append one nested-dict record across all leaf stores."""
    _shred_children(schema.root.children, record, 0, 0, 0)


def _shred_nil(children, def_level, max_rep, rep_level):
    for node in children:
        if node.is_leaf:
            if node.is_required and def_level == node.max_def_level:
                raise ValueError(f"value {node.flat_name!r} is required")
            node.store.add(None, def_level, max_rep, rep_level)
        else:
            _shred_nil(node.children, def_level, max_rep, rep_level)


def _shred_children(children, data, def_level, max_rep, rep_level):
    if not isinstance(data, dict):
        raise TypeError(f"record data must be a dict, got {type(data).__name__}")
    for node in children:
        d = data.get(node.name)
        if node.is_leaf:
            if d is None and node.is_required and def_level == node.max_def_level:
                raise ValueError(f"value {node.flat_name!r} is required")
            node.store.add(d, def_level, max_rep, rep_level)
            continue
        # group node
        lvl = def_level
        if not node.is_required and d is not None:
            lvl += 1
        if d is None:
            _shred_nil(node.children, lvl, max_rep, rep_level)
        elif isinstance(d, dict):
            if node.is_repeated:
                raise TypeError(
                    f"{node.flat_name!r} is repeated and needs a list"
                )
            _shred_children(node.children, d, lvl, max_rep, rep_level)
        elif isinstance(d, (list, tuple)):
            if not node.is_repeated:
                raise TypeError(
                    f"{node.flat_name!r} is not repeated but got a list"
                )
            m = max_rep + 1
            if len(d) == 0:
                # An empty repeated group contributes no def level of its
                # own — presence (+1) is per element in Dremel.
                _shred_nil(node.children, def_level, m, rep_level)
            else:
                rl = rep_level
                for i, item in enumerate(d):
                    if i > 0:
                        rl = m
                    _shred_children(node.children, item, lvl, m, rl)
        else:
            raise TypeError(
                f"{node.flat_name!r}: group value must be dict or list, got "
                f"{type(d).__name__}"
            )


# ----------------------------------------------------------------------
# Assembly
# ----------------------------------------------------------------------

def _first_rd_level(node: SchemaNode):
    """First (rep, def) under this subtree at the current cursors
    (``Column.getFirstRDLevel``, ``schema.go:214-233``)."""
    if node.is_leaf:
        if node.store is None or node.store.skipped:
            return -1, -1, False
        return node.store.rd_level_at()
    for child in node.children:
        rl, dl, last = _first_rd_level(child)
        if last:
            return rl, dl, last
        if rl >= 0 or dl >= 0:
            return rl, dl, last
    return -1, -1, False


def _get_group_data(node: SchemaNode):
    """One struct instance from the children cursors
    (``Column.getNextData``, ``schema.go:171-211``)."""
    ret = {}
    not_nil = 0
    max_dl = 0  # deepest def level seen: tells the caller which ancestor
    # in the chain was present when everything below is absent
    for child in node.children:
        data, dl = _get_node_data(child)
        max_dl = max(max_dl, dl)
        if data is not None:
            ret[child.name] = data
            not_nil += 1
        diff = 0 if child.is_required else 1
        if dl == child.max_def_level - diff:
            not_nil += 1
    if not_nil == 0:
        return None, max_dl
    return ret, node.max_def_level


def _get_node_data(node: SchemaNode):
    """(value, def_level) for the next record slot of this node
    (``Column.getData``, ``schema.go:235-264``)."""
    if node.is_leaf:
        if node.store is None or node.store.skipped:
            return None, 0
        return node.store.get(node.max_def_level, node.max_rep_level)
    data, max_d = _get_group_data(node)
    if not node.is_repeated or data is None:
        return data, max_d
    ret = [data]
    while True:
        rl, _, last = _first_rd_level(node)
        if last or rl < node.max_rep_level or rl == 0:
            return ret, max_d
        data, _ = _get_group_data(node)
        ret.append(data)


def assemble_record(schema: Schema) -> dict:
    """Assemble the next record from the leaf cursors."""
    data, _ = _get_group_data(schema.root)
    return data if data is not None else {}
