"""Data/dictionary page encode/decode (V1 + V2).

Layouts (``/root/reference/page_v1.go``, ``page_v2.go``, ``page_dict.go``):

* **V1**: page body = [rep levels (4-byte-length-prefixed RLE)]
  [def levels (same)] [encoded values]; the whole body is compressed;
  ``DataPageHeader`` carries num_values + encodings.
* **V2**: rep + def level streams are *outside* compression, raw RLE with
  their byte lengths in ``DataPageHeaderV2``; only the values segment is
  compressed (if ``is_compressed``).
* **Dictionary page**: PLAIN-encoded distinct values, whole body
  compressed; at most one per chunk, first.

Decoding returns either a materialized column or dictionary *indices*
(gathered once per chunk — unlike the reference's per-page gather,
``type_dict.go:39-59``).
"""

from __future__ import annotations

import os
import time
import zlib

import numpy as np

from ..compress import compress_block, decompress_block
from ..errors import CorruptPageError, TransientIOError
from ..faults import fault_point
from ..obs import recorder as _flightrec
from ..obs import trace as _trace
from ..cpu import (
    as_uint32,
    bit_width,
    decode_byte_stream_split,
    decode_delta_binary_packed,
    decode_delta_byte_array,
    decode_delta_length_byte_array,
    decode_dict_indices,
    decode_hybrid_prefixed,
    decode_levels_raw,
    decode_levels_v1,
    decode_plain,
    encode_byte_stream_split,
    encode_delta_binary_packed,
    encode_delta_byte_array,
    encode_delta_length_byte_array,
    encode_dict_indices,
    encode_hybrid_prefixed,
    encode_levels_v1,
    encode_levels_v2,
    encode_plain,
)
from ..cpu.plain import PHYSICAL_DTYPES, ByteArrayColumn
from ..format.compact import CompactWriter
from ..format.metadata import (
    CompressionCodec,
    DataPageHeader,
    DataPageHeaderV2,
    DictionaryPageHeader,
    Encoding,
    PageHeader,
    PageType,
    Type,
    encode_struct,
)

__all__ = [
    "DecodedPage",
    "decode_data_page_v1",
    "decode_data_page_v2",
    "decode_dictionary_page",
    "decode_values",
    "encode_values",
    "write_data_page_v1",
    "write_data_page_v2",
    "write_dictionary_page",
    "SUPPORTED_DATA_ENCODINGS",
    "page_crc_default",
    "crc_verify_default",
    "write_native_default",
    "page_crc32",
    "verify_page_crc",
]

# Value encodings legal per physical type (reader dispatch; mirrors
# getValuesDecoder, chunk_reader.go:58-196).
SUPPORTED_DATA_ENCODINGS = {
    Type.BOOLEAN: {Encoding.PLAIN, Encoding.RLE},
    Type.INT32: {Encoding.PLAIN, Encoding.DELTA_BINARY_PACKED,
                 Encoding.BYTE_STREAM_SPLIT},
    Type.INT64: {Encoding.PLAIN, Encoding.DELTA_BINARY_PACKED,
                 Encoding.BYTE_STREAM_SPLIT},
    Type.INT96: {Encoding.PLAIN},
    Type.FLOAT: {Encoding.PLAIN, Encoding.BYTE_STREAM_SPLIT},
    Type.DOUBLE: {Encoding.PLAIN, Encoding.BYTE_STREAM_SPLIT},
    Type.BYTE_ARRAY: {Encoding.PLAIN, Encoding.DELTA_LENGTH_BYTE_ARRAY,
                      Encoding.DELTA_BYTE_ARRAY},
    Type.FIXED_LEN_BYTE_ARRAY: {Encoding.PLAIN, Encoding.DELTA_BYTE_ARRAY,
                                Encoding.BYTE_STREAM_SPLIT},
}

_DICT_ENCODINGS = (Encoding.PLAIN_DICTIONARY, Encoding.RLE_DICTIONARY)


# ----------------------------------------------------------------------
# Page CRC32 (parquet.thrift PageHeader.crc: the standard gzip-polynomial
# CRC over the page bytes "as they appear in the file" — i.e. everything
# between the header and the next page: compressed body for V1 and
# dictionary pages, raw levels + compressed values for V2.  Matches
# parquet-mr's checksum path and pyarrow's write_page_checksum /
# page_checksum_verification.)
# ----------------------------------------------------------------------

def page_crc_default() -> bool:
    """Write-side gate: emit ``PageHeader.crc``?  Default ON (a few
    bytes per page buy end-to-end corruption detection); disable with
    ``TPQ_PAGE_CRC=0`` or per-writer via ``FileWriter(page_crc=...)``."""
    return os.environ.get("TPQ_PAGE_CRC", "1") != "0"


def crc_verify_default() -> bool:
    """Read-side gate: verify CRCs when a page header carries one?
    Default ON; disable with ``TPQ_PAGE_CRC_VERIFY=0`` or per-reader
    via ``FileReader(verify_crc=...)``."""
    return os.environ.get("TPQ_PAGE_CRC_VERIFY", "1") != "0"


def page_crc32(*segments) -> int:
    """CRC over the page's on-file body segments, as the SIGNED i32 the
    thrift field stores (crc32 is unsigned; two's-complement fold)."""
    crc = 0
    for seg in segments:
        crc = zlib.crc32(seg, crc)
    return crc - (1 << 32) if crc >= (1 << 31) else crc


def verify_page_crc(header: PageHeader, payload, *, enabled: bool,
                    column=None, page=None) -> bool:
    """Check ``payload`` (the page bytes after the header) against
    ``header.crc``; raises :class:`CorruptPageError` on mismatch.
    Returns True when a CRC was present and checked (callers count it).
    No-op when the header has no CRC or verification is disabled."""
    if header.crc is None or not enabled:
        return False
    want = header.crc & 0xFFFFFFFF
    got = zlib.crc32(payload) & 0xFFFFFFFF
    if got != want:
        from ..stats import current_stats

        st = current_stats()
        if st is not None:
            st.crc_mismatches += 1
        raise CorruptPageError(
            f"page CRC mismatch: header 0x{want:08x}, "
            f"computed 0x{got:08x}", column=column, page=page)
    return True


class DecodedPage:
    """One decoded data page: levels + either values or dict indices."""

    __slots__ = ("num_values", "rep_levels", "def_levels", "values", "indices")

    def __init__(self, num_values, rep_levels, def_levels, values=None,
                 indices=None):
        self.num_values = num_values
        self.rep_levels = rep_levels
        self.def_levels = def_levels
        self.values = values
        self.indices = indices


def decode_values(ptype: Type, encoding: Encoding, data, count: int,
                  type_length=None):
    """Non-dictionary value decode dispatch."""
    if encoding == Encoding.PLAIN:
        return decode_plain(ptype, data, count, type_length)
    if encoding == Encoding.RLE:
        if ptype != Type.BOOLEAN:
            raise ValueError("RLE data encoding is boolean-only")
        vals, _ = decode_hybrid_prefixed(data, count, 1)
        return vals.astype(np.bool_)
    if encoding == Encoding.DELTA_BINARY_PACKED:
        if ptype not in (Type.INT32, Type.INT64):
            raise ValueError("DELTA_BINARY_PACKED is int32/int64-only")
        dtype = np.int32 if ptype == Type.INT32 else np.int64
        vals, _ = decode_delta_binary_packed(data, dtype)
        if vals.size != count:
            raise ValueError(
                f"delta stream has {vals.size} values, expected {count}"
            )
        return vals
    if encoding == Encoding.DELTA_LENGTH_BYTE_ARRAY:
        if ptype != Type.BYTE_ARRAY:
            raise ValueError("DELTA_LENGTH_BYTE_ARRAY is byte_array-only")
        col, _ = decode_delta_length_byte_array(data, count)
        return col
    if encoding == Encoding.DELTA_BYTE_ARRAY:
        if ptype not in (Type.BYTE_ARRAY, Type.FIXED_LEN_BYTE_ARRAY):
            raise ValueError("DELTA_BYTE_ARRAY needs a byte-array type")
        col, _ = decode_delta_byte_array(data, count)
        if ptype == Type.FIXED_LEN_BYTE_ARRAY:
            n = type_length or 0
            lens = col.lengths()
            if col and (lens != n).any():
                raise ValueError("DELTA_BYTE_ARRAY: wrong fixed length")
            return col.data.reshape(count, n)
        return col
    if encoding == Encoding.BYTE_STREAM_SPLIT:
        if ptype == Type.FIXED_LEN_BYTE_ARRAY:
            n = type_length or 0
            need = count * n
            if len(data) < need:
                raise ValueError("BYTE_STREAM_SPLIT: input too short")
            streams = np.frombuffer(data, np.uint8, count=need).reshape(n, count)
            return np.ascontiguousarray(streams.T)
        dt = PHYSICAL_DTYPES.get(ptype)
        if dt is None or ptype == Type.BOOLEAN:
            raise ValueError("BYTE_STREAM_SPLIT unsupported for this type")
        return decode_byte_stream_split(data, count, dt)
    raise ValueError(f"unsupported value encoding {encoding!r}")


def encode_values(ptype: Type, encoding: Encoding, column,
                  type_length=None) -> bytes:
    """Non-dictionary value encode dispatch (mirrors getValuesEncoder,
    chunk_writer.go:99-159)."""
    from .values import is_device_values

    if is_device_values(column):
        # device-resident values: PLAIN/DELTA/BSS encode on device
        # (kernels/encode.py) and only the wire bytes cross to host
        return column.encode(ptype, encoding)
    if encoding == Encoding.PLAIN:
        return encode_plain(ptype, column, type_length)
    if encoding == Encoding.RLE:
        if ptype != Type.BOOLEAN:
            raise ValueError("RLE data encoding is boolean-only")
        return encode_hybrid_prefixed(
            np.asarray(column, dtype=np.bool_).astype(np.uint32), 1
        )
    if encoding == Encoding.DELTA_BINARY_PACKED:
        return encode_delta_binary_packed(column, is32=(ptype == Type.INT32))
    if encoding == Encoding.DELTA_LENGTH_BYTE_ARRAY:
        return encode_delta_length_byte_array(column)
    if encoding == Encoding.DELTA_BYTE_ARRAY:
        if isinstance(column, np.ndarray) and column.ndim == 2:
            column = ByteArrayColumn.from_list([bytes(r) for r in column])
        return encode_delta_byte_array(column)
    if encoding == Encoding.BYTE_STREAM_SPLIT:
        arr = np.asarray(column)
        if arr.ndim == 2 and arr.dtype == np.uint8:  # FLBA (N, L) matrix
            return np.ascontiguousarray(arr.T).tobytes()
        return encode_byte_stream_split(arr)
    raise ValueError(f"unsupported value encoding {encoding!r}")


# ----------------------------------------------------------------------
# Page decode
# ----------------------------------------------------------------------

def decode_data_page_v1(header: PageHeader, payload, codec: CompressionCodec,
                        node, dictionary) -> DecodedPage:
    from ..faults import filter_bytes

    h: DataPageHeader = header.data_page_header
    if h is None:
        raise CorruptPageError("DATA_PAGE header missing data_page_header")
    raw = decompress_block(codec, payload, header.uncompressed_page_size)
    raw = filter_bytes("io.pages.page_decode", raw)
    n = h.num_values
    if n is None or n < 0:
        raise CorruptPageError("DATA_PAGE header missing num_values")
    pos = 0
    rep, pos = _decode_levels_dispatch_v1(
        raw, n, node.max_rep_level, h.repetition_level_encoding, pos
    )
    dl, pos = _decode_levels_dispatch_v1(
        raw, n, node.max_def_level, h.definition_level_encoding, pos
    )
    non_null = int((dl == node.max_def_level).sum()) if node.max_def_level \
        else n
    return _decode_page_values(
        h.encoding, raw[pos:], n, non_null, rep, dl, node, dictionary
    )


def _decode_levels_dispatch_v1(raw, n, max_level, encoding, pos):
    if max_level == 0:
        return np.zeros(n, dtype=np.int32), pos
    if encoding == Encoding.BIT_PACKED:
        # deprecated MSB-first, no length prefix; width*count bits
        from ..cpu import bit_width, decode_levels_bitpacked

        w = bit_width(max_level)
        nbytes = (n * w + 7) // 8
        return (
            decode_levels_bitpacked(raw[pos : pos + nbytes], n, max_level),
            pos + nbytes,
        )
    return decode_levels_v1(raw, n, max_level, pos)


def decode_data_page_v2(header: PageHeader, payload, codec: CompressionCodec,
                        node, dictionary) -> DecodedPage:
    from ..faults import filter_bytes

    h: DataPageHeaderV2 = header.data_page_header_v2
    if h is None:
        raise CorruptPageError(
            "DATA_PAGE_V2 header missing data_page_header_v2")
    n = h.num_values
    if n is None or n < 0:
        raise CorruptPageError("DATA_PAGE_V2 header missing num_values")
    payload = filter_bytes("io.pages.page_decode", payload)
    rl_len = h.repetition_levels_byte_length or 0
    dl_len = h.definition_levels_byte_length or 0
    if rl_len + dl_len > len(payload):
        raise CorruptPageError("V2 level lengths exceed page size")
    rep = decode_levels_raw(payload[:rl_len], n, node.max_rep_level)
    dl = decode_levels_raw(
        payload[rl_len : rl_len + dl_len], n, node.max_def_level
    )
    values_seg = payload[rl_len + dl_len :]
    if h.is_compressed is not False:  # absent means compressed
        values_seg = decompress_block(
            codec,
            values_seg,
            header.uncompressed_page_size - rl_len - dl_len,
        )
    else:
        # own the bytes: payload may be a zero-copy view of the source
        # buffer, and decoded PLAIN arrays must not alias the file
        values_seg = bytes(values_seg)
    non_null = n - (h.num_nulls or 0)
    check = int((dl == node.max_def_level).sum()) if node.max_def_level else n
    if check != non_null:
        raise CorruptPageError(
            f"V2 num_nulls {h.num_nulls} disagrees with def levels "
            f"({n - check} nulls)"
        )
    return _decode_page_values(
        h.encoding, values_seg, n, non_null, rep, dl, node, dictionary
    )


def _decode_page_values(encoding, data, n, non_null, rep, dl, node,
                        dictionary) -> DecodedPage:
    if encoding in _DICT_ENCODINGS:
        if dictionary is None:
            raise ValueError(
                "dictionary-encoded page but no dictionary page seen"
            )
        idx = decode_dict_indices(data, non_null)
        return DecodedPage(n, rep, dl, indices=idx)
    ptype = Type(node.element.type)
    allowed = SUPPORTED_DATA_ENCODINGS[ptype]
    if encoding not in allowed:
        raise ValueError(
            f"encoding {Encoding(encoding).name} not valid for {ptype.name}"
        )
    vals = decode_values(
        ptype, encoding, data, non_null, node.element.type_length
    )
    return DecodedPage(n, rep, dl, values=vals)


def decode_dictionary_page(header: PageHeader, payload,
                           codec: CompressionCodec, node):
    h: DictionaryPageHeader = header.dictionary_page_header
    if h is None:
        raise CorruptPageError("DICTIONARY_PAGE header missing its struct")
    if h.encoding not in (Encoding.PLAIN, Encoding.PLAIN_DICTIONARY):
        raise ValueError(f"dictionary page encoding {h.encoding} unsupported")
    if h.num_values is None or h.num_values < 0:
        raise CorruptPageError("DICTIONARY_PAGE header missing num_values")
    raw = decompress_block(codec, payload, header.uncompressed_page_size)
    return decode_plain(
        Type(node.element.type), raw, h.num_values, node.element.type_length
    )


# ----------------------------------------------------------------------
# Page encode
# ----------------------------------------------------------------------

def _page_header_bytes(ph: PageHeader) -> bytes:
    w = CompactWriter()
    encode_struct(ph, w)
    return w.getvalue()


def write_native_default() -> bool:
    """Write-side gate: assemble data pages through the native one-pass
    pipeline (``native/page.c``) when codec and shapes allow?  Output
    is byte-identical to the pure path either way; ``TPQ_WRITE_NATIVE=0``
    forces pure (the ci.sh stage-11 parity leg)."""
    return os.environ.get("TPQ_WRITE_NATIVE", "1") != "0"


def _native_page_ctx(codec: CompressionCodec):
    """``(page_native, page_codec_ctx_or_None)`` when the native page
    pipeline can produce byte-identical output for this codec, else
    None (unsupported codec, a user-registered compressor on the codec
    id, natives unbuildable, or ``TPQ_WRITE_NATIVE=0``).  The codec
    half is a :class:`~tpuparquet.compress.PageCodecCtx` (None for
    UNCOMPRESSED — the compressor is skipped outright).  Invariant per
    chunk — ``write_chunk`` resolves it once and threads it through
    ``native_ctx=`` so a multi-page column does not pay the env read +
    registry lock per page."""
    if not write_native_default():
        return None
    from ..native import page_native

    pg = page_native()
    if pg is None:
        return None
    if codec == CompressionCodec.UNCOMPRESSED:
        from ..compress import builtin_uncompressed_registered

        if not builtin_uncompressed_registered():
            return None
        return pg, None
    from ..compress import page_codec_settings

    pc = page_codec_settings(codec)
    if pc is None:
        return None
    return pg, pc


def _hybrid_worst_case(count: int, width: int) -> int:
    """Output capacity bound for one hybrid RLE/BP stream — the
    bindings' own formula (one copy; a desync here would quietly turn
    every native page into a cap-shortfall fallback)."""
    from ..native import hybrid_encode_cap

    return hybrid_encode_cap(count, width)


def _native_values_view(node, column, encoding):
    """u8 view of a page's value segment for the native assembler:
    zero-copy for PLAIN fixed-width numpy columns (the bytes
    ``encode_plain`` would produce, without producing them), else the
    encoded bytes wrapped read-only."""
    ptype = Type(node.element.type)
    if encoding == Encoding.PLAIN and isinstance(column, np.ndarray):
        dt = PHYSICAL_DTYPES.get(ptype)
        if (ptype not in (Type.BOOLEAN, Type.FIXED_LEN_BYTE_ARRAY)
                and dt is not None and column.dtype == np.dtype(dt)
                and column.ndim == 1):
            return np.ascontiguousarray(column).view(np.uint8)
        if (ptype == Type.FIXED_LEN_BYTE_ARRAY
                and column.dtype == np.uint8 and column.ndim == 2):
            return np.ascontiguousarray(column).reshape(-1)
    b = encode_values(ptype, encoding, column, node.element.type_length)
    return np.frombuffer(b, dtype=np.uint8)


def _write_page_native(out, node, column, rep, dl, codec, encoding, ctx,
                       *, v2: bool, num_rows=None, null_count=None,
                       dictionary_size=None, statistics=None,
                       page_crc=True, arena=None, workers: int = 1):
    """One data page through the native pipeline: encode the whole body
    into a single arena-backed buffer (levels + dict-index/values, one
    C pass), block-compress it in place, CRC it, then write header +
    body with no intermediate Python ``bytes``.  Returns the pure
    path's ``(compressed, uncompressed)`` sizes, or None when this page
    must take the pure path (capacity shortfall, injected fault, or a
    value the native encoder refuses) — falling back is always safe
    because nothing has been written yet."""
    pg, pcodec = ctx
    from ..compress import page_compress_bound, page_compress_into
    from ..stats import current_stats

    st = current_stats()
    n = len(dl)
    try:
        fault_point("io.pages.page_write",
                    column=".".join(node.path), values=n)
        t0 = time.perf_counter() if st is not None else 0.0
        if dictionary_size is not None:
            idx = as_uint32(np.asarray(column))
            if idx.ndim != 1:
                return None
            idx_width = max(int(dictionary_size - 1).bit_length(), 1) \
                if dictionary_size > 1 else 1
            values = None
            enc_kind = Encoding.RLE_DICTIONARY
        else:
            idx = None
            idx_width = 0
            values = _native_values_view(node, column, encoding)
            enc_kind = encoding
        rep_w = bit_width(node.max_rep_level)
        def_w = bit_width(node.max_def_level)
        rep_arr = as_uint32(rep) if node.max_rep_level else None
        dl_arr = as_uint32(dl) if node.max_def_level else None
        cap = 16
        if rep_arr is not None:
            cap += 4 + _hybrid_worst_case(n, rep_w)
        if dl_arr is not None:
            cap += 4 + _hybrid_worst_case(n, def_w)
        cap += (1 + _hybrid_worst_case(idx.size, idx_width)
                if idx is not None else values.size)
        scratch = arena.borrow(cap) if arena is not None \
            else np.empty(cap, dtype=np.uint8)
        enc = pg.encode(rep_arr, dl_arr, n, rep_w, def_w, v2, idx,
                        idx_width, values, scratch)
        if enc is None:
            return None
        rep_len, dl_len, val_len = enc
        uncomp = rep_len + dl_len + val_len
        if st is not None:
            t1 = time.perf_counter()
            st.write_encode_s += t1 - t0
        else:
            t1 = 0.0
        # compress stage: V1 compresses the whole body, V2 only the
        # values segment (levels stay raw on file)
        lev = rep_len + dl_len
        if pcodec is None:  # UNCOMPRESSED
            segs = [scratch[:uncomp]]
        elif v2:
            vals_seg = scratch[lev:uncomp]
            outbuf = _comp_buffer(
                arena, page_compress_bound(pcodec, val_len, workers))
            comp_vals = page_compress_into(pcodec, vals_seg, outbuf,
                                           workers)
            segs = [scratch[:lev], outbuf[:comp_vals]]
        else:
            outbuf = _comp_buffer(
                arena, page_compress_bound(pcodec, uncomp, workers))
            comp = page_compress_into(pcodec, scratch[:uncomp], outbuf,
                                      workers)
            segs = [outbuf[:comp]]
        crc = None
        if page_crc:
            c = 0
            for s in segs:
                c = pg.crc32(s, c)
            crc = c - (1 << 32) if c >= (1 << 31) else c
        comp_total = sum(s.size for s in segs)
        if st is not None:
            t2 = time.perf_counter()
            st.write_compress_s += t2 - t1
        else:
            t2 = 0.0
        if v2:
            ph = PageHeader(
                type=PageType.DATA_PAGE_V2,
                uncompressed_page_size=uncomp,
                compressed_page_size=comp_total,
                crc=crc,
                data_page_header_v2=DataPageHeaderV2(
                    num_values=n,
                    num_nulls=null_count,
                    num_rows=num_rows,
                    encoding=enc_kind,
                    definition_levels_byte_length=dl_len,
                    repetition_levels_byte_length=rep_len,
                    is_compressed=codec != CompressionCodec.UNCOMPRESSED,
                    statistics=statistics,
                ),
            )
        else:
            ph = PageHeader(
                type=PageType.DATA_PAGE,
                uncompressed_page_size=uncomp,
                compressed_page_size=comp_total,
                crc=crc,
                data_page_header=DataPageHeader(
                    num_values=n,
                    encoding=enc_kind,
                    definition_level_encoding=Encoding.RLE,
                    repetition_level_encoding=Encoding.RLE,
                    statistics=statistics,
                ),
            )
        hdr = _page_header_bytes(ph)
    except (TransientIOError, ValueError):
        # injected fault / native refusal before anything was written:
        # the pure path renders this page instead (identical bytes)
        return None
    out.write(hdr)
    for s in segs:
        out.write(memoryview(s))
    if st is not None:
        st.pages_assembled_native += 1
        st.write_assemble_s += time.perf_counter() - t2
    return len(hdr) + comp_total, len(hdr) + uncomp


def _comp_buffer(arena, cap: int) -> np.ndarray:
    """Compression output buffer of the codec-computed worst case
    (``compress.page_compress_bound``)."""
    return arena.borrow(cap) if arena is not None \
        else np.empty(cap, dtype=np.uint8)


def write_data_page_v1(out, node, column, rep, dl, codec, encoding,
                       dictionary_size=None, statistics=None,
                       page_crc=True, arena=None,
                       native_ctx="auto",
                       compress_workers: int = 1) -> tuple[int, int]:
    """Append a V1 data page; returns (compressed_size, uncompressed_size)
    including the header bytes (ColumnMetaData counts headers —
    ``chunk_writer.go:209-251``).  ``native_ctx`` is the chunk-resolved
    :func:`_native_page_ctx` (None = pure path); the default resolves
    it here for direct callers.  ``compress_workers > 1`` lets the
    native path block-split large bodies for the concatenation-safe
    codecs (the pure path always writes the single serial frame)."""
    n = len(dl)
    res = None
    ctx = _native_page_ctx(codec) if native_ctx == "auto" else native_ctx
    if ctx is not None:
        res = _write_page_native(
            out, node, column, rep, dl, codec, encoding, ctx, v2=False,
            dictionary_size=dictionary_size, statistics=statistics,
            page_crc=page_crc, arena=arena, workers=compress_workers)
    if res is None:
        body = bytearray()
        if node.max_rep_level:
            body += encode_levels_v1(rep, node.max_rep_level)
        if node.max_def_level:
            body += encode_levels_v1(dl, node.max_def_level)
        if dictionary_size is not None:
            body += encode_dict_indices(column, dictionary_size)
            enc = Encoding.RLE_DICTIONARY
        else:
            body += encode_values(
                Type(node.element.type), encoding, column,
                node.element.type_length,
            )
            enc = encoding
        comp = compress_block(codec, bytes(body))
        ph = PageHeader(
            type=PageType.DATA_PAGE,
            uncompressed_page_size=len(body),
            compressed_page_size=len(comp),
            crc=page_crc32(comp) if page_crc else None,
            data_page_header=DataPageHeader(
                num_values=n,
                encoding=enc,
                definition_level_encoding=Encoding.RLE,
                repetition_level_encoding=Encoding.RLE,
                statistics=statistics,
            ),
        )
        hdr = _page_header_bytes(ph)
        out.write(hdr)
        out.write(comp)
        res = len(hdr) + len(comp), len(hdr) + len(body)
    _record_page_written(node, n)
    return res


def write_data_page_v2(out, node, column, rep, dl, codec, encoding,
                       num_rows, null_count, dictionary_size=None,
                       statistics=None, page_crc=True, arena=None,
                       native_ctx="auto",
                       compress_workers: int = 1) -> tuple[int, int]:
    n = len(dl)
    res = None
    ctx = _native_page_ctx(codec) if native_ctx == "auto" else native_ctx
    if ctx is not None:
        res = _write_page_native(
            out, node, column, rep, dl, codec, encoding, ctx, v2=True,
            num_rows=num_rows, null_count=null_count,
            dictionary_size=dictionary_size, statistics=statistics,
            page_crc=page_crc, arena=arena, workers=compress_workers)
    if res is None:
        rep_b = encode_levels_v2(rep, node.max_rep_level) \
            if node.max_rep_level else b""
        dl_b = encode_levels_v2(dl, node.max_def_level) \
            if node.max_def_level else b""
        if dictionary_size is not None:
            values_b = encode_dict_indices(column, dictionary_size)
            enc = Encoding.RLE_DICTIONARY
        else:
            values_b = encode_values(
                Type(node.element.type), encoding, column,
                node.element.type_length,
            )
            enc = encoding
        comp_values = compress_block(codec, values_b)
        ph = PageHeader(
            type=PageType.DATA_PAGE_V2,
            uncompressed_page_size=len(rep_b) + len(dl_b) + len(values_b),
            compressed_page_size=len(rep_b) + len(dl_b) + len(comp_values),
            # V2 CRC spans the on-file body: uncompressed level streams +
            # compressed values (parquet.thrift "as it appears in the
            # file")
            crc=page_crc32(rep_b, dl_b, comp_values) if page_crc
            else None,
            data_page_header_v2=DataPageHeaderV2(
                num_values=n,
                num_nulls=null_count,
                num_rows=num_rows,
                encoding=enc,
                definition_levels_byte_length=len(dl_b),
                repetition_levels_byte_length=len(rep_b),
                is_compressed=codec != CompressionCodec.UNCOMPRESSED,
                statistics=statistics,
            ),
        )
        hdr = _page_header_bytes(ph)
        out.write(hdr)
        out.write(rep_b)
        out.write(dl_b)
        out.write(comp_values)
        res = (
            len(hdr) + len(rep_b) + len(dl_b) + len(comp_values),
            len(hdr) + ph.uncompressed_page_size,
        )
    _record_page_written(node, n)
    return res


def _record_page_written(node, n_values: int) -> None:
    """Per-written-page accounting shared by every page writer: the
    ``pages_written`` counter (every page, native or pure — the
    conservation check ``pages_assembled_native <= pages_written``) and
    the flight-recorder breadcrumb (guarded so the disabled path skips
    the kwargs build; this runs once per page on the write hot loop)."""
    from ..stats import current_stats

    st = current_stats()
    if st is not None:
        st.pages_written += 1
    if _flightrec._active is not None:
        _flightrec.flight("page_write", site="io.pages",
                          column=".".join(node.path), values=n_values)
    # causal trace: write-side point span — the encode-ahead pipeline
    # workers adopt the submitting chunk's context, so these parent
    # under the writer's trace when one is open
    if _trace._active is not None:
        _trace.emit_span("page_write", time.perf_counter(), 0.0,
                         column=".".join(node.path), values=n_values)


def write_dictionary_page(out, node, dictionary, codec,
                          page_crc=True) -> tuple[int, int]:
    """PLAIN dictionary page (PLAIN_DICTIONARY is deprecated on write,
    ``page_dict.go:86``)."""
    body = encode_plain(
        Type(node.element.type), dictionary, node.element.type_length
    )
    comp = compress_block(codec, body)
    count = len(dictionary) if not isinstance(dictionary, np.ndarray) \
        else dictionary.shape[0]
    ph = PageHeader(
        type=PageType.DICTIONARY_PAGE,
        uncompressed_page_size=len(body),
        compressed_page_size=len(comp),
        crc=page_crc32(comp) if page_crc else None,
        dictionary_page_header=DictionaryPageHeader(
            num_values=count, encoding=Encoding.PLAIN
        ),
    )
    hdr = _page_header_bytes(ph)
    out.write(hdr)
    out.write(comp)
    _record_page_written(node, count)
    return len(hdr) + len(comp), len(hdr) + len(body)
