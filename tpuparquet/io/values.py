"""Per-physical-type value handling: coercion, stats, plain-value framing.

The typed-column-store equivalent of the reference's ``type_*.go`` files:
each physical type knows how to coerce incoming Python/NumPy values
(``getValues``, which accepts a scalar or — for repeated leaves — a
sequence), track min/max under the right sort order (signed vs unsigned per
ConvertedType/LogicalType, ``chunk_reader.go:30-50``), and encode a single
value for the Statistics fields (PLAIN without length prefix,
``parquet.thrift`` Statistics doc).
"""

from __future__ import annotations

import numpy as np

from ..cpu.plain import ByteArrayColumn
from ..format.metadata import ConvertedType, SchemaElement, Type

__all__ = ["ValueHandler", "handler_for", "is_unsigned",
           "is_device_values"]


def is_device_values(obj) -> bool:
    """True for :class:`tpuparquet.kernels.encode.DeviceValues` (and
    subclasses).  Lazy import keeps the io layer jax-free until a
    device column actually appears; the fast isinstance-free pre-check
    avoids importing jax for plain numpy writes."""
    if isinstance(obj, (np.ndarray, ByteArrayColumn, list, tuple)) \
            or obj is None:
        return False
    import sys

    mod = sys.modules.get("tpuparquet.kernels.encode")
    if mod is None:
        return False  # DeviceValues can't exist if its module isn't loaded
    return isinstance(obj, mod.DeviceValues)

_INT_RANGE = {
    Type.INT32: (-(2**31), 2**31 - 1),
    Type.INT64: (-(2**63), 2**63 - 1),
}


def is_unsigned(element: SchemaElement) -> bool:
    """Unsigned statistics ordering (UINT_* converted type or unsigned
    INTEGER logical type)."""
    if element.converted_type in (
        ConvertedType.UINT_8,
        ConvertedType.UINT_16,
        ConvertedType.UINT_32,
        ConvertedType.UINT_64,
    ):
        return True
    lt = element.logicalType
    if lt is not None and lt.INTEGER is not None:
        return not lt.INTEGER.isSigned
    return False


class ValueHandler:
    """Coercion + statistics for one leaf's physical type."""

    def __init__(self, element: SchemaElement):
        self.element = element
        self.ptype = Type(element.type)
        self.type_length = element.type_length
        self.unsigned = is_unsigned(element)

    # -- write-side coercion ----------------------------------------------

    def coerce_one(self, v):
        """Coerce one Python/NumPy value to the canonical buffered form."""
        p = self.ptype
        if p == Type.BOOLEAN:
            if isinstance(v, (bool, np.bool_)):
                return bool(v)
            raise TypeError(f"expected bool, got {type(v).__name__}")
        if p in (Type.INT32, Type.INT64):
            if isinstance(v, (bool, np.bool_)) or not isinstance(
                v, (int, np.integer)
            ):
                raise TypeError(f"expected int, got {type(v).__name__}")
            iv = int(v)
            lo, hi = _INT_RANGE[p]
            if self.unsigned:
                # unsigned logical values are stored two's-complement
                ulo, uhi = 0, 2 * hi + 1
                if not ulo <= iv <= uhi:
                    if not lo <= iv <= hi:
                        raise ValueError(f"{iv} out of range for u{p.name}")
                elif iv > hi:
                    iv -= 2 * (hi + 1)  # wrap to signed storage
                return iv
            if not lo <= iv <= hi:
                raise ValueError(f"{iv} out of range for {p.name}")
            return iv
        if p in (Type.FLOAT, Type.DOUBLE):
            if isinstance(v, (int, float, np.floating, np.integer)) and not \
                    isinstance(v, (bool, np.bool_)):
                return float(v)
            raise TypeError(f"expected float, got {type(v).__name__}")
        if p == Type.BYTE_ARRAY:
            if isinstance(v, str):
                return v.encode("utf-8")
            if isinstance(v, (bytes, bytearray, np.bytes_)):
                return bytes(v)
            raise TypeError(f"expected bytes/str, got {type(v).__name__}")
        if p == Type.FIXED_LEN_BYTE_ARRAY:
            if isinstance(v, str):
                v = v.encode("utf-8")
            if isinstance(v, (bytes, bytearray, np.bytes_)):
                b = bytes(v)
                if self.type_length and len(b) != self.type_length:
                    raise ValueError(
                        f"fixed_len_byte_array({self.type_length}) got "
                        f"{len(b)} bytes"
                    )
                return b
            raise TypeError(f"expected bytes, got {type(v).__name__}")
        if p == Type.INT96:
            if isinstance(v, (bytes, bytearray)) and len(v) == 12:
                return bytes(v)
            if isinstance(v, (tuple, list, np.ndarray)) and len(v) == 3:
                return np.asarray(v, dtype="<u4").tobytes()
            raise TypeError("INT96 expects 12 bytes or 3 uint32 words")
        raise TypeError(f"unsupported physical type {p}")

    def get_values(self, v, repeated: bool):
        """``getValues`` semantics: scalar -> [v]; for repeated leaves a
        sequence fans out to multiple values (``type_int32.go:171`` etc.)."""
        if repeated:
            if isinstance(v, (list, tuple, np.ndarray)):
                return [self.coerce_one(x) for x in v]
            return [self.coerce_one(v)]
        return [self.coerce_one(v)]

    def validate_array(self, arr):
        """Validate an ndarray/ByteArrayColumn for the columnar write path.

        Lists go through :meth:`coerce_one`; arrays would otherwise be
        silently cast by the encoder (1.9 -> 1 into an int32 column), so
        enforce dtype compatibility and integer range here."""
        p = self.ptype
        if isinstance(arr, ByteArrayColumn):
            if p not in (Type.BYTE_ARRAY, Type.FIXED_LEN_BYTE_ARRAY):
                raise TypeError(f"{p.name} column cannot take byte values")
            return arr
        if is_device_values(arr):
            # device-resident values (kernels/encode.py) stay in HBM:
            # validated by dtype only, stats and page encode on device
            want = {Type.INT32: np.dtype(np.int32),
                    Type.INT64: np.dtype(np.int64),
                    Type.FLOAT: np.dtype(np.float32),
                    Type.DOUBLE: np.dtype(np.float64)}.get(p)
            if want is None or arr.dtype != want:
                raise TypeError(
                    f"{p.name} column cannot take DeviceValues[{arr.dtype}]")
            return arr
        a = np.asarray(arr)
        if p == Type.BOOLEAN:
            if a.dtype != np.bool_:
                raise TypeError(f"BOOLEAN column needs bool array, got {a.dtype}")
        elif p in (Type.INT32, Type.INT64):
            if not np.issubdtype(a.dtype, np.integer) or a.dtype == np.bool_:
                raise TypeError(f"{p.name} column needs an integer array, "
                                f"got {a.dtype}")
            lo, hi = _INT_RANGE[p]
            store = np.int32 if p == Type.INT32 else np.int64
            if self.unsigned:
                # accept either the signed-storage or the logical unsigned
                # range, then wrap to two's-complement signed storage (the
                # array analogue of coerce_one above)
                if a.size and (int(a.min()) < lo or int(a.max()) > 2 * hi + 1):
                    raise ValueError(f"values out of range for u{p.name}")
                if a.dtype == store:
                    return a
                udt = np.uint32 if p == Type.INT32 else np.uint64
                return a.astype(udt, copy=False).view(store)
            if a.size and (int(a.min()) < lo or int(a.max()) > hi):
                raise ValueError(f"values out of range for {p.name}")
            return a if a.dtype == store else a.astype(store)
        elif p in (Type.FLOAT, Type.DOUBLE):
            if not (np.issubdtype(a.dtype, np.floating)
                    or np.issubdtype(a.dtype, np.integer)):
                raise TypeError(f"{p.name} column needs a numeric array, "
                                f"got {a.dtype}")
        elif p in (Type.FIXED_LEN_BYTE_ARRAY, Type.INT96):
            want = self.type_length if p == Type.FIXED_LEN_BYTE_ARRAY else \
                (3 if a.dtype.itemsize == 4 else 12)
            if a.ndim != 2 or a.shape[1] != want:
                raise TypeError(f"{p.name} column needs shape (N, {want})")
        else:
            raise TypeError(f"{p.name} column cannot take ndarray values")
        return arr

    # -- flush-time materialization ---------------------------------------

    def finalize(self, buffered: list):
        """Buffered Python values -> the codec-layer column representation."""
        p = self.ptype
        if p == Type.BOOLEAN:
            return np.asarray(buffered, dtype=np.bool_)
        if p == Type.INT32:
            return np.asarray(buffered, dtype=np.int32)
        if p == Type.INT64:
            return np.asarray(buffered, dtype=np.int64)
        if p == Type.FLOAT:
            return np.asarray(buffered, dtype=np.float32)
        if p == Type.DOUBLE:
            return np.asarray(buffered, dtype=np.float64)
        if p == Type.BYTE_ARRAY:
            return ByteArrayColumn.from_list(buffered)
        if p == Type.FIXED_LEN_BYTE_ARRAY:
            n = self.type_length or 0
            if not buffered:
                return np.empty((0, n), dtype=np.uint8)
            return np.frombuffer(b"".join(buffered), dtype=np.uint8).reshape(
                len(buffered), n
            )
        if p == Type.INT96:
            if not buffered:
                return np.empty((0, 3), dtype="<u4")
            return np.frombuffer(b"".join(buffered), dtype="<u4").reshape(
                len(buffered), 3
            )
        raise TypeError(f"unsupported physical type {p}")

    # -- read-side materialization to Python values ------------------------

    def to_pylist(self, column) -> list:
        """Codec-layer column -> Python values (for row assembly)."""
        p = self.ptype
        if isinstance(column, ByteArrayColumn):
            return column.to_list()
        arr = np.asarray(column)
        if p == Type.BOOLEAN:
            return [bool(x) for x in arr]
        if p in (Type.INT32, Type.INT64):
            if self.unsigned:
                udt = np.uint32 if p == Type.INT32 else np.uint64
                return [int(x) for x in arr.view(udt)]
            return [int(x) for x in arr]
        if p in (Type.FLOAT, Type.DOUBLE):
            return [float(x) for x in arr]
        if p in (Type.FIXED_LEN_BYTE_ARRAY, Type.INT96):
            if p == Type.INT96:
                arr = arr.view(np.uint8).reshape(len(arr), 12)
            return [bytes(row) for row in arr]
        raise TypeError(f"unsupported physical type {p}")

    # -- statistics --------------------------------------------------------

    def min_max(self, column):
        """Return (min, max) raw values under the column's sort order, or
        (None, None) for empty / undefined-order (INT96) columns."""
        p = self.ptype
        if p == Type.INT96:
            return None, None  # ordering undefined in the spec
        if is_device_values(column):
            return column.min_max(unsigned=self.unsigned)
        if isinstance(column, ByteArrayColumn):
            if len(column) == 0:
                return None, None
            return _byte_array_min_max(column)
        arr = np.asarray(column)
        if arr.size == 0:
            return None, None
        if p == Type.FIXED_LEN_BYTE_ARRAY:
            mn = _refine_lex(arr, np.min)
            mx = _refine_lex(arr, np.max)
            return mn, mx
        if self.unsigned and p in (Type.INT32, Type.INT64):
            u = arr.view(np.uint32 if p == Type.INT32 else np.uint64)
            return arr[int(np.argmin(u))], arr[int(np.argmax(u))]
        if p in (Type.FLOAT, Type.DOUBLE):
            finite = arr[~np.isnan(arr)]
            if finite.size == 0:
                return None, None
            return finite.min(), finite.max()
        return arr.min(), arr.max()

    def encode_stat_value(self, v) -> bytes:
        """PLAIN-encode one value for Statistics (no length prefix)."""
        p = self.ptype
        if v is None:
            return None
        if p == Type.BOOLEAN:
            return b"\x01" if v else b"\x00"
        if p == Type.INT32:
            return int(v).to_bytes(4, "little", signed=True)
        if p == Type.INT64:
            return int(v).to_bytes(8, "little", signed=True)
        if p == Type.FLOAT:
            return np.float32(v).tobytes()
        if p == Type.DOUBLE:
            return np.float64(v).tobytes()
        return bytes(v)

    def decode_stat_value(self, b: bytes):
        p = self.ptype
        if b is None:
            return None
        if p == Type.BOOLEAN:
            return bool(b[0]) if b else None
        if p == Type.INT32:
            return int.from_bytes(b, "little", signed=True)
        if p == Type.INT64:
            return int.from_bytes(b, "little", signed=True)
        if p == Type.FLOAT:
            return float(np.frombuffer(b, dtype="<f4")[0])
        if p == Type.DOUBLE:
            return float(np.frombuffer(b, dtype="<f8")[0])
        return bytes(b)

    def stats_bytewise_comparable(self) -> bool:
        """False when the column's declared sort order is NOT the raw
        byte order of its statistics values — DECIMAL over
        BYTE_ARRAY/FLBA sorts as a signed big-endian two's-complement
        number, so ``b'\\xff..'`` (negative) < ``b'\\x05..'`` while
        bytewise compare says the opposite.  Pruning and the strict
        validator treat such bounds as absent (conservative: no
        pruning, no false min>max finding)."""
        el = self.element
        if el.type not in (Type.BYTE_ARRAY, Type.FIXED_LEN_BYTE_ARRAY):
            return True
        from ..format.metadata import ConvertedType

        if getattr(el, "converted_type", None) == ConvertedType.DECIMAL:
            return False
        lt = getattr(el, "logicalType", None)
        if lt is not None:
            try:
                # DECIMAL sorts as a signed big-endian number, FLOAT16
                # as an IEEE half — neither matches raw byte order
                if lt.set_member()[0] in ("DECIMAL", "FLOAT16"):
                    return False
            except (TypeError, IndexError):
                pass
        return True

    def decode_stat_logical(self, b: bytes):
        """Decode a Statistics min/max value to its LOGICAL value —
        unsigned columns come back as the non-negative logical int (the
        stored bytes are two's-complement signed storage).  This is the
        form predicate pushdown and the strict validator compare in
        (``tpuparquet/filter.py``, ``format/validate.py``)."""
        v = self.decode_stat_value(b)
        if (v is not None and self.unsigned
                and self.ptype in (Type.INT32, Type.INT64)
                and v < 0):
            v += 1 << (32 if self.ptype == Type.INT32 else 64)
        return v


def _refine_lex(rows: np.ndarray, reduce_fn) -> bytes:
    """Lexicographic (unsigned byte order) extreme of a (k, L) byte
    matrix by byte-plane refinement: narrow the candidate set one byte
    position at a time (O(k) for the first plane, collapsing
    geometrically after) instead of materializing k Python bytes
    objects.  Constant planes (shared prefixes) are free progress;
    when the pass cap trips before the set collapses (adversarial
    prefixes, duplicate extremes), an exact memcmp sort over the
    surviving candidate rows finishes the job."""
    if rows.dtype != np.uint8:
        # the file stores raw bytes: compare UNSIGNED regardless of the
        # input dtype (an int8 view would invert the order)
        rows = np.ascontiguousarray(rows).view(np.uint8)
    k, L = rows.shape
    cand = np.arange(k)
    bail = L > 4096  # few, huge values: per-plane dispatch dominates
    if not bail:
        # constant planes (shared prefixes) are free progress through
        # the string; varying planes shrink the candidate set.  The
        # pass cap bounds the numpy-dispatch count for adversarial
        # shapes (very long shared prefixes, duplicate extremes).
        passes = 0
        for j in range(L):
            col = rows[cand, j]
            mn = int(col.min())
            mx = int(col.max())
            if mn != mx:
                m = mn if reduce_fn is np.min else mx
                cand = cand[col == m]
                if cand.size == 1:
                    break
            passes += 1
            if passes > 96 and cand.size > 1:
                bail = True
                break
    if bail:
        # exact memcmp sort over the surviving candidates
        sub = np.ascontiguousarray(rows[cand])
        view = sub.view(np.dtype((np.void, L))).reshape(-1)
        view = np.sort(view)
        pick = view[0] if reduce_fn is np.min else view[-1]
        return bytes(pick)
    return bytes(rows[int(cand[0])])


def _byte_array_min_max(col: ByteArrayColumn):
    """(min, max) of variable-length bytes without ``to_list``: per
    length group, gather the group's rows once and refine by byte
    plane; the true extremes are among the per-group extremes, reduced
    at the end under Python's lexicographic bytes order (which handles
    the shorter-prefix-sorts-first rule across groups)."""
    offs = np.asarray(col.offsets, dtype=np.int64)
    data = np.asarray(col.data)
    lens = offs[1:] - offs[:-1]
    mins: list = []
    maxs: list = []
    for L in np.unique(lens):
        L = int(L)
        sel = np.nonzero(lens == L)[0]
        if L == 0:
            mins.append(b"")
            maxs.append(b"")
            continue
        starts = offs[:-1][sel]
        if L > 4096 or sel.size < 8:
            vals = [bytes(data[int(s): int(s) + L]) for s in starts]
            mins.append(min(vals))
            maxs.append(max(vals))
            continue
        rows = data[starts[:, None] + np.arange(L, dtype=np.int64)]
        mins.append(_refine_lex(rows, np.min))
        maxs.append(_refine_lex(rows, np.max))
    return min(mins), max(maxs)


def handler_for(element: SchemaElement) -> ValueHandler:
    return ValueHandler(element)
