"""FileWriter: row-dict and columnar write paths.

API parity with the reference's ``FileWriter`` (``file_writer.go``):
keyword options mirror the functional options (``FileVersion``,
``WithCreator``, ``WithCompressionCodec``, ``WithMetaData``,
``WithMaxRowGroupSize`` auto-flush, ``WithSchemaDefinition``,
``WithDataPageV2``), ``add_data`` buffers + shreds one row,
``flush_row_group`` accepts per-flush key/value metadata (global and
per-column, ``file_writer.go:148-175``), ``close`` writes the footer.

TPU-first addition: :meth:`write_columns` takes whole column arrays +
validity masks and skips per-row shredding entirely — the natural writing
shape for columnar/JAX producers.
"""

from __future__ import annotations

import numpy as np

from ..format.dsl import SchemaDefinition, parse_schema_definition
from ..format.footer import MAGIC, write_footer
from ..format.metadata import (
    ColumnChunk,
    CompressionCodec,
    Encoding,
    FileMetaData,
    KeyValue,
    RowGroup,
)
from ..format.schema import Schema
from .chunk import write_chunk
from .pages import SUPPORTED_DATA_ENCODINGS
from .store import attach_stores, shred_record
from .values import handler_for

__all__ = ["FileWriter"]


class FileWriter:
    """Streaming Parquet writer.

    ``schema`` may be a :class:`Schema`, a :class:`SchemaDefinition`, or a
    DSL string.  Use as a context manager or call :meth:`close`.
    """

    def __init__(
        self,
        f,
        schema=None,
        *,
        version: int = 1,
        created_by: str = "tpuparquet",
        codec: CompressionCodec = CompressionCodec.UNCOMPRESSED,
        kv_metadata: dict | None = None,
        max_row_group_size: int | None = None,
        data_page_v2: bool = False,
        column_encodings: dict | None = None,
        allow_dict: bool = True,
        write_stats: bool = True,
    ):
        self._f = f
        self._pos = 0
        self.version = version
        self.created_by = created_by
        self.codec = CompressionCodec(codec)
        self.kv_metadata = dict(kv_metadata or {})
        self.max_row_group_size = max_row_group_size
        self.page_version = 2 if data_page_v2 else 1
        self.column_encodings = {
            k: Encoding(v) for k, v in (column_encodings or {}).items()
        }
        self.allow_dict = allow_dict
        self.write_stats = write_stats

        if schema is None:
            self.schema = Schema.empty()
        elif isinstance(schema, Schema):
            self.schema = schema
        elif isinstance(schema, SchemaDefinition):
            self.schema = Schema.from_definition(schema)
        elif isinstance(schema, str):
            self.schema = Schema.from_definition(parse_schema_definition(schema))
        else:
            raise TypeError(f"unsupported schema type {type(schema).__name__}")
        attach_stores(self.schema)
        self._validate_column_encodings()

        self.row_groups: list[RowGroup] = []
        self.total_rows = 0
        self._buffered_rows = 0
        self._approx_size = 0
        self._closed = False

    # -- plumbing ----------------------------------------------------------

    def _write(self, data: bytes) -> None:
        self._f.write(data)
        self._pos += len(data)

    def tell(self) -> int:
        return self._pos

    def write(self, data: bytes) -> None:  # stream interface for chunk layer
        self._write(data)

    def _validate_column_encodings(self) -> None:
        for path, enc in self.column_encodings.items():
            leaf = self.schema.leaf(path)
            if leaf is None:
                raise ValueError(f"no such column {path!r}")
            allowed = SUPPORTED_DATA_ENCODINGS[leaf.type]
            if enc not in allowed:
                raise ValueError(
                    f"encoding {enc.name} not allowed for column {path!r} "
                    f"({leaf.type.name})"
                )

    # -- row path ----------------------------------------------------------

    def add_data(self, row: dict) -> None:
        """Shred one nested-dict record into the column buffers; auto-flush
        when the buffered size crosses ``max_row_group_size``."""
        if self._closed:
            raise ValueError("writer is closed")
        shred_record(self.schema, row)
        self._buffered_rows += 1
        self._approx_size += _approx_record_size(row)
        if (
            self.max_row_group_size is not None
            and self._approx_size >= self.max_row_group_size
        ):
            self.flush_row_group()

    def current_row_group_size(self) -> int:
        """Approximate byte size of the buffered row group
        (≙ ``CurrentRowGroupSize``)."""
        return self._approx_size

    def current_file_size(self) -> int:
        return self._pos

    # -- columnar path (TPU-first) ----------------------------------------

    def write_columns(
        self,
        columns: dict,
        *,
        masks: dict | None = None,
        kv_metadata: dict | None = None,
        kv_per_column: dict | None = None,
    ) -> None:
        """Write one row group directly from column arrays.

        Only flat schemas (no repeated/group nesting beyond optional
        leaves).  ``columns`` maps leaf name -> array/ByteArrayColumn/list
        of **non-null** values; ``masks`` maps leaf name -> bool validity
        array (required for optional columns containing nulls).
        """
        if self._closed:
            raise ValueError("writer is closed")
        if self._buffered_rows:
            raise ValueError("cannot mix write_columns with buffered rows")
        leaves = self.schema.leaves
        n_rows = None
        prepared = []
        for leaf in leaves:
            if len(leaf.path) != 1 or leaf.max_rep_level:
                raise ValueError(
                    "write_columns supports flat schemas only; use add_data"
                )
            if leaf.name not in columns:
                raise ValueError(f"missing column {leaf.name!r}")
            vals = columns[leaf.name]
            mask = (masks or {}).get(leaf.name)
            handler = handler_for(leaf.element)
            if isinstance(vals, list):
                vals = handler.finalize([handler.coerce_one(v) for v in vals])
            else:
                vals = handler.validate_array(vals)
            if mask is not None and leaf.max_def_level == 0:
                raise ValueError(
                    f"column {leaf.name!r} is required; a validity mask "
                    "is not allowed"
                )
            if mask is not None:
                mask = np.asarray(mask, dtype=bool)
                rows = len(mask)
                nn = int(mask.sum())
                if _column_len(vals) == rows and rows != nn:
                    raise ValueError(
                        f"column {leaf.name!r}: pass only non-null values "
                        "with a mask (got full-length values)"
                    )
                if _column_len(vals) != nn:
                    raise ValueError(
                        f"column {leaf.name!r}: {_column_len(vals)} values "
                        f"vs {nn} valid mask entries"
                    )
                dl = mask.astype(np.int32) * leaf.max_def_level
            else:
                rows = _column_len(vals)
                if leaf.max_def_level:
                    dl = np.full(rows, leaf.max_def_level, dtype=np.int32)
                else:
                    dl = np.zeros(rows, dtype=np.int32)
            if n_rows is None:
                n_rows = rows
            elif n_rows != rows:
                raise ValueError("column row counts differ")
            prepared.append((leaf, vals, dl))
        self._flush_prepared(
            prepared, n_rows or 0, kv_metadata or {}, kv_per_column or {}
        )

    # -- flush -------------------------------------------------------------

    def flush_row_group(self, *, kv_metadata: dict | None = None,
                        kv_per_column: dict | None = None) -> None:
        """Flush buffered rows as one row group (no-op when empty, like the
        reference when rows==0 — ``file_writer.go:180-182``)."""
        if self._buffered_rows == 0:
            return
        prepared = []
        for leaf in self.schema.leaves:
            store = leaf.store
            column = store.handler.finalize(store.values)
            rep, dl = store.num_records_levels()
            prepared.append((leaf, column, dl, rep))
        n_rows = self._buffered_rows
        # reset buffers before writing so errors don't double-write
        for leaf in self.schema.leaves:
            leaf.store.reset()
        self._buffered_rows = 0
        self._approx_size = 0
        self._flush_prepared(
            [(l, c, d) for (l, c, d, _r) in prepared],
            n_rows,
            kv_metadata or {},
            kv_per_column or {},
            reps={l.flat_name: r for (l, _c, _d, r) in prepared},
        )

    def _flush_prepared(self, prepared, n_rows, kv_global, kv_per_column,
                        reps=None) -> None:
        if self._pos == 0:
            self._write(MAGIC)
        chunks: list[ColumnChunk] = []
        total_bytes = 0
        total_comp = 0
        for entry in prepared:
            leaf, column, dl = entry[0], entry[1], entry[2]
            rep = (reps or {}).get(
                leaf.flat_name, np.zeros(len(dl), dtype=np.int32)
            )
            kv = dict(kv_global)
            kv.update(kv_per_column.get(leaf.flat_name, {}))
            enc = self.column_encodings.get(
                leaf.flat_name, Encoding.PLAIN
            )
            cc = write_chunk(
                self, leaf, column, rep, dl,
                codec=self.codec,
                page_version=self.page_version,
                encoding=enc,
                allow_dict=self.allow_dict,
                num_rows=n_rows,
                kv_metadata=kv or None,
                write_stats=self.write_stats,
            )
            total_bytes += cc.meta_data.total_uncompressed_size
            total_comp += cc.meta_data.total_compressed_size
            chunks.append(cc)
        self.row_groups.append(
            RowGroup(
                columns=chunks,
                total_byte_size=total_bytes,
                num_rows=n_rows,
                total_compressed_size=total_comp,
                ordinal=len(self.row_groups),
            )
        )
        self.total_rows += n_rows

    # -- close -------------------------------------------------------------

    def close(self) -> None:
        if self._closed:
            return
        self.flush_row_group()
        if self._pos == 0:
            self._write(MAGIC)  # valid empty file still needs framing
        kv = [KeyValue(key=k, value=v)
              for k, v in sorted(self.kv_metadata.items())] or None
        meta = FileMetaData(
            version=self.version,
            schema=self.schema.to_elements(),
            num_rows=self.total_rows,
            row_groups=self.row_groups,
            key_value_metadata=kv,
            created_by=self.created_by,
        )
        write_footer(self, meta)
        self._closed = True

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        if exc_type is None:
            self.close()


def _column_len(vals) -> int:
    try:
        return len(vals)
    except TypeError:
        return np.asarray(vals).shape[0]


def _approx_record_size(row) -> int:
    if isinstance(row, dict):
        return sum(_approx_record_size(v) + 8 for v in row.values())
    if isinstance(row, (list, tuple)):
        return sum(_approx_record_size(v) for v in row)
    if isinstance(row, (bytes, bytearray, str)):
        return len(row)
    return 8
