"""FileWriter: row-dict and columnar write paths.

API parity with the reference's ``FileWriter`` (``file_writer.go``):
keyword options mirror the functional options (``FileVersion``,
``WithCreator``, ``WithCompressionCodec``, ``WithMetaData``,
``WithMaxRowGroupSize`` auto-flush, ``WithSchemaDefinition``,
``WithDataPageV2``), ``add_data`` buffers + shreds one row,
``flush_row_group`` accepts per-flush key/value metadata (global and
per-column, ``file_writer.go:148-175``), ``close`` writes the footer.

TPU-first addition: :meth:`write_columns` takes whole column arrays +
validity masks and skips per-row shredding entirely — the natural writing
shape for columnar/JAX producers.
"""

from __future__ import annotations

import io
import os

import numpy as np

from ..format.dsl import SchemaDefinition, parse_schema_definition
from ..format.footer import MAGIC, write_footer
from ..format.metadata import (
    ColumnChunk,
    CompressionCodec,
    ConvertedType,
    Encoding,
    FileMetaData,
    KeyValue,
    RowGroup,
)

from ..format.schema import Schema
from .chunk import write_chunk
from .pages import SUPPORTED_DATA_ENCODINGS
from .store import attach_stores, shred_record
from .values import handler_for


def _write_threads() -> int:
    """Per-column encode parallelism for row-group flushes.
    ``TPQ_WRITE_THREADS=1`` forces the serial path; default is the
    USABLE core count (affinity/cpuset-aware, capped by the column
    count at use).  A thread bound to a serve-arbiter tenant sizes
    from its tenant share instead (one share bounds ALL of a tenant's
    workers — the library never runs the plan and encode pools for
    the same operation)."""
    from ..serve import arbiter as _arbiter

    share = _arbiter.write_budget()
    if share is not None:
        return share
    _arbiter.warn_if_oversubscribed()
    v = os.environ.get("TPQ_WRITE_THREADS")
    if v is not None:
        try:
            return max(int(v), 1)
        except ValueError:
            pass  # malformed override falls back to the default
    try:
        return len(os.sched_getaffinity(0)) or 1
    except (AttributeError, OSError):
        return os.cpu_count() or 1

__all__ = ["FileWriter"]


def _is_element_struct_leaf(leaf) -> bool:
    """True when a rep-level-1 leaf sits inside an element GROUP (the
    tuple-of-per-leaf-arrays contract, even with one leaf), False for
    single-value list shapes (bare repeated leaf, 2-level legacy,
    canonical LIST of a primitive)."""
    if leaf.is_repeated:
        return False  # bare repeated leaf / 2-level legacy element
    parent = leaf.parent
    if parent is not None and parent.is_repeated:
        gp = parent.parent
        if gp is not None and len(parent.children) == 1:
            el = gp.element
            lt = getattr(el, "logicalType", None)
            if getattr(el, "converted_type", None) == ConvertedType.LIST \
                    or (lt is not None and lt.set_member()[0] == "LIST"):
                return False  # canonical LIST single element
        return True  # repeated struct group (incl. MAP key_value)
    return True  # element group below the repeated node



class FileWriter:
    """Streaming Parquet writer.

    ``schema`` may be a :class:`Schema`, a :class:`SchemaDefinition`, or a
    DSL string.  Use as a context manager or call :meth:`close`.
    """

    def __init__(
        self,
        f,
        schema=None,
        *,
        version: int = 1,
        created_by: str = "tpuparquet",
        codec: CompressionCodec = CompressionCodec.UNCOMPRESSED,
        kv_metadata: dict | None = None,
        max_row_group_size: int | None = None,
        data_page_v2: bool = False,
        column_encodings: dict | None = None,
        allow_dict: bool = True,
        write_stats: bool = True,
        page_crc: bool | None = None,
        salvage_hint: bool | None = None,
        page_index: bool | None = None,
        bloom_columns=None,
        page_rows: int | None = None,
        encode_threads: int | None = None,
    ):
        self._f = f
        self._pos = 0
        self.version = version
        self.created_by = created_by
        self.codec = CompressionCodec(codec)
        self.kv_metadata = dict(kv_metadata or {})
        self.max_row_group_size = max_row_group_size
        self.page_version = 2 if data_page_v2 else 1
        self.column_encodings = {
            k: Encoding(v) for k, v in (column_encodings or {}).items()
        }
        self.allow_dict = allow_dict
        self.write_stats = write_stats
        # page CRC32 in every PageHeader (None = env default, on):
        # readers that care (ours, parquet-mr, pyarrow with
        # page_checksum_verification) catch torn/corrupt pages exactly
        if page_crc is None:
            from .pages import page_crc_default

            page_crc = page_crc_default()
        self.page_crc = bool(page_crc)
        # salvage hint: a tiny schema+codec frame behind the head magic
        # (format/recover.py) that makes a torn write self-salvaging.
        # Spec-compatible — footers address pages absolutely, so foreign
        # readers skip it.  Default ON; disable with TPQ_SALVAGE_HINT=0
        # or per-writer.
        if salvage_hint is None:
            salvage_hint = os.environ.get("TPQ_SALVAGE_HINT", "1") != "0"
        self.salvage_hint = bool(salvage_hint)
        # per-page ColumnIndex/OffsetIndex, serialized after the row
        # groups with their offsets recorded in ColumnChunk (the read
        # side's page-pruning input).  Default ON (TPQ_PAGE_INDEX=0
        # disables); needs statistics — write_stats=False wins.
        if page_index is None:
            page_index = os.environ.get("TPQ_PAGE_INDEX", "1") != "0"
        self.page_index = bool(page_index) and self.write_stats
        # split-block bloom filters for the named (dictionary-ish)
        # columns: kwarg, else TPQ_BLOOM_COLUMNS ("a,b.c"), else none
        if bloom_columns is None:
            env = os.environ.get("TPQ_BLOOM_COLUMNS", "")
            bloom_columns = [c for c in env.split(",") if c.strip()]
        if isinstance(bloom_columns, str):
            bloom_columns = [c for c in bloom_columns.split(",")
                             if c.strip()]
        self.bloom_columns = {c.strip() for c in bloom_columns}
        # data-page split size in level positions for flat columns
        # (0 = the historical single data page per chunk).  Kwarg, else
        # TPQ_PAGE_ROWS; repeated columns always stay single-page.
        if page_rows is None:
            try:
                page_rows = int(os.environ.get("TPQ_PAGE_ROWS", "0"))
            except ValueError:
                page_rows = 0
        self.page_rows = max(int(page_rows), 0)
        # encode parallelism override: a caller that runs SEVERAL
        # writers concurrently (the partitioned dataset writer) splits
        # the shared TPQ_WRITE_THREADS budget across them and pins each
        # writer's share here; None = size from the budget at flush
        self.encode_threads = (max(int(encode_threads), 1)
                               if encode_threads is not None else None)

        if schema is None:
            self.schema = Schema.empty()
        elif isinstance(schema, Schema):
            self.schema = schema
        elif isinstance(schema, SchemaDefinition):
            self.schema = Schema.from_definition(schema)
        elif isinstance(schema, str):
            self.schema = Schema.from_definition(parse_schema_definition(schema))
        else:
            raise TypeError(f"unsupported schema type {type(schema).__name__}")
        attach_stores(self.schema)
        self._validate_column_encodings()
        for path in sorted(self.bloom_columns):
            if self.schema.leaf(path) is None:
                raise ValueError(
                    f"bloom_columns names no such column {path!r}")

        self.row_groups: list[RowGroup] = []
        self.total_rows = 0
        self._buffered_rows = 0
        self._approx_size = 0
        self._closed = False

    # -- plumbing ----------------------------------------------------------

    def _write(self, data: bytes) -> None:
        self._f.write(data)
        self._pos += len(data)

    def tell(self) -> int:
        return self._pos

    def write(self, data: bytes) -> None:  # stream interface for chunk layer
        self._write(data)

    def _validate_column_encodings(self) -> None:
        for path, enc in self.column_encodings.items():
            leaf = self.schema.leaf(path)
            if leaf is None:
                raise ValueError(f"no such column {path!r}")
            allowed = SUPPORTED_DATA_ENCODINGS[leaf.type]
            if enc not in allowed:
                raise ValueError(
                    f"encoding {enc.name} not allowed for column {path!r} "
                    f"({leaf.type.name})"
                )

    # -- row path ----------------------------------------------------------

    def add_data(self, row: dict) -> None:
        """Shred one nested-dict record into the column buffers; auto-flush
        when the buffered size crosses ``max_row_group_size``."""
        if self._closed:
            raise ValueError("writer is closed")
        shred_record(self.schema, row)
        self._buffered_rows += 1
        self._approx_size += _approx_record_size(row)
        if (
            self.max_row_group_size is not None
            and self._approx_size >= self.max_row_group_size
        ):
            self.flush_row_group()

    def current_row_group_size(self) -> int:
        """Approximate byte size of the buffered row group
        (≙ ``CurrentRowGroupSize``)."""
        return self._approx_size

    def current_file_size(self) -> int:
        return self._pos

    # -- columnar path (TPU-first) ----------------------------------------

    def write_columns(
        self,
        columns: dict,
        *,
        masks: dict | None = None,
        offsets: dict | None = None,
        element_masks: dict | None = None,
        kv_metadata: dict | None = None,
        kv_per_column: dict | None = None,
    ) -> None:
        """Write one row group directly from column arrays.

        Flat leaves: ``columns`` maps leaf name -> array/ByteArrayColumn/
        list of **non-null** values; ``masks`` maps leaf name -> bool
        validity (required for optional columns containing nulls).

        LIST columns (one repeated level on the path, e.g. the standard
        3-level ``optional group f (LIST) { repeated group list {
        element } }`` or a bare ``repeated`` leaf): key by the top-level
        field name, pass the **non-null element** values in ``columns``
        and the per-row slot ranges in ``offsets`` (int array of
        ``n_rows+1``).  ``masks[f]`` marks null *rows* (their offset
        range must be empty); ``element_masks[f]`` marks valid *slots*
        for optional elements.  Rep/def levels are derived exactly as
        the row path's shredder would (``io/store.py``; reference
        semantics ``schema.go:733-778``).

        Nested STRUCT leaves (non-repeated groups on the path): key by
        the dotted flat name (``"a.b"``), pass non-null values only;
        ``masks`` entries on group prefixes (``"a"``) mark rows where
        that whole group is null.

        Multi-leaf repeated groups (MAP ``key_value``, LIST of struct):
        ``columns[f]`` is a tuple of per-leaf arrays in schema leaf
        order (for a MAP: ``(keys, values)``) sharing ``offsets[f]``;
        ``element_masks[f]`` is then a dict keyed by leaf flat name
        (e.g. ``"m.key_value.value"``).
        """
        if self._closed:
            raise ValueError("writer is closed")
        if self._buffered_rows:
            raise ValueError("cannot mix write_columns with buffered rows")
        leaves = self.schema.leaves
        n_rows = None
        prepared = []
        reps = {}
        rep_leaf_counts: dict[str, int] = {}
        rep_leaf_index: dict[str, int] = {}
        for leaf in leaves:
            if leaf.max_rep_level:
                top = leaf.path[0]
                rep_leaf_counts[top] = rep_leaf_counts.get(top, 0) + 1
        for leaf in leaves:
            if leaf.max_rep_level:
                key = leaf.path[0]
                if key not in columns:
                    raise ValueError(f"missing column {key!r}")
                if offsets is None or key not in offsets:
                    raise ValueError(
                        f"repeated column {key!r} needs offsets= "
                        "(row -> element ranges)"
                    )
                k_leaves = rep_leaf_counts[key]
                if k_leaves > 1 or _is_element_struct_leaf(leaf):
                    # MAP key_value / element struct: one tuple of
                    # per-leaf arrays (schema leaf order) sharing the
                    # row->slot offsets; element masks are keyed by
                    # leaf flat name
                    col = columns[key]
                    if not isinstance(col, (tuple, list)) \
                            or len(col) != k_leaves:
                        raise ValueError(
                            f"repeated group {key!r} has {k_leaves} "
                            "leaves; pass a tuple of per-leaf arrays "
                            "(schema leaf order)"
                        )
                    i = rep_leaf_index.get(key, 0)
                    rep_leaf_index[key] = i + 1
                    leaf_vals = col[i]
                    em = (element_masks or {}).get(key)
                    gm = None
                    if isinstance(em, dict):
                        # the element GROUP's flat name marks null
                        # elements (one level below null fields)
                        gm = em.get(leaf.parent.flat_name)
                        em = em.get(leaf.flat_name)
                    elif em is not None:
                        raise ValueError(
                            f"element_masks[{key!r}] must be a dict "
                            "keyed by leaf flat name for a multi-leaf "
                            "group"
                        )
                else:
                    leaf_vals = columns[key]
                    em = (element_masks or {}).get(key)
                    gm = None
                vals, rep, dl, rows, nc = self._prepare_repeated(
                    leaf, leaf_vals, np.asarray(offsets[key]),
                    (masks or {}).get(key), em, group_null=gm,
                )
                reps[leaf.flat_name] = rep
            elif len(leaf.path) != 1:
                # nested struct leaf (non-repeated groups on the path):
                # keyed by dotted flat name, null ancestors marked by
                # masks on the group prefixes ("a", "a.b", ...)
                if leaf.flat_name not in columns:
                    raise ValueError(f"missing column {leaf.flat_name!r}")
                vals, dl, rows, nc = self._prepare_struct(
                    leaf, columns[leaf.flat_name], masks or {}
                )
            else:
                if leaf.name not in columns:
                    raise ValueError(f"missing column {leaf.name!r}")
                vals, dl, rows, nc = self._prepare_flat(
                    leaf, columns[leaf.name], (masks or {}).get(leaf.name)
                )
            if n_rows is None:
                n_rows = rows
            elif n_rows != rows:
                raise ValueError("column row counts differ")
            prepared.append((leaf, vals, dl, nc))
        self._flush_prepared(
            prepared, n_rows or 0, kv_metadata or {}, kv_per_column or {},
            reps=reps or None,
        )

    def _prepare_flat(self, leaf, vals, mask):
        handler = handler_for(leaf.element)
        if isinstance(vals, list):
            vals = handler.finalize([handler.coerce_one(v) for v in vals])
        else:
            vals = handler.validate_array(vals)
        if mask is not None and leaf.max_def_level == 0:
            raise ValueError(
                f"column {leaf.name!r} is required; a validity mask "
                "is not allowed"
            )
        if mask is not None:
            mask = np.asarray(mask, dtype=bool)
            rows = len(mask)
            nn = int(mask.sum())
            if _column_len(vals) == rows and rows != nn:
                raise ValueError(
                    f"column {leaf.name!r}: pass only non-null values "
                    "with a mask (got full-length values)"
                )
            if _column_len(vals) != nn:
                raise ValueError(
                    f"column {leaf.name!r}: {_column_len(vals)} values "
                    f"vs {nn} valid mask entries"
                )
            dl = mask.astype(np.int32) * leaf.max_def_level
            return vals, dl, rows, rows - nn
        rows = _column_len(vals)
        if leaf.max_def_level:
            dl = np.full(rows, leaf.max_def_level, dtype=np.int32)
        else:
            dl = np.zeros(rows, dtype=np.int32)
        return vals, dl, rows, 0

    def _prepare_struct(self, leaf, vals, masks):
        """Nested non-repeated leaf -> (values, def levels, n_rows,
        null_count).

        Def levels are derived outermost-ancestor-first: a row absent at
        group ``a`` stays at ``a``'s parent definition level, exactly as
        the row-path shredder would record a None group
        (``io/store.py``; reference ``schema.go:714-732``).  Masks are
        keyed by dotted prefix (``"a"``, ``"a.b"``, leaf flat name);
        ``columns`` carries only the fully-present values."""
        handler = handler_for(leaf.element)
        if isinstance(vals, list):
            vals = handler.finalize([handler.coerce_one(v) for v in vals])
        else:
            vals = handler.validate_array(vals)
        chain = []
        node = leaf
        while node is not None and node.parent is not None:
            chain.append(node)
            node = node.parent
        chain.reverse()
        prefixes = [".".join(n.name for n in chain[: i + 1])
                    for i in range(len(chain))]
        # row count: the first mask on the path knows it; an all-present
        # column falls back to the value count
        n_rows = None
        for pref in prefixes:
            m = masks.get(pref)
            if m is not None:
                n_rows = len(np.asarray(m))
                break
        if n_rows is None:
            n_rows = _column_len(vals)
        present = np.ones(n_rows, dtype=bool)
        dl = np.zeros(n_rows, dtype=np.int32)
        for node, pref in zip(chain, prefixes):
            m = masks.get(pref)
            if node.is_required:
                if m is not None:
                    raise ValueError(
                        f"{pref!r} is required; a validity mask is not "
                        "allowed")
                continue
            if m is not None:
                m = np.asarray(m, dtype=bool)
                if m.size != n_rows:
                    raise ValueError(
                        f"mask {pref!r}: {m.size} entries vs {n_rows} "
                        "rows")
                present &= m
            dl[present] = node.max_def_level
        nn = int(present.sum())
        if _column_len(vals) != nn:
            raise ValueError(
                f"column {leaf.flat_name!r}: {_column_len(vals)} values "
                f"vs {nn} present rows (pass only non-null values)")
        return vals, dl, n_rows, n_rows - nn

    def _prepare_repeated(self, leaf, vals, offs, row_mask, elem_mask,
                          group_null=None):
        """Offsets-based LIST column -> (values, rep, def, n_rows,
        null_count) — null_count in the Parquet sense: level slots not
        carrying a value (empty/null rows, null elements).

        ``group_null`` (full-slot bool, True = the element GROUP is
        null at that slot) serves lists of structs whose element group
        is optional: a null element sits one definition level below a
        present element with null fields."""
        # the nearest repeated ancestor sets the empty/null def levels
        node = leaf
        rep_node = None
        elem_opt = None  # optional group strictly between rep and leaf
        while node is not None:
            if node.is_repeated:
                rep_node = node
            elif node is not leaf and rep_node is None \
                    and not node.is_required and node.parent is not None:
                elem_opt = node
            node = node.parent
        if leaf.max_rep_level != 1 or rep_node is None:
            raise ValueError(
                f"column {leaf.flat_name!r}: write_columns supports one "
                "repeated level; use add_data for deeper nesting"
            )
        offs = offs.astype(np.int64, copy=False)
        if offs.ndim != 1 or offs.size == 0 or (np.diff(offs) < 0).any() \
                or offs[0] != 0:
            raise ValueError("offsets must be monotone and start at 0")
        counts = np.diff(offs)
        n_rows = counts.size
        empty_def = rep_node.max_def_level - 1
        if row_mask is not None:
            row_mask = np.asarray(row_mask, dtype=bool)
            if row_mask.size != n_rows:
                raise ValueError("row mask length != offsets rows")
            if rep_node.max_def_level < 2:
                raise ValueError(
                    f"column {leaf.path[0]!r} has no optional ancestor; "
                    "a row mask is not allowed"
                )
            if (counts[~row_mask] != 0).any():
                raise ValueError("null rows must have empty offset ranges")
        # each row occupies max(count, 1) slots (empty/null rows keep a
        # placeholder slot carrying the low def level)
        slots = np.maximum(counts, 1)
        first = np.cumsum(slots) - slots
        total = int(slots.sum())
        rep = np.ones(total, dtype=np.int32) * leaf.max_rep_level
        rep[first] = 0
        dl = np.full(total, leaf.max_def_level, dtype=np.int32)
        placeholder = first[counts == 0]
        dl[placeholder] = empty_def
        if row_mask is not None:
            dl[first[~row_mask]] = rep_node.max_def_level - 2
        if group_null is not None:
            if elem_opt is None:
                raise ValueError(
                    f"column {leaf.flat_name!r}: no optional element "
                    "group on the path; a group-null mask is not allowed"
                )
            group_null = np.asarray(group_null, dtype=bool)
            if group_null.size != int(offs[-1]):
                raise ValueError(
                    "group-null mask length != total elements")
        if elem_mask is not None or group_null is not None:
            if elem_mask is not None:
                elem_mask = np.asarray(elem_mask, dtype=bool)
                if elem_mask.size != int(offs[-1]):
                    raise ValueError(
                        "element mask length != total elements")
                # the leaf itself must be optional: its def must sit
                # one above the innermost optional ancestor (element
                # group if present, else the repeated node) — a mask on
                # a required field would write a schema-violating file
                floor_def = (elem_opt.max_def_level
                             if elem_opt is not None
                             else rep_node.max_def_level)
                if leaf.max_def_level == floor_def:
                    raise ValueError(
                        f"column {leaf.flat_name!r}: element is "
                        "required; an element mask is not allowed"
                    )
            elem_slots = np.ones(total, dtype=bool)
            elem_slots[placeholder] = False
            dl_elems = np.full(int(offs[-1]), leaf.max_def_level,
                               dtype=np.int32)
            valid = np.ones(int(offs[-1]), dtype=bool)
            if elem_mask is not None:
                dl_elems[~elem_mask] = leaf.max_def_level - 1
                valid &= elem_mask
            if group_null is not None:
                # a null element group sits below any field-level null
                dl_elems[group_null] = elem_opt.max_def_level - 1
                valid &= ~group_null
            dl[elem_slots] = dl_elems
            n_vals = int(valid.sum())
        else:
            n_vals = int(offs[-1])
        handler = handler_for(leaf.element)
        if isinstance(vals, list):
            vals = handler.finalize([handler.coerce_one(v) for v in vals])
        else:
            vals = handler.validate_array(vals)
        if _column_len(vals) != n_vals:
            raise ValueError(
                f"column {leaf.path[0]!r}: {_column_len(vals)} values vs "
                f"{n_vals} non-null elements"
            )
        return vals, rep, dl, n_rows, total - n_vals

    # -- flush -------------------------------------------------------------

    def flush_row_group(self, *, kv_metadata: dict | None = None,
                        kv_per_column: dict | None = None) -> None:
        """Flush buffered rows as one row group (no-op when empty, like the
        reference when rows==0 — ``file_writer.go:180-182``)."""
        if self._buffered_rows == 0:
            return
        prepared = []
        for leaf in self.schema.leaves:
            store = leaf.store
            column = store.handler.finalize(store.values)
            rep, dl = store.num_records_levels()
            prepared.append((leaf, column, dl, rep))
        n_rows = self._buffered_rows
        # reset buffers before writing so errors don't double-write
        for leaf in self.schema.leaves:
            leaf.store.reset()
        self._buffered_rows = 0
        self._approx_size = 0
        self._flush_prepared(
            [(l, c, d) for (l, c, d, _r) in prepared],
            n_rows,
            kv_metadata or {},
            kv_per_column or {},
            reps={l.flat_name: r for (l, _c, _d, r) in prepared},
        )

    def _write_head(self) -> None:
        """Leading magic + (optionally) the salvage hint frame."""
        self._write(MAGIC)
        if self.salvage_hint and self.schema.leaves:
            from ..format.recover import encode_salvage_hint

            self._write(encode_salvage_hint(
                self.schema, self.codec, created_by=self.created_by))

    def _flush_prepared(self, prepared, n_rows, kv_global, kv_per_column,
                        reps=None) -> None:
        if self._pos == 0:
            self._write_head()
        jobs = []
        for entry in prepared:
            leaf, column, dl = entry[0], entry[1], entry[2]
            # null_count computed once by the columnar prepare step
            # (O(1) from the masks); the row path passes None and the
            # chunk layer derives it from the def levels
            nc = entry[3] if len(entry) > 3 else None
            rep = (reps or {}).get(
                leaf.flat_name, np.zeros(len(dl), dtype=np.int32)
            )
            kv = dict(kv_global)
            kv.update(kv_per_column.get(leaf.flat_name, {}))
            enc = self.column_encodings.get(
                leaf.flat_name, Encoding.PLAIN
            )
            jobs.append((leaf, column, rep, dl, kv, enc, nc))

        # parallel-flush workers re-enter the flushing thread's trace
        # context so the per-page write spans parent causally under
        # the writer's trace (when one is open) despite the pool hop
        from ..obs import trace as _trace

        _tctx = _trace.current_ctx()

        def render(leaf, column, rep, dl, kv, enc, nc):
            # each chunk renders into its own buffer at position 0;
            # offsets in the returned metadata are made absolute when
            # the buffer is appended below — bytes are identical to
            # the direct-write path, columns land in schema order.
            # Stats collect per-thread and merge at append time (the
            # active collector is thread-local; shared += would race).
            from ..stats import worker_stats

            buf = io.BytesIO()
            with _trace.adopt(_tctx), worker_stats() as ws:
                cc = write_chunk(
                    buf, leaf, column, rep, dl,
                    codec=self.codec,
                    page_version=self.page_version,
                    encoding=enc,
                    allow_dict=self.allow_dict,
                    num_rows=n_rows,
                    kv_metadata=kv or None,
                    write_stats=self.write_stats,
                    page_crc=self.page_crc,
                    page_index=self.page_index,
                    bloom=leaf.flat_name in self.bloom_columns,
                    null_count=nc,
                    page_rows=self.page_rows,
                )
            return buf.getvalue(), cc, ws

        chunks: list[ColumnChunk] = []
        total_bytes = 0
        total_comp = 0
        # Parallel per-column encode: the walls (block compression,
        # interning, hybrid/bit-pack encode) run in C or numpy and
        # release the GIL, so a thread per column is a real speedup
        # (pyarrow's writer encodes columns concurrently too — the
        # external anchor was unbeatable single-threaded).  Gate on the
        # VALUE count (len(dl) covers list columns whose few rows hold
        # millions of elements); small flushes skip the pool.
        n_workers = self.encode_threads \
            if self.encode_threads is not None else _write_threads()
        total_values = sum(len(j[3]) for j in jobs)
        if len(jobs) > 1 and n_workers > 1 and total_values > 65536:
            from concurrent.futures import ThreadPoolExecutor

            from ..stats import current_stats

            _ws_sink = current_stats()
            n_w = min(len(jobs), n_workers)
            with ThreadPoolExecutor(max_workers=n_w) as ex:
                # bounded submission window, matching pipelined_reads:
                # at most n_workers+1 chunks are in flight (rendering
                # or rendered-not-yet-written), so a slow file write
                # cannot pile up every remaining column's blob in
                # memory — job i+ahead is only submitted once job i's
                # blob has been written and dropped
                ahead = n_w + 1
                futs = {}

                def submit(j):
                    if j < len(jobs):
                        futs[j] = ex.submit(render, *jobs[j])

                for j0 in range(min(ahead, len(jobs))):
                    submit(j0)
                for i in range(len(jobs)):
                    blob, cc, ws = futs.pop(i).result()
                    base = self._pos
                    self._write(blob)
                    cc.file_offset += base
                    cm = cc.meta_data
                    cm.data_page_offset += base
                    if cm.dictionary_page_offset is not None:
                        cm.dictionary_page_offset += base
                    pi = getattr(cc, "_page_index", None)
                    if pi is not None:
                        # page locations were recorded against the
                        # chunk's private buffer; make them absolute
                        for loc in pi[1].page_locations:
                            loc.offset += base
                    total_bytes += cm.total_uncompressed_size
                    total_comp += cm.total_compressed_size
                    chunks.append(cc)
                    if _ws_sink is not None:
                        _ws_sink.merge_from(ws)
                    del blob
                    submit(i + ahead)
        else:
            # serial path writes straight into the file: no per-chunk
            # buffer or blob copy (identical to the pre-pool behavior).
            # The whole TPQ_WRITE_THREADS budget goes to the intra-
            # column page pipeline here (combined-budget rule: columns
            # and pages share one knob; the parallel path above keeps
            # pages serial because its workers already fill the budget)
            for leaf, column, rep, dl, kv, enc, nc in jobs:
                cc = write_chunk(
                    self, leaf, column, rep, dl,
                    codec=self.codec,
                    page_version=self.page_version,
                    encoding=enc,
                    allow_dict=self.allow_dict,
                    num_rows=n_rows,
                    kv_metadata=kv or None,
                    write_stats=self.write_stats,
                    page_crc=self.page_crc,
                    page_index=self.page_index,
                    bloom=leaf.flat_name in self.bloom_columns,
                    null_count=nc,
                    page_rows=self.page_rows,
                    pipeline_workers=n_workers,
                )
                total_bytes += cc.meta_data.total_uncompressed_size
                total_comp += cc.meta_data.total_compressed_size
                chunks.append(cc)
        self.row_groups.append(
            RowGroup(
                columns=chunks,
                total_byte_size=total_bytes,
                num_rows=n_rows,
                total_compressed_size=total_comp,
                ordinal=len(self.row_groups),
            )
        )
        self.total_rows += n_rows

    # -- close -------------------------------------------------------------

    def _write_indexes(self) -> None:
        """Serialize the collected bloom filters and per-page
        ``ColumnIndex``/``OffsetIndex`` structs between the last row
        group and the footer (the parquet-format layout), recording
        their offsets/lengths in each ``ColumnChunk``/``ColumnMetaData``
        so readers can seek straight to them.  Spec order: blooms,
        then every ColumnIndex, then every OffsetIndex (grouped by row
        group, columns in schema order)."""
        for rg in self.row_groups:
            for cc in rg.columns:
                b = getattr(cc, "_bloom", None)
                if b is None:
                    continue
                blob = b.to_bytes()
                cc.meta_data.bloom_filter_offset = self._pos
                cc.meta_data.bloom_filter_length = len(blob)
                self._write(blob)
        for rg in self.row_groups:
            for cc in rg.columns:
                pi = getattr(cc, "_page_index", None)
                if pi is None:
                    continue
                blob = pi[0].to_bytes()
                cc.column_index_offset = self._pos
                cc.column_index_length = len(blob)
                self._write(blob)
        for rg in self.row_groups:
            for cc in rg.columns:
                pi = getattr(cc, "_page_index", None)
                if pi is None:
                    continue
                blob = pi[1].to_bytes()
                cc.offset_index_offset = self._pos
                cc.offset_index_length = len(blob)
                self._write(blob)

    def close(self) -> None:
        if self._closed:
            return
        self.flush_row_group()
        if self._pos == 0:
            self._write_head()  # valid empty file still needs framing
        self._write_indexes()
        kv = [KeyValue(key=k, value=v)
              for k, v in sorted(self.kv_metadata.items())] or None
        meta = FileMetaData(
            version=self.version,
            schema=self.schema.to_elements(),
            num_rows=self.total_rows,
            row_groups=self.row_groups,
            key_value_metadata=kv,
            created_by=self.created_by,
        )
        write_footer(self, meta)
        self._closed = True

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        if exc_type is None:
            self.close()


def _column_len(vals) -> int:
    try:
        return len(vals)
    except TypeError:
        return np.asarray(vals).shape[0]


def _approx_record_size(row) -> int:
    # class-identity fast paths: flat scalar rows (the common case)
    # never recurse, which keeps add_data's per-row accounting cheap
    if isinstance(row, dict):
        t = 0
        for v in row.values():
            c = v.__class__
            if c is str or c is bytes:
                t += len(v) + 8
            elif c is dict or c is list or c is tuple:
                t += _approx_record_size(v) + 8
            else:
                t += 16
        return t
    if isinstance(row, (list, tuple)):
        return sum(_approx_record_size(v) for v in row)
    if isinstance(row, (bytes, bytearray, str)):
        return len(row)
    return 8
