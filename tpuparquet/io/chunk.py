"""Column-chunk read/write: page loop, dictionary handling, statistics.

Read: :func:`read_chunk` parses every page in a chunk's byte range —
enforcing at most one leading dictionary page (``chunk_reader.go:222``) —
and concatenates decoded pages into one (values, rep, def) triple, gathering
dictionary indices once per chunk.

Write: :func:`write_chunk` optionally emits a dictionary page (size
heuristic like ``useDictionary``, ``data_store.go:34-49``) then one data
page, and builds the ``ColumnMetaData`` with sizes including page headers,
statistics (min/max/null_count/distinct_count,
``chunk_writer.go:272-299``) and the encodings list.
"""

from __future__ import annotations

import time

import numpy as np

from ..cpu import gather
from ..cpu.dictionary import build_dictionary
from ..cpu.plain import ByteArrayColumn
from ..errors import CorruptChunkError, CorruptPageError, ScanError
from ..faults import filter_bytes
from ..obs import profiler as _profiler
from ..obs import recorder as _flightrec
from ..obs import trace as _trace
from ..format.compact import CompactReader
from ..format.metadata import (
    ColumnChunk,
    ColumnMetaData,
    CompressionCodec,
    Encoding,
    KeyValue,
    PageHeader,
    PageType,
    Statistics,
    Type,
    decode_struct,
)
from ..format.schema import SchemaNode
from .pages import (
    DecodedPage,
    _native_page_ctx,
    crc_verify_default,
    decode_data_page_v1,
    decode_data_page_v2,
    decode_dictionary_page,
    verify_page_crc,
    write_data_page_v1,
    write_data_page_v2,
    write_dictionary_page,
)

__all__ = ["read_chunk", "write_chunk", "ChunkData"]

MAX_DICT_ENTRIES = 1 << 15  # data_store.go:44 (math.MaxInt16)


class ChunkData:
    """Decoded column chunk: codec-layer column + level arrays."""

    __slots__ = ("values", "rep_levels", "def_levels", "num_values",
                 "null_count")

    def __init__(self, values, rep_levels, def_levels, null_count):
        self.values = values
        self.rep_levels = rep_levels
        self.def_levels = def_levels
        self.num_values = len(def_levels)
        self.null_count = null_count


def read_chunk(blob: "bytes | memoryview", cm: ColumnMetaData,
               node: SchemaNode, verify_crc: bool | None = None,
               keep_rows=None):
    """Decode one column chunk from the file bytes.

    Pass a memoryview for zero-copy page payloads (a bytes blob still
    works but its page slices copy).  ``verify_crc`` gates page CRC32
    verification when headers carry one (None = env default, see
    :func:`~tpuparquet.io.pages.crc_verify_default`).

    ``keep_rows`` (predicate-pushdown page pruning; flat non-repeated
    columns only) is a bool mask over the chunk's rows: data pages
    whose whole row range is False are SKIPPED — header parsed, body
    neither decompressed nor decoded (``DecodeStats.pages_pruned``).
    The return becomes ``(ChunkData, kept)`` where ``kept`` holds the
    global row indices of the decoded rows (the rows of every kept
    page — a superset of the True rows, exact at page granularity)."""
    codec = CompressionCodec(cm.codec)
    col_path = ".".join(cm.path_in_schema)
    if verify_crc is None:
        verify_crc = crc_verify_default()
    if keep_rows is not None:
        if node.max_rep_level:
            raise ValueError(
                f"page pruning needs a non-repeated column, not "
                f"{col_path!r}")
        keep_rows = np.asarray(keep_rows, dtype=bool)
        if keep_rows.size != cm.num_values:
            raise ValueError(
                f"keep_rows has {keep_rows.size} entries for a "
                f"{cm.num_values}-value chunk")
    kept_parts: list = []  # per kept page: (row_start, n)
    row_base = 0
    start = cm.data_page_offset
    if cm.dictionary_page_offset is not None:
        start = min(start, cm.dictionary_page_offset)
    end = start + cm.total_compressed_size
    if end > len(blob) or start < 0:
        raise CorruptChunkError("column chunk byte range out of bounds",
                                column=col_path)

    from ..stats import current_stats

    r = CompactReader(blob, start, end)
    dictionary = None
    pages: list[DecodedPage] = []
    values_read = 0
    page_i = 0  # walk ordinal (all page types) — the error coordinate
    total = cm.num_values
    st = current_stats()
    # per-page event log (obs/): transport "cpu" marks oracle-path
    # pages; with no collector (or a plain collect_stats()) every
    # emission below is skipped without allocating anything
    ev = None if st is None else st.events
    if st is not None:
        st.chunks += 1
        st.bytes_compressed += cm.total_compressed_size
        st.bytes_uncompressed += cm.total_uncompressed_size or 0
        st.values += total
    while values_read < total:
        if r.pos >= end:
            raise CorruptChunkError(
                f"column chunk exhausted at {values_read}/{total} values",
                column=col_path,
            )
        ph = decode_struct(PageHeader, r)
        if ph.compressed_page_size is None or ph.compressed_page_size < 0:
            raise CorruptPageError("page header missing compressed size",
                                   column=col_path, page=page_i)
        if r.pos + ph.compressed_page_size > end:
            raise CorruptPageError("page payload overruns column chunk",
                                   column=col_path, page=page_i)
        # zero-copy view: the codec layer's own bytes() conversion makes
        # the single owned copy (a bytes() here would copy every
        # compressed page a second time)
        payload = blob[r.pos : r.pos + ph.compressed_page_size]
        if len(payload) != ph.compressed_page_size:
            raise CorruptPageError("page payload truncated",
                                   column=col_path, page=page_i)
        payload = filter_bytes("io.chunk.page_payload", payload,
                               column=col_path, page=page_i)
        r.pos += ph.compressed_page_size
        ptype = PageType(ph.type)
        if keep_rows is not None and ptype in (
                PageType.DATA_PAGE, PageType.DATA_PAGE_V2):
            h = (ph.data_page_header_v2
                 if ptype == PageType.DATA_PAGE_V2
                 else ph.data_page_header)
            n_pg = None if h is None else h.num_values
            if n_pg is not None and n_pg >= 0 \
                    and not keep_rows[row_base:row_base + n_pg].any():
                # pruned page: header walked, body never verified,
                # decompressed, nor decoded — the predicate proved no
                # row of it survives
                values_read += n_pg
                row_base += n_pg
                page_i += 1
                if st is not None:
                    st.pages_pruned += 1
                if _flightrec._active is not None:
                    _flightrec.flight(
                        "page_pruned", site="io.chunk",
                        column=col_path, page=page_i - 1, values=n_pg)
                continue
        checked = verify_page_crc(ph, payload, enabled=verify_crc,
                                  column=col_path, page=page_i)
        if checked and st is not None:
            st.pages_crc_verified += 1
        try:
            if ptype == PageType.DICTIONARY_PAGE:
                if dictionary is not None:
                    raise CorruptChunkError(
                        "only one dictionary page allowed per chunk")
                if pages:
                    raise CorruptChunkError(
                        "dictionary page must precede data pages")
                dictionary = decode_dictionary_page(ph, payload, codec,
                                                    node)
                # Some writers put the dictionary away from the data
                # pages: after decoding it, continue at data_page_offset
                # (chunk_reader.go:243-249).
                if r.pos != cm.data_page_offset:
                    r.pos = cm.data_page_offset
            elif ptype in (PageType.DATA_PAGE, PageType.DATA_PAGE_V2):
                v2 = ptype == PageType.DATA_PAGE_V2
                t_pg = time.perf_counter() if ev is not None else 0.0
                pg = (decode_data_page_v2 if v2 else decode_data_page_v1)(
                    ph, payload, codec, node, dictionary)
                values_read += pg.num_values
                if keep_rows is not None:
                    kept_parts.append((row_base, pg.num_values))
                    row_base += pg.num_values
                pages.append(pg)
                # flight recorder: page coordinates ride the ring even
                # with no collector (one `is None` check when off —
                # guarded here so the disabled path skips the kwargs
                # build too; this is the per-page hot loop)
                if _flightrec._active is not None:
                    _flightrec.flight(
                        "page", site="io.chunk", column=col_path,
                        page=len(pages) - 1, values=pg.num_values)
                if st is not None:
                    st.pages += 1
                    st.hist("page_comp_bytes").record(
                        ph.compressed_page_size)
                    st.hist("page_uncomp_bytes").record(
                        ph.uncompressed_page_size)
                    if ev is not None:
                        h = ph.data_page_header_v2 if v2 \
                            else ph.data_page_header
                        ev.page(column=col_path, page=len(pages) - 1,
                                page_type="v2" if v2 else "v1",
                                encoding=Encoding(h.encoding).name,
                                codec=codec.name,
                                num_values=pg.num_values,
                                non_null=None, transport="cpu",
                                plan_s=time.perf_counter() - t_pg)
            elif ptype == PageType.INDEX_PAGE:
                page_i += 1
                continue  # skip (reference ignores index pages)
            else:
                raise CorruptPageError(f"unexpected page type {ph.type}")
        except ScanError as e:
            raise e.annotate(column=col_path, page=page_i)
        except ValueError as e:
            # domain errors from the codec layer become taxonomy errors
            # WITH coordinates; raw crash types still propagate as the
            # bugs they are (tests/test_fuzz.py's _clean contract)
            raise CorruptPageError(str(e), column=col_path,
                                   page=page_i) from e
        page_i += 1
    if values_read != total:
        raise CorruptChunkError(
            f"chunk decoded {values_read} values, metadata says {total}",
            column=col_path,
        )

    # single-page chunks (our writer's default layout; TPQ_PAGE_ROWS
    # opts into splits) keep the page's level arrays as-is:
    # np.concatenate of one array still copies, and at 50M values the
    # two level streams paid ~100 MB of pure memcpy
    if not pages:
        rep = np.empty(0, dtype=np.int32)
        dl = np.empty(0, dtype=np.int32)
    elif len(pages) == 1:
        rep = pages[0].rep_levels
        dl = pages[0].def_levels
    else:
        rep = np.concatenate([p.rep_levels for p in pages])
        dl = np.concatenate([p.def_levels for p in pages])
    null_count = int((dl != node.max_def_level).sum()) if node.max_def_level \
        else 0

    values = _merge_page_values(pages, dictionary, node)
    cd = ChunkData(values, rep, dl, null_count)
    if keep_rows is None:
        return cd
    kept = (np.concatenate([np.arange(s, s + n, dtype=np.int64)
                            for s, n in kept_parts])
            if kept_parts else np.empty(0, dtype=np.int64))
    return cd, kept


def _merge_page_values(pages, dictionary, node):
    cols = []
    idx_parts = []
    for p in pages:
        if p.indices is not None:
            idx_parts.append(p.indices)
        elif p.values is not None:
            if idx_parts:
                cols.append(gather(dictionary, np.concatenate(idx_parts)))
                idx_parts = []
            cols.append(p.values)
    if idx_parts:
        cols.append(gather(dictionary, np.concatenate(idx_parts)))
    if not cols:
        from .values import handler_for

        return handler_for(node.element).finalize([])
    if len(cols) == 1:
        return cols[0]
    if isinstance(cols[0], ByteArrayColumn):
        offsets = [np.zeros(1, dtype=np.int64)]
        datas = []
        base = 0
        for c in cols:
            offsets.append(c.offsets[1:] + base)
            datas.append(c.data)
            base += int(c.offsets[-1])
        return ByteArrayColumn(np.concatenate(offsets), np.concatenate(datas))
    return np.concatenate(cols)


# ----------------------------------------------------------------------
# Write
# ----------------------------------------------------------------------

def _column_size_of(column) -> int:
    if isinstance(column, ByteArrayColumn):
        return int(column.data.size) + 4 * len(column)
    from .values import is_device_values

    if is_device_values(column):
        return column.count * column.dtype.itemsize
    arr = np.asarray(column)
    return int(arr.nbytes)


def _maybe_dictionary(column, allow_dict: bool):
    """Dictionary heuristic: use it when the dictionary + indices are
    smaller than the plain values and the dictionary stays small."""
    if not allow_dict:
        return None, None
    from .values import is_device_values

    if is_device_values(column):
        # device-resident integers intern ON DEVICE (range table +
        # first-occurrence scatter); only the int32 index stream and
        # the tiny dictionary cross the link — identical output to the
        # host interner for small-RANGE columns, so those files match
        # the host path byte for byte.  (Known divergence: wide-range
        # few-distinct columns stay non-dict here.)  The index pull is
        # deferred until the size gates below accept the dictionary.
        from ..kernels.encode import device_dict_build

        built = device_dict_build(column)
        if built is None:
            return None, None
        dictionary, indices = built
        n = column.count
    else:
        n = len(column) if isinstance(column, ByteArrayColumn) else \
            np.asarray(column).shape[0]
        if n == 0:
            return None, None
        if isinstance(column, ByteArrayColumn):
            from ..cpu.dictionary import intern_byte_column
            from ..native import TOO_MANY_DISTINCT

            # cap at MAX-1: the size gate rejects dsize >= MAX, so a
            # column reaching MAX distinct should abort in O(cap)
            # rather than pay the full intern + gather it discards
            out = intern_byte_column(column, MAX_DICT_ENTRIES - 1)
            if out is TOO_MANY_DISTINCT:
                return None, None
            if out is not None:
                dictionary, indices = out
                return _dict_size_gate(column, dictionary, indices, n)
        if not isinstance(column, ByteArrayColumn):
            arr = np.asarray(column)
            if arr.ndim == 1 and arr.dtype.kind in "iuf" and n > 4096:
                # strictly monotonic values (timestamps, row ids) are
                # all distinct: the dictionary would be the column
                # itself plus packed indices — reject without paying
                # the sort.  Elementwise compares, NOT np.diff: a diff
                # wraps on unsigned dtypes (and on int64 steps past
                # 2**63) and would misclassify unsorted data as
                # monotonic.
                a, b = arr[1:], arr[:-1]
                if bool((a > b).all()) or bool((a < b).all()):
                    return None, None
            if arr.ndim == 1 and arr.dtype.kind in "iuf" and n > 1 << 17:
                # High-cardinality early reject: distinct(sample) is a
                # LOWER bound on distinct(full), so a strided sample
                # that already fails the dictionary gates proves the
                # full intern would be discarded — skip its O(n log n)
                # sort.  (Random float columns paid a full argsort here
                # just to throw the dictionary away: 2/3 of the config-4
                # write wall.)
                sample = arr[:: n // 65536][:65536]
                ds = int(np.unique(sample).size)
                width = max((ds - 1).bit_length(), 1)
                if (ds >= MAX_DICT_ENTRIES
                        or ds * arr.itemsize + n * width // 8
                        >= arr.nbytes):
                    return None, None
        dictionary, indices = build_dictionary(column)
    return _dict_size_gate(column, dictionary, indices, n)


def _dict_size_gate(column, dictionary, indices, n: int):
    """Accept the dictionary only when it pays: small enough, and
    dictionary + packed indices smaller than the plain values."""
    dsize = len(dictionary) if isinstance(dictionary, ByteArrayColumn) else \
        dictionary.shape[0]
    if dsize >= MAX_DICT_ENTRIES:
        return None, None
    width = max((dsize - 1).bit_length(), 1)
    approx_dict = _column_size_of(dictionary) + n * width // 8
    if approx_dict >= _column_size_of(column):
        return None, None
    if callable(indices):
        indices = indices()  # deferred device->host index pull
    return dictionary, indices


def _page_bounds(node, page_column, n_values: int, page_rows: int):
    """Level-position page boundaries for one chunk: the single page
    the writer always emitted, or ``page_rows``-sized splits when the
    knob is set and the column is splittable (flat/struct columns
    only — a repeated column's pages must break at record boundaries,
    which stay single-page; device-resident values can't slice)."""
    from .values import is_device_values

    if (page_rows and page_rows > 0 and n_values > page_rows
            and node.max_rep_level == 0
            and not is_device_values(page_column)
            and isinstance(page_column, (np.ndarray, ByteArrayColumn))):
        return [(a, min(a + page_rows, n_values))
                for a in range(0, n_values, page_rows)]
    return [(0, n_values)]


def _slice_column(column, va: int, vb: int):
    """Zero-copy value slice [va, vb) of a page column (ndarray view or
    a ByteArrayColumn over rebased offset views)."""
    if isinstance(column, ByteArrayColumn):
        offs = column.offsets
        return ByteArrayColumn(offs[va:vb + 1] - offs[va],
                               column.data[offs[va]:offs[vb]])
    return column[va:vb]


def _page_statistics(handler, node, values, pg_null: int, chunk_stats,
                     dictionary):
    """Per-page Statistics for a multi-page chunk.  Exact bounds from
    the page's value slice for direct columns; dictionary-encoded pages
    reuse the CHUNK bounds (always valid page bounds — every page value
    appears in the dictionary — without paying a per-page gather)."""
    if chunk_stats is None:
        return None
    if dictionary is not None or len(values) == 0:
        mn_b, mx_b = chunk_stats.min_value, chunk_stats.max_value
    else:
        mn, mx = handler.min_max(values)
        mn_b = handler.encode_stat_value(mn)
        mx_b = handler.encode_stat_value(mx)
    st = Statistics(null_count=pg_null, distinct_count=None,
                    min_value=mn_b, max_value=mx_b)
    if chunk_stats.min is not None:
        st.min = st.min_value
        st.max = st.max_value
    return st


def write_chunk(out, node: SchemaNode, column, rep, dl, *,
                codec: CompressionCodec, page_version: int = 1,
                encoding: Encoding = Encoding.PLAIN,
                allow_dict: bool = True,
                num_rows: int | None = None,
                kv_metadata: dict | None = None,
                write_stats: bool = True,
                page_crc: bool = True,
                page_index: bool = False,
                bloom: bool = False,
                null_count: int | None = None,
                page_rows: int = 0,
                pipeline_workers: int = 1) -> ColumnChunk:
    """Write one column chunk at the current position of ``out`` (a
    position-tracking binary stream); returns its ColumnChunk metadata.

    ``page_index=True`` attaches a per-page ``ColumnIndex``/
    ``OffsetIndex`` pair as ``cc._page_index`` (page offsets relative
    to this stream's positions; the writer serializes them after the
    row groups and records their offsets — see
    ``FileWriter._write_indexes``).  ``bloom=True`` attaches a
    split-block bloom filter over the chunk's distinct values as
    ``cc._bloom`` (``format/bloom.py``).

    ``null_count`` is the precomputed ``(dl != max_def).sum()`` when
    the caller already knows it (the columnar prepare step derives it
    from the masks in O(1)); None recomputes it here.  ``page_rows``
    splits flat columns into multiple data pages of that many level
    positions (0 = the single page this writer always emitted);
    ``pipeline_workers > 1`` overlaps encode(page N+1) with
    compress+write(page N) on an encode-ahead worker."""
    from .values import handler_for

    handler = handler_for(node.element)
    pos0 = out.tell()
    dl = np.asarray(dl, dtype=np.int32)
    rep = np.asarray(rep, dtype=np.int32)
    n_values = len(dl)
    if null_count is None:
        null_count = int((dl != node.max_def_level).sum()) \
            if node.max_def_level else 0

    # Booleans never dict-encode: PLAIN is already 1 bit/value and other
    # readers reject it (the reference's boolean store also disallows dict).
    dictionary, indices = _maybe_dictionary(
        column,
        allow_dict
        and encoding == Encoding.PLAIN
        and node.element.type != Type.BOOLEAN,
    )
    total_comp = 0
    total_uncomp = 0
    dict_page_offset = None
    distinct = None
    from ..kernels.arena import lease_arena, return_arena

    arena = lease_arena()
    # stage hint: the span substrate only learns about page writes
    # after the fact (emit_span), so the sampler needs an explicit
    # marker to bucket in-flight stacks under "write"
    ptok = _profiler.stage_begin("write") \
        if _profiler._active is not None else None
    try:
        if dictionary is not None:
            dict_page_offset = pos0
            c, u = write_dictionary_page(out, node, dictionary, codec,
                                         page_crc=page_crc)
            total_comp += c
            total_uncomp += u
            distinct = len(dictionary) \
                if isinstance(dictionary, ByteArrayColumn) \
                else dictionary.shape[0]

        stats = None
        if write_stats:
            # min/max over the DICTIONARY when one was built: every
            # distinct value appears in it, so the reduction runs over
            # D entries instead of materializing n Python objects
            # (byte columns paid a 2M-element to_list here)
            mn, mx = handler.min_max(
                dictionary if dictionary is not None else column)
            stats = Statistics(
                null_count=null_count,
                distinct_count=distinct,
                min_value=handler.encode_stat_value(mn),
                max_value=handler.encode_stat_value(mx),
            )
            # The deprecated min/max fields are defined under SIGNED
            # comparison only (parquet.thrift Statistics doc); writing
            # them for unsigned-ordered or byte-wise-ordered columns
            # can make legacy readers mis-prune (min > max
            # two's-complement).
            if not handler.unsigned and node.element.type not in (
                Type.BYTE_ARRAY, Type.FIXED_LEN_BYTE_ARRAY
            ):
                stats.min = stats.min_value
                stats.max = stats.max_value

        data_page_offset = out.tell()
        page_column = indices if dictionary is not None else column
        dict_size = distinct if dictionary is not None else None
        bounds = _page_bounds(node, page_column, n_values, page_rows)
        # resolve the native-pipeline verdict once per chunk (env read
        # + registry lock); every page below inherits it
        nat_ctx = _native_page_ctx(codec)
        if len(bounds) == 1:
            # the single-page fast path: whole arrays, chunk stats in
            # the page header (byte-identical to the pre-split writer).
            # With no page split to pipeline, spare workers go to the
            # block-parallel codec split inside the one page.
            if page_version == 2:
                c, u = write_data_page_v2(
                    out, node, page_column, rep, dl, codec, encoding,
                    num_rows=num_rows if num_rows is not None
                    else n_values,
                    null_count=null_count, dictionary_size=dict_size,
                    statistics=stats, page_crc=page_crc, arena=arena,
                    native_ctx=nat_ctx,
                    compress_workers=pipeline_workers,
                )
            else:
                c, u = write_data_page_v1(
                    out, node, page_column, rep, dl, codec, encoding,
                    dictionary_size=dict_size, statistics=stats,
                    page_crc=page_crc, arena=arena, native_ctx=nat_ctx,
                    compress_workers=pipeline_workers,
                )
            total_comp += c
            total_uncomp += u
            page_entries = [(stats, data_page_offset, c, 0)]
        else:
            c, page_entries = _write_split_pages(
                out, node, handler, page_column, dl, codec, encoding,
                bounds, dict_size, stats, dictionary, page_version,
                page_crc, arena, pipeline_workers, nat_ctx)
            total_comp += sum(e[2] for e in page_entries)
            total_uncomp += c
    finally:
        if ptok is not None:
            _profiler.stage_end(ptok)
        # page bodies have been copied into the output stream; slabs
        # recycle for the next chunk on this thread
        arena.release_all()
        return_arena(arena)

    encodings = [Encoding.RLE, encoding]
    if dictionary is not None:
        encodings.append(Encoding.RLE_DICTIONARY)
    kv = None
    if kv_metadata:
        kv = [KeyValue(key=k, value=v)
              for k, v in sorted(kv_metadata.items())]

    cm = ColumnMetaData(
        type=Type(node.element.type),
        encodings=encodings,
        path_in_schema=list(node.path),
        codec=codec,
        num_values=n_values,
        total_uncompressed_size=total_uncomp,
        total_compressed_size=total_comp,
        data_page_offset=data_page_offset,
        dictionary_page_offset=dict_page_offset,
        statistics=stats,
        key_value_metadata=kv,
    )
    cc = ColumnChunk(file_offset=pos0, meta_data=cm)
    if page_index and stats is not None:
        pi = _build_page_index(node, page_entries, n_values)
        if pi is not None:
            cc._page_index = pi
    if bloom:
        b = _build_bloom(node, column, dictionary)
        if b is not None:
            cc._bloom = b
    return cc


def _write_split_pages(out, node, handler, page_column, dl, codec,
                       encoding, bounds, dict_size, chunk_stats,
                       dictionary, page_version, page_crc, arena,
                       pipeline_workers, nat_ctx):
    """The multi-page data loop behind ``page_rows``: one data page per
    ``bounds`` entry, each with exact per-page statistics.  With
    ``pipeline_workers > 1`` an encode-ahead worker renders page N+1
    (native encode + compress, GIL released across both) while this
    thread writes page N — the write pipeline's intra-column overlap.
    Returns ``(total_uncompressed, page_entries)`` with one
    ``(stats, offset, compressed_size, first_row)`` entry per page."""
    max_def = node.max_def_level
    if max_def:
        nnp = np.zeros(len(dl) + 1, dtype=np.int64)
        np.cumsum(dl == max_def, out=nnp[1:])
    rep0 = np.zeros(0, dtype=np.int32)  # flat columns: no rep stream

    def page_args(a, b):
        dl_pg = dl[a:b]
        va, vb = (int(nnp[a]), int(nnp[b])) if max_def else (a, b)
        vals = _slice_column(page_column, va, vb)
        pg_null = (b - a) - (vb - va)
        pg_stats = _page_statistics(
            handler, node,
            vals if dict_size is None else None,
            pg_null, chunk_stats,
            dictionary if dict_size is not None else None)
        return vals, dl_pg, pg_null, pg_stats

    # the encode-ahead worker re-enters the submitting thread's trace
    # context so its page_write spans parent under the writer's trace
    _tctx = _trace.current_ctx()

    def render(a, b, like):
        # render one page's bytes into a private buffer (pipelined
        # mode): offsets rebase at append time, stats merge at join
        from ..stats import worker_stats

        buf = _CountingBuf()
        with _trace.adopt(_tctx), worker_stats(like) as ws:
            c, u, pg_stats = write_page(buf, a, b)
        return buf.parts, c, u, pg_stats, ws

    def write_page(sink, a, b):
        vals, dl_pg, pg_null, pg_stats = page_args(a, b)
        if page_version == 2:
            c, u = write_data_page_v2(
                sink, node, vals, rep0, dl_pg, codec, encoding,
                num_rows=b - a, null_count=pg_null,
                dictionary_size=dict_size, statistics=pg_stats,
                page_crc=page_crc,
                arena=arena if sink is out else None,
                native_ctx=nat_ctx,
            )
        else:
            c, u = write_data_page_v1(
                sink, node, vals, rep0, dl_pg, codec, encoding,
                dictionary_size=dict_size, statistics=pg_stats,
                page_crc=page_crc, arena=arena if sink is out else None,
                native_ctx=nat_ctx,
            )
        return c, u, pg_stats

    entries = []
    total_uncomp = 0
    if pipeline_workers > 1 and len(bounds) > 1:
        from concurrent.futures import ThreadPoolExecutor

        from ..stats import current_stats

        st = current_stats()
        # bounded encode-ahead (one in-flight page beyond the one being
        # written): encode(N+1) overlaps compress/write(N) with at most
        # two page buffers alive
        with ThreadPoolExecutor(max_workers=1) as ex:
            futs = {}
            for j in range(min(2, len(bounds))):
                futs[j] = ex.submit(render, *bounds[j], st)
            for i, (a, b) in enumerate(bounds):
                parts, c, u, pg_stats, ws = futs.pop(i).result()
                off = out.tell()
                for p in parts:
                    out.write(p)
                if st is not None:
                    st.merge_from(ws)
                entries.append((pg_stats, off, c, a))
                total_uncomp += u
                j = i + 2
                if j < len(bounds):
                    futs[j] = ex.submit(render, *bounds[j], st)
    else:
        for a, b in bounds:
            off = out.tell()
            c, u, pg_stats = write_page(out, a, b)
            entries.append((pg_stats, off, c, a))
            total_uncomp += u
    return total_uncomp, entries


class _CountingBuf:
    """Minimal position-tracking sink for pipelined page rendering:
    collects the written segments so the coordinator can append them
    without a concatenation copy."""

    __slots__ = ("parts", "_pos")

    def __init__(self):
        self.parts = []
        self._pos = 0

    def tell(self) -> int:
        return self._pos

    def write(self, data) -> None:
        self.parts.append(bytes(data))
        self._pos += len(data)


def _build_page_index(node, page_entries, n_values: int):
    """Per-page ``(ColumnIndex, OffsetIndex)`` from one entry
    ``(stats, offset, compressed_size, first_row)`` per data page —
    the historical single-page chunk (page summary == chunk
    statistics, ASCENDING) and the ``page_rows`` multi-page splits
    (exact per-page bounds; UNORDERED, since page order is the data's
    order).  Returns None when the column's order admits no index
    (INT96, or stats carry no bounds for a non-empty page)."""
    from ..format.metadata import (
        BoundaryOrder,
        ColumnIndex,
        OffsetIndex,
        PageLocation,
    )

    mins, maxs, null_pages, null_counts = [], [], [], []
    n_pages = len(page_entries)
    for i, (stats, _off, _size, first_row) in enumerate(page_entries):
        if stats is None:
            return None
        pg_values = (page_entries[i + 1][3] if i + 1 < n_pages
                     else n_values) - first_row
        all_null = (stats.null_count is not None
                    and stats.null_count == pg_values)
        if stats.min_value is None or stats.max_value is None:
            if not all_null:
                return None  # unordered type (INT96): no index possible
            mins.append(b"")
            maxs.append(b"")
            null_pages.append(True)
        else:
            mins.append(stats.min_value)
            maxs.append(stats.max_value)
            null_pages.append(all_null)
        null_counts.append(stats.null_count)
    ci = ColumnIndex(
        null_pages=null_pages,
        min_values=mins,
        max_values=maxs,
        boundary_order=(BoundaryOrder.ASCENDING if n_pages == 1
                        else BoundaryOrder.UNORDERED),
        null_counts=(null_counts
                     if all(c is not None for c in null_counts)
                     else None),
    )
    oi = OffsetIndex(page_locations=[
        PageLocation(offset=off, compressed_page_size=size,
                     first_row_index=first_row)
        for (_st, off, size, first_row) in page_entries
    ])
    return ci, oi


# skip bloom construction past this many distinct values: the filter
# would be megabytes and the column is not "dictionary-ish"
MAX_BLOOM_DISTINCT = 1 << 16


def _build_bloom(node, column, dictionary):
    """Split-block bloom filter over the chunk's distinct values, or
    None when the column is unsuitable (too many distinct, undefined
    order, empty).  The dictionary, when one was built, IS the
    distinct set; otherwise distinct values are derived here."""
    from ..format.bloom import SplitBlockBloom, optimal_bytes
    from .values import handler_for, is_device_values

    handler = handler_for(node.element)
    if handler.ptype in (Type.INT96, Type.BOOLEAN):
        return None  # undefined order / 1-bit domain: bloom is useless
    src = dictionary if dictionary is not None else column
    if is_device_values(src):
        src = src.to_numpy()  # device columns: pull once for hashing
    if isinstance(src, ByteArrayColumn):
        distinct = set(src.to_list())
        if len(distinct) > MAX_BLOOM_DISTINCT:
            return None
        encoded = distinct
    else:
        arr = np.asarray(src)
        if arr.size == 0:
            return None
        if arr.ndim == 2:  # FLBA byte rows
            view = np.ascontiguousarray(arr).view(
                np.dtype((np.void, arr.shape[1]))).reshape(-1)
            uniq = np.unique(view)
            if uniq.size > MAX_BLOOM_DISTINCT:
                return None
            encoded = [bytes(v) for v in uniq]
        else:
            uniq = np.unique(arr)
            if uniq.size > MAX_BLOOM_DISTINCT:
                return None
            # PLAIN little-endian bytes of each distinct value — the
            # same framing encode_stat_value uses, one bulk tobytes
            encoded = [uniq[i:i + 1].tobytes()
                       for i in range(uniq.size)]
    if not encoded:
        return None
    b = SplitBlockBloom(optimal_bytes(len(encoded)))
    for e in encoded:
        b.insert(e)
    return b
