"""csv2parquet: convert CSV files to Parquet.

Parity with the reference converter (``/root/reference/cmd/csv2parquet/
main.go``): schema derivation from the header row, ``--typehints``
``col=type`` overrides (``main.go:283``), per-type parsers incl. the
full int8..64/uint8..64 range checks (``main.go:188-434``), empty
strings mapping to null for optional columns, row-group size and codec
flags.

Run as ``python -m tpuparquet.cli.csv2parquet --input in.csv
--output out.parquet``.
"""

from __future__ import annotations

import argparse
import csv
import json
import os
import re
import sys

from ..format.metadata import CompressionCodec
from ..io.writer import FileWriter
from . import CODECS as _CODECS

_IDENT = re.compile(r"^[A-Za-z_][A-Za-z0-9_]*$")


def _int_parser(bits: int, signed: bool):
    if signed:
        lo, hi = -(1 << (bits - 1)), (1 << (bits - 1)) - 1
    else:
        lo, hi = 0, (1 << bits) - 1

    def parse(s: str):
        v = int(s)
        if not lo <= v <= hi:
            raise ValueError(f"{v} out of range [{lo}, {hi}]")
        return v

    return parse


def _json_parser(s: str) -> bytes:
    json.loads(s)  # validate
    return s.encode("utf-8")


def _bool_parser(s: str) -> bool:
    t = s.strip().lower()
    if t in ("true", "t", "1", "yes"):
        return True
    if t in ("false", "f", "0", "no"):
        return False
    raise ValueError(f"invalid boolean {s!r}")


# type name -> (DSL leaf type, annotation, value parser)
# (``validTypeList``/``field handlers``, ``main.go:188-434``)
TYPES = {
    "string": ("binary", "(STRING)", lambda s: s.encode("utf-8")),
    "byte_array": ("binary", "", lambda s: s.encode("utf-8")),
    "boolean": ("boolean", "", _bool_parser),
    "int8": ("int32", "(INT(8, true))", _int_parser(8, True)),
    "uint8": ("int32", "(INT(8, false))", _int_parser(8, False)),
    "int16": ("int32", "(INT(16, true))", _int_parser(16, True)),
    "uint16": ("int32", "(INT(16, false))", _int_parser(16, False)),
    "int32": ("int32", "(INT(32, true))", _int_parser(32, True)),
    "uint32": ("int32", "(INT(32, false))", _int_parser(32, False)),
    "int64": ("int64", "(INT(64, true))", _int_parser(64, True)),
    "uint64": ("int64", "(INT(64, false))", _int_parser(64, False)),
    "int": ("int64", "(INT(64, true))", _int_parser(64, True)),
    "float": ("float", "", float),
    "double": ("double", "", float),
    "json": ("binary", "(JSON)", _json_parser),
}


def parse_type_hints(s: str) -> dict[str, str]:
    """``col=type,col=type`` -> mapping (``main.go:283-300``)."""
    hints = {}
    if not s:
        return hints
    for part in s.split(","):
        part = part.strip()
        if not part:
            continue
        if "=" not in part:
            raise ValueError(f"invalid type hint {part!r}")
        col, typ = (x.strip() for x in part.split("=", 1))
        if typ not in TYPES:
            raise ValueError(
                f"unknown type {typ!r} for column {col!r}; valid: "
                + ", ".join(sorted(TYPES)))
        hints[col] = typ
    return hints


def derive_schema(header: list[str], hints: dict[str, str]) -> str:
    """All columns optional; hinted type or string (``deriveSchema``,
    ``main.go:154-186``)."""
    lines = []
    for col in header:
        typ = hints.get(col, "string")
        leaf, annot, _ = TYPES[typ]
        annot = f" {annot}" if annot else ""
        lines.append(f"  optional {leaf} {col}{annot};")
    return "message msg {\n" + "\n".join(lines) + "\n}"


def convert(in_f, out_f, *, hints=None, codec=CompressionCodec.SNAPPY,
            rowgroup_size=100 * 1024 * 1024, delimiter=",",
            created_by="csv2parquet", verbose=False, log=sys.stderr) -> int:
    """Stream CSV rows into a Parquet file; returns rows written."""
    hints = hints or {}
    if len(delimiter) != 1:
        raise ValueError(f"delimiter must be one character, got "
                         f"{delimiter!r}")
    reader = csv.reader(in_f, delimiter=delimiter)
    try:
        header = next(reader)
    except StopIteration:
        raise ValueError("empty CSV input: no header row")
    seen = set()
    for col in header:
        if not _IDENT.match(col):
            raise ValueError(f"column name {col!r} is not a valid "
                             "identifier")
        if col in seen:
            raise ValueError(f"duplicate column name {col!r} in header")
        seen.add(col)
    for col in hints:
        if col not in header:
            raise ValueError(f"type hint for unknown column {col!r}")
    parsers = [TYPES[hints.get(col, "string")][2] for col in header]
    schema = derive_schema(header, hints)
    if verbose:
        print(f"derived schema:\n{schema}", file=log)

    w = FileWriter(out_f, schema, codec=codec, created_by=created_by,
                   max_row_group_size=rowgroup_size or None)
    n = 0
    for lineno, rec in enumerate(reader, start=2):
        if len(rec) != len(header):
            raise ValueError(
                f"line {lineno}: {len(rec)} fields, header has "
                f"{len(header)}")
        row = {}
        for col, parser, raw in zip(header, parsers, rec):
            if raw == "":
                # empty string -> null (optional wrapping, main.go:428)
                continue
            try:
                row[col] = parser(raw)
            except ValueError as e:
                raise ValueError(f"line {lineno}, column {col!r}: {e}")
        w.add_data(row)
        n += 1
    w.close()
    if verbose:
        print(f"wrote {n} rows", file=log)
    return n


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="csv2parquet", description="Convert CSV files to Parquet")
    p.add_argument("--input", required=True, help="CSV file input")
    p.add_argument("--output", required=True, help="output parquet file")
    p.add_argument("--typehints", default="",
                   help="comma-separated col=type hints; valid types: "
                        + ", ".join(sorted(TYPES)))
    p.add_argument("--rowgroup-size", type=int, default=100 * 1024 * 1024,
                   help="row group size in bytes (0 = unbounded)")
    p.add_argument("--compression", default="snappy",
                   choices=sorted(_CODECS))
    p.add_argument("--delimiter", default=",")
    p.add_argument("--created-by", default="csv2parquet")
    p.add_argument("-v", dest="verbose", action="store_true",
                   help="enable verbose logging")
    args = p.parse_args(argv)

    created_output = False
    try:
        hints = parse_type_hints(args.typehints)
        with open(args.input, newline="") as in_f:
            with open(args.output, "wb") as out_f:
                created_output = True
                convert(in_f, out_f, hints=hints,
                        codec=_CODECS[args.compression],
                        rowgroup_size=args.rowgroup_size,
                        delimiter=args.delimiter,
                        created_by=args.created_by,
                        verbose=args.verbose)
    except (OSError, ValueError) as e:
        print(f"csv2parquet: {e}", file=sys.stderr)
        if created_output:
            try:  # don't leave a truncated, footer-less parquet behind
                os.unlink(args.output)
            except OSError:
                pass
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
