"""parquet-tool: inspect, verify and split Parquet files.

Subcommand parity with the reference's cobra tool
(``/root/reference/cmd/parquet-tool/cmds/``): ``cat``, ``head``,
``meta``, ``schema``, ``rowcount``, ``split``; plus ``verify``
(CPU-vs-device bit-exact decode comparison) and ``profile``
(per-column transport/gate/timing telemetry with JSON-lines and
Perfetto exports) — TPU-build additions.

Run as ``python -m tpuparquet.cli.parquet_tool <cmd> <file>``.
"""

from __future__ import annotations

import argparse
import os
import sys

from ..io.reader import FileReader
from ..io.writer import FileWriter
from . import CODECS as _CODECS

# ``humanToByte`` table (``cmd/parquet-tool/cmds/helpers.go:9-20``) —
# the reference maps *B to binary and *iB to decimal multiples; we keep
# the conventional meaning instead (KB=1000, KiB=1024).
_SUFFIX = {
    "KB": 1000, "KiB": 1024,
    "MB": 1000**2, "MiB": 1024**2,
    "GB": 1000**3, "GiB": 1024**3,
    "TB": 1000**4, "TiB": 1024**4,
    "PB": 1000**5, "PiB": 1024**5,
}


def human_to_bytes(s: str) -> int:
    s = s.strip()
    try:
        return int(s)
    except ValueError:
        pass
    for suf, mult in _SUFFIX.items():
        if s.endswith(suf):
            return int(s[: -len(suf)].strip()) * mult
    raise ValueError(f"invalid size {s!r}")


# ----------------------------------------------------------------------
# Row printing (``readfile.go printData``: flat "name = value" lines,
# nested groups as "name:" with dot-prefixed children)
# ----------------------------------------------------------------------

def _print_value(out, indent: str, name: str, v) -> None:
    if isinstance(v, dict):
        print(f"{indent}{name}:", file=out)
        _print_row(out, v, indent + ".")
    elif isinstance(v, (list, tuple)):
        for item in v:
            if isinstance(item, dict):
                print(f"{indent}{name}:", file=out)
                _print_row(out, item, indent + ".")
            else:
                _print_value(out, indent, name, item)
    elif isinstance(v, bytes):
        print(f"{indent}{name} = {v.decode('utf-8', 'replace')}", file=out)
    else:
        print(f"{indent}{name} = {v}", file=out)


def _print_row(out, row: dict, indent: str = "") -> None:
    for name, v in row.items():
        _print_value(out, indent, name, v)


def cmd_cat(args, out=None) -> int:
    out = out or sys.stdout
    return _cat(args.file, -1, out, trace=getattr(args, "trace", False))


def cmd_head(args, out=None) -> int:
    out = out or sys.stdout
    return _cat(args.file, args.n, out,
                trace=getattr(args, "trace", False))


def _cat(path: str, n: int, out, trace: bool = False) -> int:
    import contextlib

    from ..stats import collect_stats

    ctx = collect_stats() if trace else contextlib.nullcontext()
    with ctx as st, FileReader(path) as r:
        for i, row in enumerate(r.rows()):
            if n != -1 and i >= n:
                break
            _print_row(out, row)
            print(file=out)
    if trace and st is not None:
        print(st.summary(), file=sys.stderr)
    return 0


def cmd_meta(args, out=None) -> int:
    """Flat schema with repetition + R/D levels (``readfile.go:75-104``)."""
    out = out or sys.stdout
    with FileReader(args.file) as r:
        _print_flat(out, r.schema.root, 0)
        print(file=out)
        meta = r.metadata()
        print(f"rows: {meta.num_rows}  row groups: "
              f"{len(meta.row_groups)}  created by: {meta.created_by}",
              file=out)
        for i, rg in enumerate(meta.row_groups):
            print(f"row group {i}: {rg.num_rows} rows, "
                  f"{rg.total_byte_size} bytes", file=out)
            for cc in rg.columns:
                cm = cc.meta_data
                print(f"  {'.'.join(cm.path_in_schema)}: "
                      f"{cm.type.name} {cm.codec.name} "
                      f"values={cm.num_values} "
                      f"compressed={cm.total_compressed_size} "
                      f"uncompressed={cm.total_uncompressed_size}",
                      file=out)
    return 0


def _print_flat(out, node, lvl: int) -> None:
    dot = "." * lvl
    for child in node.children:
        rep = child.repetition_type.name if child.repetition_type is not None else "?"
        if child.is_leaf:
            print(f"{dot}{child.name}:\t\t{rep} {child.type.name} "
                  f"R:{child.max_rep_level} D:{child.max_def_level}",
                  file=out)
        else:
            print(f"{dot}{child.name}:\t\t{rep} F:{len(child.children)}",
                  file=out)
            _print_flat(out, child, lvl + 1)


def cmd_schema(args, out=None) -> int:
    out = out or sys.stdout
    with FileReader(args.file) as r:
        print(r.get_schema_definition(), file=out)
    return 0


def cmd_rowcount(args, out=None) -> int:
    out = out or sys.stdout
    with FileReader(args.file) as r:
        print(f"Total RowCount: {r.num_rows}", file=out)
    return 0


def cmd_verify(args, out=None) -> int:
    """Decode every row group on BOTH paths (CPU oracle and device
    kernels) and compare bit-exactly — the file doctor for the decode
    backend.  No reference analogue (the reference has one path)."""
    import time

    import numpy as np

    out = out or sys.stdout
    from ..cpu.plain import ByteArrayColumn
    from ..kernels.device import read_row_group_device

    rc = 0
    with FileReader(args.file) as r:
        for rg in range(r.row_group_count()):
            t0 = time.perf_counter()
            cpu = r.read_row_group_arrays(rg)
            t1 = time.perf_counter()
            # read_row_group_device drains all buffers in one batched
            # sync before returning — no per-column sync needed
            dev = read_row_group_device(r, rg)
            t2 = time.perf_counter()
            n = sum(len(cd.def_levels) for cd in cpu.values())
            bad = []
            for path, cd in cpu.items():
                vals, rep, dl = dev[path].to_numpy()
                ok = (np.array_equal(rep, cd.rep_levels)
                      and np.array_equal(dl, cd.def_levels))
                if ok:
                    if isinstance(cd.values, ByteArrayColumn):
                        ok = vals == cd.values
                    else:
                        # bitwise, not value, comparison: NaN payloads
                        # must compare equal for a bit-exact check
                        a = np.ascontiguousarray(np.asarray(vals))
                        b = np.ascontiguousarray(np.asarray(cd.values))
                        ok = (a.shape == b.shape and a.dtype == b.dtype
                              and a.tobytes() == b.tobytes())
                if not ok:
                    bad.append(path)
            status = "OK" if not bad else f"MISMATCH: {', '.join(bad)}"
            print(f"row group {rg}: {n:,} values  "
                  f"cpu {(t1 - t0) * 1e3:.1f}ms  "
                  f"device {(t2 - t1) * 1e3:.1f}ms  {status}", file=out)
            if bad:
                rc = 1
    print("verify: " + ("all row groups bit-exact" if rc == 0
                        else "MISMATCHES FOUND"), file=out)
    return rc


def cmd_profile(args, out=None) -> int:
    """Decode with full telemetry on and print the per-column
    transport/timing table: which wire transport each column's pages
    took, WHY the gate chose it (the competition's wire-size numbers),
    and where the host wall went.  Optional dumps: ``--events`` writes
    the raw per-page JSON-lines log, ``--perfetto`` a Chrome-trace
    JSON of the host phase spans (load at ui.perfetto.dev).  No
    reference analogue — this is the observability face of the device
    decode backend."""
    out = out or sys.stdout
    from .. import obs
    from ..stats import collect_stats

    with FileReader(args.file) as r:
        with collect_stats(events=True) as st:
            if getattr(args, "cpu", False):
                for rg in range(r.row_group_count()):
                    r.read_row_group_arrays(rg)
            else:
                from ..kernels.device import read_row_groups_device

                for _rg, cols in read_row_groups_device(r):
                    for c in cols.values():
                        c.block_until_ready()
    print(obs.format_column_table(obs.column_table(st.events)), file=out)
    d = st.as_dict()
    print(f"\nphases: plan {d['plan_s']:.3f}s  "
          f"transfer {d['transfer_s']:.3f}s  "
          f"dispatch {d['dispatch_s']:.3f}s  wall {d['wall_s']:.3f}s",
          file=out)
    print(st.summary(), file=out)
    h = st.hists.get("page_comp_bytes")
    if h is not None and h.n:
        print(f"compressed page size: p50 < {h.quantile(0.5):,}B, "
              f"p99 < {h.quantile(0.99):,}B over {h.n} pages", file=out)
    if getattr(args, "events", None):
        st.events.write_jsonl(args.events)
        print(f"wrote page events to {args.events}", file=out)
    if getattr(args, "perfetto", None):
        obs.write_chrome_trace(st.events, args.perfetto)
        print(f"wrote Perfetto trace to {args.perfetto}", file=out)
    return 0


def cmd_split(args, out=None) -> int:
    """Re-shard into multiple files of ~--file-size each
    (``split.go:33-122``)."""
    out = out or sys.stdout
    target = human_to_bytes(args.file_size)
    rg_size = human_to_bytes(args.row_group_size)
    codec = _CODECS[args.compression.lower()]
    folder = args.target_folder or os.path.dirname(os.path.abspath(args.file))
    base = os.path.splitext(os.path.basename(args.file))[0]

    with FileReader(args.file) as r:
        schema_def = r.get_schema_definition()
        part = 0
        w = None
        f = None
        current = None

        def open_part():
            nonlocal part, w, f, current
            current = os.path.join(folder, f"{base}_{part:03d}.parquet")
            f = open(current, "wb")
            w = FileWriter(f, schema_def, codec=codec,
                           max_row_group_size=rg_size or None,
                           created_by="parquet-tool split")
            print(f"writing {current}", file=out)
            part += 1

        def close_part():
            nonlocal w, f
            w.close()
            f.close()
            w = f = None

        try:
            # Parts open lazily so a threshold hit on the last row
            # doesn't leave a trailing empty file.
            for row in r.rows():
                if w is None:
                    open_part()
                w.add_data(row)
                if (w.current_file_size()
                        + w.current_row_group_size() >= target):
                    close_part()
            if w is not None:
                close_part()
            elif part == 0:  # empty input: emit one valid (empty) file
                open_part()
                close_part()
        except BaseException:
            # Don't leave a footer-less, truncated part behind.
            if f is not None:
                f.close()
                try:
                    os.unlink(current)
                except OSError:
                    pass
            raise
    return 0


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="parquet-tool", description="Tool to manage parquet files")
    sub = p.add_subparsers(dest="cmd", required=True)

    c = sub.add_parser("cat", help="print the parquet file content")
    c.add_argument("--trace", action="store_true",
                   help="print decode statistics to stderr")
    c.add_argument("file")
    c.set_defaults(fn=cmd_cat)

    h = sub.add_parser("head", help="print the first N records")
    h.add_argument("--trace", action="store_true",
                   help="print decode statistics to stderr")
    h.add_argument("-n", type=int, default=5,
                   help="number of records to print")
    h.add_argument("file")
    h.set_defaults(fn=cmd_head)

    m = sub.add_parser("meta", help="print the file metadata")
    m.add_argument("file")
    m.set_defaults(fn=cmd_meta)

    s = sub.add_parser("schema", help="print the file schema definition")
    s.add_argument("file")
    s.set_defaults(fn=cmd_schema)

    v = sub.add_parser(
        "verify",
        help="decode on the CPU and device paths and compare bit-exactly")
    v.add_argument("file")
    v.set_defaults(fn=cmd_verify)

    pf = sub.add_parser(
        "profile",
        help="decode with telemetry on; print the per-column "
             "transport/timing table")
    pf.add_argument("--cpu", action="store_true",
                    help="profile the CPU oracle path instead of the "
                         "device path")
    pf.add_argument("--events", metavar="FILE", default="",
                    help="write the per-page event log as JSON-lines")
    pf.add_argument("--perfetto", metavar="FILE", default="",
                    help="write a Chrome-trace JSON of the host phase "
                         "spans (ui.perfetto.dev)")
    pf.add_argument("file")
    pf.set_defaults(fn=cmd_profile)

    rc = sub.add_parser("rowcount", help="print the total row count")
    rc.add_argument("file")
    rc.set_defaults(fn=cmd_rowcount)

    sp = sub.add_parser("split", help="split into multiple parquet files")
    sp.add_argument("-s", "--file-size", default="100MB",
                    help="target output file size")
    sp.add_argument("-t", "--target-folder", default="",
                    help="target folder (default: source folder)")
    sp.add_argument("-r", "--row-group-size", default="128MB",
                    help="uncompressed row group size")
    sp.add_argument("-c", "--compression", default="snappy",
                    choices=sorted(_CODECS), help="compression codec")
    sp.add_argument("file")
    sp.set_defaults(fn=cmd_split)
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    try:
        return args.fn(args)
    except (OSError, ValueError, KeyError) as e:
        print(f"parquet-tool: {e}", file=sys.stderr)
        return 1


if __name__ == "__main__":
    sys.exit(main())
