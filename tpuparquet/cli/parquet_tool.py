"""parquet-tool: inspect, verify and split Parquet files.

Subcommand parity with the reference's cobra tool
(``/root/reference/cmd/parquet-tool/cmds/``): ``cat``, ``head``,
``meta``, ``schema``, ``rowcount``, ``split``; plus ``verify``
(CPU-vs-device bit-exact decode comparison + strict metadata
validation), ``profile`` (per-column transport/gate/timing telemetry
with JSON-lines/Perfetto/``--json`` exports and ``--from-events``
replay of a saved log), ``top`` (live view of a running scan's
exported progress), ``watch`` (RED view + budgets + alerts over a
time-series ring), ``slo report`` (error-budget/burn-rate evaluation
with nonzero exit on violation), ``meta --strict`` (metadata
validator findings with nonzero exit) and ``rescue`` (rewrite a torn
file's recoverable row groups into a clean file) — TPU-build
additions.

Run as ``python -m tpuparquet.cli.parquet_tool <cmd> <file>``.
"""

from __future__ import annotations

import argparse
import os
import sys

from ..io.reader import FileReader
from ..io.writer import FileWriter
from . import CODECS as _CODECS

# ``humanToByte`` table (``cmd/parquet-tool/cmds/helpers.go:9-20``) —
# the reference maps *B to binary and *iB to decimal multiples; we keep
# the conventional meaning instead (KB=1000, KiB=1024).
_SUFFIX = {
    "KB": 1000, "KiB": 1024,
    "MB": 1000**2, "MiB": 1024**2,
    "GB": 1000**3, "GiB": 1024**3,
    "TB": 1000**4, "TiB": 1024**4,
    "PB": 1000**5, "PiB": 1024**5,
}


def human_to_bytes(s: str) -> int:
    s = s.strip()
    try:
        return int(s)
    except ValueError:
        pass
    for suf, mult in _SUFFIX.items():
        if s.endswith(suf):
            return int(s[: -len(suf)].strip()) * mult
    raise ValueError(f"invalid size {s!r}")


# ----------------------------------------------------------------------
# Row printing (``readfile.go printData``: flat "name = value" lines,
# nested groups as "name:" with dot-prefixed children)
# ----------------------------------------------------------------------

def _print_value(out, indent: str, name: str, v) -> None:
    if isinstance(v, dict):
        print(f"{indent}{name}:", file=out)
        _print_row(out, v, indent + ".")
    elif isinstance(v, (list, tuple)):
        for item in v:
            if isinstance(item, dict):
                print(f"{indent}{name}:", file=out)
                _print_row(out, item, indent + ".")
            else:
                _print_value(out, indent, name, item)
    elif isinstance(v, bytes):
        print(f"{indent}{name} = {v.decode('utf-8', 'replace')}", file=out)
    else:
        print(f"{indent}{name} = {v}", file=out)


def _print_row(out, row: dict, indent: str = "") -> None:
    for name, v in row.items():
        _print_value(out, indent, name, v)


def cmd_cat(args, out=None) -> int:
    out = out or sys.stdout
    return _cat(args.file, -1, out, trace=getattr(args, "trace", False))


def cmd_head(args, out=None) -> int:
    out = out or sys.stdout
    return _cat(args.file, args.n, out,
                trace=getattr(args, "trace", False))


def _cat(path: str, n: int, out, trace: bool = False) -> int:
    import contextlib

    from ..stats import collect_stats

    ctx = collect_stats() if trace else contextlib.nullcontext()
    with ctx as st, FileReader(path) as r:
        for i, row in enumerate(r.rows()):
            if n != -1 and i >= n:
                break
            _print_row(out, row)
            print(file=out)
    if trace and st is not None:
        print(st.summary(), file=sys.stderr)
    return 0


def _fmt_stat(v, limit: int = 24) -> str:
    if isinstance(v, bytes):
        s = repr(v.decode("utf-8", "replace"))
    else:
        s = repr(v)
    return s if len(s) <= limit else s[: limit - 2] + ".."


def _chunk_extras(r, cc) -> str:
    """Per-chunk Statistics (decoded to LOGICAL values) + pruning-index
    presence flags for ``meta`` — the operator's view of what predicate
    pushdown has to work with."""
    from ..io.values import handler_for

    cm = cc.meta_data
    bits = []
    st = cm.statistics
    if st is not None:
        node = r.schema.leaf(".".join(cm.path_in_schema))
        if node is not None and (st.min_value is not None
                                 or st.max_value is not None):
            h = handler_for(node.element)
            try:
                mn = (h.decode_stat_logical(st.min_value)
                      if st.min_value is not None else None)
                mx = (h.decode_stat_logical(st.max_value)
                      if st.max_value is not None else None)
                bits.append(f"stats=[{_fmt_stat(mn)} .. {_fmt_stat(mx)}]")
            except (ValueError, TypeError):
                bits.append("stats=<undecodable>")
        if st.null_count is not None:
            bits.append(f"nulls={st.null_count}")
    idx = []
    if cc.column_index_offset is not None:
        idx.append("column")
    if cc.offset_index_offset is not None:
        idx.append("offset")
    if idx:
        bits.append(f"page-index={'+'.join(idx)}")
    if cm.bloom_filter_offset is not None:
        bits.append("bloom=yes")
    return ("  " + "  ".join(bits)) if bits else ""


def cmd_meta(args, out=None) -> int:
    """Flat schema with repetition + R/D levels (``readfile.go:75-104``),
    per-chunk statistics decoded to logical values, and page-index /
    bloom presence flags; ``--strict`` additionally runs the metadata
    validator (``format/validate.py``) and exits nonzero on error
    findings."""
    out = out or sys.stdout
    rc = 0
    with FileReader(args.file) as r:
        _print_flat(out, r.schema.root, 0)
        print(file=out)
        meta = r.metadata()
        print(f"rows: {meta.num_rows}  row groups: "
              f"{len(meta.row_groups)}  created by: {meta.created_by}",
              file=out)
        for i, rg in enumerate(meta.row_groups):
            print(f"row group {i}: {rg.num_rows} rows, "
                  f"{rg.total_byte_size} bytes", file=out)
            for cc in rg.columns:
                cm = cc.meta_data
                print(f"  {'.'.join(cm.path_in_schema)}: "
                      f"{cm.type.name} {cm.codec.name} "
                      f"values={cm.num_values} "
                      f"compressed={cm.total_compressed_size} "
                      f"uncompressed={cm.total_uncompressed_size}"
                      + _chunk_extras(r, cc),
                      file=out)
        if getattr(args, "strict", False):
            rc = _report_findings(r, args.file, out)
    return rc


def _report_findings(r, path: str, out) -> int:
    """Run strict metadata validation on an open reader; print findings;
    return 1 when any is an error."""
    from ..format.validate import validate_metadata

    findings = validate_metadata(r.metadata(), os.path.getsize(path))
    for fd in findings:
        print(f"  {fd}", file=out)
    errors = sum(1 for fd in findings if fd.is_error)
    if errors:
        print(f"metadata: {errors} error finding(s), "
              f"{len(findings) - errors} warning(s)", file=out)
        return 1
    print("metadata: strict validation passed"
          + (f" ({len(findings)} warning(s))" if findings else ""),
          file=out)
    return 0


def _print_flat(out, node, lvl: int) -> None:
    dot = "." * lvl
    for child in node.children:
        rep = child.repetition_type.name if child.repetition_type is not None else "?"
        if child.is_leaf:
            print(f"{dot}{child.name}:\t\t{rep} {child.type.name} "
                  f"R:{child.max_rep_level} D:{child.max_def_level}",
                  file=out)
        else:
            print(f"{dot}{child.name}:\t\t{rep} F:{len(child.children)}",
                  file=out)
            _print_flat(out, child, lvl + 1)


def cmd_schema(args, out=None) -> int:
    out = out or sys.stdout
    with FileReader(args.file) as r:
        print(r.get_schema_definition(), file=out)
    return 0


def cmd_rowcount(args, out=None) -> int:
    out = out or sys.stdout
    with FileReader(args.file) as r:
        print(f"Total RowCount: {r.num_rows}", file=out)
    return 0


def cmd_verify(args, out=None) -> int:
    """Decode every row group on BOTH paths (CPU oracle and device
    kernels) and compare bit-exactly — the file doctor for the decode
    backend.  No reference analogue (the reference has one path)."""
    import time

    import numpy as np

    out = out or sys.stdout
    from ..cpu.plain import ByteArrayColumn
    from ..kernels.device import read_row_group_device

    rc = 0
    with FileReader(args.file) as r:
        # metadata first: a footer that fails strict validation makes
        # the decode comparison below meaningless (and possibly a crash)
        if _report_findings(r, args.file, out):
            print("verify: METADATA INVALID", file=out)
            return 1
        for rg in range(r.row_group_count()):
            t0 = time.perf_counter()
            cpu = r.read_row_group_arrays(rg)
            t1 = time.perf_counter()
            # read_row_group_device drains all buffers in one batched
            # sync before returning — no per-column sync needed
            dev = read_row_group_device(r, rg)
            t2 = time.perf_counter()
            n = sum(len(cd.def_levels) for cd in cpu.values())
            bad = []
            for path, cd in cpu.items():
                vals, rep, dl = dev[path].to_numpy()
                ok = (np.array_equal(rep, cd.rep_levels)
                      and np.array_equal(dl, cd.def_levels))
                if ok:
                    if isinstance(cd.values, ByteArrayColumn):
                        ok = vals == cd.values
                    else:
                        # bitwise, not value, comparison: NaN payloads
                        # must compare equal for a bit-exact check
                        a = np.ascontiguousarray(np.asarray(vals))
                        b = np.ascontiguousarray(np.asarray(cd.values))
                        ok = (a.shape == b.shape and a.dtype == b.dtype
                              and a.tobytes() == b.tobytes())
                if not ok:
                    bad.append(path)
            status = "OK" if not bad else f"MISMATCH: {', '.join(bad)}"
            print(f"row group {rg}: {n:,} values  "
                  f"cpu {(t1 - t0) * 1e3:.1f}ms  "
                  f"device {(t2 - t1) * 1e3:.1f}ms  {status}", file=out)
            if bad:
                rc = 1
    print("verify: " + ("all row groups bit-exact" if rc == 0
                        else "MISMATCHES FOUND"), file=out)
    return rc


def profile_report(events, stats=None) -> dict:
    """Machine-readable profile digest: everything the human table
    prints, as one JSON-safe dict.  ``stats`` optional — a profile
    rebuilt from a saved ``pages.jsonl`` has events only, so the
    counter/histogram sections derive from the events where they can
    and are omitted where they can't."""
    from .. import obs

    rep: dict = {
        "columns": obs.column_table(events),
        "transport_counts": events.transport_counts(),
        "event_summary": obs.event_summary(events),
        "plan_cache_spans": obs.plan_cache_span_counts(events),
        "fault_tallies": obs.fault_counts_by_column(events),
        "faults": len(events.faults),
    }
    # phase walls: exact from the collector when present, else the
    # span sums (the same numbers, minus wall_s which only a live
    # collector can know)
    if stats is not None:
        d = stats.as_dict()
        rep["counters"] = d
        rep["histograms"] = stats.histograms_dict()
        rep["phases"] = {k: d[k] for k in
                         ("plan_s", "transfer_s", "dispatch_s",
                          "wall_s")}
        # attribution view: per-stage cpu-seconds derived by the SAME
        # function the scan ledgers/doctor use (obs.stage_seconds), so
        # profile, top and doctor agree on numbers by construction
        rep["attribution"] = {
            "cpu_s": obs.stage_seconds(d),
            "bytes": {"read": d.get("bytes_read", 0),
                      "staged": d.get("bytes_staged", 0),
                      "moved": d.get("gather_bytes_moved", 0)},
        }
    else:
        phases: dict = {}
        for s in events.spans:
            key = {"plan": "plan_s", "transfer": "transfer_s",
                   "dispatch": "dispatch_s"}.get(s.get("name"))
            if key:
                phases[key] = round(phases.get(key, 0.0) + s["dur"], 6)
        rep["phases"] = phases
    return rep


def cmd_profile(args, out=None) -> int:
    """Decode with full telemetry on and print the per-column
    transport/timing table: which wire transport each column's pages
    took, WHY the gate chose it (the competition's wire-size numbers),
    and where the host wall went.  Optional dumps: ``--events`` writes
    the raw per-page JSON-lines log, ``--perfetto`` a Chrome-trace
    JSON of the host phase spans (load at ui.perfetto.dev),
    ``--json`` the whole digest as machine-readable JSON.
    ``--from-events pages.jsonl`` analyzes a SAVED event log instead
    of re-running the decode (no file argument needed).  No reference
    analogue — this is the observability face of the device decode
    backend."""
    out = out or sys.stdout
    from .. import obs
    from ..stats import collect_stats

    from ..obs import trace as _trace

    saved = getattr(args, "from_events", None)
    troot = None
    if saved:
        if args.file:
            raise ValueError(
                "profile --from-events analyzes the saved log; drop "
                "the file argument (or drop --from-events to re-run)")
        log = obs.load_jsonl(saved)
        st = None
    elif not args.file:
        raise ValueError("profile needs a parquet file "
                         "(or --from-events pages.jsonl)")
    else:
        mirrors = [m for m in (getattr(args, "mirror", None) or []) if m]
        filt = None
        if getattr(args, "filter", None):
            from ..filter import parse_filter

            filt = parse_filter(args.filter)
        with FileReader(args.file, mirrors=mirrors) as r:
            # with TPQ_TRACE on, the profiled decode runs as its own
            # trace so the TRACE section below can walk its span tree
            with _trace.trace_scope("profile") as troot, \
                    collect_stats(events=True) as st:
                if filt is not None:
                    # predicate-pushdown profile: the pruning section
                    # below shows what the filter statically skipped
                    from ..kernels.device import read_row_group_device

                    for rg in range(r.row_group_count()):
                        if getattr(args, "cpu", False):
                            r.read_row_group_arrays(rg, filter=filt)
                        else:
                            cols = read_row_group_device(
                                r, rg, filter=filt)
                            for c in cols.values():
                                c.block_until_ready()
                elif getattr(args, "cpu", False):
                    for rg in range(r.row_group_count()):
                        r.read_row_group_arrays(rg)
                else:
                    from ..kernels.device import read_row_groups_device

                    for _rg, cols in read_row_groups_device(r):
                        for c in cols.values():
                            c.block_until_ready()
        log = st.events
    # causal-trace section (TPQ_TRACE=1): the doctor's critical-path
    # walk over the profiled decode — per-stage share + bound verdict
    trace_diag = None
    if troot is not None and _trace._active is not None:
        from ..obs.attribution import diagnose

        trace_diag = diagnose(
            _trace._active.snapshot(troot["trace"]))
    if getattr(args, "json", False):
        import json as _json

        rep = profile_report(log, st)
        rep["file"] = args.file or saved
        if trace_diag is not None:
            rep["trace"] = {k: trace_diag[k] for k in
                            ("verdict", "bound_stage", "verdict_share",
                             "stage_share", "stages_s", "coverage",
                             "wall_s", "units")}
        _json.dump(rep, out, sort_keys=True, default=str)
        print(file=out)
        # stdout is now a JSON document consumers parse whole: the
        # dump status lines must not corrupt it
        status = sys.stderr
    else:
        _print_profile(log, st, out, trace_diag)
        status = out
    if getattr(args, "events", None):
        log.write_jsonl(args.events)
        print(f"wrote page events to {args.events}", file=status)
    if getattr(args, "perfetto", None):
        obs.write_chrome_trace(log, args.perfetto)
        print(f"wrote Perfetto trace to {args.perfetto}", file=status)
    return 0


def _print_profile(log, st, out, trace_diag=None) -> None:
    """The human rendering of a profile (live collector or saved
    events)."""
    from .. import obs

    print(obs.format_column_table(obs.column_table(log)), file=out)
    if st is not None:
        d = st.as_dict()
        print(f"\nphases: plan {d['plan_s']:.3f}s  "
              f"transfer {d['transfer_s']:.3f}s  "
              f"dispatch {d['dispatch_s']:.3f}s  "
              f"wall {d['wall_s']:.3f}s",
              file=out)
        # attribution section: the stage cpu_s view shared with the
        # scan ledgers / doctor (obs.stage_seconds)
        cpu = obs.stage_seconds(d)
        if any(cpu.values()):
            print("attribution: "
                  + "  ".join(f"{k} {v:.3f}s"
                              for k, v in cpu.items() if v)
                  + f"  read {d['bytes_read']:,}B", file=out)
        if trace_diag is not None and trace_diag.get("bound_stage"):
            print(f"trace: {trace_diag['verdict']} — "
                  f"{trace_diag['bound_stage']} is "
                  f"{100 * trace_diag['verdict_share']:.1f}% of the "
                  f"traced wall "
                  f"(coverage {100 * trace_diag['coverage']:.1f}%)",
                  file=out)
        # footer-keyed plan cache effectiveness (TPQ_PLAN_CACHE_MB):
        # per-span verdicts localize WHICH column plans hit
        cache_spans = obs.plan_cache_span_counts(log)
        if d["plan_cache_hits"] or d["plan_cache_misses"]:
            print(f"plan cache: {d['plan_cache_hits']} hits  "
                  f"{d['plan_cache_misses']} misses  "
                  f"{d['plan_cache_evictions']} evictions  "
                  f"(spans: {cache_spans})", file=out)
        # gather/output-placement section: what the reshard to the
        # consumer placement actually shipped (shard/scan.py gathers)
        if d["gather_bytes_moved"] or d["gather_reshard_s"]:
            print(f"gather: {d['gather_bytes_moved']:,}B to consumers  "
                  f"{d['gather_bytes_replicated']:,}B replication  "
                  f"reshard {d['gather_reshard_s']:.3f}s", file=out)
        # write-pipeline section (io/pages.py native page assembly):
        # how many pages this scope wrote, how many took the native
        # one-pass path, and where the write wall went
        if d["pages_written"]:
            print(f"write: {d['pages_written']} pages "
                  f"({d['pages_assembled_native']} native)  "
                  f"encode {d['write_encode_s']:.3f}s  "
                  f"compress {d['write_compress_s']:.3f}s  "
                  f"assemble {d['write_assemble_s']:.3f}s", file=out)
        # remote-source section (io/source.py byte-range backends):
        # round trips actually issued vs saved by coalescing, and the
        # tiered range cache's hit economics (io/rangecache.py)
        if (d["remote_ranges_fetched"] or d["cache_hits_mem"]
                or d["cache_hits_disk"] or d["cache_misses_mem"]
                or d["cache_misses_disk"]):
            print(f"remote: {d['remote_ranges_fetched']} ranges fetched "
                  f"({d['ranges_coalesced']} coalesced away)  "
                  f"{d['remote_bytes']:,}B  "
                  f"{d['remote_retry']} retries", file=out)
            print(f"range cache: mem {d['cache_hits_mem']}h/"
                  f"{d['cache_misses_mem']}m/"
                  f"{d['cache_evictions_mem']}e  "
                  f"disk {d['cache_hits_disk']}h/"
                  f"{d['cache_misses_disk']}m/"
                  f"{d['cache_evictions_disk']}e", file=out)
        # predicate-pushdown section: what the filter statically skipped
        # and what the exact pass kept (tpuparquet/filter.py)
        if (d["row_groups_pruned"] or d["pages_pruned"]
                or d["rows_pruned"] or d["bloom_hits"]
                or d["filter_rows_in"]):
            sel = (f"  selectivity {d['selectivity']:.4f}"
                   if d.get("selectivity") is not None else "")
            print(f"pruning: {d['row_groups_pruned']} row groups  "
                  f"{d['pages_pruned']} pages  "
                  f"{d['rows_pruned']:,} rows skipped  "
                  f"{d['bloom_hits']} bloom hits  "
                  f"exact {d['filter_rows_out']:,}/"
                  f"{d['filter_rows_in']:,} rows{sel}", file=out)
        print(st.summary(), file=out)
    # per-column time-domain tallies: which column's reads hedged /
    # expired (global counts alone can't localize a degraded replica)
    tally = obs.fault_counts_by_column(log)
    if tally:
        print("\nhedges/deadlines per column:", file=out)
        for col in sorted(tally):
            row = tally[col]
            print(f"  {col}: "
                  f"hedges issued {row.get('hedge_issued', 0)}, "
                  f"won {row.get('hedge_won', 0)}, "
                  f"deadlines exceeded "
                  f"{row.get('deadline_exceeded', 0)}", file=out)
    h = None if st is None else st.hists.get("page_comp_bytes")
    if h is not None and h.n:
        print(f"compressed page size: p50 < {h.quantile(0.5):,}B, "
              f"p99 < {h.quantile(0.99):,}B over {h.n} pages", file=out)


def _fmt_eta(s) -> str:
    if s is None:
        return "-"
    s = int(s)
    if s >= 3600:
        return f"{s // 3600}h{(s % 3600) // 60:02d}m"
    if s >= 60:
        return f"{s // 60}m{s % 60:02d}s"
    return f"{s}s"


def render_top_frame(frames: list[dict], width: int = 40) -> str:
    """One ``top`` screen for one or more scan status frames (a
    multi-host scan exports one file per host)."""
    lines = []
    for f in frames:
        done, total = f["units_done"], f["units_total"]
        frac = done / total if total else 1.0
        filled = int(frac * width)
        bar = "#" * filled + "-" * (width - filled)
        lines.append(
            f"{f.get('label', 'scan')} [{bar}] "
            f"{done}/{total} units ({frac * 100:.1f}%)  "
            f"state={f['state']}")
        lines.append(
            f"  rows {f['rows_done']:,} @ {f['rows_per_s']:,.0f}/s  "
            f"elapsed {f['elapsed_s']:.1f}s  "
            f"eta {_fmt_eta(f.get('eta_s'))}  "
            f"inflight {f.get('units_inflight', 0)}"
            + (f"  QUARANTINED {f['units_quarantined']}"
               if f.get("units_quarantined") else "")
            + (f"  staged {f['bytes_staged']:,}B"
               if f.get("bytes_staged") else ""))
        attr = f.get("attribution")
        if attr and attr.get("cpu_s"):
            cpu = "  ".join(f"{k} {v:.2f}s"
                            for k, v in attr["cpu_s"].items() if v)
            by = attr.get("bytes") or {}
            lines.append(
                "  cpu: " + (cpu or "-")
                + (f"  read {by['read']:,}B" if by.get("read") else "")
                + (f"  peak_arena {attr['peak_arena_bytes']:,}B"
                   if attr.get("peak_arena_bytes") else ""))
        prof = f.get("profile")
        if prof and prof.get("samples"):
            lines.append(
                "  PROFILE "
                f"{prof['samples']} samples "
                f"@ {prof.get('rate_hz') or 0:.0f}/s  "
                f"off-cpu {(prof.get('offcpu_share') or 0) * 100:.0f}%"
                f"  top {prof.get('top_frame') or '-'}")
        if f.get("_stale_s") is not None:
            lines.append(
                f"  STALE: no update for {f['_stale_s']:.0f}s "
                f"(writer pid {f.get('pid', '?')} dead or hung? "
                "the cursor, if any, is resumable)")
        for s in f.get("stragglers") or []:
            lines.append(
                f"  STRAGGLER unit {s['unit']}: "
                f"{s['elapsed_s']}s in flight "
                f"(p95 {s['p95_s']}s)")
    return "\n".join(lines)


def cmd_top(args, out=None) -> int:
    """Live view of running scans: tail the JSON status file(s) a
    ``ShardedScan``/``MultiHostScan`` exports (``progress_export=`` /
    ``TPQ_PROGRESS_EXPORT``) and render progress bars, rates, ETA and
    stragglers, refreshing until every scan leaves the running state.
    ``--once`` prints a single frame and exits (scripts/tests).  No
    reference analogue — this is the operator's window into the
    always-on telemetry layer."""
    import time as _time

    from ..obs.progress import read_progress_file

    out = out or sys.stdout
    interval = max(getattr(args, "interval", 1.0), 0.05)
    once = getattr(args, "once", False)
    while True:
        frames = []
        missing = []
        dead_files = []
        for path in args.status:
            try:
                f = read_progress_file(path)
            except (OSError, ValueError):
                missing.append(path)
                continue
            # a "running" frame whose writer went silent well past its
            # own unit cadence is flagged STALE — a SIGKILLed scan
            # never writes its "done"/"error" frame, and a frozen bar
            # with no indication would lie to the operator.  Frames
            # export at unit boundaries (start AND done), so the
            # tolerance scales with the frame's own EWMA unit wall: a
            # scan of 30s units is not "stale" 10s into a unit.
            age = _time.time() - f.get("ts", 0)
            stale_after = max(10.0, 5.0 * interval,
                              10.0 * (f.get("ewma_unit_s") or 0.0))
            if f.get("state") == "running" and age > stale_after:
                f["_stale_s"] = age
            # the harder verdict keys on the FILE's mtime, not the
            # frame's ts (a restored backup carries an old ts with a
            # fresh mtime; only the mtime says whether any writer is
            # alive): a running frame whose file hasn't been touched
            # for 2x its write interval means the writer is gone, and
            # --once must not hand a script old numbers with rc 0
            if f.get("state") == "running":
                try:
                    m_age = _time.time() - os.path.getmtime(path)
                except OSError:
                    m_age = None
                write_iv = max(f.get("ewma_unit_s") or 0.0,
                               5.0, interval)
                if m_age is not None and m_age > 2.0 * write_iv:
                    dead_files.append((path, m_age))
                    f["_stale_s"] = max(f.get("_stale_s") or 0.0,
                                        m_age)
            frames.append(f)
        if frames:
            print(render_top_frame(frames), file=out)
        for path in missing:
            print(f"(waiting for {path})", file=out)
        if once:
            if dead_files:
                for path, m_age in dead_files:
                    print(f"parquet-tool top: {path} is stale "
                          f"(not written for {m_age:.0f}s, > 2x its "
                          f"write interval) — the scan is likely "
                          f"dead; numbers above are old",
                          file=sys.stderr)
                return 1
            return 0 if frames else 1
        if frames and not missing and \
                all(f["state"] != "running" for f in frames):
            return 0
        _time.sleep(interval)
        print(file=out)


def render_watch(frames: list[dict], objectives: list[dict],
                 alerts: list[dict], now: float) -> str:
    """One ``watch`` screen: the RED view (rate / errors / duration)
    per scan label over the fast window, error-budget state per
    objective, and whatever is firing."""
    from ..obs.slo import (
        DEFAULT_FAST_WINDOW_S,
        evaluate,
        window_digest,
        window_ledger,
    )

    lines = []
    if not frames:
        return "(no frames in ring)"
    last = frames[-1]
    labels = sorted(set(last.get("ledgers") or {})
                    | set(last.get("digests") or {}))
    w = DEFAULT_FAST_WINDOW_S
    lines.append(f"RED over last {w:g}s "
                 f"({len(frames)} frames in ring)")
    for label in labels:
        if label == "deadline":
            continue  # expiry-site digests, not a scan label
        led = window_ledger(frames, label, w, now)
        attempts = led.get("row_groups", 0) \
            + led.get("units_quarantined", 0)
        errors = led.get("units_quarantined", 0) \
            + led.get("deadline_exceeded", 0)
        dig = window_digest(frames, label, "unit", w, now)
        dur = ("-" if not dig.n
               else f"p50 {dig.quantile(0.5) / 1000.0:.0f}ms / "
                    f"p99 {dig.quantile(0.99) / 1000.0:.0f}ms")
        lines.append(
            f"  {label}: rate {attempts / w:.2f} units/s  "
            f"errors {errors}"
            + (f" ({errors / attempts * 100.0:.2f}%)" if attempts
               else "")
            + f"  duration {dur}")
    # one-line PROFILE section when a sampler is armed: the sampler
    # mirrors its counters/gauges into the registry, so they ride the
    # same ring frames the RED view reads — stable under --once
    if (last.get("counters") or {}).get("profile_samples"):
        c = last["counters"]
        g = last.get("gauges") or {}
        share = g.get("profile_offcpu_share")
        if share is None and c["profile_samples"]:
            share = (c.get("profile_samples_offcpu", 0)
                     / c["profile_samples"])
        lines.append(
            f"  PROFILE {c['profile_samples']} samples "
            f"@ {g.get('profile_rate_hz') or 0:.0f}/s  "
            f"off-cpu {(share or 0) * 100:.0f}%  "
            f"top {g.get('profile_top_frame') or '-'}"
            + (f"  drops {c['profile_drops']}"
               if c.get("profile_drops") else ""))
    if objectives:
        report = evaluate(frames, objectives, now)
        for row in report["objectives"]:
            b = row.get("budget")
            if b is None:
                continue
            burn = row.get("burn") or {}
            f_burn = burn.get("fast")
            lines.append(
                f"  budget {row['label']}: "
                f"{b['remaining_fraction'] * 100.0:.1f}% remaining"
                + (f"  burn {f_burn:.1f}x" if f_burn is not None
                   else ""))
    for a in alerts:
        label = f" label={a['label']}" if a.get("label") else ""
        lines.append(f"  FIRING [{a.get('severity', 'page')}] "
                     f"{a['name']}{label}: {a.get('msg', '')}")
    return "\n".join(lines)


def cmd_watch(args, out=None) -> int:
    """Live RED view over a time-series ring (``TPQ_TIMESERIES_DIR``):
    per-label rate/errors/duration, error-budget remaining per SLO
    objective, and firing alerts — the one screen an operator tails
    during an incident.  ``--once`` renders a single screen and exits
    (nonzero when the ring is empty).  No reference analogue — the
    serve-regime face of the longitudinal telemetry layer."""
    import time as _time

    from ..obs.alerts import AlertEngine, default_rules
    from ..obs.slo import load_objectives
    from ..obs.timeseries import load_ring

    out = out or sys.stdout
    interval = max(getattr(args, "interval", 2.0), 0.05)
    objectives = load_objectives(args.slo or None)
    engine = AlertEngine(default_rules(objectives), record_path="")
    while True:
        frames = load_ring(args.ring)
        now = _time.time()
        alerts = engine.evaluate(frames, now) if frames else []
        print(render_watch(frames, objectives, alerts, now), file=out)
        if getattr(args, "once", False):
            return 0 if frames else 1
        _time.sleep(interval)
        print(file=out)


def cmd_slo(args, out=None) -> int:
    """Evaluate SLO objectives over a saved time-series ring and
    print the report (error budgets, burn rates, latency verdicts).
    ``report`` is the only action today.  Exits nonzero when any
    objective is in violation — scriptable as a release gate."""
    import json as _json

    from ..obs.slo import evaluate, format_report, load_objectives
    from ..obs.timeseries import load_ring

    out = out or sys.stdout
    if args.action != "report":
        raise ValueError(f"unknown slo action {args.action!r} "
                         f"(expected 'report')")
    objectives = load_objectives(args.slo or None)
    if not objectives:
        raise ValueError("no SLO objectives: pass --slo FILE or set "
                         "TPQ_SLO_FILE")
    frames = load_ring(args.ring)
    report = evaluate(frames, objectives)
    if getattr(args, "json", False):
        print(_json.dumps(report, sort_keys=True), file=out)
    else:
        print(format_report(report), file=out)
    violated = any(
        (row.get("latency") or {}).get("ok") is False
        or (row.get("errors") or {}).get("ok") is False
        for row in report["objectives"])
    return 2 if violated else 0


def cmd_serve(args, out=None) -> int:
    """Run a :class:`tpuparquet.serve.ScanServer` from a JSON spec:
    register tenants, submit their jobs, serve until everything is
    done or a SIGTERM drains (in-flight scans checkpoint durable
    cursors; rerunning the same spec on a successor resumes them).

    Spec shape::

        {"state_dir": "...",            # optional (TPQ_SERVE_STATE_DIR)
         "workers": 4,                  # optional global budget
         "status_export": "st.json",    # optional, for `tenants`
         "tenants": [{"label": "a", "weight": 2.0,
                      "byte_budget": null, "latency_target_ms": 500,
                      "error_rate_target": 0.01}],
         "jobs": [{"tenant": "a", "job_id": "j0",
                   "sources": ["a.parquet"], "columns": ["x", "y"],
                   "unit_deadline": 0.2, "scan_deadline": null,
                   "checkpoint_every": 1, "sink_dir": "out/a"}]}

    A job with ``sink_dir`` persists each decoded unit as a keyed
    atomic ``unit<k>.npz`` (tmp + rename — the crash-safe consumer
    discipline), so drained-and-resumed runs converge to a
    duplicate-free, bit-exact union.

    Admission shedding is not failure: a job rejected with a
    retryable :class:`~tpuparquet.errors.AdmissionRejected` (queue
    full, byte budget, drain race) is held back and resubmitted after
    its ``retry_after_s`` hint — the rejection contract guarantees
    the request was never queued, so the retry is duplicate-free.

    Exit 0 = every job done; 3 = drained with work remaining (resume
    on a successor); 1 = a job failed."""
    import json as _json
    import time as _time

    from ..errors import AdmissionRejected
    from ..serve import ScanServer

    out = out or sys.stdout
    with open(args.spec) as f:
        spec = _json.load(f)
    arbiter = None
    if spec.get("workers"):
        from ..serve import ResourceArbiter

        arbiter = ResourceArbiter(total_workers=int(spec["workers"]))
    server = ScanServer(arbiter=arbiter,
                        state_dir=spec.get("state_dir"))

    def _submit(j):
        sink = (_npz_sink(j["sink_dir"])
                if j.get("sink_dir") else None)
        return server.submit(
            j["tenant"], j["sources"], *j.get("columns", []),
            job_id=j.get("job_id"),
            unit_deadline=j.get("unit_deadline"),
            scan_deadline=j.get("scan_deadline"),
            checkpoint_every=j.get("checkpoint_every"),
            sink=sink)

    try:
        for t in spec.get("tenants", []):
            server.add_tenant(
                t["label"], weight=float(t.get("weight", 1.0)),
                byte_budget=t.get("byte_budget"),
                latency_target_ms=t.get("latency_target_ms"),
                error_rate_target=t.get("error_rate_target"))
        jobs = []
        pending = []  # [due_monotonic, jobspec] — shed, to resubmit
        for j in spec.get("jobs", []):
            try:
                jobs.append(_submit(j))
            except AdmissionRejected as e:
                hint = e.retry_after_s or 0.5
                print(f"{j['tenant']}/{j.get('job_id') or '?'}: shed "
                      f"({e.reason}), retrying in {hint:g}s", file=out)
                pending.append([_time.monotonic() + hint, j])
        server.install_signal_handlers()
        status_path = spec.get("status_export")
        while pending or not all(job.terminal for job in jobs):
            if server.draining:
                server.drain()
                break
            now = _time.monotonic()
            held = []
            for due, j in pending:
                if now < due:
                    held.append([due, j])
                    continue
                try:
                    jobs.append(_submit(j))
                except AdmissionRejected as e:
                    held.append([now + (e.retry_after_s or 0.5), j])
            pending = held
            if status_path:
                server.write_status(status_path)
            _time.sleep(0.2)
        if status_path:
            server.write_status(status_path)
        for job in jobs:
            job.wait(5.0)
            print(f"{job.tenant}/{job.job_id}: {job.state} "
                  f"({job.units_done}/{job.units_total} units)",
                  file=out)
        for _due, j in pending:
            print(f"{j['tenant']}/{j.get('job_id') or '?'}: shed "
                  f"(never admitted)", file=out)
        if any(job.state == "failed" for job in jobs):
            return 1
        if pending or any(job.state != "done" for job in jobs):
            return 3  # drained: resume on a successor
        return 0
    finally:
        server.shutdown(drain=False)


def _npz_sink(sink_dir: str):
    """Keyed atomic per-unit writer (``tests/checkpoint_child.py``
    discipline): re-decoded units after a crash/drain overwrite with
    identical bytes instead of duplicating."""
    os.makedirs(sink_dir, exist_ok=True)

    def sink(k: int, unit_out: dict) -> None:
        import numpy as np

        arrays = {}
        for name in sorted(unit_out):
            for i, arr in enumerate(unit_out[name].to_numpy()):
                if arr is not None:
                    arrays[f"{name}.{i}"] = np.asarray(arr)
        tmp = os.path.join(sink_dir, f".unit{k}.npz.tmp")
        with open(tmp, "wb") as f:
            np.savez(f, **arrays)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, os.path.join(sink_dir, f"unit{k}.npz"))

    return sink


def cmd_tenants(args, out=None) -> int:
    """Render a running server's tenant table from its
    ``status_export`` JSON (see ``serve``): per-tenant share of the
    global worker budget, queue depth, accounting, and the adaptive
    feedback (bound verdict, error-budget burn, unit p99) the
    arbiter last rebalanced on."""
    import json as _json

    out = out or sys.stdout
    with open(args.status) as f:
        st = _json.load(f)
    if getattr(args, "json", False):
        print(_json.dumps(st, sort_keys=True), file=out)
        return 0
    drain = " DRAINING" if st.get("draining") else ""
    print(f"workers={st.get('total_workers')}{drain} "
          f"state_dir={st.get('state_dir') or '-'}", file=out)
    hdr = (f"{'tenant':<16} {'share':>5} {'queued':>6} {'run':>3} "
           f"{'done':>5} {'rej':>4} {'bound':<12} {'burn':>6} "
           f"{'p99_ms':>8}")
    print(hdr, file=out)
    for label in sorted(st.get("tenants", {})):
        row = st["tenants"][label]
        burn = row.get("burn")
        p99 = row.get("p99_ms")
        burn_s = "-" if burn is None else f"{burn:.2f}"
        p99_s = "-" if p99 is None else f"{p99:.1f}"
        print(f"{label:<16} {row.get('share', 0):>5} "
              f"{len(row.get('queued') or []):>6} "
              f"{1 if row.get('running') else 0:>3} "
              f"{row.get('jobs_done', 0):>5} "
              f"{row.get('rejected', 0):>4} "
              f"{row.get('bound') or '-':<12} "
              f"{burn_s:>6} {p99_s:>8}", file=out)
    return 0


def cmd_flame(args, out=None) -> int:
    """Render a sampling-profile export (the native ``tpq-profile``
    envelope a scan wrote via ``TPQ_PROFILE_EXPORT``): top-N frames
    by self samples with total/share columns, filterable by
    ``--label``/``--stage``.  ``--diff A B`` prints the weighted
    per-frame share delta between two profiles — each normalizes to
    its own sample total, so runs of different length compare and the
    biggest movers localize a regression.  ``--collapsed`` dumps
    collapsed-stack lines for flamegraph.pl / speedscope; ``--json``
    emits machine-readable rows."""
    import json as _json

    from ..obs.profiler import (
        collapsed_lines,
        diff_states,
        load_profile_file,
        top_frames,
    )

    out = out or sys.stdout
    n = getattr(args, "n", 15)
    if getattr(args, "diff", None):
        a = load_profile_file(args.diff[0])
        b = load_profile_file(args.diff[1])
        rows = diff_states(a, b, n=n)
        if getattr(args, "json", False):
            print(_json.dumps(rows, sort_keys=True), file=out)
            return 0
        print(f"share delta {args.diff[0]} -> {args.diff[1]} "
              f"(+ grew in B)", file=out)
        for r in rows:
            print(f"  {r['delta'] * 100:+7.2f}%  "
                  f"{r['share_a'] * 100:6.2f}% -> "
                  f"{r['share_b'] * 100:6.2f}%  {r['frame']}",
                  file=out)
        return 0
    if not getattr(args, "profile_file", None):
        raise ValueError("flame: pass a PROFILE file or --diff A B")
    state = load_profile_file(args.profile_file)
    if getattr(args, "collapsed", False):
        for line in collapsed_lines(state):
            print(line, file=out)
        return 0
    label = getattr(args, "label", None)
    stage = getattr(args, "stage", None)
    rows = top_frames(state, label=label, stage=stage, n=n)
    if getattr(args, "json", False):
        print(_json.dumps(
            {"counters": state.get("counters") or {},
             "period_s": state.get("period_s"),
             "top": rows}, sort_keys=True), file=out)
        return 0
    c = state.get("counters") or {}
    total = c.get("profile_samples", 0)
    off = c.get("profile_samples_offcpu", 0)
    sel = "".join(
        [f" label={label}" if label else "",
         f" stage={stage}" if stage else ""])
    print(f"{total} samples ({off} off-cpu, "
          f"{c.get('profile_drops', 0)} drops) "
          f"@ {state.get('hz') or 0:g} Hz{sel}", file=out)
    if not rows:
        print("  (no samples match)", file=out)
        return 1
    print(f"  {'self':>7} {'total':>7} {'share':>7}  frame", file=out)
    for r in rows:
        print(f"  {r['self_s']:7.3f} {r['total_s']:7.3f} "
              f"{r['share'] * 100:6.2f}%  {r['frame']}", file=out)
    return 0


def _render_doctor_profile(state: dict, d: dict) -> str:
    """The ``doctor --profile`` tail: name the top frames inside the
    diagnosis's dominant stage and cross-check sampled seconds
    against the span-derived stage walls."""
    from ..obs.profiler import profile_consistency, top_frames

    bound = d.get("bound_stage")
    rows = top_frames(state, label=d.get("label"), stage=bound, n=5)
    if not rows:
        # multi-label exports may not key this trace's label; the
        # stage-filtered whole-profile view still answers "what ran"
        rows = top_frames(state, stage=bound, n=5)
    lines = [f"  profile: top frames in {bound} "
             f"({state.get('hz') or 0:g} Hz sampler)"]
    if not rows:
        lines.append("    (no samples in this stage)")
    for r in rows:
        lines.append(f"    {r['self_s']:8.3f}s self  "
                     f"{r['share'] * 100:5.1f}%  {r['frame']}")
    for w in profile_consistency(state, d.get("stages_s") or {}):
        lines.append(f"  WARNING {w}")
    return "\n".join(lines)


def cmd_doctor(args, out=None) -> int:
    """Walk a causal scan trace and say what bounds the wall.

    Input: a trace export — the file a scan wrote via
    ``TPQ_TRACE_EXPORT`` (the native ``tpq-trace`` envelope, read
    live mid-scan or after), a bare span-list JSON, or a
    ``*.perfetto.json`` round trip.  For each trace in the file:
    the per-unit stage decomposition (exclusive-time critical-path
    walk — stage buckets sum to the unit wall exactly), the
    scan-level bound verdict (read-bound / plan-bound /
    decompress-bound / decode-bound / gather-bound) with its share,
    straggler units ranked against the rolling p95 of unit walls
    (``deadline.LatencyTracker``, the same detector ``top`` uses
    live), and the plan-pool concurrency note that turns the
    PLAN_SCALE thread-degradation mystery into one line.  Attribution
    ledgers embedded in the export print alongside; a ledger whose
    counters show remote-source or range-cache traffic gets a REMOTE
    line (origin fetches vs cache hits, retry/hedge tallies) and an
    ORIGIN-BOUND callout when the read-bound verdict is dominated by
    origin round trips rather than local disk.  ``--json`` emits the
    full machine-readable reports.  No reference analogue — this is
    the diagnosis face of the causal tracing layer."""
    import json as _json

    out = out or sys.stdout
    from ..obs.attribution import diagnose, format_diagnosis
    from ..obs.export import load_trace_file

    spans, ledgers = load_trace_file(args.trace)
    by_trace: dict = {}
    for s in spans:
        by_trace.setdefault(s.get("trace"), []).append(s)
    sel = getattr(args, "trace_id", None)
    if sel is not None:
        if sel not in by_trace:
            raise ValueError(
                f"trace id {sel!r} not in {args.trace!r}; present: "
                f"{sorted(k for k in by_trace if k is not None)}")
        by_trace = {sel: by_trace[sel]}
    if not by_trace:
        print("(no spans — was TPQ_TRACE=1 set on the scan?)",
              file=out)
        return 1
    reports = [diagnose(ss) for _tid, ss in
               sorted(by_trace.items(),
                      key=lambda kv: min(s["t0"] for s in kv[1]))]
    pstate = None
    if getattr(args, "profile", None):
        from ..obs.profiler import load_profile_file

        pstate = load_profile_file(args.profile)
    if getattr(args, "json", False):
        from ..obs.attribution import remote_report

        verdict0 = reports[0].get("verdict") if reports else None
        doc = {"reports": reports, "ledgers": ledgers,
               "remote": {
                   label: remote_report(
                       (led or {}).get("counters") or {},
                       verdict=verdict0)
                   for label, led in sorted((ledgers or {}).items())}}
        if pstate is not None:
            from ..obs.profiler import profile_consistency, top_frames

            doc["profile"] = [
                {"trace": d.get("trace"),
                 "bound_stage": d.get("bound_stage"),
                 "top_frames": top_frames(
                     pstate, stage=d.get("bound_stage"), n=5),
                 "warnings": profile_consistency(
                     pstate, d.get("stages_s") or {})}
                for d in reports]
        _json.dump(doc, out, sort_keys=True, default=str)
        print(file=out)
        return 0
    for i, d in enumerate(reports):
        if i:
            print(file=out)
        print(format_diagnosis(d, ledgers if i == 0 else None),
              file=out)
        if pstate is not None:
            print(_render_doctor_profile(pstate, d), file=out)
    return 0


def cmd_rescue(args, out=None) -> int:
    """Rewrite a torn/corrupt file's recoverable row groups into a
    clean file: open through the salvage path (footer recovery /
    valid-prefix trim, ``format/recover.py``), byte-copy each
    recovered chunk (no re-encode — the output is bit-identical to
    the surviving data), and write a fresh validated footer.  The
    output reopens under ``strict_metadata=True`` and under pyarrow.
    No reference analogue — parquet-mr ships footer *recovery* but not
    a rescue rewriter."""
    from ..format.metadata import CompressionCodec

    out = out or sys.stdout
    like = getattr(args, "like", None) or None
    # a recovery tool must never destroy its own input: opening the
    # output 'wb' would truncate the source if they are the same file
    if os.path.exists(args.output) and \
            os.path.samefile(args.file, args.output):
        raise ValueError(
            "rescue output must differ from the input file")
    created: list = []
    try:
        rc = _rescue(args, like, out, CompressionCodec, created)
    except BaseException:
        # don't leave a truncated, footer-less output behind — but only
        # remove a file THIS invocation created: a failure before the
        # output was opened must not delete a pre-existing file
        if created:
            try:
                os.unlink(args.output)
            except OSError:
                pass
        raise
    return rc


def _rescue(args, like, out, CompressionCodec, created: list) -> int:
    from ..format.footer import MAGIC, write_footer
    from ..format.metadata import (
        ColumnChunk,
        ColumnMetaData,
        FileMetaData,
        KeyValue,
        RowGroup,
    )
    from ..format.recover import SALVAGED_KEY, encode_salvage_hint
    from ..format.schema import Schema

    with FileReader(args.file, salvage=True, salvage_like=like) as r, \
            open(args.file, "rb") as src, \
            open(args.output, "wb") as dst:
        created.append(True)  # output now exists (and was truncated)
        meta = r.metadata()
        dst.write(MAGIC)
        schema = Schema.from_elements(meta.schema)
        codec = None
        new_rgs = []
        for i, rg in enumerate(meta.row_groups):
            cols = []
            for cc in rg.columns:
                cm = cc.meta_data
                if codec is None:
                    codec = cm.codec
                    # rescued files are themselves salvageable — but a
                    # codec enum from a future writer (strict treats it
                    # as a warning; rescue byte-copies without decoding)
                    # cannot be named in the hint, so skip the frame
                    if isinstance(cm.codec, CompressionCodec):
                        dst.write(encode_salvage_hint(
                            schema, cm.codec,
                            created_by="parquet-tool rescue"))
                start = cm.data_page_offset
                if cm.dictionary_page_offset is not None:
                    start = min(start, cm.dictionary_page_offset)
                src.seek(start)
                blob = src.read(cm.total_compressed_size)
                if len(blob) != cm.total_compressed_size:
                    raise ValueError(
                        f"short read copying chunk at {start}")
                pos = dst.tell()
                dst.write(blob)
                shift = pos - start
                ncm = ColumnMetaData(**{
                    name: getattr(cm, name) for name in cm._NAMES})
                ncm.data_page_offset = cm.data_page_offset + shift
                if cm.dictionary_page_offset is not None:
                    ncm.dictionary_page_offset = \
                        cm.dictionary_page_offset + shift
                # page/bloom indexes are NOT copied: drop their offsets
                ncm.index_page_offset = None
                ncm.bloom_filter_offset = None
                ncm.bloom_filter_length = None
                cols.append(ColumnChunk(file_offset=pos, meta_data=ncm))
            new_rgs.append(RowGroup(
                columns=cols,
                total_byte_size=rg.total_byte_size,
                total_compressed_size=rg.total_compressed_size,
                num_rows=rg.num_rows,
                sorting_columns=rg.sorting_columns,
                ordinal=i,
            ))
        kv = [x for x in (meta.key_value_metadata or [])
              if x.key != SALVAGED_KEY]
        kv.append(KeyValue(key="tpq.rescued.from",
                           value=os.path.basename(args.file)))
        write_footer(dst, FileMetaData(
            version=meta.version if meta.version is not None else 1,
            schema=meta.schema,
            num_rows=sum(rg.num_rows for rg in new_rgs),
            row_groups=new_rgs,
            key_value_metadata=kv,
            created_by=meta.created_by,
        ))
        if r.salvaged:
            rep = r.salvage_report or {}
            print(f"salvaged {len(new_rgs)} row group(s) "
                  f"({sum(rg.num_rows for rg in new_rgs)} rows) from "
                  f"{args.file}; stop: {rep.get('stop_reason', '?')} "
                  f"at offset {rep.get('stop_offset', '?')}", file=out)
        else:
            print(f"{args.file} was already clean; copied "
                  f"{len(new_rgs)} row group(s)", file=out)
    # the point of rescue: the output must stand on its own
    with FileReader(args.output, strict_metadata=True) as check:
        print(f"wrote {args.output}: {check.num_rows} rows in "
              f"{check.row_group_count()} row group(s), "
              "strict validation passed", file=out)
    return 0


def cmd_analyze(args, out=None) -> int:
    """Run the tpq-analyze invariant passes (``tools/analyze``) and
    report findings — the same gate ``python -m tools.analyze`` and
    ci.sh stage 9 run, surfaced as a tool subcommand with ``--json``
    output consistent with ``profile --json``.  Exits nonzero when
    the gate fails.  Source-tree only: the analyzer ships with the
    repo, not the installed wheel."""
    import json as _json

    out = out or sys.stdout
    root = args.root or os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    if not os.path.isdir(os.path.join(root, "tools", "analyze")):
        raise ValueError(
            f"no tools/analyze under {root!r} — parquet-tool analyze "
            f"runs from a source checkout (pass --root)")
    if root not in sys.path:
        sys.path.insert(0, root)
    from tools.analyze import (Allowlist, DEFAULT_ALLOWLIST, RepoTree,
                               run_analysis)

    if getattr(args, "allowlist_audit", False):
        tree = RepoTree.from_disk(root)
        report = Allowlist.load(DEFAULT_ALLOWLIST).audit(tree)
        if getattr(args, "json", False):
            report["root"] = root
            _json.dump(report, out, sort_keys=True)
            print(file=out)
        else:
            for e in report["entries"]:
                mark = (" MISSING-TARGET"
                        if not e["target_exists"] else "")
                print(f"{e['added']}  {e['pass']:20s} {e['file']}::"
                      f"{e['key']}{mark}", file=out)
            print(f"allowlist-audit: {len(report['entries'])} "
                  f"entr(y/ies), {len(report['missing_target'])} "
                  f"with missing target file — "
                  + ("PASSED" if report["ok"] else "FAILED"),
                  file=out)
        return 0 if report["ok"] else 1

    res = run_analysis(root=root, passes=args.passes or None)
    if getattr(args, "json", False):
        res["root"] = root
        _json.dump(res, out, sort_keys=True)
        print(file=out)
    else:
        for f in res["findings"]:
            print(f"{f['file']}:{f['line']}: [{f['pass']}/"
                  f"{f['code']}] {f['key']}: {f['why']}", file=out)
        for e in res["stale_allowlist"]:
            print(f"allowlist: stale entry ({e['pass']}, {e['file']}, "
                  f"{e['key']}) suppresses nothing — drop it",
                  file=out)
        print(f"analyze: {len(res['findings'])} finding(s), "
              f"{len(res['suppressed'])} allowlisted — gate "
              + ("PASSED" if res["ok"] else "FAILED"), file=out)
    return 0 if res["ok"] else 1


def cmd_split(args, out=None) -> int:
    """Re-shard into multiple files of ~--file-size each
    (``split.go:33-122``)."""
    out = out or sys.stdout
    target = human_to_bytes(args.file_size)
    rg_size = human_to_bytes(args.row_group_size)
    codec = _CODECS[args.compression.lower()]
    folder = args.target_folder or os.path.dirname(os.path.abspath(args.file))
    base = os.path.splitext(os.path.basename(args.file))[0]

    with FileReader(args.file) as r:
        schema_def = r.get_schema_definition()
        part = 0
        w = None
        f = None
        current = None

        def open_part():
            nonlocal part, w, f, current
            current = os.path.join(folder, f"{base}_{part:03d}.parquet")
            f = open(current, "wb")
            try:
                w = FileWriter(f, schema_def, codec=codec,
                               max_row_group_size=rg_size or None,
                               created_by="parquet-tool split")
            except BaseException:
                f.close()
                f = None
                raise
            print(f"writing {current}", file=out)
            part += 1

        def close_part():
            nonlocal w, f
            w.close()
            f.close()
            w = f = None

        try:
            # Parts open lazily so a threshold hit on the last row
            # doesn't leave a trailing empty file.
            for row in r.rows():
                if w is None:
                    open_part()
                w.add_data(row)
                if (w.current_file_size()
                        + w.current_row_group_size() >= target):
                    close_part()
            if w is not None:
                close_part()
            elif part == 0:  # empty input: emit one valid (empty) file
                open_part()
                close_part()
        except BaseException:
            # Don't leave a footer-less, truncated part behind.
            if f is not None:
                f.close()
                try:
                    os.unlink(current)
                except OSError:
                    pass
            raise
    return 0


def cmd_compact(args, out=None) -> int:
    """Merge a partitioned dataset's small files into rolling
    target-sized ones through the atomic manifest commit."""
    out = out or sys.stdout
    from ..dataset import compact_dataset

    try:
        rep = compact_dataset(
            args.dataset,
            sort_by=args.sort_by,
            target_mb=args.target_mb,
            manifest_keep=args.keep,
        )
    except (FileNotFoundError, ValueError, NotImplementedError) as e:
        print(f"compact: {e}", file=out)
        return 1
    print(f"compacted {args.dataset}: {rep['files_before']} -> "
          f"{rep['files_after']} files, {rep['rows']} rows, "
          f"manifest v{rep['version']}", file=out)
    for rel in rep["gc"]:
        print(f"  gc {rel}", file=out)
    return 0


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="parquet-tool", description="Tool to manage parquet files")
    sub = p.add_subparsers(dest="cmd", required=True)

    c = sub.add_parser("cat", help="print the parquet file content")
    c.add_argument("--trace", action="store_true",
                   help="print decode statistics to stderr")
    c.add_argument("file")
    c.set_defaults(fn=cmd_cat)

    h = sub.add_parser("head", help="print the first N records")
    h.add_argument("--trace", action="store_true",
                   help="print decode statistics to stderr")
    h.add_argument("-n", type=int, default=5,
                   help="number of records to print")
    h.add_argument("file")
    h.set_defaults(fn=cmd_head)

    m = sub.add_parser("meta", help="print the file metadata")
    m.add_argument("--strict", action="store_true",
                   help="run strict metadata validation and exit "
                        "nonzero on error findings")
    m.add_argument("file")
    m.set_defaults(fn=cmd_meta)

    s = sub.add_parser("schema", help="print the file schema definition")
    s.add_argument("file")
    s.set_defaults(fn=cmd_schema)

    v = sub.add_parser(
        "verify",
        help="decode on the CPU and device paths and compare bit-exactly")
    v.add_argument("file")
    v.set_defaults(fn=cmd_verify)

    pf = sub.add_parser(
        "profile",
        help="decode with telemetry on; print the per-column "
             "transport/timing table")
    pf.add_argument("--cpu", action="store_true",
                    help="profile the CPU oracle path instead of the "
                         "device path")
    pf.add_argument("--mirror", action="append", metavar="FILE",
                    help="replica copy to hedge chunk reads against "
                         "(repeatable); hedge/deadline counters appear "
                         "in the summary and per-column table")
    pf.add_argument("--events", metavar="FILE", default="",
                    help="write the per-page event log as JSON-lines")
    pf.add_argument("--perfetto", metavar="FILE", default="",
                    help="write a Chrome-trace JSON of the host phase "
                         "spans (ui.perfetto.dev)")
    pf.add_argument("--json", action="store_true",
                    help="emit the whole profile digest as "
                         "machine-readable JSON instead of the table")
    pf.add_argument("--filter", default="",
                    help="predicate to push down, e.g. "
                         "\"x > 100 & s in ('a','b')\" — the profile "
                         "then shows the pruning counters")
    pf.add_argument("--from-events", metavar="FILE", default="",
                    dest="from_events",
                    help="analyze a SAVED pages.jsonl event log "
                         "instead of re-running the decode")
    pf.add_argument("file", nargs="?", default="")
    pf.set_defaults(fn=cmd_profile)

    tp = sub.add_parser(
        "top",
        help="live view of a running scan's exported progress "
             "status file(s)")
    tp.add_argument("--once", action="store_true",
                    help="print one frame and exit")
    tp.add_argument("--interval", type=float, default=1.0,
                    help="refresh interval in seconds")
    tp.add_argument("status", nargs="+",
                    help="progress status file(s) a scan exports via "
                         "progress_export= / TPQ_PROGRESS_EXPORT")
    tp.set_defaults(fn=cmd_top)

    w = sub.add_parser(
        "watch",
        help="live RED view (rate/errors/duration, budgets, alerts) "
             "over a time-series ring directory")
    w.add_argument("--once", action="store_true",
                   help="render one screen and exit")
    w.add_argument("--interval", type=float, default=2.0,
                   help="refresh interval in seconds")
    w.add_argument("--slo", default="",
                   help="SLO objectives JSON (default: TPQ_SLO_FILE)")
    w.add_argument("ring",
                   help="time-series ring directory a process records "
                        "via TPQ_TIMESERIES_DIR")
    w.set_defaults(fn=cmd_watch)

    so = sub.add_parser(
        "slo",
        help="evaluate SLO objectives over a saved time-series ring "
             "(error budgets, burn rates); nonzero exit on violation")
    so.add_argument("action", choices=["report"],
                    help="what to do (report: print the evaluation)")
    so.add_argument("--slo", default="",
                    help="SLO objectives JSON (default: TPQ_SLO_FILE)")
    so.add_argument("--json", action="store_true",
                    help="emit the machine-readable report")
    so.add_argument("ring",
                    help="time-series ring directory to evaluate")
    so.set_defaults(fn=cmd_slo)

    sv = sub.add_parser(
        "serve",
        help="run the multi-tenant scan server from a JSON spec "
             "(tenants + jobs); SIGTERM drains with durable cursors "
             "so rerunning the spec resumes")
    sv.add_argument("spec",
                    help="server spec JSON (tenants, jobs, state_dir, "
                         "status_export — see the command docstring)")
    sv.set_defaults(fn=cmd_serve)

    tn = sub.add_parser(
        "tenants",
        help="render a running scan server's per-tenant status table "
             "from its status_export JSON")
    tn.add_argument("status",
                    help="status JSON the server exports "
                         "(spec key status_export)")
    tn.add_argument("--json", action="store_true",
                    help="emit the raw status document")
    tn.set_defaults(fn=cmd_tenants)

    dr = sub.add_parser(
        "doctor",
        help="walk a causal scan trace (TPQ_TRACE_EXPORT file) and "
             "name the bounding stage, stragglers and attribution")
    dr.add_argument("--json", action="store_true",
                    help="emit the full diagnosis reports as "
                         "machine-readable JSON")
    dr.add_argument("--trace-id", default=None, dest="trace_id",
                    help="analyze only this trace id (default: every "
                         "trace in the file)")
    dr.add_argument("--profile", default=None,
                    help="sampling-profile export (TPQ_PROFILE_EXPORT "
                         "native envelope): name the top frames inside "
                         "the dominant stage and cross-check sampled "
                         "seconds against the span stage walls")
    dr.add_argument("trace",
                    help="trace export: the tpq-trace envelope a scan "
                         "writes via TPQ_TRACE_EXPORT, a bare span "
                         "list, or a *.perfetto.json round trip")
    dr.set_defaults(fn=cmd_doctor)

    fl = sub.add_parser(
        "flame",
        help="render a sampling-profile export (TPQ_PROFILE_EXPORT): "
             "top frames by self time, or --diff two profiles")
    fl.add_argument("--diff", nargs=2, metavar=("A", "B"),
                    default=None,
                    help="weighted per-frame share delta between two "
                         "profile exports (regression localization)")
    fl.add_argument("--label", default=None,
                    help="only samples of this scan label")
    fl.add_argument("--stage", default=None,
                    help="only samples tagged with this stage "
                         "(read/plan/decompress/transfer/dispatch/"
                         "gather/write/other)")
    fl.add_argument("-n", type=int, default=15,
                    help="rows to print (default 15)")
    fl.add_argument("--collapsed", action="store_true",
                    help="dump collapsed-stack lines "
                         "(flamegraph.pl / speedscope input)")
    fl.add_argument("--json", action="store_true",
                    help="emit machine-readable rows")
    fl.add_argument("profile_file", nargs="?", default=None,
                    metavar="profile",
                    help="a native tpq-profile export (not needed "
                         "with --diff)")
    fl.set_defaults(fn=cmd_flame)

    rc = sub.add_parser("rowcount", help="print the total row count")
    rc.add_argument("file")
    rc.set_defaults(fn=cmd_rowcount)

    rs = sub.add_parser(
        "rescue",
        help="rewrite a torn file's recoverable row groups into a "
             "clean, strictly-valid file")
    rs.add_argument("--like", default="",
                    help="schema donor (a healthy sibling file) for "
                         "torn files without an embedded salvage hint")
    rs.add_argument("file")
    rs.add_argument("output")
    rs.set_defaults(fn=cmd_rescue)

    an = sub.add_parser(
        "analyze",
        help="run the tpq-analyze static invariant passes over the "
             "source tree (tools/analyze)")
    an.add_argument("--json", action="store_true",
                    help="emit the full findings digest as "
                         "machine-readable JSON (like profile --json)")
    an.add_argument("--pass", dest="passes", action="append",
                    metavar="NAME",
                    help="run only this pass (repeatable)")
    an.add_argument("--root", default="",
                    help="repo root (default: the checkout this "
                         "module ships in)")
    an.add_argument("--allowlist-audit", action="store_true",
                    dest="allowlist_audit",
                    help="audit the allowlist instead of running the "
                         "passes: list entries by age/pass, fail on "
                         "entries whose target file no longer exists")
    an.set_defaults(fn=cmd_analyze)

    sp = sub.add_parser("split", help="split into multiple parquet files")
    sp.add_argument("-s", "--file-size", default="100MB",
                    help="target output file size")
    sp.add_argument("-t", "--target-folder", default="",
                    help="target folder (default: source folder)")
    sp.add_argument("-r", "--row-group-size", default="128MB",
                    help="uncompressed row group size")
    sp.add_argument("-c", "--compression", default="snappy",
                    choices=sorted(_CODECS), help="compression codec")
    sp.add_argument("file")
    sp.set_defaults(fn=cmd_split)

    cp = sub.add_parser(
        "compact",
        help="merge a partitioned dataset's small files atomically")
    cp.add_argument("--sort-by", default=None,
                    help="re-sort each partition by this data column "
                         "so page min/max stats become tight")
    cp.add_argument("--target-mb", type=int, default=None,
                    help="rolling output file target in MiB "
                         "(default: TPQ_DATASET_TARGET_MB or 64)")
    cp.add_argument("--keep", type=int, default=None,
                    help="manifest snapshots to retain "
                         "(default: TPQ_DATASET_MANIFEST_KEEP or 3)")
    cp.add_argument("dataset", help="dataset root directory or URI")
    cp.set_defaults(fn=cmd_compact)
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    try:
        return args.fn(args)
    except (OSError, ValueError, KeyError) as e:
        print(f"parquet-tool: {e}", file=sys.stderr)
        return 1


if __name__ == "__main__":
    sys.exit(main())
