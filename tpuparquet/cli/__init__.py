"""Command-line tools (≙ ``cmd/parquet-tool`` and ``cmd/csv2parquet``)."""

from ..format.metadata import CompressionCodec

#: Shared --compression flag values for both CLIs.
CODECS = {
    "snappy": CompressionCodec.SNAPPY,
    "gzip": CompressionCodec.GZIP,
    "zstd": CompressionCodec.ZSTD,
    "lz4_raw": CompressionCodec.LZ4_RAW,
    "none": CompressionCodec.UNCOMPRESSED,
}
