"""Deterministic fault injection + retry/backoff + quarantine report.

The fault-tolerance layer has three moving parts, all here:

* **Injection harness** — named fault sites instrumented into the read
  and dispatch paths fire *deterministically* (site + occurrence
  counting, no randomness) under an :func:`inject_faults` scope.  Used
  by ``tests/test_faults.py`` to prove each fault class takes its
  designed path; zero-cost in production (one module-global ``is
  None`` check per site).
* **Retry with bounded exponential backoff** —
  :func:`retry_transient` for transient I/O,
  :func:`backoff_delays` shared with the device-dispatch retry in
  ``kernels/device.py``.
* **Quarantine report** — :class:`QuarantineReport` accumulates the
  exact coordinates (file / row group / column / page) and error class
  of every unit a ``ShardedScan(on_error="quarantine")`` isolated.

Fault sites (``site`` argument to :meth:`FaultInjector.inject`):

====================================  =====================================
site                                  instrumented where / supported kinds
====================================  =====================================
``io.reader.chunk_read``              ``FileReader.iter_selected_chunks``
                                      — ``oserror``, ``transient``,
                                      ``corrupt``, ``truncate``
``io.chunk.page_payload``             CPU page loop (``io/chunk.py``)
                                      — ``corrupt``, ``truncate``
``io.pages.page_decode``              ``decode_data_page_v1/v2``
                                      — ``corrupt``, ``truncate``
``io.pages.page_write``               native page assembly
                                      (``_write_page_native``; firing
                                      drops the page to the pure
                                      writer, bytes identical)
                                      — ``transient``
``kernels.device.page_payload``       device plan page loop
                                      — ``corrupt``, ``truncate``
``kernels.device.page_dispatch``      device plan, per data page
                                      — ``dispatch``
``kernels.device.unit_dispatch``      ``_finish_row_group`` (per unit)
                                      — ``dispatch``
``format.footer.tail``                8-byte length+magic tail read
                                      (``format/footer.py``)
                                      — ``corrupt``, ``truncate``
``format.footer.blob``                footer thrift blob read
                                      — ``corrupt``, ``truncate``
``io.reader.open``                    ``FileReader.__init__`` (per open)
                                      — ``oserror``, ``transient``
``io.chunk.hang``                     chunk byte read (the hedgeable
                                      primary/mirror read callable in
                                      ``io/reader.py``; ctx carries
                                      ``file`` so a rule can hang ONE
                                      replica) — ``hang``
``kernels.device.hang``               device dispatch
                                      (``_finish_row_group``) — ``hang``
``format.pageindex``                  page-index / bloom-filter blob
                                      reads (``io/reader.py``) —
                                      ``oserror``, ``transient``,
                                      ``corrupt``, ``truncate``
``io.remote.open``                    byte-range source open
                                      (``io/source.py``) — ``oserror``,
                                      ``transient``
``io.remote.throttle``                per range request, before the
                                      read (the HTTP-429 slot;
                                      ``io/source.py``) — ``transient``
``io.remote.range``                   range request payload
                                      (``io/source.py``; short/truncated
                                      responses are detected and raised
                                      as transient, never returned) —
                                      ``oserror``, ``transient``,
                                      ``corrupt``, ``truncate``
``dataset.manifest.write``            dataset manifest / commit-journal
                                      publication (``dataset/
                                      manifest.py``, before the tmp
                                      write) — ``oserror``,
                                      ``transient``
``dataset.manifest.load``             manifest / journal blob read
                                      (``dataset/manifest.py``) —
                                      ``oserror``, ``transient``,
                                      ``corrupt``, ``truncate``
``dataset.file.promote``              staged data-file rename into its
                                      partition directory
                                      (``dataset/writer.py``) —
                                      ``oserror``, ``transient``
====================================  =====================================

Kinds: ``oserror`` raises ``OSError(EIO)``; ``transient`` raises
:class:`~tpuparquet.errors.TransientIOError`; ``dispatch`` raises
:class:`~tpuparquet.errors.DeviceDispatchError`; ``corrupt`` XORs one
byte of the stream (``offset=``, ``xor=``); ``truncate`` drops the
tail (``keep=``); ``hang`` BLOCKS the calling thread (``seconds=``,
default 30) — but releases early the moment its :func:`inject_faults`
scope exits, so abandoned hedge/deadline worker threads never outlive
a test.  Each rule fires on the first ``times`` matching calls after
skipping ``after`` — "fail twice then succeed" is ``times=2``, which a
retry loop must survive.

The active injector is a **process-global** (not thread-local): the
pipelined reader plans on worker threads and faults must reach them.
Each firing increments ``DecodeStats.faults_injected`` on the firing
thread's collector and appends to :attr:`FaultInjector.log`.
"""

from __future__ import annotations

import contextlib
import errno as _errno
import os
import sys
import threading
import time
import zlib

from .errors import DeviceDispatchError, TransientIOError

__all__ = [
    "FaultInjector",
    "inject_faults",
    "fault_point",
    "filter_bytes",
    "retry_transient",
    "backoff_delays",
    "is_transient",
    "QuarantineReport",
    "SITES",
    "ChaosSchedule",
    "chaos_scope",
]

#: The fault-site registry: every instrumented site name and the
#: fault kinds it supports.  Sites match rules by STRING EQUALITY, so
#: a drifted name doesn't error — it just never fires; this registry
#: is the single source of truth that the instrumentation hooks, the
#: docstring table above, and the matrices in ``tests/test_faults.py``
#: are all checked against (``tools/analyze`` fault-site pass).  Add
#: the row HERE first when instrumenting a new site.
SITES: dict[str, tuple] = {
    "io.reader.open": ("oserror", "transient"),
    "io.reader.chunk_read": ("oserror", "transient",
                             "corrupt", "truncate"),
    "io.chunk.page_payload": ("corrupt", "truncate"),
    "io.chunk.hang": ("hang",),
    "io.pages.page_decode": ("corrupt", "truncate"),
    "io.pages.page_write": ("transient",),
    "kernels.device.page_payload": ("corrupt", "truncate"),
    "kernels.device.page_dispatch": ("dispatch",),
    "kernels.device.unit_dispatch": ("dispatch",),
    "kernels.device.hang": ("hang",),
    "format.footer.tail": ("corrupt", "truncate"),
    "format.footer.blob": ("corrupt", "truncate"),
    "format.pageindex": ("oserror", "transient",
                         "corrupt", "truncate"),
    "io.remote.open": ("oserror", "transient"),
    "io.remote.throttle": ("transient",),
    "io.remote.range": ("oserror", "transient",
                        "corrupt", "truncate"),
    "dataset.manifest.write": ("oserror", "transient"),
    "dataset.manifest.load": ("oserror", "transient",
                              "corrupt", "truncate"),
    "dataset.file.promote": ("oserror", "transient"),
}

_active: "FaultInjector | None" = None


class _Rule:
    __slots__ = ("site", "kind", "times", "after", "kw", "match",
                 "seen", "fired")

    def __init__(self, site, kind, times, after, match, kw):
        self.site = site
        self.kind = kind
        self.times = times
        self.after = after
        self.match = match or {}
        self.kw = kw
        self.seen = 0    # matching calls observed
        self.fired = 0   # faults actually delivered


class FaultInjector:
    """Deterministic fault plan: rules added with :meth:`inject`, a
    :attr:`log` of ``(site, kind, ctx)`` for every fault delivered."""

    def __init__(self):
        self.rules: list[_Rule] = []
        self.log: list[dict] = []
        self._lock = threading.Lock()

    def inject(self, site: str, kind: str, *, times: int = 1,
               after: int = 0, match: dict | None = None, **kw) -> _Rule:
        """Arm a rule: at ``site``, deliver ``kind`` on the first
        ``times`` matching calls after skipping ``after``.  ``match``
        restricts by context equality (e.g. ``match={"column": "a"}``).
        Extra ``kw`` parameterize the kind (``offset``/``xor`` for
        ``corrupt``, ``keep`` for ``truncate``)."""
        r = _Rule(site, kind, times, after, match, kw)
        with self._lock:
            self.rules.append(r)
        return r

    # -- firing (called from the instrumented sites) ---------------------

    def _next_rule(self, site: str, ctx: dict,
                   kinds: tuple) -> "_Rule | None":
        with self._lock:
            for r in self.rules:
                if r.site != site or r.kind not in kinds:
                    continue
                if any(ctx.get(k) != v for k, v in r.match.items()):
                    continue
                r.seen += 1
                if r.seen <= r.after or r.fired >= r.times:
                    continue
                r.fired += 1
                self.log.append(
                    {"site": site, "kind": r.kind, **ctx})
                return r
        return None

    def _record_stats(self, site: str, kind: str, ctx: dict) -> None:
        from .obs.recorder import flight
        from .stats import current_stats

        # flight recorder sees every delivered fault, collector or not
        flight(f"fault:{kind}", site=site, **ctx)
        st = current_stats()
        if st is not None:
            st.faults_injected += 1
            if st.events is not None:
                st.events.fault(site=site, kind=kind, **ctx)

    def fire_raise(self, site: str, ctx: dict) -> None:
        # byte-kinds (corrupt/truncate) never match here: a site name
        # can host BOTH hooks (fault_point for failures, filter_bytes
        # for the data it read), and a byte rule must wait for the
        # byte hook rather than be consumed by this one
        r = self._next_rule(site, ctx, ("oserror", "transient",
                                        "dispatch", "hang"))
        if r is None:
            return
        self._record_stats(site, r.kind, ctx)
        if r.kind == "hang":
            # simulate a read/dispatch that never returns: block until
            # the cap, or until this injector's scope exits (so
            # abandoned hedge/deadline workers release with the test)
            seconds = r.kw.get("seconds", 30.0)
            t0 = time.monotonic()
            while _active is self and \
                    time.monotonic() - t0 < seconds:
                time.sleep(0.005)
            return
        if r.kind == "oserror":
            raise OSError(_errno.EIO,
                          f"injected I/O error at {site}")
        if r.kind == "transient":
            raise TransientIOError(
                f"injected transient fault at {site}", **_coords(ctx))
        raise DeviceDispatchError(
            f"injected device dispatch failure at {site}",
            **_coords(ctx))

    def fire_bytes(self, site: str, data, ctx: dict):
        r = self._next_rule(site, ctx, ("corrupt", "truncate"))
        if r is None:
            return data
        self._record_stats(site, r.kind, ctx)
        if r.kind == "truncate":
            keep = r.kw.get("keep", len(data) // 2)
            return bytes(data[:keep])
        buf = bytearray(data)
        if not buf:
            return data
        off = r.kw.get("offset", len(buf) // 2) % len(buf)
        buf[off] ^= r.kw.get("xor", 0xFF) or 0xFF
        return bytes(buf)


def _coords(ctx: dict) -> dict:
    return {k: ctx[k] for k in ("file", "row_group", "column", "page")
            if k in ctx}


@contextlib.contextmanager
def inject_faults():
    """Scope with a fresh active :class:`FaultInjector` (yields it).
    Process-global and not reentrant — one scope at a time; intended
    for tests and chaos drills."""
    global _active
    if _active is not None:
        raise RuntimeError("inject_faults scopes do not nest")
    inj = FaultInjector()
    _active = inj
    try:
        yield inj
    finally:
        _active = None


def fault_point(site: str, **ctx) -> None:
    """Instrumentation hook: may raise an injected fault.  No-op (one
    global ``is None`` check) when no injector is active."""
    ch = _chaos
    if ch is not None:
        ch.perturb(site)
    inj = _active
    if inj is not None:
        inj.fire_raise(site, ctx)


def filter_bytes(site: str, data, **ctx):
    """Instrumentation hook for byte streams: returns ``data`` (the
    common case, zero-copy) or an injected corruption/truncation of
    it; may also raise for read-failure kinds."""
    ch = _chaos
    if ch is not None:
        ch.perturb(site)
    inj = _active
    if inj is not None:
        return inj.fire_bytes(site, data, ctx)
    return data


# ----------------------------------------------------------------------
# Schedule chaos: deterministic interleaving perturbation
# ----------------------------------------------------------------------
#
# The fault sites double as NAMED YIELD POINTS: under a
# :func:`chaos_scope`, every ``fault_point``/``filter_bytes`` call may
# sleep a few microseconds or force a GIL release, and the interpreter
# switch interval is pinned to a seed-derived aggressive value.  The
# perturbation PLAN is a pure function of (seed, site, occurrence
# ordinal) — no global ``random`` state, no wall-clock input — so a
# seed names one chaos schedule.  What chaos runs assert is OUTPUT
# invariance (byte-identical scan/write results, exact counter
# conservation) across seeds, not schedule identity: the OS may still
# interleave threads differently, and that is the point.

_chaos: "ChaosSchedule | None" = None


class ChaosSchedule:
    """A seeded interleaving-perturbation plan over the fault-site
    registry (zero-cost when inactive: one module-global ``is None``
    check per site, same discipline as the injector)."""

    #: per-site occurrence draw: (do nothing, yield GIL, short sleep)
    _SLEEP_MAX_S = 200e-6

    def __init__(self, seed: int):
        self.seed = int(seed)
        # benign-race counters: a lost increment only shifts which
        # perturbation a thread draws, never the data path — keeping
        # this lock-free means chaos adds no lock-order edges
        self._counts: dict[str, int] = {}
        self.perturbations = 0
        import random

        rng = random.Random(self.seed)
        #: seed-derived interpreter switch interval, aggressive enough
        #: to force switches inside critical regions (default is 5ms)
        self.switch_interval = 10 ** rng.uniform(-6.0, -4.0)

    def _draw(self, site: str, n: int) -> float:
        key = f"{self.seed}:{site}:{n}".encode()
        return (zlib.crc32(key) & 0xFFFFFFFF) / 0x100000000

    def perturb(self, site: str) -> None:
        n = self._counts.get(site, 0)
        self._counts[site] = n + 1
        u = self._draw(site, n)
        if u < 0.4:
            return
        self.perturbations += 1
        if u < 0.7:
            time.sleep(0)          # force a GIL release / reschedule
        else:
            # a short sleep moves this thread to the back of the line
            time.sleep((u - 0.7) / 0.3 * self._SLEEP_MAX_S)


@contextlib.contextmanager
def chaos_scope(seed: int | None = None):
    """Scope with an active :class:`ChaosSchedule` (yields it):
    perturbs thread interleavings at every registered fault site and
    pins a seed-derived ``sys.setswitchinterval``.  ``seed`` falls
    back to ``TPQ_CHAOS_SEED`` (default 0).  Process-global and not
    reentrant, like :func:`inject_faults` (the two compose: chaos
    perturbs first, then the injector fires)."""
    global _chaos
    if _chaos is not None:
        raise RuntimeError("chaos_scope scopes do not nest")
    if seed is None:
        seed = _env_int("TPQ_CHAOS_SEED", 0)
    sched = ChaosSchedule(seed)
    prev = sys.getswitchinterval()
    sys.setswitchinterval(sched.switch_interval)
    _chaos = sched
    try:
        yield sched
    finally:
        _chaos = None
        sys.setswitchinterval(prev)


# ----------------------------------------------------------------------
# Retry with bounded exponential backoff
# ----------------------------------------------------------------------

_TRANSIENT_ERRNOS = frozenset(
    getattr(_errno, name)
    for name in ("EIO", "EAGAIN", "EBUSY", "EINTR", "ETIMEDOUT",
                 "ENETRESET", "ECONNRESET", "ESTALE")
    if hasattr(_errno, name)
)

_PERMANENT_OS = (FileNotFoundError, PermissionError, IsADirectoryError,
                 NotADirectoryError)


def is_transient(exc: BaseException) -> bool:
    """Is this failure worth retrying?  TransientIOError always;
    plain OSError only for retryable errnos — a FileNotFoundError
    will not heal with backoff."""
    if isinstance(exc, TransientIOError):
        return True
    if isinstance(exc, _PERMANENT_OS):
        return False
    if isinstance(exc, (TimeoutError, InterruptedError, ConnectionError)):
        return True
    if isinstance(exc, OSError):
        return exc.errno in _TRANSIENT_ERRNOS
    return False


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, ""))
    except ValueError:
        return default


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, ""))
    except ValueError:
        return default


def backoff_delays(retries: int | None = None,
                   base: float | None = None,
                   cap: float | None = None,
                   jitter: float | None = None,
                   seed: int | None = None) -> list[float]:
    """The bounded exponential schedule: ``[base*2^0, base*2^1, ...]``
    clamped to ``cap``, one entry per retry.  Knobs (env):
    ``TPQ_IO_RETRIES`` (default 3), ``TPQ_RETRY_BASE_S`` (0.01),
    ``TPQ_RETRY_MAX_S`` (0.5).

    ``jitter`` spreads each delay multiplicatively by up to ±that
    fraction (decorrelates retry storms across a fleet; env
    ``TPQ_RETRY_JITTER``, default 0.0 = the exact schedule).  The
    jitter stream is drawn from a LOCAL PRNG, never global ``random``
    state, seeded by ``seed`` (else ``TPQ_RETRY_SEED``, else a
    per-process derivation from the pid — distinct hosts/processes
    get distinct schedules, which is what breaks the herd).  With
    ``seed``/``TPQ_RETRY_SEED`` pinned the schedule is fully
    deterministic, so retry-timing assertions are reproducible rather
    than flaky."""
    if retries is None:
        retries = _env_int("TPQ_IO_RETRIES", 3)
    if base is None:
        base = _env_float("TPQ_RETRY_BASE_S", 0.01)
    if cap is None:
        cap = _env_float("TPQ_RETRY_MAX_S", 0.5)
    if jitter is None:
        jitter = _env_float("TPQ_RETRY_JITTER", 0.0)
    delays = [min(base * (2 ** i), cap) for i in range(max(retries, 0))]
    if jitter:
        import random

        if seed is None:
            # per-process default: decorrelate across the fleet while
            # staying stable within one process; pin TPQ_RETRY_SEED
            # (or pass seed=) for cross-run determinism
            seed = _env_int("TPQ_RETRY_SEED", os.getpid() ^ 0x7E9)
        rng = random.Random(seed)
        delays = [max(d * (1.0 + jitter * (2.0 * rng.random() - 1.0)),
                      0.0)
                  for d in delays]
    return delays


def retry_transient(fn, *, retries: int | None = None,
                    base: float | None = None, cap: float | None = None,
                    sleep=time.sleep, counter: str = "io_retries"):
    """Call ``fn()``; on a transient failure (:func:`is_transient`)
    retry up to ``retries`` times with bounded exponential backoff.
    Permanent errors and the final exhausted attempt propagate
    unchanged.  Each retry increments ``DecodeStats.<counter>`` on the
    active collector.

    A transient error carrying a ``retry_after_s`` attribute (an
    HTTP 429/503 with a ``Retry-After`` header, mapped by
    :class:`~tpuparquet.io.source.HttpByteRangeSource`) stretches
    that retry's sleep to the origin's hint — bounded by the backoff
    cap, so a hostile header can never stall a scan — and never
    shortens it below the scheduled delay."""
    from .stats import current_stats

    if cap is None:
        cap = _env_float("TPQ_RETRY_MAX_S", 0.5)
    delays = backoff_delays(retries, base, cap)
    for delay in delays:
        try:
            return fn()
        except Exception as e:
            if not is_transient(e):
                raise
            hint = getattr(e, "retry_after_s", None)
            if hint is not None:
                delay = max(delay, min(float(hint), cap))
            st = current_stats()
            if st is not None:
                setattr(st, counter, getattr(st, counter) + 1)
            sleep(delay)
    return fn()


# ----------------------------------------------------------------------
# Quarantine report
# ----------------------------------------------------------------------

class QuarantineReport:
    """Where the bad units went: one entry per quarantined scan unit,
    carrying exact coordinates and the error class.  JSON-serializable
    (:meth:`as_dicts` / :meth:`from_dicts`) so it rides scan cursors
    and the cross-host all-gather."""

    def __init__(self, entries: list[dict] | None = None):
        self.entries: list[dict] = list(entries or [])

    def add(self, *, unit: int, file, row_group: int,
            error: BaseException) -> dict:
        entry = {
            "unit": unit,
            "file": file,
            "row_group": row_group,
            "error": type(error).__name__,
            "message": str(error),
        }
        return self._finish(entry, error)

    def add_file(self, *, file, error: BaseException, **extra) -> dict:
        """A FILE-granularity entry: the whole file was rejected at
        open/validate time (torn footer, strict-metadata reject), or a
        salvaged file's unreadable remainder.  ``unit``/``row_group``
        are None — no unit ever existed for the lost data."""
        entry = {
            "unit": None,
            "file": file,
            "row_group": None,
            "error": type(error).__name__,
            "message": str(error),
        }
        entry.update(extra)
        return self._finish(entry, error)

    def _finish(self, entry: dict, error: BaseException) -> dict:
        # ScanErrors pinpoint deeper: column / page / a more precise
        # file label from an inner layer
        coords = getattr(error, "coordinates", None)
        if callable(coords):
            for k, v in coords().items():
                if k == "file":
                    entry["file_detail"] = v
                elif k != "row_group":
                    entry[k] = v
        self.entries.append(entry)
        return entry

    def units(self) -> list[int]:
        """Unit ordinals of unit-level entries (file-level entries have
        no unit and are listed by :meth:`files`)."""
        return [e["unit"] for e in self.entries if e["unit"] is not None]

    def files(self) -> list:
        """Files with a file-granularity entry (open/validate reject
        or salvaged remainder)."""
        return [e["file"] for e in self.entries if e["unit"] is None]

    def __len__(self) -> int:
        return len(self.entries)

    def __bool__(self) -> bool:
        return bool(self.entries)

    def as_dicts(self) -> list[dict]:
        return [dict(e) for e in self.entries]

    @classmethod
    def from_dicts(cls, entries) -> "QuarantineReport":
        return cls([dict(e) for e in entries or []])

    def merge_from(self, other: "QuarantineReport") -> None:
        self.entries.extend(dict(e) for e in other.entries)

    # identity of an entry for resume dedup: the coordinates + error
    # class (NOT the message/extras — a re-opened bad file may phrase
    # its failure slightly differently run to run)
    _KEY_FIELDS = ("unit", "file", "row_group", "column", "page",
                   "error")

    @classmethod
    def entry_key(cls, e: dict) -> tuple:
        return tuple(e.get(k) for k in cls._KEY_FIELDS)

    def merge_unique(self, entries) -> int:
        """Append entries whose coordinate key isn't already present;
        returns how many were added.  Used on cursor resume: a resumed
        scan re-opens its sources, so a file already quarantined in
        the checkpointed cursor is rejected AGAIN at open time — the
        fresh entry must not duplicate the checkpointed one."""
        seen = {self.entry_key(e) for e in self.entries}
        added = 0
        for e in entries or []:
            k = self.entry_key(e)
            if k in seen:
                continue
            seen.add(k)
            self.entries.append(dict(e))
            added += 1
        return added

    def summary(self) -> str:
        if not self.entries:
            return "quarantine: empty"
        lines = [f"quarantine: {len(self.entries)} entr(y/ies)"]
        for e in self.entries:
            at = ", ".join(
                f"{k}={e[k]}" for k in
                ("file", "row_group", "column", "page")
                if e.get(k) is not None)
            head = f"unit {e['unit']}" if e.get("unit") is not None \
                else "file"
            lines.append(f"  {head} [{at}]: "
                         f"{e['error']}: {e['message']}")
        return "\n".join(lines)
