/* LZ4 raw block codec (Parquet's LZ4_RAW), from scratch, for the
 * tpuparquet host runtime.
 *
 * Wire format implemented from the public LZ4 block format
 * description: a stream of sequences, each a token byte (high nibble
 * literal length, low nibble match length - 4, 15 = extended with
 * 255-bytes), literal bytes, a 2-byte little-endian match offset
 * (1..65535), and match-length extension bytes.  The final sequence
 * is literals only.  Encoder end rules: the last 5 bytes are always
 * literals and no match starts within the last 12 bytes.
 *
 * The encoder mirrors snappy.c's proven shape: greedy hash-match over
 * 64 KiB blocks (match candidates never leave the current block, so
 * offsets always fit the 2-byte form and the position table stays
 * uint16/L1-resident), golang-style miss-skip acceleration, and one
 * pending literal run carried across blocks so incompressible input
 * still encodes as a single final literal sequence.  The pure-Python
 * encoder in compress.py implements the SAME algorithm step for step
 * (including the zero-initialized table's position-0 candidate
 * semantics) — the byte-parity leg in ci.sh pins that equivalence.
 *
 * API (lengths in bytes, return 0 on success, negative error codes):
 *   tpq_lz4_max_compressed_length(n)
 *   tpq_lz4_compress(in, n, out, out_cap, &produced)
 *   tpq_lz4_decompress(in, n, out, out_cap, &produced)
 */

#include <stddef.h>
#include <stdint.h>
#include <string.h>

#define TPQ_OK 0
#define TPQ_ERR_CORRUPT (-1)
#define TPQ_ERR_TOO_BIG (-2)
#define TPQ_ERR_BUFFER (-3)

#define LZ4_MIN_MATCH 4
#define LZ4_MFLIMIT 12  /* no match may start within the last 12 bytes */
#define LZ4_LASTLITERALS 5 /* the last 5 bytes are always literals */

/* ------------------------------------------------------------------ */
/* decompress                                                         */
/* ------------------------------------------------------------------ */

int tpq_lz4_decompress(const uint8_t *in, size_t n, uint8_t *out,
                       size_t out_cap, size_t *produced) {
  size_t ip = 0, op = 0;
  if (n == 0) { /* zero-byte stream only decodes to zero bytes */
    *produced = 0;
    return TPQ_OK;
  }
  for (;;) {
    if (ip >= n) return TPQ_ERR_CORRUPT; /* stream must end after the
      final literal run, not between sequences */
    uint8_t token = in[ip++];
    size_t lit = token >> 4;
    if (lit == 15) {
      uint8_t b;
      do {
        if (ip >= n) return TPQ_ERR_CORRUPT;
        b = in[ip++];
        lit += b;
        if (lit > out_cap) return TPQ_ERR_CORRUPT; /* cap runaway
          255-chains before they overflow size_t */
      } while (b == 255);
    }
    if (ip + lit > n) return TPQ_ERR_CORRUPT;
    if (op + lit > out_cap) return TPQ_ERR_BUFFER;
    memcpy(out + op, in + ip, lit);
    ip += lit;
    op += lit;
    if (ip == n) break; /* final sequence: literals only */
    if (ip + 2 > n) return TPQ_ERR_CORRUPT;
    size_t off = (size_t)in[ip] | ((size_t)in[ip + 1] << 8);
    ip += 2;
    if (off == 0 || off > op) return TPQ_ERR_CORRUPT;
    size_t mlen = (size_t)(token & 0xF);
    if (mlen == 15) {
      uint8_t b;
      do {
        if (ip >= n) return TPQ_ERR_CORRUPT;
        b = in[ip++];
        mlen += b;
        if (mlen > out_cap) return TPQ_ERR_CORRUPT;
      } while (b == 255);
    }
    mlen += LZ4_MIN_MATCH;
    if (op + mlen > out_cap) return TPQ_ERR_BUFFER;
    {
      uint8_t *dst = out + op;
      const uint8_t *src = dst - off;
      if (off >= 8) {
        if (off >= mlen) {
          memcpy(dst, src, mlen);
        } else {
          /* overlap with period >= 8: 8-byte blocks never read their
           * own output */
          size_t rem = mlen;
          while (rem >= 8) {
            memcpy(dst, src, 8);
            dst += 8;
            src += 8;
            rem -= 8;
          }
          if (rem) memcpy(dst, src, rem);
        }
      } else {
        /* short period: seed one pattern then double it */
        size_t copied = off;
        for (size_t i = 0; i < off && i < mlen; i++) dst[i] = src[i];
        if (copied < mlen) {
          while (copied * 2 <= mlen) {
            memcpy(dst + copied, dst, copied);
            copied *= 2;
          }
          memcpy(dst + copied, dst, mlen - copied);
        }
      }
    }
    op += mlen;
  }
  *produced = op;
  return TPQ_OK;
}

/* ------------------------------------------------------------------ */
/* compress                                                           */
/* ------------------------------------------------------------------ */

uint64_t tpq_lz4_max_compressed_length(uint64_t n) {
  /* one literal-only sequence: token + 255-extension bytes + payload */
  return n + n / 255 + 16;
}

#define LZ4_HASH_BITS 14
#define LZ4_HASH_SIZE (1u << LZ4_HASH_BITS)
#define LZ4_BLOCK_LOG 16
#define LZ4_BLOCK_SIZE (1u << LZ4_BLOCK_LOG)

static inline uint32_t lz4_load32(const uint8_t *p) {
  uint32_t v;
  memcpy(&v, p, 4);
  return v;
}

static inline uint32_t lz4_hash32(uint32_t v) {
  return (v * 2654435761u) >> (32 - LZ4_HASH_BITS);
}

/* token + literal-length extension + literal payload */
static size_t lz4_emit_literals(uint8_t *out, const uint8_t *data,
                                size_t lit, size_t mcode) {
  size_t i = 0;
  if (lit >= 15) {
    out[i++] = (uint8_t)((15u << 4) | mcode);
    size_t rem = lit - 15;
    while (rem >= 255) {
      out[i++] = 255;
      rem -= 255;
    }
    out[i++] = (uint8_t)rem;
  } else {
    out[i++] = (uint8_t)((lit << 4) | mcode);
  }
  memcpy(out + i, data, lit);
  return i + lit;
}

static size_t lz4_emit_match_ext(uint8_t *out, size_t mext) {
  /* extension bytes for a match length whose token nibble was 15 */
  size_t i = 0, rem = mext - 15;
  while (rem >= 255) {
    out[i++] = 255;
    rem -= 255;
  }
  out[i++] = (uint8_t)rem;
  return i;
}

int tpq_lz4_compress(const uint8_t *in, size_t n, uint8_t *out,
                     size_t out_cap, size_t *produced) {
  if (n > 0x7fffffffull) return TPQ_ERR_TOO_BIG;
  if (out_cap < tpq_lz4_max_compressed_length(n)) return TPQ_ERR_BUFFER;
  if (n == 0) { /* canonical empty block: one zero token */
    out[0] = 0;
    *produced = 1;
    return TPQ_OK;
  }
  size_t op = 0;
  uint16_t table[LZ4_HASH_SIZE];
  size_t lit_start = 0; /* ABSOLUTE: pending literals span blocks */

  for (size_t base = 0; base < n; base += LZ4_BLOCK_SIZE) {
    size_t blen = n - base < LZ4_BLOCK_SIZE ? n - base : LZ4_BLOCK_SIZE;
    const uint8_t *b = in + base;
    /* matches may neither start past blen-4 (4-byte load) nor within
     * the input's last MFLIMIT bytes (format end rule) */
    if (n < LZ4_MFLIMIT + 1 || base + LZ4_MFLIMIT > n) continue;
    size_t limit = blen >= 4 ? blen - 4 : 0;
    size_t abs_limit = n - LZ4_MFLIMIT - base; /* n >= MFLIMIT here */
    if (limit > abs_limit) limit = abs_limit;
    if (blen < 4) continue; /* tail rides the final literal flush */
    memset(table, 0, sizeof(table));
    size_t pos = 0;
    uint32_t skip = 32; /* golang-style acceleration: skip>>5 per miss */
    while (pos <= limit) {
      uint32_t key = lz4_load32(b + pos);
      uint32_t h = lz4_hash32(key);
      size_t cand = table[h];
      table[h] = (uint16_t)pos;
      if (cand < pos && lz4_load32(b + cand) == key) {
        size_t len = 4;
        /* extend to block end, but matches must stop LASTLITERALS
         * bytes before the end of the whole input */
        size_t max = blen - pos;
        size_t abs_max = (n - LZ4_LASTLITERALS) - (base + pos);
        if (max > abs_max) max = abs_max;
        while (len + 8 <= max) {
          uint64_t a, w;
          memcpy(&a, b + cand + len, 8);
          memcpy(&w, b + pos + len, 8);
          uint64_t diff = a ^ w;
          if (diff) {
            len += (size_t)(__builtin_ctzll(diff) >> 3);
            goto matched;
          }
          len += 8;
        }
        while (len < max && b[cand + len] == b[pos + len]) len++;
      matched:;
        if (len < 4) { /* end-rule clamp ate the match */
          size_t step = skip >> 5;
          pos += step;
          skip += (uint32_t)step;
          continue;
        }
        size_t lit = base + pos - lit_start;
        size_t mext = len - LZ4_MIN_MATCH;
        size_t off = pos - cand;
        op += lz4_emit_literals(out + op, in + lit_start, lit,
                                mext >= 15 ? 15 : mext);
        out[op++] = (uint8_t)off;
        out[op++] = (uint8_t)(off >> 8);
        if (mext >= 15) op += lz4_emit_match_ext(out + op, mext);
        /* seed the table inside the match so long runs keep matching */
        size_t end = pos + len;
        if (end <= limit && end >= 1) {
          size_t seed = end - 1;
          table[lz4_hash32(lz4_load32(b + seed))] = (uint16_t)seed;
        }
        pos = end;
        lit_start = base + pos;
        skip = 32;
      } else {
        size_t step = skip >> 5;
        pos += step;
        skip += (uint32_t)step;
      }
    }
    /* no per-block literal flush: the pending run carries forward */
  }
  /* final sequence: the remaining literals (>= LASTLITERALS by the
   * end rules, or the whole input when nothing matched) */
  op += lz4_emit_literals(out + op, in + lit_start, n - lit_start, 0);
  *produced = op;
  return TPQ_OK;
}
