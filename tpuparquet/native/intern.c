/* One-pass first-occurrence interning of variable-length byte values —
 * the dictionary build for BYTE_ARRAY columns (≙ the reference's
 * per-value map interning, type_dict.go getIndex, but one C pass with
 * an open-addressed table instead of a Go map).
 *
 * The vectorized numpy interner groups values by length, gathers row
 * matrices, hashes, and re-ranks — ~0.33 s for 2.5M short strings.
 * This kernel replaces all of it with one sequential pass (~FNV hash +
 * linear-probe table + memcmp verify per value), and adds the early
 * exit the numpy path cannot express: the caller bounds the distinct
 * count (MAX_DICT_ENTRIES), so a high-cardinality column aborts after
 * max_d distinct values instead of paying a full intern whose result
 * the dictionary gate then discards.
 *
 * slots:   T int32, caller-initialized to -1, T a power of two
 * firsts:  capacity max_d int64 — first-occurrence value index per id
 * indices: n int32 out
 * Returns the distinct count D (ids are first-occurrence ranks by
 * construction), or -1 table saturated (caller resizes), -2 more than
 * max_d distinct (caller rejects the dictionary), -3 corrupt offsets.
 */
#include <stdint.h>
#include <string.h>

long long tpq_intern_var(const uint8_t *data, long long data_len,
                         const int64_t *offs, long long n,
                         int32_t *slots, long long t_mask, int tbits,
                         int64_t *firsts, long long max_d,
                         int32_t *indices) {
    long long d = 0;
    for (long long i = 0; i < n; i++) {
        int64_t s0 = offs[i], e0 = offs[i + 1];
        if (s0 < 0 || e0 < s0 || e0 > data_len)
            return -3;
        int64_t len = e0 - s0;
        uint64_t h = 1469598103934665603ull + 31ull * (uint64_t)len;
        for (int64_t p = s0; p < e0; p++)
            h = (h ^ data[p]) * 1099511628211ull;
        /* Fibonacci slot: multiply, take the high bits (low bits of the
         * FNV multiply chain carry linear structure; cf. the numpy
         * interner's slot-collapse finding) */
        long long slot =
            (long long)((h * 0x9E3779B97F4A7C15ull) >> (64 - tbits));
        long long probes = 0;
        for (;;) {
            int32_t id = slots[slot];
            if (id < 0) {
                if (d >= max_d)
                    return -2;
                slots[slot] = (int32_t)d;
                firsts[d] = i;
                indices[i] = (int32_t)d;
                d++;
                break;
            }
            int64_t fs = offs[firsts[id]];
            int64_t fe = offs[firsts[id] + 1];
            if (fe - fs == len
                && memcmp(data + fs, data + s0, (size_t)len) == 0) {
                indices[i] = id;
                break;
            }
            slot = (slot + 1) & t_mask;
            if (++probes > t_mask)
                return -1;
        }
    }
    return d;
}
