/* One-pass first-occurrence interning of variable-length byte values —
 * the dictionary build for BYTE_ARRAY columns (≙ the reference's
 * per-value map interning, type_dict.go getIndex, but one C pass with
 * an open-addressed table instead of a Go map).
 *
 * The vectorized numpy interner groups values by length, gathers row
 * matrices, hashes, and re-ranks — ~0.33 s for 2.5M short strings.
 * This kernel replaces all of it with one sequential pass (~FNV hash +
 * linear-probe table + memcmp verify per value), and adds the early
 * exit the numpy path cannot express: the caller bounds the distinct
 * count (MAX_DICT_ENTRIES), so a high-cardinality column aborts after
 * max_d distinct values instead of paying a full intern whose result
 * the dictionary gate then discards.
 *
 * slots:   T int32, caller-initialized to -1, T a power of two
 * firsts:  capacity max_d int64 — first-occurrence value index per id
 * indices: n int32 out
 * Returns the distinct count D (ids are first-occurrence ranks by
 * construction), or -1 table saturated (caller resizes), -2 more than
 * max_d distinct (caller rejects the dictionary), -3 corrupt offsets.
 */
#include <stdint.h>
#include <string.h>

/* Small-range integer intern (the dictionary-friendly case: category
 * codes, quantized measures): one sequential pass assigning each value
 * its first-occurrence rank via a dense rank table over the value
 * range — replaces the multi-pass numpy formulation (widen, reversed
 * scatter, presence scan, argsort, gather) whose temporaries were a
 * first-order slice of the config-2 write wall.
 *
 * Values are taken as raw 32/64-bit words; `lo` is the column minimum
 * in the same width, and offsets are computed with wraparound
 * subtraction, which is exact for BOTH signed and unsigned columns as
 * long as every (v - lo) lies in [0, rng) — the caller guarantees that
 * by computing lo/rng from the true min/max.  rank must hold rng
 * int32 entries pre-filled with -1.  uniq_pos receives the first-
 * occurrence value index per id (ids are first-occurrence ranks by
 * construction, so no re-ranking pass exists).  Returns the distinct
 * count D, or -3 when a value falls outside [lo, lo+rng). */
long long tpq_intern_range32(const uint32_t *v, long long n, uint32_t lo,
                             long long rng, int32_t *rank,
                             int64_t *uniq_pos, int32_t *indices) {
    long long d = 0;
    for (long long i = 0; i < n; i++) {
        uint32_t off = v[i] - lo;
        if ((uint64_t)off >= (uint64_t)rng)
            return -3;
        int32_t r = rank[off];
        if (r < 0) {
            r = (int32_t)d;
            rank[off] = r;
            uniq_pos[d++] = i;
        }
        indices[i] = r;
    }
    return d;
}

long long tpq_intern_range64(const uint64_t *v, long long n, uint64_t lo,
                             long long rng, int32_t *rank,
                             int64_t *uniq_pos, int32_t *indices) {
    long long d = 0;
    for (long long i = 0; i < n; i++) {
        uint64_t off = v[i] - lo;
        if (off >= (uint64_t)rng)
            return -3;
        int32_t r = rank[off];
        if (r < 0) {
            r = (int32_t)d;
            rank[off] = r;
            uniq_pos[d++] = i;
        }
        indices[i] = r;
    }
    return d;
}

long long tpq_intern_var(const uint8_t *data, long long data_len,
                         const int64_t *offs, long long n,
                         int32_t *slots, long long t_mask, int tbits,
                         int64_t *firsts, long long max_d,
                         int32_t *indices) {
    long long d = 0;
    for (long long i = 0; i < n; i++) {
        int64_t s0 = offs[i], e0 = offs[i + 1];
        if (s0 < 0 || e0 < s0 || e0 > data_len)
            return -3;
        int64_t len = e0 - s0;
        uint64_t h = 1469598103934665603ull + 31ull * (uint64_t)len;
        for (int64_t p = s0; p < e0; p++)
            h = (h ^ data[p]) * 1099511628211ull;
        /* Fibonacci slot: multiply, take the high bits (low bits of the
         * FNV multiply chain carry linear structure; cf. the numpy
         * interner's slot-collapse finding) */
        long long slot =
            (long long)((h * 0x9E3779B97F4A7C15ull) >> (64 - tbits));
        long long probes = 0;
        for (;;) {
            int32_t id = slots[slot];
            if (id < 0) {
                if (d >= max_d)
                    return -2;
                slots[slot] = (int32_t)d;
                firsts[d] = i;
                indices[i] = (int32_t)d;
                d++;
                break;
            }
            int64_t fs = offs[firsts[id]];
            int64_t fe = offs[firsts[id] + 1];
            if (fe - fs == len
                && memcmp(data + fs, data + s0, (size_t)len) == 0) {
                indices[i] = id;
                break;
            }
            slot = (slot + 1) & t_mask;
            if (++probes > t_mask)
                return -1;
        }
    }
    return d;
}
