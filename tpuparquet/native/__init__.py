"""Native host-runtime components (C, loaded via ctypes).

The compute plane is JAX/XLA; the host runtime around it (block codecs,
byte-stream scanning) is native C where a Python loop would dominate —
the TPU-build counterpart of the reference keeping its codecs in compiled
Go.  The shared library is built from the checked-in sources with the
system compiler on first import and cached next to them; every consumer
must degrade gracefully to its pure-Python fallback when no compiler is
available (``snappy_native() is None``).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

__all__ = ["snappy_native", "NativeSnappy"]

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "snappy.c")
_SO = os.path.join(_DIR, "_tpq_snappy.so")

_lock = threading.Lock()
_cached: "NativeSnappy | None | bool" = False  # False = not tried yet


def _build() -> bool:
    """(Re)build the shared library if stale; returns success."""
    try:
        if os.path.exists(_SO) and (
            os.path.getmtime(_SO) >= os.path.getmtime(_SRC)
        ):
            return True
        # per-process temp name: concurrent builders must not interleave
        # writes into one file and then promote the garbage via replace
        tmp = f"{_SO}.{os.getpid()}.tmp"
        for cc in ("cc", "gcc", "clang"):
            try:
                subprocess.run(
                    [cc, "-O3", "-shared", "-fPIC", "-o", tmp, _SRC],
                    check=True, capture_output=True, timeout=120,
                )
                os.replace(tmp, _SO)
                return True
            except (FileNotFoundError, subprocess.CalledProcessError,
                    subprocess.TimeoutExpired):
                continue
        return False
    except OSError:
        return False


class NativeSnappy:
    """ctypes bindings over the C snappy block codec."""

    def __init__(self, lib: ctypes.CDLL):
        self._lib = lib
        lib.tpq_snappy_decompress.restype = ctypes.c_int
        lib.tpq_snappy_decompress.argtypes = [
            ctypes.c_char_p, ctypes.c_size_t,
            ctypes.c_char_p, ctypes.c_size_t,
            ctypes.POINTER(ctypes.c_size_t),
        ]
        lib.tpq_snappy_compress.restype = ctypes.c_int
        lib.tpq_snappy_compress.argtypes = [
            ctypes.c_char_p, ctypes.c_size_t,
            ctypes.c_char_p, ctypes.c_size_t,
            ctypes.POINTER(ctypes.c_size_t),
        ]
        lib.tpq_snappy_uncompressed_length.restype = ctypes.c_int
        lib.tpq_snappy_uncompressed_length.argtypes = [
            ctypes.c_char_p, ctypes.c_size_t,
            ctypes.POINTER(ctypes.c_uint64),
        ]
        lib.tpq_snappy_max_compressed_length.restype = ctypes.c_uint64
        lib.tpq_snappy_max_compressed_length.argtypes = [ctypes.c_uint64]

    def uncompressed_length(self, block: bytes) -> int:
        out = ctypes.c_uint64()
        rc = self._lib.tpq_snappy_uncompressed_length(
            block, len(block), ctypes.byref(out)
        )
        if rc != 0:
            raise ValueError("snappy: bad size header")
        return out.value

    def decompress(self, block: bytes, expected_size: int | None = None):
        total = self.uncompressed_length(block)
        if expected_size is not None and total != expected_size:
            raise ValueError(
                f"snappy: header size {total} != expected {expected_size}"
            )
        buf = ctypes.create_string_buffer(max(total, 1))
        produced = ctypes.c_size_t()
        rc = self._lib.tpq_snappy_decompress(
            block, len(block), buf, total, ctypes.byref(produced)
        )
        if rc != 0:
            raise ValueError(f"snappy: corrupt block (rc={rc})")
        return ctypes.string_at(buf, produced.value)

    def compress(self, data: bytes) -> bytes:
        cap = self._lib.tpq_snappy_max_compressed_length(len(data))
        buf = ctypes.create_string_buffer(cap)
        produced = ctypes.c_size_t()
        rc = self._lib.tpq_snappy_compress(
            data, len(data), buf, cap, ctypes.byref(produced)
        )
        if rc != 0:
            raise ValueError(f"snappy: compress failed (rc={rc})")
        return ctypes.string_at(buf, produced.value)


def snappy_native() -> NativeSnappy | None:
    """The process-wide native codec, or None if unbuildable."""
    global _cached
    with _lock:
        if _cached is False:
            _cached = None
            if _build():
                try:
                    _cached = NativeSnappy(ctypes.CDLL(_SO))
                except OSError:
                    _cached = None
        return _cached
