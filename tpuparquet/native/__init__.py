"""Native host-runtime components (C, loaded via ctypes).

The compute plane is JAX/XLA; the host runtime around it (block codecs,
byte-stream scanning) is native C where a Python loop would dominate —
the TPU-build counterpart of the reference keeping its codecs in compiled
Go.  The shared library is built from the checked-in sources with the
system compiler on first import and cached next to them; every consumer
must degrade gracefully to its pure-Python fallback when no compiler is
available (``snappy_native() is None``).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np

__all__ = ["snappy_native", "NativeSnappy", "hybrid_native", "NativeHybrid",
           "plane_native", "NativePlane", "delta_native", "NativeDelta",
           "pack_native", "NativePack", "page_native", "NativePage",
           "lz4_native", "NativeLz4"]

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRCS = [os.path.join(_DIR, "snappy.c"), os.path.join(_DIR, "hybrid.c"),
         os.path.join(_DIR, "plane.c"), os.path.join(_DIR, "delta.c"),
         os.path.join(_DIR, "pack.c"), os.path.join(_DIR, "intern.c"),
         os.path.join(_DIR, "page.c"), os.path.join(_DIR, "lz4raw.c")]
_SO = os.path.join(_DIR, "_tpq_native.so")

_lock = threading.Lock()
_cached: "ctypes.CDLL | None | bool" = False  # False = not tried yet


def _as_u8(block) -> np.ndarray:
    """Zero-copy u8 view of bytes / memoryview / ndarray input."""
    if isinstance(block, np.ndarray):
        return np.ascontiguousarray(block.reshape(-1).view(np.uint8))
    return np.frombuffer(block, dtype=np.uint8)


def hybrid_encode_cap(count: int, width: int) -> int:
    """Output-capacity bound for one hybrid RLE/BP encode of ``count``
    ``width``-bit values: packed groups + per-group headers + slack.
    The ONE copy of this formula — the encoder bindings size their
    buffers with it and the write-side page assembler
    (``io/pages.py``) budgets its body buffer from it; a silent
    desync would turn every native page into a cap-shortfall
    fallback."""
    groups = (count + 7) // 8
    return groups * width + 5 * (groups + 2) + 32


def _build() -> bool:
    """(Re)build the shared library if stale; returns success."""
    try:
        if os.path.exists(_SO) and all(
            os.path.getmtime(_SO) >= os.path.getmtime(src) for src in _SRCS
        ):
            return True
        # per-process temp name: concurrent builders must not interleave
        # writes into one file and then promote the garbage via replace
        tmp = f"{_SO}.{os.getpid()}.tmp"
        for cc in ("cc", "gcc", "clang"):
            try:
                subprocess.run(
                    [cc, "-O3", "-shared", "-fPIC", "-o", tmp, *_SRCS],
                    check=True, capture_output=True, timeout=120,
                )
                os.replace(tmp, _SO)
                return True
            except (FileNotFoundError, subprocess.CalledProcessError,
                    subprocess.TimeoutExpired):
                continue
        return False
    except OSError:
        return False


def _lib() -> "ctypes.CDLL | None":
    global _cached
    with _lock:
        if _cached is False:
            _cached = None
            # TPQ_NATIVE_SO: load a prebuilt shared library instead of
            # building from the checked-in sources — the sanitizer leg
            # (tools/analyze/native.sh) points this at its ASan+UBSan
            # instrumented build so the whole test suite exercises the
            # instrumented codecs without touching the cached .so
            override = os.environ.get("TPQ_NATIVE_SO")
            if override:
                try:
                    _cached = ctypes.CDLL(override)
                except OSError:
                    _cached = None
            elif _build():
                try:
                    _cached = ctypes.CDLL(_SO)
                except OSError:
                    _cached = None
        return _cached


class NativeSnappy:
    """ctypes bindings over the C snappy block codec."""

    def __init__(self, lib: ctypes.CDLL):
        self._lib = lib
        lib.tpq_snappy_decompress.restype = ctypes.c_int
        lib.tpq_snappy_decompress.argtypes = [
            ctypes.c_void_p, ctypes.c_size_t,
            ctypes.c_void_p, ctypes.c_size_t,
            ctypes.POINTER(ctypes.c_size_t),
        ]
        lib.tpq_snappy_compress.restype = ctypes.c_int
        lib.tpq_snappy_compress.argtypes = [
            ctypes.c_char_p, ctypes.c_size_t,
            ctypes.c_char_p, ctypes.c_size_t,
            ctypes.POINTER(ctypes.c_size_t),
        ]
        self._compress_opt_fn = getattr(lib, "tpq_snappy_compress_opt", None)
        if self._compress_opt_fn is not None:
            self._compress_opt_fn.restype = ctypes.c_int
            self._compress_opt_fn.argtypes = [
                ctypes.c_char_p, ctypes.c_size_t,
                ctypes.c_char_p, ctypes.c_size_t,
                ctypes.POINTER(ctypes.c_size_t),
                ctypes.c_int,
            ]
        lib.tpq_snappy_uncompressed_length.restype = ctypes.c_int
        lib.tpq_snappy_uncompressed_length.argtypes = [
            ctypes.c_void_p, ctypes.c_size_t,
            ctypes.POINTER(ctypes.c_uint64),
        ]
        lib.tpq_snappy_max_compressed_length.restype = ctypes.c_uint64
        lib.tpq_snappy_max_compressed_length.argtypes = [ctypes.c_uint64]
        # optional symbol (absent in a stale .so): bind once here rather
        # than per call — ctypes function objects are shared across threads
        self._scan_tokens_fn = getattr(lib, "tpq_snappy_scan_tokens", None)
        if self._scan_tokens_fn is not None:
            self._scan_tokens_fn.restype = ctypes.c_int
            self._scan_tokens_fn.argtypes = [
                ctypes.c_void_p, ctypes.c_size_t,
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64,
                ctypes.c_void_p, ctypes.c_size_t,
                ctypes.POINTER(ctypes.c_int64),
                ctypes.POINTER(ctypes.c_size_t),
                ctypes.POINTER(ctypes.c_uint64),
            ]

    def uncompressed_length(self, block) -> int:
        buf = _as_u8(block)
        out = ctypes.c_uint64()
        rc = self._lib.tpq_snappy_uncompressed_length(
            buf.ctypes.data, buf.size, ctypes.byref(out)
        )
        if rc != 0:
            raise ValueError("snappy: bad size header")
        return out.value

    def scan_tokens(self, block: bytes):
        """Parse the tag stream into (tok_out_end, tok_src, literals,
        out_len) for the device copy-resolution kernel — host cost is
        O(#tokens + literal bytes), no output materialization."""
        fn = self._scan_tokens_fn
        if fn is None:
            raise RuntimeError("native library too old; rebuild")
        buf = _as_u8(block)  # zero-copy for bytes/memoryview/ndarray
        cap_tokens = max(buf.size, 1)  # every token needs >= 1 input byte
        tok_end = np.empty(cap_tokens, dtype=np.int64)
        tok_src = np.empty(cap_tokens, dtype=np.int64)
        lits = np.empty(cap_tokens, dtype=np.uint8)
        n_tok = ctypes.c_int64()
        lit_len = ctypes.c_size_t()
        out_len = ctypes.c_uint64()
        rc = fn(buf.ctypes.data, buf.size,
                tok_end.ctypes.data, tok_src.ctypes.data, cap_tokens,
                lits.ctypes.data, lits.size,
                ctypes.byref(n_tok), ctypes.byref(lit_len),
                ctypes.byref(out_len))
        if rc != 0:
            raise ValueError(f"snappy: corrupt block (rc={rc})")
        t = int(n_tok.value)
        return (tok_end[:t], tok_src[:t], lits[: lit_len.value],
                int(out_len.value))

    def decompress_np(self, block, expected_size: int | None = None,
                      out: np.ndarray | None = None) -> np.ndarray:
        """Decompress into a numpy buffer (no intermediate copies).

        ``out``, when given, must be a u8 array of >= total + 16 bytes
        (the slack opts into the codec's fixed-width speculative copies);
        the caller owns its lifetime (arena recycling)."""
        buf = _as_u8(block)
        total = self.uncompressed_length(buf)
        if expected_size is not None and total != expected_size:
            raise ValueError(
                f"snappy: header size {total} != expected {expected_size}"
            )
        if out is None:
            out = np.empty(max(total, 1) + 16, dtype=np.uint8)
        elif out.size < total + 16:
            raise ValueError("snappy: output buffer too small")
        produced = ctypes.c_size_t()
        rc = self._lib.tpq_snappy_decompress(
            buf.ctypes.data, buf.size, out.ctypes.data,
            out.size, ctypes.byref(produced),
        )
        if rc != 0:
            raise ValueError(f"snappy: corrupt block (rc={rc})")
        return out[: produced.value]

    def decompress(self, block: bytes, expected_size: int | None = None):
        return self.decompress_np(block, expected_size).tobytes()

    def compress_into(self, src, out: np.ndarray,
                      min_match: int = 8) -> int:
        """Compress ``src`` into the caller's u8 buffer (arena-backed on
        the write path); returns the produced length.  No intermediate
        zeroed ctypes buffer and no copy-out — the two hidden whole-
        body passes ``compress`` pays per page."""
        buf = _as_u8(src)
        cap = self._lib.tpq_snappy_max_compressed_length(buf.size)
        if out.size < cap:
            raise ValueError("snappy: output buffer too small")
        produced = ctypes.c_size_t()
        opt = self._compress_opt_fn
        src_p = buf.ctypes.data_as(ctypes.c_char_p)
        out_p = out.ctypes.data_as(ctypes.c_char_p)
        if opt is not None:
            rc = opt(src_p, buf.size, out_p, out.size,
                     ctypes.byref(produced), min_match)
        else:  # stale .so without the tunable: fixed min_match = 8
            rc = self._lib.tpq_snappy_compress(
                src_p, buf.size, out_p, out.size, ctypes.byref(produced))
        if rc != 0:
            raise ValueError(f"snappy: compress failed (rc={rc})")
        return int(produced.value)

    def compress(self, data: bytes, min_match: int = 8) -> bytes:
        cap = self._lib.tpq_snappy_max_compressed_length(len(data))
        buf = ctypes.create_string_buffer(cap)
        produced = ctypes.c_size_t()
        opt = self._compress_opt_fn
        if opt is not None:
            rc = opt(data, len(data), buf, cap, ctypes.byref(produced),
                     min_match)
        else:  # stale .so without the tunable: fixed min_match = 8
            rc = self._lib.tpq_snappy_compress(
                data, len(data), buf, cap, ctypes.byref(produced)
            )
        if rc != 0:
            raise ValueError(f"snappy: compress failed (rc={rc})")
        return ctypes.string_at(buf, produced.value)


class NativeHybrid:
    """ctypes bindings over the C hybrid RLE/BP run scanner."""

    def __init__(self, lib: ctypes.CDLL):
        self._lib = lib
        self._scan = lib.tpq_hybrid_scan
        self._scan.restype = ctypes.c_int
        self._scan.argtypes = [
            ctypes.c_char_p, ctypes.c_size_t, ctypes.c_size_t,
            ctypes.c_int64, ctypes.c_int,
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
            ctypes.c_void_p, ctypes.c_int64,
            ctypes.c_void_p, ctypes.c_size_t,
            ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_size_t), ctypes.POINTER(ctypes.c_size_t),
        ]
        # optional symbol (absent in a stale .so): bind once here rather
        # than per call — ctypes function objects are shared across threads
        self._bp_stats_fn = getattr(lib, "tpq_bp_stats", None)
        if self._bp_stats_fn is not None:
            self._bp_stats_fn.restype = ctypes.c_int
            self._bp_stats_fn.argtypes = [
                ctypes.c_char_p, ctypes.c_size_t, ctypes.c_int,
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64,
                ctypes.c_uint32,
                ctypes.POINTER(ctypes.c_uint32),
                ctypes.POINTER(ctypes.c_int64),
            ]

    def bp_stats(self, bp_bytes, width: int, starts, lens,
                 target: int = 0):
        """(max value | None, count of == target) over the consumed lanes
        of bit-packed segments — one C pass, no unpack materialization."""
        fn = self._bp_stats_fn
        if fn is None:
            raise RuntimeError("native library too old; rebuild")
        bp = np.ascontiguousarray(
            np.frombuffer(bp_bytes, dtype=np.uint8)
            if not isinstance(bp_bytes, np.ndarray) else bp_bytes
        )
        s = np.ascontiguousarray(starts, dtype=np.int64)
        ln = np.ascontiguousarray(lens, dtype=np.int64)
        mx = ctypes.c_uint32()
        cnt = ctypes.c_int64()
        rc = fn(bp.ctypes.data_as(ctypes.c_char_p), bp.size, width,
                s.ctypes.data, ln.ctypes.data, s.size, target,
                ctypes.byref(mx), ctypes.byref(cnt))
        if rc == 1:
            return None, 0
        if rc != 0:
            raise ValueError(f"bit-packed segment out of bounds (rc={rc})")
        return int(mx.value), int(cnt.value)

    def scan(self, buf, count: int, width: int, pos: int = 0):
        """Parse run headers; returns (run_ends, run_is_rle, run_value,
        run_bp_start, bp_bytes, n_bp_values, end_pos) — numpy arrays plus
        the concatenated bit-packed segment bytes."""
        if isinstance(buf, np.ndarray):
            data = np.ascontiguousarray(buf.view(np.uint8))
        else:
            data = np.frombuffer(buf, dtype=np.uint8)  # zero-copy
        # every run consumes >= 1 header byte, so runs are bounded by the
        # stream's byte length as well as by the value count
        cap_runs = max(min(count, max(data.size - pos, 0)) + 1, 1)
        bp_cap = max(data.size - pos, 1)
        ends = np.empty(cap_runs, dtype=np.int32)
        is_rle = np.empty(cap_runs, dtype=np.uint8)
        value = np.empty(cap_runs, dtype=np.uint32)
        bp_start = np.empty(cap_runs, dtype=np.int32)
        bp_out = np.empty(bp_cap, dtype=np.uint8)
        n_runs = ctypes.c_int64()
        n_bp = ctypes.c_int64()
        bp_len = ctypes.c_size_t()
        end_pos = ctypes.c_size_t()
        rc = self._scan(
            data.ctypes.data_as(ctypes.c_char_p), data.size, pos, count,
            width,
            ends.ctypes.data, is_rle.ctypes.data, value.ctypes.data,
            bp_start.ctypes.data, cap_runs,
            bp_out.ctypes.data, bp_cap,
            ctypes.byref(n_runs), ctypes.byref(n_bp),
            ctypes.byref(bp_len), ctypes.byref(end_pos),
        )
        if rc == -1:
            raise ValueError("truncated hybrid run")
        if rc == -2:
            raise ValueError("zero-length RLE run")
        if rc == -6:
            raise ValueError("RLE run value exceeds bit width")
        if rc != 0:
            raise ValueError(f"hybrid scan failed (rc={rc})")
        r = int(n_runs.value)
        return (ends[:r], is_rle[:r].astype(bool), value[:r], bp_start[:r],
                bp_out[: bp_len.value], int(n_bp.value), int(end_pos.value))


class NativePlane:
    """ctypes bindings over the strided lane/byte-plane primitives used
    by the device wire planner (one C pass per run-scan / gather)."""

    def __init__(self, lib: ctypes.CDLL):
        self._scan32 = getattr(lib, "tpq_run_scan32", None)
        self._scan8 = getattr(lib, "tpq_run_scan8", None)
        self._gather32 = getattr(lib, "tpq_lane_gather32", None)
        self._gather8 = getattr(lib, "tpq_lane_gather8", None)
        if None in (self._scan32, self._scan8,
                    self._gather32, self._gather8):
            raise RuntimeError("native library too old; rebuild")
        for fn, val in ((self._scan32, ctypes.c_longlong),
                        (self._scan8, ctypes.c_longlong)):
            fn.restype = val
            fn.argtypes = [
                ctypes.c_void_p, ctypes.c_longlong, ctypes.c_longlong,
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_longlong,
            ]
        for fn in (self._gather32, self._gather8):
            fn.restype = None
            fn.argtypes = [
                ctypes.c_void_p, ctypes.c_longlong, ctypes.c_longlong,
                ctypes.c_void_p,
            ]

    @staticmethod
    def _strided(arr: np.ndarray, esize: int):
        """(base pointer, element stride) for a 1-D strided view."""
        if arr.ndim != 1 or arr.itemsize != esize:
            raise ValueError("expected a 1-D view of the element type")
        return arr.ctypes.data, arr.strides[0]

    def run_scan(self, plane: np.ndarray, max_runs: int):
        """Run-table scan of a strided u32/u8 view.  Returns
        (ends[:n], vals[:n]) or None when the plane has more than
        ``max_runs`` runs (the table cannot beat shipping raw)."""
        cap = max(int(max_runs), 1)
        ends = np.empty(cap, dtype=np.int32)
        if plane.itemsize == 4:
            vals = np.empty(cap, dtype=np.uint32)
            base, stride = self._strided(plane, 4)
            n = self._scan32(base, plane.size, stride,
                             ends.ctypes.data, vals.ctypes.data, cap)
        else:
            vals = np.empty(cap, dtype=np.uint8)
            base, stride = self._strided(plane, 1)
            n = self._scan8(base, plane.size, stride,
                            ends.ctypes.data, vals.ctypes.data, cap)
        if n < 0:
            return None
        return ends[:n], vals[:n]

    def gather(self, plane: np.ndarray) -> np.ndarray:
        """Contiguous copy of a strided u32/u8 view (one pass)."""
        out = np.empty(plane.size, dtype=plane.dtype)
        if plane.itemsize == 4:
            base, stride = self._strided(plane, 4)
            self._gather32(base, plane.size, stride, out.ctypes.data)
        else:
            base, stride = self._strided(plane, 1)
            self._gather8(base, plane.size, stride, out.ctypes.data)
        return out


class NativeDelta:
    """ctypes binding over the DELTA_BINARY_PACKED block scanner."""

    _ERRORS = {
        -1: "truncated uvarint",
        -5: "truncated miniblock width list",
        -7: "truncated miniblock payload",
        -9: "uvarint too long",
    }

    def __init__(self, lib: ctypes.CDLL):
        self._scan = getattr(lib, "tpq_delta_scan_blocks", None)
        if self._scan is None:
            raise RuntimeError("native library too old; rebuild")
        self._scan.restype = ctypes.c_longlong
        self._scan.argtypes = [
            ctypes.c_void_p, ctypes.c_longlong, ctypes.c_longlong,
            ctypes.c_longlong, ctypes.c_longlong, ctypes.c_longlong,
            ctypes.c_int,
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
            ctypes.c_void_p, ctypes.c_longlong, ctypes.c_longlong,
            ctypes.POINTER(ctypes.c_longlong),
            ctypes.POINTER(ctypes.c_longlong),
            ctypes.POINTER(ctypes.c_longlong),
        ]
        self._decode = getattr(lib, "tpq_delta_decode", None)
        if self._decode is not None:
            self._decode.restype = ctypes.c_longlong
            self._decode.argtypes = [
                ctypes.c_void_p, ctypes.c_longlong,
                ctypes.c_void_p, ctypes.c_longlong,
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
                ctypes.c_longlong, ctypes.c_longlong, ctypes.c_longlong,
                ctypes.c_longlong, ctypes.c_uint64,
                ctypes.c_void_p,
            ]
        self._gather = getattr(lib, "tpq_gather_segments", None)
        if self._gather is not None:
            self._gather.restype = ctypes.c_longlong
            self._gather.argtypes = [
                ctypes.c_void_p, ctypes.c_longlong,
                ctypes.c_void_p, ctypes.c_longlong, ctypes.c_longlong,
                ctypes.c_void_p,
            ]
        self._gather_var = getattr(lib, "tpq_gather_var", None)
        if self._gather_var is not None:
            self._gather_var.restype = ctypes.c_longlong
            self._gather_var.argtypes = [
                ctypes.c_void_p, ctypes.c_longlong,
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_longlong,
                ctypes.c_void_p, ctypes.c_longlong,
            ]
        self._dba = getattr(lib, "tpq_dba_assemble", None)
        if self._dba is not None:
            self._dba.restype = ctypes.c_longlong
            self._dba.argtypes = [
                ctypes.c_void_p, ctypes.c_void_p,
                ctypes.c_void_p, ctypes.c_longlong,
                ctypes.c_void_p, ctypes.c_longlong,
                ctypes.c_void_p, ctypes.POINTER(ctypes.c_longlong),
            ]
        self._ba_emit = getattr(lib, "tpq_byte_array_emit", None)
        if self._ba_emit is not None:
            self._ba_emit.restype = ctypes.c_longlong
            self._ba_emit.argtypes = [
                ctypes.c_void_p, ctypes.c_longlong,
                ctypes.c_void_p, ctypes.c_longlong,
                ctypes.c_void_p,
            ]
        self._ba_scan = getattr(lib, "tpq_byte_array_scan", None)
        if self._ba_scan is not None:
            self._ba_scan.restype = ctypes.c_longlong
            self._ba_scan.argtypes = [
                ctypes.c_void_p, ctypes.c_longlong, ctypes.c_longlong,
                ctypes.c_void_p, ctypes.c_void_p,
                ctypes.POINTER(ctypes.c_longlong),
                ctypes.POINTER(ctypes.c_longlong),
            ]

    def decode_all(self, data, st) -> "np.ndarray | None":
        """Full DELTA_BINARY_PACKED decode from a scanned
        :class:`~tpuparquet.cpu.delta.DeltaStructure` — unpack + per-block
        min_delta + prefix sum in one GIL-releasing C pass.  Returns the
        (total,) uint64 value array (two's-complement wrap, byte-exact
        with the numpy decode), or None when the symbol is missing
        (stale .so)."""
        if self._decode is None:
            return None
        buf = _as_u8(data)
        md = np.ascontiguousarray(st.md_blocks, dtype=np.int64)
        w = np.ascontiguousarray(st.mb_w, dtype=np.int32)
        p = np.ascontiguousarray(st.mb_pos, dtype=np.int64)
        s = np.ascontiguousarray(st.mb_start, dtype=np.int64)
        out = np.empty(max(st.total, 1), dtype=np.uint64)[: st.total]
        rc = self._decode(
            buf.ctypes.data, buf.size, md.ctypes.data, md.size,
            w.ctypes.data, p.ctypes.data, s.ctypes.data, w.size,
            st.mb_size, st.block_size, st.total,
            ctypes.c_uint64(st.first & 0xFFFFFFFFFFFFFFFF),
            out.ctypes.data)
        if rc != 0:
            raise ValueError(f"delta decode failed (rc={rc})")
        return out

    def dba_assemble(self, prefix_lens, suffix_offs, suffix_data,
                     out_offsets, total: int):
        """Front-coded DELTA_BYTE_ARRAY fill in one C pass; None when
        the symbol is missing.  Raises ValueError with the CPU
        assembler's messages on malformed streams."""
        if self._dba is None:
            return None
        pl = np.ascontiguousarray(prefix_lens, dtype=np.int64)
        so = np.ascontiguousarray(suffix_offs, dtype=np.int64)
        sd = _as_u8(suffix_data)
        oo = np.ascontiguousarray(out_offsets, dtype=np.int64)
        out = np.empty(max(total, 1), dtype=np.uint8)[:total]
        err = ctypes.c_longlong()
        rc = self._dba(pl.ctypes.data, so.ctypes.data,
                       sd.ctypes.data, sd.size,
                       oo.ctypes.data, pl.size, out.ctypes.data,
                       ctypes.byref(err))
        if rc == -1:
            raise ValueError("DELTA_BYTE_ARRAY: first prefix must be 0")
        if rc == -2:
            raise ValueError(
                f"DELTA_BYTE_ARRAY: prefix {int(pl[err.value])} longer "
                "than previous value")
        if rc != 0:
            raise ValueError(f"DELTA_BYTE_ARRAY assembly failed "
                             f"(rc={rc})")
        return out

    def byte_array_emit(self, data, offsets):
        """PLAIN-encode a ByteArrayColumn's records (u32-LE prefix +
        bytes) in one C pass; None when the symbol is missing."""
        if self._ba_emit is None:
            return None
        d = _as_u8(data)
        offs = np.ascontiguousarray(offsets, dtype=np.int64)
        count = offs.size - 1
        total = 4 * count + int(offs[-1]) - int(offs[0])
        out = np.empty(max(total, 1), dtype=np.uint8)[:total]
        rc = self._ba_emit(d.ctypes.data, d.size, offs.ctypes.data,
                           count, out.ctypes.data)
        if rc != 0:
            raise ValueError(
                "byte-array offsets out of bounds or value too long "
                "for a u32 prefix")
        return out

    def byte_array_scan(self, buf, count: int):
        """Scan PLAIN BYTE_ARRAY length prefixes in one C pass:
        (positions, offsets) or None when the symbol is missing.
        Raises ValueError with the CPU scanner's messages."""
        if self._ba_scan is None or count < 0:
            return None  # negative counts keep the legacy Python path
        b = _as_u8(buf)
        positions = np.empty(max(count, 1), dtype=np.int64)[:count]
        offsets = np.zeros(count + 1, dtype=np.int64)
        err = ctypes.c_longlong()
        err_len = ctypes.c_longlong()
        rc = self._ba_scan(b.ctypes.data, b.size, count,
                           positions.ctypes.data, offsets.ctypes.data,
                           ctypes.byref(err), ctypes.byref(err_len))
        if rc == -1:
            raise ValueError(
                f"PLAIN BYTE_ARRAY: truncated length prefix at value "
                f"{err.value}")
        if rc == -2:
            raise ValueError(
                f"PLAIN BYTE_ARRAY: length {err_len.value} out of "
                f"bounds at value {err.value}")
        if rc != 0:
            raise ValueError(f"byte-array scan failed (rc={rc})")
        return positions, offsets

    def gather_var(self, src, starts, lens, total: int):
        """Concatenate variable-length segments of ``src`` in one C
        pass; None when the symbol is missing (stale .so)."""
        if self._gather_var is None:
            return None
        buf = _as_u8(src)
        s = np.ascontiguousarray(starts, dtype=np.int64)
        ln = np.ascontiguousarray(lens, dtype=np.int64)
        out = np.empty(max(total, 1), dtype=np.uint8)[:total]
        rc = self._gather_var(buf.ctypes.data, buf.size,
                              s.ctypes.data, ln.ctypes.data, s.size,
                              out.ctypes.data, total)
        if rc != 0:
            raise ValueError("segment out of bounds")
        return out

    def gather_segments(self, src, positions, nbytes: int):
        """Concatenate fixed-size segments of ``src`` at ``positions``
        in one C pass; None when the symbol is missing (stale .so)."""
        if self._gather is None:
            return None
        buf = _as_u8(src)
        pos = np.ascontiguousarray(positions, dtype=np.int64)
        out = np.empty(pos.size * nbytes, dtype=np.uint8)
        rc = self._gather(buf.ctypes.data, buf.size, pos.ctypes.data,
                          pos.size, nbytes, out.ctypes.data)
        if rc != 0:
            raise ValueError("miniblock payload out of bounds")
        return out

    def scan_blocks(self, data, pos: int, n_deltas: int, mb_size: int,
                    n_miniblocks: int, max_width: int):
        """Scan the block loop of a DELTA stream whose 4 header varints
        the caller already consumed.  Returns (md_blocks, mb_w, mb_pos,
        mb_start, end_pos) as numpy arrays / int; raises ValueError with
        the CPU scanner's messages on malformed input."""
        buf = _as_u8(data)
        block_size = mb_size * n_miniblocks
        # clamp by remaining bytes: each block consumes >= 1 byte of
        # min_delta varint + n_miniblocks width bytes, so a corrupt
        # total claiming 2^62 values must not size the allocation (the
        # scan will hit its truncation error long before these caps)
        max_blocks = max(buf.size - pos, 0) // (1 + n_miniblocks) + 2
        cap_blocks = min(n_deltas // block_size + 2, max_blocks)
        # likewise for recorded miniblocks: each non-zero-width one
        # consumes >= mb_size/8 payload bytes, so a corrupt header with
        # a huge n_miniblocks cannot size a multi-GB table either
        max_mb = max(buf.size - pos, 0) // max(mb_size // 8, 1) + 2
        cap_mb = min(cap_blocks * n_miniblocks + 2, max_mb)
        md = np.empty(cap_blocks, dtype=np.int64)
        w = np.empty(cap_mb, dtype=np.int32)
        p = np.empty(cap_mb, dtype=np.int64)
        s = np.empty(cap_mb, dtype=np.int64)
        nb = ctypes.c_longlong()
        nm = ctypes.c_longlong()
        end = ctypes.c_longlong()
        rc = self._scan(
            buf.ctypes.data, buf.size, pos,
            n_deltas, mb_size, n_miniblocks, max_width,
            md.ctypes.data, w.ctypes.data, p.ctypes.data, s.ctypes.data,
            cap_blocks, cap_mb,
            ctypes.byref(nb), ctypes.byref(nm), ctypes.byref(end),
        )
        if rc == -6:
            raise ValueError(
                f"delta miniblock width > {max_width} for this column's "
                "physical type")
        if rc != 0:
            raise ValueError(self._ERRORS.get(
                rc, f"delta scan failed (rc={rc})"))
        b, m = int(nb.value), int(nm.value)
        return md[:b], w[:m], p[:m], s[:m], int(end.value)


class NativePack:
    """ctypes bindings over the bit-packing primitives."""

    def __init__(self, lib: ctypes.CDLL):
        self._pack64 = getattr(lib, "tpq_pack64", None)
        self._repack = getattr(lib, "tpq_hybrid_repack", None)
        self._expand = getattr(lib, "tpq_hybrid_expand32", None)
        if None in (self._pack64, self._repack, self._expand):
            raise RuntimeError("native library too old; rebuild")
        self._delta_emit = getattr(lib, "tpq_delta_emit", None)
        if self._delta_emit is not None:
            self._delta_emit.restype = ctypes.c_longlong
            self._delta_emit.argtypes = [
                ctypes.c_void_p, ctypes.c_void_p,
                ctypes.c_longlong, ctypes.c_longlong,
                ctypes.c_void_p, ctypes.c_longlong, ctypes.c_longlong,
                ctypes.c_void_p, ctypes.c_longlong,
                ctypes.POINTER(ctypes.c_longlong),
            ]
        self._hybrid_encode = getattr(lib, "tpq_hybrid_encode", None)
        if self._hybrid_encode is not None:
            self._hybrid_encode.restype = ctypes.c_longlong
            self._hybrid_encode.argtypes = [
                ctypes.c_void_p, ctypes.c_longlong, ctypes.c_int,
                ctypes.c_void_p, ctypes.c_longlong,
                ctypes.POINTER(ctypes.c_longlong),
            ]
        self._hybrid_encode32 = getattr(lib, "tpq_hybrid_encode32", None)
        if self._hybrid_encode32 is not None:
            self._hybrid_encode32.restype = ctypes.c_longlong
            self._hybrid_encode32.argtypes = [
                ctypes.c_void_p, ctypes.c_longlong, ctypes.c_int,
                ctypes.c_void_p, ctypes.c_longlong,
                ctypes.POINTER(ctypes.c_longlong),
            ]
        self._expand.restype = ctypes.c_longlong
        self._expand.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
            ctypes.c_void_p, ctypes.c_longlong,
            ctypes.c_void_p, ctypes.c_longlong, ctypes.c_longlong,
            ctypes.c_longlong, ctypes.c_int, ctypes.c_void_p,
        ]
        self._pack64.restype = ctypes.c_longlong
        self._pack64.argtypes = [
            ctypes.c_void_p, ctypes.c_longlong, ctypes.c_int,
            ctypes.c_void_p,
        ]
        self._repack.restype = ctypes.c_longlong
        self._repack.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
            ctypes.c_void_p, ctypes.c_longlong,
            ctypes.c_void_p, ctypes.c_longlong, ctypes.c_longlong,
            ctypes.c_longlong, ctypes.c_int, ctypes.c_void_p,
        ]

    def pack(self, values: np.ndarray, width: int) -> np.ndarray:
        """LSB-first pack of a contiguous uint64 array; raises on a
        value that does not fit ``width`` bits."""
        v = np.ascontiguousarray(values, dtype=np.uint64)
        n = (v.size * width + 7) // 8
        out = np.empty(n + 8, dtype=np.uint8)  # word-writer slack
        rc = self._pack64(v.ctypes.data, v.size, width, out.ctypes.data)
        if rc == -1:
            raise ValueError(
                f"value {int(v.max())} does not fit in {width} bits")
        if rc != 0:
            raise ValueError(f"bit width {width} out of range 0..64")
        return out[:n]

    def hybrid_encode(self, values: np.ndarray, width: int):
        """Hybrid RLE/BP encode in one C pass, byte-identical to the
        Python encoder.  None when the symbol is missing (stale .so) or
        the capacity estimate fell short (the fallback then encodes);
        raises on a value that does not fit the width — writing it
        would corrupt the stream at read time."""
        if self._hybrid_encode is None:
            return None
        v = np.ascontiguousarray(values, dtype=np.uint64)
        cap = hybrid_encode_cap(v.size, width)
        out = np.empty(cap, dtype=np.uint8)
        out_len = ctypes.c_longlong()
        rc = self._hybrid_encode(v.ctypes.data, v.size, width,
                                 out.ctypes.data, cap,
                                 ctypes.byref(out_len))
        if rc == -1:
            raise ValueError(
                f"value {int(v.max())} does not fit in {width} bits")
        if rc != 0:
            return None  # cap shortfall / bad width: fallback decides
        return out[: out_len.value]

    def hybrid_encode32(self, values: np.ndarray, width: int):
        """Hybrid RLE/BP encode straight from a u32 array — the same
        bytes as :meth:`hybrid_encode` without the u64-widening copy
        the write path paid per dict-index/level stream.  None when
        the symbol is missing (stale .so) or the capacity estimate
        fell short; raises on a value that does not fit the width."""
        if self._hybrid_encode32 is None or width > 32:
            return None
        v = np.ascontiguousarray(values, dtype=np.uint32)
        cap = hybrid_encode_cap(v.size, width)
        out = np.empty(cap, dtype=np.uint8)
        out_len = ctypes.c_longlong()
        rc = self._hybrid_encode32(v.ctypes.data, v.size, width,
                                   out.ctypes.data, cap,
                                   ctypes.byref(out_len))
        if rc == -1:
            raise ValueError(
                f"value {int(v.max())} does not fit in {width} bits")
        if rc != 0:
            return None  # cap shortfall / bad width: fallback decides
        return out[: out_len.value]

    def delta_emit(self, adj, widths, mb_size: int, min_deltas,
                   n_miniblocks: int):
        """Emit the per-block body of a DELTA_BINARY_PACKED stream in
        one C pass (zigzag min_delta varints + width bytes + packed
        miniblocks); None when the symbol is missing (stale .so)."""
        if self._delta_emit is None:
            return None
        a = np.ascontiguousarray(adj, dtype=np.uint64).reshape(-1)
        w = np.ascontiguousarray(widths, dtype=np.uint8)
        md = np.ascontiguousarray(min_deltas, dtype=np.int64)
        n_mb = w.size
        packed_bytes = int((w.astype(np.int64) * mb_size).sum()) // 8
        cap = packed_bytes + md.size * (10 + n_miniblocks) + 16
        out = np.empty(cap, dtype=np.uint8)
        out_len = ctypes.c_longlong()
        rc = self._delta_emit(
            a.ctypes.data, w.ctypes.data, n_mb, mb_size,
            md.ctypes.data, md.size, n_miniblocks,
            out.ctypes.data, cap, ctypes.byref(out_len))
        if rc != 0:
            raise ValueError(f"delta emit failed (rc={rc})")
        return out[: out_len.value]

    @staticmethod
    def _run_table(run_ends, run_is_rle, run_value, run_bp_start,
                   bp_bytes, count: int, width: int):
        """Validated, C-ready run table for expand/repack, or None
        when the fallback must handle it: widths > 32, or a table that
        does not cover count — that shape cannot come from a valid
        scan, and the numpy paths disagree with each other on it, so
        don't pin semantics here."""
        if not 0 < width <= 32 or not len(run_ends):
            return None
        if int(run_ends[-1]) < count:
            return None
        return (np.ascontiguousarray(run_ends, dtype=np.int32),
                np.ascontiguousarray(run_is_rle, dtype=np.uint8),
                np.ascontiguousarray(run_value, dtype=np.uint32),
                np.ascontiguousarray(run_bp_start, dtype=np.int32),
                _as_u8(bp_bytes))

    def hybrid_expand(self, run_ends, run_is_rle, run_value,
                      run_bp_start, bp_bytes, n_bp: int, count: int,
                      width: int) -> np.ndarray | None:
        """Run table -> (count,) u32 values in one C pass (pass 2 of
        the two-pass hybrid decode).  None for widths > 32 or tables
        that do not cover count (caller falls back to numpy)."""
        t = self._run_table(run_ends, run_is_rle, run_value,
                            run_bp_start, bp_bytes, count, width)
        if t is None:
            return None
        ends, rle, val, bps, bp = t
        out = np.empty(count, dtype=np.uint32)
        rc = self._expand(
            ends.ctypes.data, rle.ctypes.data, val.ctypes.data,
            bps.ctypes.data, ends.size, bp.ctypes.data, bp.size,
            int(n_bp), count, width, out.ctypes.data)
        if rc != 0:
            raise ValueError(f"hybrid expand failed (rc={rc})")
        return out

    def hybrid_repack(self, run_ends, run_is_rle, run_value,
                      run_bp_start, bp_bytes, n_bp: int, count: int,
                      width: int) -> np.ndarray | None:
        """Run table -> ONE bit-packed run, no expanded intermediate.
        Returns the packed bytes, or None for widths > 32 (caller
        falls back to expand + pack)."""
        t = self._run_table(run_ends, run_is_rle, run_value,
                            run_bp_start, bp_bytes, count, width)
        if t is None:
            return None
        ends, rle, val, bps, bp = t
        n = (count * width + 7) // 8
        out = np.empty(n + 8, dtype=np.uint8)  # word-writer slack
        rc = self._repack(
            ends.ctypes.data, rle.ctypes.data, val.ctypes.data,
            bps.ctypes.data, ends.size, bp.ctypes.data, bp.size,
            int(n_bp), count, width, out.ctypes.data)
        if rc == -1:  # same contract as pack(): refuse, don't truncate
            raise ValueError(
                f"value {int(val.max())} does not fit in {width} bits")
        if rc != 0:
            raise ValueError(f"hybrid repack failed (rc={rc})")
        return out[:n]


class NativePage:
    """ctypes bindings over the write-side page assembly (page.c):
    one-pass body encode into a caller buffer + the zlib-polynomial
    CRC32 the PageHeader carries."""

    def __init__(self, lib: ctypes.CDLL):
        self._encode = getattr(lib, "tpq_page_encode", None)
        self._crc = getattr(lib, "tpq_crc32", None)
        if None in (self._encode, self._crc):
            raise RuntimeError("native library too old; rebuild")
        self._encode.restype = ctypes.c_longlong
        self._encode.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_longlong,
            ctypes.c_int, ctypes.c_int, ctypes.c_int,
            ctypes.c_void_p, ctypes.c_longlong, ctypes.c_int,
            ctypes.c_void_p, ctypes.c_longlong,
            ctypes.c_void_p, ctypes.c_longlong,
            ctypes.POINTER(ctypes.c_longlong),
            ctypes.POINTER(ctypes.c_longlong),
            ctypes.POINTER(ctypes.c_longlong),
        ]
        self._crc.restype = ctypes.c_uint32
        self._crc.argtypes = [ctypes.c_void_p, ctypes.c_longlong,
                              ctypes.c_uint32]

    def crc32(self, buf, crc: int = 0) -> int:
        """zlib-compatible CRC32 (slice-by-8, GIL released)."""
        b = _as_u8(buf)
        return int(self._crc(b.ctypes.data, b.size, crc & 0xFFFFFFFF))

    def encode(self, rep, dl, n: int, rep_width: int, def_width: int,
               v2: bool, idx, idx_width: int, values,
               out: np.ndarray):
        """Lay one data page's uncompressed body into ``out``:
        ``[rep stream][def stream][values]``, V1 length-prefixed or V2
        raw level framing.  ``rep``/``dl`` are u32 level arrays or
        None; the values segment is either ``idx`` (u32 dictionary
        indices, hybrid-encoded behind the width byte) or ``values``
        (pre-encoded u8 bytes, copied verbatim).  Returns
        ``(rep_len, dl_len, val_len)`` — framing included — or None
        when the buffer capacity fell short (caller falls back);
        raises on a level/index exceeding its width."""
        def _c(a):
            # contiguity is load-bearing: C walks n consecutive words
            # from the base pointer (no-op for the write path's own
            # arrays; a caller-provided strided view copies here)
            return None if a is None else np.ascontiguousarray(a)

        def _p(a):
            return None if a is None else a.ctypes.data

        rep, dl, idx, values = _c(rep), _c(dl), _c(idx), _c(values)
        rep_len = ctypes.c_longlong()
        dl_len = ctypes.c_longlong()
        val_len = ctypes.c_longlong()
        rc = self._encode(
            _p(rep), _p(dl), n, rep_width, def_width, 1 if v2 else 0,
            _p(idx), 0 if idx is None else idx.size, idx_width,
            _p(values), 0 if values is None else values.size,
            out.ctypes.data, out.size,
            ctypes.byref(rep_len), ctypes.byref(dl_len),
            ctypes.byref(val_len))
        if rc == -1:
            raise ValueError("level/index value does not fit its width")
        if rc != 0:
            return None  # cap shortfall / bad width: fallback decides
        return int(rep_len.value), int(dl_len.value), int(val_len.value)


class NativeLz4:
    """ctypes bindings over the C LZ4 raw-block codec (lz4raw.c) —
    Parquet's LZ4_RAW.  Same buffer discipline as :class:`NativeSnappy`:
    ``compress_into``/``decompress_np`` take caller (arena) buffers so
    the write/read hot paths pay no scratch copies."""

    def __init__(self, lib: ctypes.CDLL):
        self._lib = lib
        comp = getattr(lib, "tpq_lz4_compress", None)
        dec = getattr(lib, "tpq_lz4_decompress", None)
        bound = getattr(lib, "tpq_lz4_max_compressed_length", None)
        if None in (comp, dec, bound):
            raise RuntimeError("native library too old; rebuild")
        comp.restype = ctypes.c_int
        comp.argtypes = [
            ctypes.c_void_p, ctypes.c_size_t,
            ctypes.c_void_p, ctypes.c_size_t,
            ctypes.POINTER(ctypes.c_size_t),
        ]
        dec.restype = ctypes.c_int
        dec.argtypes = [
            ctypes.c_void_p, ctypes.c_size_t,
            ctypes.c_void_p, ctypes.c_size_t,
            ctypes.POINTER(ctypes.c_size_t),
        ]
        bound.restype = ctypes.c_uint64
        bound.argtypes = [ctypes.c_uint64]
        self._comp = comp
        self._dec = dec
        self._bound = bound

    def max_compressed_length(self, n: int) -> int:
        return int(self._bound(n))

    def compress_into(self, src, out: np.ndarray) -> int:
        """Compress ``src`` into the caller's u8 buffer; returns the
        produced length.  ``out`` must hold max_compressed_length."""
        buf = _as_u8(src)
        if out.size < self.max_compressed_length(buf.size):
            raise ValueError("lz4: output buffer too small")
        produced = ctypes.c_size_t()
        rc = self._comp(buf.ctypes.data, buf.size, out.ctypes.data,
                        out.size, ctypes.byref(produced))
        if rc != 0:
            raise ValueError(f"lz4: compress failed (rc={rc})")
        return int(produced.value)

    def compress(self, data) -> bytes:
        buf = _as_u8(data)
        out = np.empty(self.max_compressed_length(buf.size),
                       dtype=np.uint8)
        return out[: self.compress_into(buf, out)].tobytes()

    def decompress_np(self, block, expected_size: int,
                      out: np.ndarray | None = None) -> np.ndarray:
        """Decompress into a numpy buffer sized by the caller's
        ``expected_size`` (LZ4 raw blocks carry no length header; the
        Parquet page header supplies it)."""
        buf = _as_u8(block)
        if expected_size < 0:
            raise ValueError("lz4: missing decompressed size")
        if out is None:
            out = np.empty(max(expected_size, 1), dtype=np.uint8)
        elif out.size < expected_size:
            raise ValueError("lz4: output buffer too small")
        produced = ctypes.c_size_t()
        rc = self._dec(buf.ctypes.data, buf.size, out.ctypes.data,
                       ctypes.c_size_t(expected_size),
                       ctypes.byref(produced))
        if rc != 0:
            raise ValueError(f"lz4: corrupt block (rc={rc})")
        if int(produced.value) != expected_size:
            raise ValueError(
                f"lz4: stream produced {int(produced.value)} bytes, "
                f"expected {expected_size}")
        return out[:expected_size]

    def decompress(self, block, expected_size: int) -> bytes:
        return self.decompress_np(block, expected_size).tobytes()


# sentinel: the interner hit its distinct-value cap (callers compare
# with ``is``; a string literal here invited silent typo mismatches)
TOO_MANY_DISTINCT = object()


class NativeIntern:
    """ctypes binding over the one-pass byte-value interner."""

    def __init__(self, lib: ctypes.CDLL):
        self._intern = getattr(lib, "tpq_intern_var", None)
        if self._intern is None:
            raise RuntimeError("native library too old; rebuild")
        self._intern.restype = ctypes.c_longlong
        self._intern.argtypes = [
            ctypes.c_void_p, ctypes.c_longlong,
            ctypes.c_void_p, ctypes.c_longlong,
            ctypes.c_void_p, ctypes.c_longlong, ctypes.c_int,
            ctypes.c_void_p, ctypes.c_longlong,
            ctypes.c_void_p,
        ]
        # optional symbols (absent in a stale .so): bound once here
        self._range32 = getattr(lib, "tpq_intern_range32", None)
        self._range64 = getattr(lib, "tpq_intern_range64", None)
        for fn, lo_t in ((self._range32, ctypes.c_uint32),
                         (self._range64, ctypes.c_uint64)):
            if fn is not None:
                fn.restype = ctypes.c_longlong
                fn.argtypes = [
                    ctypes.c_void_p, ctypes.c_longlong, lo_t,
                    ctypes.c_longlong,
                    ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
                ]

    def intern_range(self, arr: np.ndarray, lo: int, rng: int):
        """First-occurrence intern of a small-range integer column in
        one C pass: ``(uniq_positions int64[D], indices int32[n])``, or
        None when the symbol is missing (stale .so).  ``lo``/``rng``
        come from the column's true min/max (offsets are computed with
        wraparound subtraction, exact for signed and unsigned alike);
        raises on a value outside ``[lo, lo + rng)``."""
        fn = self._range64 if arr.itemsize == 8 else self._range32
        if fn is None or arr.itemsize not in (4, 8):
            return None
        u = np.ascontiguousarray(arr).view(
            np.uint64 if arr.itemsize == 8 else np.uint32)
        mask = (1 << (8 * arr.itemsize)) - 1
        rank = np.full(rng, -1, dtype=np.int32)
        uniq_pos = np.empty(rng, dtype=np.int64)
        indices = np.empty(max(u.size, 1), dtype=np.int32)[: u.size]
        d = fn(u.ctypes.data, u.size, lo & mask, rng,
               rank.ctypes.data, uniq_pos.ctypes.data,
               indices.ctypes.data)
        if d < 0:
            raise ValueError(f"value outside interning range (rc={d})")
        return uniq_pos[:d].copy(), indices

    def intern_var(self, data, offsets, max_d: int):
        """First-occurrence intern of n variable byte values.

        Returns ``(first_indices int64[D], indices int32[n])``, or
        ``TOO_MANY_DISTINCT`` when more than ``max_d`` distinct values
        exist (the early exit the caller's dictionary gate wants), or
        raises on corrupt offsets."""
        buf = _as_u8(data)
        offs = np.ascontiguousarray(offsets, dtype=np.int64)
        n = offs.size - 1
        # ~4x max occupancy at the distinct cap keeps probe chains
        # short; the cap (not n) sizes the table, so high-cardinality
        # columns abort cheaply instead of growing the table
        tbits = max(16, (4 * max_d - 1).bit_length())
        # rc=-1 is the C pass reporting table saturation ("caller
        # resizes" in intern.c): unreachable under the 4x sizing above
        # (at most max_d entries ever occupy T >= 4*max_d slots), but
        # honored anyway — retry with a doubled table rather than
        # failing a write on a contract bug.  Bounded at +3 doublings
        # (32x occupancy headroom): a .so that STILL claims saturation
        # is lying, and an unbounded ladder would allocate multi-GiB
        # tables on its way to the error below.
        max_tbits = min(tbits + 3, 31)
        firsts = np.empty(max_d, dtype=np.int64)
        indices = np.empty(max(n, 1), dtype=np.int32)[:n]
        while True:
            T = 1 << tbits
            slots = np.full(T, -1, dtype=np.int32)
            d = self._intern(buf.ctypes.data, buf.size,
                             offs.ctypes.data, n,
                             slots.ctypes.data, T - 1, tbits,
                             firsts.ctypes.data, max_d,
                             indices.ctypes.data)
            if d != -1 or tbits >= max_tbits:
                break
            tbits += 1
        if d == -2:
            return TOO_MANY_DISTINCT
        if d == -3:
            raise ValueError("byte column offsets out of bounds")
        if d < 0:
            raise ValueError(f"intern failed (rc={d})")
        return firsts[:d].copy(), indices


_snappy_inst: "NativeSnappy | None" = None
_hybrid_inst: "NativeHybrid | None" = None
_PLANE_UNAVAILABLE = object()  # cached stale-.so miss (see plane_native)
_plane_inst = None
_DELTA_UNAVAILABLE = object()
_delta_inst = None
_PACK_UNAVAILABLE = object()
_pack_inst = None
_INTERN_UNAVAILABLE = object()
_intern_inst = None
_PAGE_UNAVAILABLE = object()
_page_inst = None
_LZ4_UNAVAILABLE = object()
_lz4_inst = None


def snappy_native() -> NativeSnappy | None:
    """The process-wide native snappy codec, or None if unbuildable."""
    global _snappy_inst
    lib = _lib()
    if lib is None:
        return None
    if _snappy_inst is None:
        _snappy_inst = NativeSnappy(lib)
    return _snappy_inst


def hybrid_native() -> NativeHybrid | None:
    """The process-wide native hybrid scanner, or None if unbuildable."""
    global _hybrid_inst
    lib = _lib()
    if lib is None:
        return None
    if _hybrid_inst is None:
        _hybrid_inst = NativeHybrid(lib)
    return _hybrid_inst


def delta_native() -> NativeDelta | None:
    """The process-wide delta block scanner, or None if unbuildable."""
    global _delta_inst
    if _delta_inst is not None:
        return None if _delta_inst is _DELTA_UNAVAILABLE else _delta_inst
    lib = _lib()
    if lib is None:
        return None
    try:
        _delta_inst = NativeDelta(lib)
    except RuntimeError:  # stale .so predating delta.c: cache the miss
        _delta_inst = _DELTA_UNAVAILABLE
        from ..stats import current_stats

        st = current_stats()
        if st is not None:
            st.native_fallbacks += 1
        return None
    return _delta_inst


def pack_native() -> NativePack | None:
    """The process-wide packing primitives, or None if unbuildable."""
    global _pack_inst
    if _pack_inst is not None:
        return None if _pack_inst is _PACK_UNAVAILABLE else _pack_inst
    lib = _lib()
    if lib is None:
        return None
    try:
        _pack_inst = NativePack(lib)
    except RuntimeError:  # stale .so predating pack.c: cache the miss
        _pack_inst = _PACK_UNAVAILABLE
        from ..stats import current_stats

        st = current_stats()
        if st is not None:
            st.native_fallbacks += 1
        return None
    return _pack_inst


def intern_native() -> NativeIntern | None:
    """The process-wide byte interner, or None if unbuildable."""
    global _intern_inst
    if _intern_inst is not None:
        return None if _intern_inst is _INTERN_UNAVAILABLE \
            else _intern_inst
    lib = _lib()
    if lib is None:
        return None
    try:
        _intern_inst = NativeIntern(lib)
    except RuntimeError:  # stale .so predating intern.c: cache the miss
        _intern_inst = _INTERN_UNAVAILABLE
        from ..stats import current_stats

        st = current_stats()
        if st is not None:
            st.native_fallbacks += 1
        return None
    return _intern_inst


def page_native() -> NativePage | None:
    """The process-wide page assembler, or None if unbuildable."""
    global _page_inst
    if _page_inst is not None:
        return None if _page_inst is _PAGE_UNAVAILABLE else _page_inst
    lib = _lib()
    if lib is None:
        return None
    try:
        _page_inst = NativePage(lib)
    except RuntimeError:  # stale .so predating page.c: cache the miss
        _page_inst = _PAGE_UNAVAILABLE
        from ..stats import current_stats

        st = current_stats()
        if st is not None:
            st.native_fallbacks += 1
        return None
    return _page_inst


def lz4_native() -> NativeLz4 | None:
    """The process-wide native LZ4 raw-block codec, or None if
    unbuildable."""
    global _lz4_inst
    if _lz4_inst is not None:
        return None if _lz4_inst is _LZ4_UNAVAILABLE else _lz4_inst
    lib = _lib()
    if lib is None:
        return None
    try:
        _lz4_inst = NativeLz4(lib)
    except RuntimeError:  # stale .so predating lz4raw.c: cache the miss
        _lz4_inst = _LZ4_UNAVAILABLE
        from ..stats import current_stats

        st = current_stats()
        if st is not None:
            st.native_fallbacks += 1
        return None
    return _lz4_inst


def plane_native() -> NativePlane | None:
    """The process-wide plane primitives, or None if unbuildable."""
    global _plane_inst
    if _plane_inst is not None:
        return None if _plane_inst is _PLANE_UNAVAILABLE else _plane_inst
    lib = _lib()
    if lib is None:
        return None
    try:
        _plane_inst = NativePlane(lib)
    except RuntimeError:  # stale .so predating plane.c: cache the miss
        _plane_inst = _PLANE_UNAVAILABLE
        from ..stats import current_stats

        st = current_stats()
        if st is not None:
            st.native_fallbacks += 1
        return None
    return _plane_inst
