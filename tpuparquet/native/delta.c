/* DELTA_BINARY_PACKED block-header scanner.
 *
 * The structure pass (cpu/delta.py scan_delta_structure) walks
 * min_delta zigzag varints and per-miniblock width bytes — a Python
 * while-loop costing ~6 us per 128-value block, which dominates the
 * device planner and the CPU oracle at tens of millions of values.
 * This is the same one-pass scan in C; the Python wrapper reads and
 * validates the four stream-header varints first, so this function
 * starts at the first block and the caller can size the output arrays.
 *
 * Return codes mirror the Python error taxonomy:
 *   0 ok,  -1 truncated varint,  -5 truncated width list,
 *  -6 width > max_width,  -7 truncated payload,
 *  -8 output cap exceeded (caller bug),  -9 varint value out of range.
 */

#include <stddef.h>
#include <stdint.h>

static int read_uvarint64(const uint8_t *d, long long len, long long *pos,
                          uint64_t *out) {
    unsigned __int128 v = 0;
    int shift = 0;
    for (;;) {
        if (*pos >= len)
            return -1;
        uint8_t b = d[(*pos)++];
        v |= (unsigned __int128)(b & 0x7F) << shift;
        if (!(b & 0x80))
            break;
        shift += 7;
        if (shift > 70)
            return -1;
    }
    if (v > (unsigned __int128)UINT64_MAX)
        return -9;
    *out = (uint64_t)v;
    return 0;
}

/* Gather k fixed-size segments (miniblock payloads) from src into one
 * contiguous buffer — the numpy formulation concatenates one Python
 * slice per miniblock (tens of thousands per chunk). */
long long tpq_gather_segments(const uint8_t *src, long long src_len,
                              const int64_t *pos, long long k,
                              long long nbytes, uint8_t *out) {
    for (long long i = 0; i < k; i++) {
        if (pos[i] < 0 || pos[i] + nbytes > src_len)
            return -1;
        __builtin_memcpy(out + i * nbytes, src + pos[i], (size_t)nbytes);
    }
    return 0;
}

/* Scan count PLAIN BYTE_ARRAY records (u32-LE length prefix + bytes):
 * emits each value's payload position and the cumulative offsets.
 * Returns 0, or -1 truncated prefix / -2 length out of bounds with
 * *err_index the offending value and *err_len its claimed length. */
long long tpq_byte_array_scan(const uint8_t *buf, long long n,
                              long long count, int64_t *positions,
                              int64_t *offsets, long long *err_index,
                              long long *err_len) {
    if (count < 0)
        return -3;
    long long pos = 0, total = 0;
    offsets[0] = 0;
    for (long long i = 0; i < count; i++) {
        if (pos + 4 > n) {
            *err_index = i;
            return -1;
        }
        uint32_t ln;
        __builtin_memcpy(&ln, buf + pos, 4);
        pos += 4;
        if ((long long)ln > n - pos) {
            *err_index = i;
            *err_len = (long long)ln;
            return -2;
        }
        positions[i] = pos;
        total += (long long)ln;
        offsets[i + 1] = total;
        pos += (long long)ln;
    }
    return 0;
}

/* Emit count PLAIN BYTE_ARRAY records (u32-LE length prefix + bytes)
 * from a ByteArrayColumn's offsets + contiguous data — the encode twin
 * of tpq_byte_array_scan.  out must hold 4*count + data length. */
long long tpq_byte_array_emit(const uint8_t *data, long long data_len,
                              const int64_t *offsets, long long count,
                              uint8_t *out) {
    long long o = 0;
    for (long long i = 0; i < count; i++) {
        long long L = offsets[i + 1] - offsets[i];
        /* bounds-check against the data buffer: an inconsistent
         * ByteArrayColumn must not copy adjacent heap bytes into the
         * file */
        if (L < 0 || L > 0xFFFFFFFFLL || offsets[i] < 0
            || offsets[i] + L > data_len)
            return -1;
        uint32_t ln = (uint32_t)L;
        __builtin_memcpy(out + o, &ln, 4);
        o += 4;
        __builtin_memcpy(out + o, data + offsets[i], (size_t)L);
        o += L;
    }
    return 0;
}

/* Gather n variable-length segments into one contiguous buffer —
 * the byte-array dictionary gather (one memcpy per value instead of
 * numpy arange/repeat position temporaries). */
long long tpq_gather_var(const uint8_t *src, long long src_len,
                         const int64_t *start, const int64_t *lens,
                         long long n, uint8_t *out, long long out_len) {
    long long o = 0;
    for (long long i = 0; i < n; i++) {
        long long L = lens[i];
        if (L < 0 || start[i] < 0 || start[i] + L > src_len
            || o + L > out_len)
            return -1;
        __builtin_memcpy(out + o, src + start[i], (size_t)L);
        o += L;
    }
    return 0;
}

/* Front-coded DELTA_BYTE_ARRAY reconstruction: value i = the first
 * prefix_lens[i] bytes of value i-1 (from the OUTPUT, inherently
 * sequential) + its suffix bytes.  out_offsets are precomputed
 * cumulative total lengths (count+1 entries).  Returns 0, or -1 first
 * prefix nonzero / -2 prefix longer than the previous value, with
 * *err_index set. */
long long tpq_dba_assemble(const int64_t *prefix_lens,
                           const int64_t *suffix_offs,
                           const uint8_t *suffix_data,
                           long long suffix_len,
                           const int64_t *out_offsets, long long count,
                           uint8_t *out, long long *err_index) {
    long long prev_start = 0, prev_len = 0;
    for (long long i = 0; i < count; i++) {
        long long start = out_offsets[i];
        long long plen = prefix_lens[i];
        if (i == 0 && plen != 0) {
            *err_index = i;
            return -1;
        }
        if (plen < 0 || plen > prev_len) {
            *err_index = i;
            return -2;
        }
        long long slen = suffix_offs[i + 1] - suffix_offs[i];
        if (slen < 0 || suffix_offs[i] < 0
            || suffix_offs[i] + slen > suffix_len
            || start + plen + slen != out_offsets[i + 1]) {
            *err_index = i;
            return -3;
        }
        if (plen)
            __builtin_memcpy(out + start, out + prev_start,
                             (size_t)plen);
        __builtin_memcpy(out + start + plen,
                         suffix_data + suffix_offs[i], (size_t)slen);
        prev_start = start;
        prev_len = plen + slen;
    }
    return 0;
}

long long tpq_delta_scan_blocks(
    const uint8_t *data, long long data_len, long long pos,
    long long n_deltas, long long mb_size, long long n_miniblocks,
    int max_width,
    int64_t *md_blocks, int32_t *mb_w, int64_t *mb_pos,
    int64_t *mb_start, long long cap_blocks, long long cap_mb,
    long long *n_blocks_out, long long *n_mb_out,
    long long *end_pos_out) {
    long long got = 0, nb = 0, nm = 0;
    while (got < n_deltas) {
        uint64_t u;
        int rc = read_uvarint64(data, data_len, &pos, &u);
        if (rc)
            return rc;
        /* zigzag decode; the wrap is int64 two's complement */
        int64_t min_delta = (int64_t)(u >> 1) ^ -(int64_t)(u & 1);
        if (nb >= cap_blocks)
            return -8;
        md_blocks[nb++] = min_delta;
        if (pos + n_miniblocks > data_len)
            return -5;
        const uint8_t *widths = data + pos;
        pos += n_miniblocks;
        for (long long i = 0; i < n_miniblocks; i++) {
            if (got >= n_deltas)
                break;
            int w = widths[i];
            if (w > max_width)
                return -6;
            long long nbytes = mb_size * w / 8;
            if (pos + nbytes > data_len)
                return -7;
            if (w) {
                if (nm >= cap_mb)
                    return -8;
                mb_w[nm] = w;
                mb_pos[nm] = pos;
                mb_start[nm] = got;
                nm++;
            }
            pos += nbytes;
            got += mb_size;
        }
    }
    *n_blocks_out = nb;
    *n_mb_out = nm;
    *end_pos_out = pos;
    return 0;
}

/* Full DELTA_BINARY_PACKED decode from a scanned structure (the
 * miniblock table tpq_delta_scan_blocks emits): unpack every recorded
 * miniblock's w-bit LSB-first deltas, add the per-block min_delta,
 * prefix-sum from first.  One GIL-releasing C pass replacing the
 * numpy formulation (per-width gather + unpack + astype + repeat +
 * cumsum — five full-size temporaries and ~70% of the config-3 CPU
 * decode wall).  All arithmetic is uint64 two's-complement wrap,
 * byte-exact with the numpy path; out holds total values.
 * Returns 0, or -7 when a miniblock payload overruns data (the scan
 * already rejects this; defensive). */
long long tpq_delta_decode(
    const uint8_t *data, long long data_len,
    const int64_t *md_blocks, long long n_blocks,
    const int32_t *mb_w, const int64_t *mb_pos, const int64_t *mb_start,
    long long n_mb, long long mb_size, long long block_size,
    long long total, uint64_t first, uint64_t *out) {
    if (total <= 0)
        return 0;
    long long n_deltas = total - 1;
    __builtin_memset(out + 1, 0, (size_t)n_deltas * 8);
    for (long long m = 0; m < n_mb; m++) {
        int w = mb_w[m];
        long long pos = mb_pos[m];
        long long nbytes = mb_size * w / 8;
        if (w <= 0 || w > 64 || pos < 0 || pos + nbytes > data_len
            || mb_start[m] < 0 || mb_start[m] >= n_deltas)
            return -7;
        long long take = n_deltas - mb_start[m];
        if (take > mb_size)
            take = mb_size;
        const uint8_t *p = data + pos;
        uint64_t *dst = out + 1 + mb_start[m];
        uint64_t mask = (w == 64) ? ~(uint64_t)0
                                  : (((uint64_t)1 << w) - 1);
        /* speculative 16-byte loads need headroom past the last value's
         * final byte; values near data_len take the byte-wise path */
        long long fast = ((data_len - pos) - 16) * 8 / w;
        if (fast > take)
            fast = take;
        if (fast < 0)
            fast = 0;
        long long j = 0;
        for (; j < fast; j++) {
            long long bit = j * (long long)w;
            unsigned __int128 v;
            __builtin_memcpy(&v, p + (bit >> 3), 16);
            dst[j] = (uint64_t)(v >> (bit & 7)) & mask;
        }
        for (; j < take; j++) {
            long long bit = j * (long long)w;
            long long byte = bit >> 3;
            int shift = (int)(bit & 7);
            int need = (shift + w + 7) >> 3;
            unsigned __int128 acc = 0;
            for (int k = 0; k < need; k++)
                acc |= (unsigned __int128)p[byte + k] << (8 * k);
            dst[j] = (uint64_t)(acc >> shift) & mask;
        }
    }
    uint64_t acc = first;
    out[0] = acc;
    long long i = 0;
    for (long long b = 0; b < n_blocks && i < n_deltas; b++) {
        uint64_t md = (uint64_t)md_blocks[b];
        long long lim = i + block_size;
        if (lim > n_deltas)
            lim = n_deltas;
        for (; i < lim; i++) {
            acc += md + out[1 + i];
            out[1 + i] = acc;
        }
    }
    for (; i < n_deltas; i++) {   /* deltas past the declared blocks */
        acc += out[1 + i];
        out[1 + i] = acc;
    }
    return 0;
}
