/* LSB-first bit packing primitives.
 *
 * tpq_pack64 is the native core of cpu/bitpack.pack (the numpy
 * formulation explodes every value into a byte-per-bit matrix — ~68 ms
 * per million values).  tpq_hybrid_repack fuses the level/index stream
 * re-pack (kernels/hybrid.py plan_stream_args): a mixed-run hybrid
 * stream whose run table would out-weigh plain bits goes straight from
 * the run table to one bit-packed run, without materializing the
 * expanded values the numpy path needed (expand_scan + pack were the
 * planner's hottest functions at bench scale).
 *
 * Both writers keep a u64 accumulator and flush whole 64-bit words
 * (one unaligned store per 64 output bits); at most one value straddles
 * a flush, recovered with a single shift.
 */

#include <stddef.h>
#include <stdint.h>
#include <string.h>

/* Shared word-writer core: pack count width-bit values (already known
 * to fit) LSB-first at out, which must have 8 bytes of slack past the
 * exact (count*width + 7)/8 payload.  Returns the exact payload
 * length.  The accumulator flushes whole 64-bit words; at most one
 * value straddles a flush, recovered with a single shift. */
static inline long long pack_words(const uint64_t *v, long long count,
                                   int width, uint8_t *out) {
    uint64_t acc = 0;
    int nbits = 0;
    long long o = 0;
    for (long long i = 0; i < count; i++) {
        acc |= nbits < 64 ? v[i] << nbits : 0;
        nbits += width;
        if (nbits >= 64) {
            __builtin_memcpy(out + o, &acc, 8);
            o += 8;
            nbits -= 64;
            /* bits of v[i] that did not fit (0 when the flush landed
             * exactly on a value boundary) */
            acc = nbits ? v[i] >> (width - nbits) : 0;
        }
    }
    if (nbits > 0)
        __builtin_memcpy(out + o, &acc, 8); /* slack covers the tail */
    return (count * (long long)width + 7) / 8;
}

/* Pack count LSB-first width-bit values from a contiguous u64 array.
 * out must hold (count*width + 7)/8 + 8 bytes (8 slack for the word
 * writer; the caller slices to the exact length).  Returns 0, or -1 if
 * a value does not fit in width bits (silent truncation would corrupt
 * the stream). */
long long tpq_pack64(const uint64_t *v, long long count, int width,
                     uint8_t *out) {
    if (width <= 0 || width > 64)
        return -2;
    const uint64_t lim_mask =
        width >= 64 ? 0 : ~((uint64_t)0) << width; /* high bits set */
    for (long long i = 0; i < count; i++)
        if (v[i] & lim_mask)
            return -1;
    pack_words(v, count, width, out);
    return 0;
}

static inline long long emit_uvarint(uint8_t *out, long long o,
                                     uint64_t v) {
    while (v >= 0x80) {
        out[o++] = (uint8_t)(v | 0x80);
        v >>= 7;
    }
    out[o++] = (uint8_t)v;
    return o;
}

/* Emit the block body of a DELTA_BINARY_PACKED stream: per block a
 * zigzag-varint min_delta, the miniblock width bytes, then each
 * non-zero-width miniblock's LSB-first packed payload — the assembly
 * loop that ran per block in Python.  adj is the (n_mb * mb_size)
 * min_delta-adjusted matrix (padding lanes zero), widths one byte per
 * miniblock.  Returns 0 and *out_len, or -1 if cap would overflow. */
long long tpq_delta_emit(const uint64_t *adj, const uint8_t *widths,
                         long long n_mb, long long mb_size,
                         const int64_t *min_deltas, long long n_blocks,
                         long long n_miniblocks, uint8_t *out,
                         long long cap, long long *out_len) {
    long long o = 0;
    for (long long b = 0; b < n_blocks; b++) {
        if (o + 10 + n_miniblocks > cap)
            return -1;
        uint64_t u = (uint64_t)min_deltas[b];
        o = emit_uvarint(out, o, (u << 1) ^ (uint64_t)(min_deltas[b] >> 63));
        for (long long m = 0; m < n_miniblocks; m++) {
            long long mb = b * n_miniblocks + m;
            out[o++] = mb < n_mb ? widths[mb] : 0;
        }
        for (long long m = 0; m < n_miniblocks; m++) {
            long long mb = b * n_miniblocks + m;
            if (mb >= n_mb)
                continue;
            int width = widths[mb];
            if (width == 0)
                continue;
            long long nbytes = mb_size * width / 8;
            if (o + nbytes + 8 > cap)
                return -1;
            o += pack_words(adj + mb * mb_size, mb_size, width, out + o);
        }
    }
    *out_len = o;
    return 0;
}

/* Emit one bit-packed region (header + 8-value groups, zero-padded
 * tail group): shared by the mid-stream and end-of-stream flushes of
 * tpq_hybrid_encode.  Returns the new offset, or -1 when cap would
 * overflow. */
static long long emit_bp_region(const uint64_t *v, long long bp_n,
                                int width, uint8_t *out, long long cap,
                                long long o) {
    if (bp_n <= 0)
        return o;
    long long groups = (bp_n + 7) / 8;
    if (o + 10 + groups * width + 8 > cap)
        return -1;
    o = emit_uvarint(out, o, ((uint64_t)groups << 1) | 1);
    long long full = bp_n / 8 * 8;
    if (full)
        o += pack_words(v, full, width, out + o);
    if (bp_n > full) { /* zero-padded tail group */
        uint64_t tmp[8] = {0};
        for (long long k = 0; k < bp_n - full; k++)
            tmp[k] = v[full + k];
        o += pack_words(tmp, 8, width, out + o);
    }
    return o;
}

/* Hybrid RLE/BP encode: RLE for constant stretches >= 8, bit-packing
 * for the rest (8-value groups, zero-padded tail) — byte-identical to
 * cpu/hybrid.encode_hybrid, whose long-run loop ran in Python.  out
 * needs 8 bytes of slack past the worst case.  Returns 0 with
 * *out_len, -1 if a value exceeds width bits, -2 on bad width. */
long long tpq_hybrid_encode(const uint64_t *v, long long n, int width,
                            uint8_t *out, long long cap,
                            long long *out_len) {
    if (width <= 0 || width > 64)
        return -2;
    const uint64_t lim_mask =
        width >= 64 ? 0 : ~((uint64_t)0) << width;
    for (long long i = 0; i < n; i++)
        if (v[i] & lim_mask)
            return -1;
    const int vbytes = (width + 7) / 8;
    long long o = 0;
    long long pending = 0; /* start of the un-emitted bit-packed region */
    long long i = 0;
    while (i < n) {
        /* find the constant run starting at i */
        long long e = i + 1;
        while (e < n && v[e] == v[i])
            e++;
        if (e - i >= 8) { /* long run: flush pending BP, then RLE */
            long long flush_end = i;
            if ((flush_end - pending) % 8) {
                long long r = pending + ((i - pending + 7) / 8) * 8;
                flush_end = r < e ? r : e;
            }
            o = emit_bp_region(v + pending, flush_end - pending, width,
                               out, cap, o);
            if (o < 0)
                return -3;
            if (e - flush_end >= 1) {
                if (o + 10 + vbytes > cap)
                    return -3;
                o = emit_uvarint(out, o,
                                 (uint64_t)(e - flush_end) << 1);
                uint64_t x = v[i];
                for (int b = 0; b < vbytes; b++) {
                    out[o++] = (uint8_t)x;
                    x >>= 8;
                }
            }
            pending = e;
        }
        i = e;
    }
    o = emit_bp_region(v + pending, n - pending, width, out, cap, o);
    if (o < 0)
        return -3;
    *out_len = o;
    return 0;
}

static inline uint64_t load_bits(const uint8_t *bp, long long bp_len,
                                 long long bitpos, int width) {
    /* read width (<=32) bits at bitpos; safe at the tail */
    long long byte = bitpos >> 3;
    int shift = (int)(bitpos & 7);
    uint64_t w = 0;
    if (byte + 8 <= bp_len) {
        __builtin_memcpy(&w, bp + byte, 8);
    } else {
        for (int i = 0; byte + i < bp_len && i < 8; i++)
            w |= (uint64_t)bp[byte + i] << (8 * i);
    }
    w >>= shift;
    return w & (((uint64_t)1 << width) - 1);
}

/* Expand a hybrid RLE/BP run table to values — pass 2 of the two-pass
 * decode, one C pass instead of the numpy searchsorted-over-runs
 * formulation (the CPU oracle's hottest function on mixed-run level
 * and dict-index streams).  Clamp semantics mirror the numpy mixed
 * branch: the last run extends to count, bit-packed positions clamp to
 * the stream's final value.  width 1..32. */
long long tpq_hybrid_expand32(const int32_t *ends, const uint8_t *is_rle,
                              const uint32_t *value,
                              const int32_t *bp_start, long long n_runs,
                              const uint8_t *bp, long long bp_len,
                              long long n_bp, long long count, int width,
                              uint32_t *out) {
    if (width <= 0 || width > 32 || n_runs <= 0)
        return -2;
    long long o = 0;
    long long prev = 0;
    for (long long r = 0; r < n_runs && prev < count; r++) {
        long long end = (r == n_runs - 1) ? count : ends[r];
        if (end > count)
            end = count;
        if (end < prev)
            return -2;
        long long len = end - prev;
        if (is_rle[r]) {
            const uint32_t x = value[r];
            for (long long i = 0; i < len; i++)
                out[o++] = x;
        } else {
            long long lim = (n_bp > 0 ? n_bp - 1 : 0) * (long long)width;
            long long bit = (long long)bp_start[r] * width;
            for (long long i = 0; i < len; i++, bit += width)
                out[o++] = (uint32_t)load_bits(
                    bp, bp_len, bit > lim ? lim : bit, width);
        }
        prev = end;
    }
    while (o < count)
        out[o++] = 0; /* unreachable for valid scans (ends cover count) */
    return 0;
}

/* Re-pack a hybrid RLE/BP run table into ONE bit-packed run.
 * Run k covers value indices [ends[k-1], ends[k]); RLE runs repeat
 * value[k], bit-packed runs read consecutive width-bit values from the
 * concatenated bp stream starting at value index bp_start[k].  out
 * must hold (count*width + 7)/8 + 8 bytes (8 slack; caller slices).
 * width 1..32.  Returns 0, or -2 on a bad width / non-monotone
 * table. */
long long tpq_hybrid_repack(const int32_t *ends, const uint8_t *is_rle,
                            const uint32_t *value, const int32_t *bp_start,
                            long long n_runs, const uint8_t *bp,
                            long long bp_len, long long n_bp,
                            long long count, int width, uint8_t *out) {
    if (width <= 0 || width > 32 || n_runs <= 0)
        return -2;
    uint64_t acc = 0;
    int nbits = 0;
    long long o = 0;
    long long prev = 0;
    for (long long r = 0; r < n_runs && prev < count; r++) {
        /* the clamp mirrors the numpy expand (cpu/hybrid.expand_scan):
         * the LAST run extends to cover any values past the table's
         * final end, and bit-packed positions clamp to the stream's
         * last value */
        long long end = (r == n_runs - 1) ? count : ends[r];
        if (end > count)
            end = count;
        if (end < prev)
            return -2;
        long long len = end - prev;
        if (is_rle[r]) {
            const uint64_t x = value[r];
            if (width < 32 && (x >> width))
                return -1; /* would silently read back truncated */
            for (long long i = 0; i < len; i++) {
                acc |= x << nbits;
                nbits += width;
                if (nbits >= 64) {
                    __builtin_memcpy(out + o, &acc, 8);
                    o += 8;
                    nbits -= 64;
                    acc = nbits ? x >> (width - nbits) : 0;
                }
            }
        } else {
            long long lim = (n_bp > 0 ? n_bp - 1 : 0) * (long long)width;
            long long bit = (long long)bp_start[r] * width;
            for (long long i = 0; i < len; i++, bit += width) {
                uint64_t x = load_bits(bp, bp_len,
                                       bit > lim ? lim : bit, width);
                acc |= x << nbits;
                nbits += width;
                if (nbits >= 64) {
                    __builtin_memcpy(out + o, &acc, 8);
                    o += 8;
                    nbits -= 64;
                    acc = nbits ? x >> (width - nbits) : 0;
                }
            }
        }
        prev = end;
    }
    long long total = (count * width + 7) / 8;
    if (nbits > 0 && o < total)
        __builtin_memcpy(out + o, &acc, 8); /* slack covers the tail */
    return 0;
}
