/* Strided lane/byte-plane primitives for the device wire planner.
 *
 * The lane/byte-plane RLE transport (kernels/device.py _plan_plane_words)
 * decides per u32 lane of a PLAIN fixed-width values segment whether to
 * ship the lane as a whole-lane run table, per-byte-plane run tables, or
 * raw words.  The numpy formulation of the build phase costs several
 * passes per engaged lane (strided compare -> bool temp -> flatnonzero ->
 * fancy index); these helpers do each job in ONE branch-light pass over
 * the strided source so the plan thread — which the pipelined reader
 * overlaps with device transfers — stays ahead of the wire.
 *
 * Run-table semantics match kernels/device.py _rle_table: run k covers
 * [ends[k-1], ends[k]) (ends[-1] == 0 implied) with value vals[k]; the
 * final run's end equals count.  The caller bucket-pads.
 */

#include <stddef.h>
#include <stdint.h>

/* Scan a strided u32 stream for value runs.  Returns the run count, or
 * -1 when more than cap runs exist (caller ships the lane raw — the
 * table could not beat raw words anyway). */
long long tpq_run_scan32(const uint8_t *base, long long count,
                         long long stride, int32_t *ends, uint32_t *vals,
                         long long cap) {
    if (count <= 0 || cap <= 0)
        return -1;
    uint32_t cur;
    __builtin_memcpy(&cur, base, 4);
    long long n = 0;
    for (long long i = 1; i < count; i++) {
        uint32_t v;
        __builtin_memcpy(&v, base + i * stride, 4);
        if (v != cur) {
            if (n >= cap)
                return -1;
            ends[n] = (int32_t)i;
            vals[n] = cur;
            n++;
            cur = v;
        }
    }
    if (n >= cap)
        return -1;
    ends[n] = (int32_t)count;
    vals[n] = cur;
    return n + 1;
}

/* Same, for a strided byte plane. */
long long tpq_run_scan8(const uint8_t *base, long long count,
                        long long stride, int32_t *ends, uint8_t *vals,
                        long long cap) {
    if (count <= 0 || cap <= 0)
        return -1;
    uint8_t cur = base[0];
    long long n = 0;
    for (long long i = 1; i < count; i++) {
        uint8_t v = base[i * stride];
        if (v != cur) {
            if (n >= cap)
                return -1;
            ends[n] = (int32_t)i;
            vals[n] = cur;
            n++;
            cur = v;
        }
    }
    if (n >= cap)
        return -1;
    ends[n] = (int32_t)count;
    vals[n] = cur;
    return n + 1;
}

/* Gather a strided u32 lane into a contiguous buffer.  The stride-8
 * case (u32 lanes of int64/double columns) is written as a
 * low-word-of-u64 loop the compiler can turn into load+shuffle SIMD. */
void tpq_lane_gather32(const uint8_t *base, long long count,
                       long long stride, uint32_t *out) {
    if (stride == 8) {
        /* the widened load reads 8 bytes but only 4 belong to the last
         * element — stop one early so a lane whose base is offset into
         * the segment (lane 1 of an int64 column) never reads past the
         * caller's buffer (which may be a zero-copy view of the file or
         * an exactly-sized arena slab) */
        for (long long i = 0; i + 1 < count; i++) {
            uint64_t w;
            __builtin_memcpy(&w, base + i * 8, 8);
            out[i] = (uint32_t)w; /* little-endian low word */
        }
        if (count > 0)
            __builtin_memcpy(&out[count - 1], base + (count - 1) * 8, 4);
        return;
    }
    for (long long i = 0; i < count; i++)
        __builtin_memcpy(&out[i], base + i * stride, 4);
}

/* Gather a strided byte plane into a contiguous buffer. */
void tpq_lane_gather8(const uint8_t *base, long long count,
                      long long stride, uint8_t *out) {
    if (stride == 4) {
        for (long long i = 0; i + 1 < count; i++) {
            uint32_t w;
            __builtin_memcpy(&w, base + i * 4, 4);
            out[i] = (uint8_t)w;
        }
        if (count > 0)
            out[count - 1] = base[(count - 1) * 4];
        return;
    }
    if (stride == 8) {
        for (long long i = 0; i + 1 < count; i++) {
            uint64_t w;
            __builtin_memcpy(&w, base + i * 8, 8);
            out[i] = (uint8_t)w;
        }
        if (count > 0)
            out[count - 1] = base[(count - 1) * 8];
        return;
    }
    for (long long i = 0; i < count; i++)
        out[i] = base[i * stride];
}
