/* RLE/bit-packed hybrid stream scanner.
 *
 * Parses the uvarint-chained run headers of a Parquet hybrid stream
 * (levels, dictionary indices, boolean RLE) into a flat run table plus
 * the concatenated bit-packed segment bytes.  This is the host-side
 * "pass 1" of the two-pass decode: the run table is metadata-sized, and
 * both the CPU oracle (vectorized numpy expand) and the TPU kernels
 * (device expand) consume it.  Replaces a per-run Python loop that
 * dominated decode profiles on streams with thousands of runs.
 */

#include <stddef.h>
#include <stdint.h>
#include <string.h>

#define TPQ_OK 0
#define TPQ_ERR_TRUNCATED (-1)
#define TPQ_ERR_ZERO_RLE (-2)
#define TPQ_ERR_RUN_CAP (-3)
#define TPQ_ERR_BP_CAP (-4)
#define TPQ_ERR_WIDTH (-5)
#define TPQ_ERR_VALUE (-6)

/* Read one unsigned LEB128 varint; returns new position or 0 on error. */
static size_t read_uvarint(const uint8_t *buf, size_t len, size_t pos,
                           uint64_t *out) {
  uint64_t v = 0;
  int shift = 0;
  while (pos < len && shift < 64) {
    uint8_t b = buf[pos++];
    v |= (uint64_t)(b & 0x7f) << shift;
    if (!(b & 0x80)) {
      *out = v;
      return pos;
    }
    shift += 7;
  }
  return 0;
}

int tpq_hybrid_scan(const uint8_t *buf, size_t buflen, size_t pos,
                    int64_t count, int width,
                    int32_t *run_ends, uint8_t *run_is_rle,
                    uint32_t *run_value, int32_t *run_bp_start,
                    int64_t cap_runs, uint8_t *bp_out, size_t bp_cap,
                    int64_t *n_runs, int64_t *n_bp_values,
                    size_t *bp_len, size_t *end_pos) {
  if (width < 0 || width > 32) return TPQ_ERR_WIDTH;
  size_t vbytes = (size_t)(width + 7) / 8;
  uint32_t vmask =
      width >= 32 ? 0xffffffffu : ((1u << width) - 1u);
  int64_t filled = 0, runs = 0, bp_values = 0;
  size_t bp_used = 0;

  while (filled < count) {
    uint64_t h;
    size_t np = read_uvarint(buf, buflen, pos, &h);
    if (np == 0) return TPQ_ERR_TRUNCATED;
    pos = np;
    if (runs >= cap_runs) return TPQ_ERR_RUN_CAP;
    /* A 9-byte varint header can encode group counts whose value count
     * would overflow int64 arithmetic; any such run is necessarily
     * longer than the buffer, so reject it up front. */
    if ((h >> 1) > ((uint64_t)1 << 40)) return TPQ_ERR_TRUNCATED;
    if (h & 1) {
      int64_t n = (int64_t)(h >> 1) * 8;
      size_t nbytes = ((size_t)n * (size_t)width + 7) / 8;
      if (pos + nbytes > buflen) return TPQ_ERR_TRUNCATED;
      if (bp_used + nbytes > bp_cap) return TPQ_ERR_BP_CAP;
      memcpy(bp_out + bp_used, buf + pos, nbytes);
      bp_used += nbytes;
      pos += nbytes;
      run_is_rle[runs] = 0;
      run_value[runs] = 0;
      run_bp_start[runs] = (int32_t)bp_values;
      int64_t take = n < count - filled ? n : count - filled;
      bp_values += n; /* full groups stay; consumers index via bp_start */
      filled += take;
    } else {
      int64_t n = (int64_t)(h >> 1);
      if (n == 0) return TPQ_ERR_ZERO_RLE;
      if (pos + vbytes > buflen) return TPQ_ERR_TRUNCATED;
      uint32_t v = 0;
      for (size_t i = 0; i < vbytes; i++)
        v |= (uint32_t)buf[pos + i] << (8 * i);
      pos += vbytes;
      if (v & ~vmask) return TPQ_ERR_VALUE; /* corrupt: exceeds width */
      run_is_rle[runs] = 1;
      run_value[runs] = v;
      run_bp_start[runs] = (int32_t)bp_values;
      int64_t take = n < count - filled ? n : count - filled;
      filled += take;
    }
    run_ends[runs++] = (int32_t)filled;
  }
  *n_runs = runs;
  *n_bp_values = bp_values;
  *bp_len = bp_used;
  *end_pos = pos;
  return TPQ_OK;
}
