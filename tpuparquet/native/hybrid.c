/* RLE/bit-packed hybrid stream scanner.
 *
 * Parses the uvarint-chained run headers of a Parquet hybrid stream
 * (levels, dictionary indices, boolean RLE) into a flat run table plus
 * the concatenated bit-packed segment bytes.  This is the host-side
 * "pass 1" of the two-pass decode: the run table is metadata-sized, and
 * both the CPU oracle (vectorized numpy expand) and the TPU kernels
 * (device expand) consume it.  Replaces a per-run Python loop that
 * dominated decode profiles on streams with thousands of runs.
 */

#include <stddef.h>
#include <stdint.h>
#include <string.h>

#define TPQ_OK 0
#define TPQ_ERR_TRUNCATED (-1)
#define TPQ_ERR_ZERO_RLE (-2)
#define TPQ_ERR_RUN_CAP (-3)
#define TPQ_ERR_BP_CAP (-4)
#define TPQ_ERR_WIDTH (-5)
#define TPQ_ERR_VALUE (-6)

/* Read one unsigned LEB128 varint; returns new position or 0 on error. */
static size_t read_uvarint(const uint8_t *buf, size_t len, size_t pos,
                           uint64_t *out) {
  uint64_t v = 0;
  int shift = 0;
  while (pos < len && shift < 64) {
    uint8_t b = buf[pos++];
    v |= (uint64_t)(b & 0x7f) << shift;
    if (!(b & 0x80)) {
      *out = v;
      return pos;
    }
    shift += 7;
  }
  return 0;
}

int tpq_hybrid_scan(const uint8_t *buf, size_t buflen, size_t pos,
                    int64_t count, int width,
                    int32_t *run_ends, uint8_t *run_is_rle,
                    uint32_t *run_value, int32_t *run_bp_start,
                    int64_t cap_runs, uint8_t *bp_out, size_t bp_cap,
                    int64_t *n_runs, int64_t *n_bp_values,
                    size_t *bp_len, size_t *end_pos) {
  if (width < 0 || width > 32) return TPQ_ERR_WIDTH;
  size_t vbytes = (size_t)(width + 7) / 8;
  uint32_t vmask =
      width >= 32 ? 0xffffffffu : ((1u << width) - 1u);
  int64_t filled = 0, runs = 0, bp_values = 0;
  size_t bp_used = 0;

  while (filled < count) {
    uint64_t h;
    size_t np = read_uvarint(buf, buflen, pos, &h);
    if (np == 0) return TPQ_ERR_TRUNCATED;
    pos = np;
    if (runs >= cap_runs) return TPQ_ERR_RUN_CAP;
    /* A 9-byte varint header can encode group counts whose value count
     * would overflow int64 arithmetic; any such run is necessarily
     * longer than the buffer, so reject it up front. */
    if ((h >> 1) > ((uint64_t)1 << 40)) return TPQ_ERR_TRUNCATED;
    if (h & 1) {
      int64_t n = (int64_t)(h >> 1) * 8;
      size_t nbytes = ((size_t)n * (size_t)width + 7) / 8;
      if (pos + nbytes > buflen) return TPQ_ERR_TRUNCATED;
      if (bp_used + nbytes > bp_cap) return TPQ_ERR_BP_CAP;
      memcpy(bp_out + bp_used, buf + pos, nbytes);
      bp_used += nbytes;
      pos += nbytes;
      run_is_rle[runs] = 0;
      run_value[runs] = 0;
      run_bp_start[runs] = (int32_t)bp_values;
      int64_t take = n < count - filled ? n : count - filled;
      bp_values += n; /* full groups stay; consumers index via bp_start */
      filled += take;
    } else {
      int64_t n = (int64_t)(h >> 1);
      if (n == 0) return TPQ_ERR_ZERO_RLE;
      if (pos + vbytes > buflen) return TPQ_ERR_TRUNCATED;
      uint32_t v = 0;
      for (size_t i = 0; i < vbytes; i++)
        v |= (uint32_t)buf[pos + i] << (8 * i);
      pos += vbytes;
      if (v & ~vmask) return TPQ_ERR_VALUE; /* corrupt: exceeds width */
      run_is_rle[runs] = 1;
      run_value[runs] = v;
      run_bp_start[runs] = (int32_t)bp_values;
      int64_t take = n < count - filled ? n : count - filled;
      filled += take;
    }
    run_ends[runs++] = (int32_t)filled;
  }
  *n_runs = runs;
  *n_bp_values = bp_values;
  *bp_len = bp_used;
  *end_pos = pos;
  return TPQ_OK;
}

/* ------------------------------------------------------------------ */
/* Hybrid RLE/BP ENCODER (u32 input) — the write-side mirror of the
 * scanner above.  Byte-identical to cpu/hybrid.encode_hybrid and to
 * pack.c's u64 tpq_hybrid_encode, but takes the uint32 arrays the
 * write path actually holds (dictionary indices, levels), so the
 * encode no longer pays a full u64-widening copy per page.           */
/* ------------------------------------------------------------------ */

static long long emit_uvarint32(uint8_t *out, long long o, uint64_t v) {
  while (v >= 0x80) {
    out[o++] = (uint8_t)(v | 0x80);
    v >>= 7;
  }
  out[o++] = (uint8_t)v;
  return o;
}

/* Pack count width-bit u32 values LSB-first at out (8 bytes slack past
 * the exact payload); returns the exact payload length.  Same word-
 * accumulator scheme as pack.c's pack_words. */
static long long pack_words32(const uint32_t *v, long long count,
                              int width, uint8_t *out) {
  uint64_t acc = 0;
  int nbits = 0;
  long long o = 0;
  for (long long i = 0; i < count; i++) {
    acc |= nbits < 64 ? (uint64_t)v[i] << nbits : 0;
    nbits += width;
    if (nbits >= 64) {
      memcpy(out + o, &acc, 8);
      o += 8;
      nbits -= 64;
      acc = nbits ? (uint64_t)v[i] >> (width - nbits) : 0;
    }
  }
  if (nbits > 0)
    memcpy(out + o, &acc, 8); /* slack covers the tail */
  return (count * (long long)width + 7) / 8;
}

/* One bit-packed region (header + 8-value groups, zero-padded tail),
 * shared by the mid-stream and final flushes.  Returns the new offset,
 * or -1 when cap would overflow. */
static long long emit_bp_region32(const uint32_t *v, long long bp_n,
                                  int width, uint8_t *out, long long cap,
                                  long long o) {
  if (bp_n <= 0)
    return o;
  long long groups = (bp_n + 7) / 8;
  if (o + 10 + groups * width + 8 > cap)
    return -1;
  o = emit_uvarint32(out, o, ((uint64_t)groups << 1) | 1);
  long long full = bp_n / 8 * 8;
  if (full)
    o += pack_words32(v, full, width, out + o);
  if (bp_n > full) { /* zero-padded tail group */
    uint32_t tmp[8] = {0};
    for (long long k = 0; k < bp_n - full; k++)
      tmp[k] = v[full + k];
    o += pack_words32(tmp, 8, width, out + o);
  }
  return o;
}

/* Hybrid RLE/BP encode from u32 values: RLE for constant stretches
 * >= 8, bit-packing (8-value groups, zero-padded tail) for the rest —
 * byte-identical to the Python encoder and pack.c's u64 variant.  out
 * needs 8 bytes of slack past the worst case.  Returns 0 with
 * *out_len, -1 if a value exceeds width bits, -2 on bad width, -3 on
 * cap overflow. */
long long tpq_hybrid_encode32(const uint32_t *v, long long n, int width,
                              uint8_t *out, long long cap,
                              long long *out_len) {
  if (width <= 0 || width > 32)
    return -2;
  const uint32_t lim_mask =
      width >= 32 ? 0 : ~((uint32_t)0) << width; /* high bits set */
  for (long long i = 0; i < n; i++)
    if (v[i] & lim_mask)
      return -1;
  const int vbytes = (width + 7) / 8;
  long long o = 0;
  long long pending = 0; /* start of the un-emitted bit-packed region */
  long long i = 0;
  while (i < n) {
    /* find the constant run starting at i */
    long long e = i + 1;
    while (e < n && v[e] == v[i])
      e++;
    if (e - i >= 8) { /* long run: flush pending BP, then RLE */
      long long flush_end = i;
      if ((flush_end - pending) % 8) {
        long long r = pending + ((i - pending + 7) / 8) * 8;
        flush_end = r < e ? r : e;
      }
      o = emit_bp_region32(v + pending, flush_end - pending, width, out,
                           cap, o);
      if (o < 0)
        return -3;
      if (e - flush_end >= 1) {
        if (o + 10 + vbytes > cap)
          return -3;
        o = emit_uvarint32(out, o, (uint64_t)(e - flush_end) << 1);
        uint32_t x = v[i];
        for (int b = 0; b < vbytes; b++) {
          out[o++] = (uint8_t)x;
          x >>= 8;
        }
      }
      pending = e;
    }
    i = e;
  }
  o = emit_bp_region32(v + pending, n - pending, width, out, cap, o);
  if (o < 0)
    return -3;
  *out_len = o;
  return 0;
}

/* Unpack value i (LSB-first within bytes) from a width-bit stream.
 * Caller guarantees the value's bits lie within bp_len bytes. */
static inline uint32_t bp_get(const uint8_t *bp, size_t bp_len, int64_t i,
                              int width, uint32_t vmask) {
  uint64_t bit = (uint64_t)i * (uint64_t)width;
  size_t byte = (size_t)(bit >> 3);
  int shift = (int)(bit & 7);
  uint64_t w;
  if (byte + 8 <= bp_len) {
    memcpy(&w, bp + byte, 8); /* single unaligned load (little-endian) */
  } else {
    w = 0;
    for (size_t k = 0; byte + k < bp_len && k < 8; k++)
      w |= (uint64_t)bp[byte + k] << (8 * k);
  }
  return (uint32_t)(w >> shift) & vmask;
}

/* Aggregate statistics over the CONSUMED lanes of bit-packed segments:
 * max value and count of lanes equal to `target`.  Segments are
 * (start, len) pairs in value positions within the concatenated
 * bit-packed stream (the run table's bp_start column); per-run
 * 8-group padding lanes are skipped by construction.  One pass at C
 * speed replaces a numpy unpack + scatter + cumsum per stream. */
int tpq_bp_stats(const uint8_t *bp, size_t bp_len, int width,
                 const int64_t *starts, const int64_t *lens,
                 int64_t n_segs, uint32_t target,
                 uint32_t *out_max, int64_t *out_count_eq) {
  if (width < 0 || width > 32) return TPQ_ERR_WIDTH;
  uint32_t vmask = width >= 32 ? 0xffffffffu : ((1u << width) - 1u);
  uint32_t mx = 0;
  int64_t cnt = 0;
  int seen = 0;
  for (int64_t s = 0; s < n_segs; s++) {
    int64_t start = starts[s], len = lens[s];
    if (start < 0 || len < 0) return TPQ_ERR_TRUNCATED;
    if (len == 0) continue;
    if ((uint64_t)(start + len) * (uint64_t)width > (uint64_t)bp_len * 8)
      return TPQ_ERR_TRUNCATED;
    if (width == 0) {
      seen = 1;
      cnt += (target == 0) ? len : 0;
      continue;
    }
    if (width == 1) {
      /* def-level fast path: popcount whole bytes, mask the edges */
      int64_t i = start, end = start + len;
      int64_t ones = 0;
      while (i < end && (i & 7))
        ones += (bp[i >> 3] >> (i & 7)) & 1, i++;
      while (i + 8 <= end) {
        ones += __builtin_popcount(bp[i >> 3]);
        i += 8;
      }
      while (i < end)
        ones += (bp[i >> 3] >> (i & 7)) & 1, i++;
      if (ones && 1u > mx) mx = 1u;
      seen = 1;
      cnt += (target == 1) ? ones : (target == 0 ? len - ones : 0);
      continue;
    }
    if (width <= 8) {
      /* dict-index/level widths: 8 values span exactly `width` bytes
       * starting on a byte boundary whenever the value index is a
       * multiple of 8 — one 8-byte load serves the whole group */
      int64_t i = start, end = start + len;
      while (i < end && (i & 7)) {
        uint32_t v = bp_get(bp, bp_len, i, width, vmask);
        if (v > mx) mx = v;
        cnt += (v == target);
        i++;
      }
      while (i + 8 <= end) {
        uint64_t byte_off = (uint64_t)i * width >> 3;
        uint64_t w64;
        if (byte_off + 8 <= bp_len) {
          memcpy(&w64, bp + byte_off, 8);
        } else {
          w64 = 0;
          memcpy(&w64, bp + byte_off, bp_len - byte_off);
        }
        for (int k = 0; k < 8; k++) {
          uint32_t v = (uint32_t)(w64 >> (k * width)) & vmask;
          if (v > mx) mx = v;
          cnt += (v == target);
        }
        i += 8;
      }
      while (i < end) {
        uint32_t v = bp_get(bp, bp_len, i, width, vmask);
        if (v > mx) mx = v;
        cnt += (v == target);
        i++;
      }
      seen = 1;
      continue;
    }
    for (int64_t i = start; i < start + len; i++) {
      uint32_t v = bp_get(bp, bp_len, i, width, vmask);
      if (v > mx) mx = v;
      cnt += (v == target);
    }
    seen = 1;
  }
  *out_max = mx;
  *out_count_eq = cnt;
  return seen ? TPQ_OK : 1; /* 1 = no lanes (max undefined) */
}
