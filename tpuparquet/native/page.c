/* Write-side page assembly: the native core of io/pages.py's
 * write_data_page_v1/v2 fast path.
 *
 * The pure-Python page writer builds each page body out of separate
 * bytes objects (prefixed level streams, the dict-index stream, the
 * values segment) concatenated through a bytearray, then hands one
 * more full copy to the block compressor and another to zlib.crc32 —
 * at 50M values that per-page churn dominated the config-2 write wall
 * (reference analogue: chunk_writer.go renders pages into one
 * buffer).  tpq_page_encode lays the whole body into a single
 * caller-provided (arena-backed) buffer in one pass; the compress and
 * CRC stages run over that buffer in place.  Byte-identical to the
 * pure path by construction: the level/index streams come from the
 * same hybrid encoder (tpq_hybrid_encode32), the values segment is
 * memcpy'd verbatim, and tpq_crc32 is the standard zlib polynomial.
 */

#include <stddef.h>
#include <stdint.h>
#include <string.h>

/* from hybrid.c */
long long tpq_hybrid_encode32(const uint32_t *v, long long n, int width,
                              uint8_t *out, long long cap,
                              long long *out_len);

/* ------------------------------------------------------------------ */
/* CRC32 (zlib/gzip polynomial 0xEDB88320, reflected) — slice-by-8.
 * Matches zlib.crc32 bit for bit; the PageHeader.crc field is the
 * same CRC parquet-mr and pyarrow verify.                            */
/* ------------------------------------------------------------------ */

static uint32_t crc_tab[8][256];

__attribute__((constructor)) static void crc_init(void) {
  for (uint32_t i = 0; i < 256; i++) {
    uint32_t c = i;
    for (int k = 0; k < 8; k++)
      c = (c & 1) ? 0xEDB88320u ^ (c >> 1) : c >> 1;
    crc_tab[0][i] = c;
  }
  for (uint32_t i = 0; i < 256; i++) {
    uint32_t c = crc_tab[0][i];
    for (int t = 1; t < 8; t++) {
      c = crc_tab[0][c & 0xff] ^ (c >> 8);
      crc_tab[t][i] = c;
    }
  }
}

uint32_t tpq_crc32(const uint8_t *p, long long n, uint32_t crc) {
  crc = ~crc;
  while (n && ((uintptr_t)p & 7)) {
    crc = crc_tab[0][(crc ^ *p++) & 0xff] ^ (crc >> 8);
    n--;
  }
  while (n >= 8) {
    uint32_t lo, hi;
    memcpy(&lo, p, 4);
    memcpy(&hi, p + 4, 4);
    lo ^= crc;
    crc = crc_tab[7][lo & 0xff] ^ crc_tab[6][(lo >> 8) & 0xff] ^
          crc_tab[5][(lo >> 16) & 0xff] ^ crc_tab[4][lo >> 24] ^
          crc_tab[3][hi & 0xff] ^ crc_tab[2][(hi >> 8) & 0xff] ^
          crc_tab[1][(hi >> 16) & 0xff] ^ crc_tab[0][hi >> 24];
    p += 8;
    n -= 8;
  }
  while (n--) crc = crc_tab[0][(crc ^ *p++) & 0xff] ^ (crc >> 8);
  return ~crc;
}

/* ------------------------------------------------------------------ */
/* Page body encode                                                   */
/* ------------------------------------------------------------------ */

/* Lay a data page's uncompressed body into out in one pass:
 *
 *   [rep level stream][def level stream][values segment]
 *
 * Level streams are the hybrid encode of n u32 levels; with v2 == 0
 * each is preceded by its 4-byte LE byte length (the V1 framing),
 * with v2 != 0 they are raw (V2 keeps lengths in the page header).
 * A NULL rep/dl pointer skips that stream entirely (max level 0).
 * The values segment is either the dictionary-index stream (idx !=
 * NULL: one width byte + hybrid encode of n_idx u32 indices) or the
 * caller's pre-encoded bytes memcpy'd verbatim.
 *
 * Returns 0 and fills *rep_len / *dl_len / *val_len (framing
 * included; body length is their sum), -1 if a level/index exceeds
 * its width, -2 on a bad width, -3 when cap would overflow. */
long long tpq_page_encode(const uint32_t *rep, const uint32_t *dl,
                          long long n, int rep_width, int def_width,
                          int v2, const uint32_t *idx, long long n_idx,
                          int idx_width, const uint8_t *values,
                          long long values_len, uint8_t *out,
                          long long cap, long long *rep_len,
                          long long *dl_len, long long *val_len) {
  long long o = 0;
  const int prefix = v2 ? 0 : 4;
  *rep_len = *dl_len = *val_len = 0;
  for (int s = 0; s < 2; s++) {
    const uint32_t *lv = s == 0 ? rep : dl;
    int width = s == 0 ? rep_width : def_width;
    if (lv == NULL)
      continue;
    if (o + prefix > cap)
      return -3;
    long long body = 0;
    long long rc = tpq_hybrid_encode32(lv, n, width, out + o + prefix,
                                       cap - o - prefix, &body);
    if (rc != 0)
      return rc;
    if (prefix) { /* 4-byte LE length, written after the size is known */
      uint32_t le = (uint32_t)body;
      memcpy(out + o, &le, 4);
    }
    o += prefix + body;
    *(s == 0 ? rep_len : dl_len) = prefix + body;
  }
  if (idx != NULL) {
    if (o + 1 > cap)
      return -3;
    out[o] = (uint8_t)idx_width;
    long long body = 0;
    long long rc = tpq_hybrid_encode32(idx, n_idx, idx_width, out + o + 1,
                                       cap - o - 1, &body);
    if (rc != 0)
      return rc;
    o += 1 + body;
    *val_len = 1 + body;
  } else if (values_len > 0) {
    if (o + values_len > cap)
      return -3;
    memcpy(out + o, values, (size_t)values_len);
    o += values_len;
    *val_len = values_len;
  }
  return 0;
}
