/* Snappy block codec, from scratch, for the tpuparquet host runtime.
 *
 * The reference keeps its hot path in Go with golang/snappy
 * (compress.go:46-48); our host runtime is Python, where a per-token
 * interpreter loop dominates whole-file decode time, so the block codec
 * lives here in C behind a ctypes boundary.  Wire format implemented
 * from the public snappy format description: a uvarint uncompressed
 * length followed by literal/copy tags (2-bit type, 1/2/4-byte offsets).
 *
 * API (all lengths in bytes, return 0 on success, negative error codes):
 *   tpq_snappy_uncompressed_length(in, n, &len)
 *   tpq_snappy_decompress(in, n, out, out_cap, &produced)
 *   tpq_snappy_max_compressed_length(n)
 *   tpq_snappy_compress(in, n, out, out_cap, &produced)
 */

#include <stddef.h>
#include <stdint.h>
#include <string.h>

#define TPQ_OK 0
#define TPQ_ERR_CORRUPT (-1)
#define TPQ_ERR_TOO_BIG (-2)
#define TPQ_ERR_BUFFER (-3)

/* ------------------------------------------------------------------ */
/* uvarint                                                            */
/* ------------------------------------------------------------------ */

static int read_uvarint(const uint8_t *in, size_t n, size_t *pos,
                        uint64_t *out) {
  uint64_t v = 0;
  int shift = 0;
  while (*pos < n && shift < 64) {
    uint8_t b = in[(*pos)++];
    v |= (uint64_t)(b & 0x7f) << shift;
    if (!(b & 0x80)) {
      *out = v;
      return TPQ_OK;
    }
    shift += 7;
  }
  return TPQ_ERR_CORRUPT;
}

int tpq_snappy_uncompressed_length(const uint8_t *in, size_t n,
                                   uint64_t *len) {
  size_t pos = 0;
  return read_uvarint(in, n, &pos, len);
}

/* ------------------------------------------------------------------ */
/* decompress                                                         */
/* ------------------------------------------------------------------ */

/* Decompress.  When the caller provides >= 16 bytes of slack past
 * `total` in the output buffer (out_cap >= total + 16), short copies use
 * fixed-width speculative stores — the main throughput lever, since a
 * variable-length memcpy per 4..16-byte token dominates otherwise. */
int tpq_snappy_decompress(const uint8_t *in, size_t n, uint8_t *out,
                          size_t out_cap, size_t *produced) {
  size_t pos = 0;
  uint64_t total;
  int rc = read_uvarint(in, n, &pos, &total);
  if (rc != TPQ_OK) return rc;
  if (total > out_cap) return TPQ_ERR_BUFFER;
  int slack = out_cap >= total + 16;

  size_t op = 0;
  while (pos < n) {
    uint8_t tag = in[pos++];
    uint32_t kind = tag & 3;
    size_t len, off;
    if (kind == 0) { /* literal */
      len = tag >> 2;
      if (len >= 60) {
        size_t extra = len - 59;
        if (pos + extra > n) return TPQ_ERR_CORRUPT;
        len = 0;
        for (size_t i = 0; i < extra; i++)
          len |= (size_t)in[pos + i] << (8 * i);
        pos += extra;
      }
      len += 1;
      if (pos + len > n || op + len > total) return TPQ_ERR_CORRUPT;
      if (slack && len <= 16 && pos + 16 <= n) {
        memcpy(out + op, in + pos, 16); /* fixed-size: two stores */
      } else {
        memcpy(out + op, in + pos, len);
      }
      pos += len;
      op += len;
      continue;
    }
    if (kind == 1) {
      if (pos >= n) return TPQ_ERR_CORRUPT;
      len = ((tag >> 2) & 0x7) + 4;
      off = ((size_t)(tag >> 5) << 8) | in[pos];
      pos += 1;
    } else if (kind == 2) {
      if (pos + 2 > n) return TPQ_ERR_CORRUPT;
      len = (tag >> 2) + 1;
      off = (size_t)in[pos] | ((size_t)in[pos + 1] << 8);
      pos += 2;
    } else {
      if (pos + 4 > n) return TPQ_ERR_CORRUPT;
      len = (tag >> 2) + 1;
      off = (size_t)in[pos] | ((size_t)in[pos + 1] << 8) |
            ((size_t)in[pos + 2] << 16) | ((size_t)in[pos + 3] << 24);
      pos += 4;
    }
    if (off == 0 || off > op || op + len > total) return TPQ_ERR_CORRUPT;
    {
      uint8_t *dst = out + op;
      const uint8_t *src = dst - off;
      if (off >= 8) {
        if (slack && len <= 16) {
          /* speculative, bounded by slack; split so each memcpy's
           * src/dst stay disjoint when 8 <= off < 16 */
          if (off >= 16) {
            memcpy(dst, src, 16);
          } else {
            memcpy(dst, src, 8);
            memcpy(dst + 8, src + 8, 8);
          }
        } else if (off >= len) {
          memcpy(dst, src, len);
        } else {
          /* overlap with period >= 8: 8-byte blocks never read their
           * own output */
          size_t rem = len;
          while (rem >= 8) {
            memcpy(dst, src, 8);
            dst += 8;
            src += 8;
            rem -= 8;
          }
          if (rem) memcpy(dst, src, slack ? 8 : rem);
        }
      } else {
        /* short period: seed one pattern then double it */
        size_t copied = off;
        for (size_t i = 0; i < off && i < len; i++) dst[i] = src[i];
        if (copied < len) {
          while (copied * 2 <= len) {
            memcpy(dst + copied, dst, copied);
            copied *= 2;
          }
          memcpy(dst + copied, dst, len - copied);
        }
      }
    }
    op += len;
  }
  if (op != total) return TPQ_ERR_CORRUPT;
  *produced = op;
  return TPQ_OK;
}

/* ------------------------------------------------------------------ */
/* compress                                                           */
/* ------------------------------------------------------------------ */

uint64_t tpq_snappy_max_compressed_length(uint64_t n) {
  /* worst case: varint header + one literal token set per 2^16 chunk */
  return 32 + n + n / 6;
}

static size_t emit_uvarint(uint8_t *out, uint64_t v) {
  size_t i = 0;
  while (v >= 0x80) {
    out[i++] = (uint8_t)(v | 0x80);
    v >>= 7;
  }
  out[i++] = (uint8_t)v;
  return i;
}

static size_t emit_literal(uint8_t *out, const uint8_t *data, size_t len) {
  size_t i = 0;
  size_t l = len - 1;
  if (l < 60) {
    out[i++] = (uint8_t)(l << 2);
  } else if (l < 256) {
    out[i++] = 60 << 2;
    out[i++] = (uint8_t)l;
  } else if (l < 65536) {
    out[i++] = 61 << 2;
    out[i++] = (uint8_t)l;
    out[i++] = (uint8_t)(l >> 8);
  } else if (l < (1u << 24)) {
    out[i++] = 62 << 2;
    out[i++] = (uint8_t)l;
    out[i++] = (uint8_t)(l >> 8);
    out[i++] = (uint8_t)(l >> 16);
  } else {
    out[i++] = 63 << 2;
    out[i++] = (uint8_t)l;
    out[i++] = (uint8_t)(l >> 8);
    out[i++] = (uint8_t)(l >> 16);
    out[i++] = (uint8_t)(l >> 24);
  }
  memcpy(out + i, data, len);
  return i + len;
}

static size_t emit_copy(uint8_t *out, size_t off, size_t len) {
  size_t i = 0;
  /* long matches: peel 64-byte 2-byte-offset copies */
  while (len >= 68) {
    out[i++] = (63 << 2) | 2;
    out[i++] = (uint8_t)off;
    out[i++] = (uint8_t)(off >> 8);
    len -= 64;
  }
  if (len > 64) { /* leave >= 4 for the final copy */
    out[i++] = (59 << 2) | 2;
    out[i++] = (uint8_t)off;
    out[i++] = (uint8_t)(off >> 8);
    len -= 60;
  }
  if (len >= 12 || off >= 2048) {
    out[i++] = (uint8_t)(((len - 1) << 2) | 2);
    out[i++] = (uint8_t)off;
    out[i++] = (uint8_t)(off >> 8);
  } else {
    out[i++] = (uint8_t)(((off >> 8) << 5) | ((len - 4) << 2) | 1);
    out[i++] = (uint8_t)off;
  }
  return i;
}

#define HASH_BITS 14
#define HASH_SIZE (1u << HASH_BITS)

static inline uint32_t load32(const uint8_t *p) {
  uint32_t v;
  memcpy(&v, p, 4);
  return v;
}

static inline uint32_t hash32(uint32_t v) {
  return (v * 0x1e35a7bdu) >> (32 - HASH_BITS);
}

/* min_match: shortest back-reference worth emitting.  8 is the decode-
 * throughput sweet spot for numeric column data (short copies decode
 * token-at-a-time); 4 recovers the ratio on text/byte-array pages whose
 * redundancy is mostly 4..7-byte matches.  Values < 4 clamp to 4 (the
 * format's copy minimum).
 *
 * The encoder works in 64 KiB blocks (the upstream snappy fragment
 * size): match candidates never leave the current block, so the hash
 * table holds uint16 block-relative positions — 32 KiB, L1-resident,
 * where the former whole-input uint32 table thrashed on multi-MB page
 * bodies (the config-2 write wall measured this encoder at ~360 MB/s;
 * the block form runs close to memory speed on the same bodies).
 * Offsets are <= 65535 by construction, so every copy fits the 1/2-
 * byte forms.  Stale table entries from the previous block are
 * harmless: the 4-byte load32 compare validates every candidate, and
 * `cand < pos` rejects self/forward references. */
#define BLOCK_LOG 16
#define BLOCK_SIZE (1u << BLOCK_LOG)

int tpq_snappy_compress_opt(const uint8_t *in, size_t n, uint8_t *out,
                            size_t out_cap, size_t *produced,
                            int min_match) {
  if (n > 0xffffffffu) return TPQ_ERR_TOO_BIG; /* literal length
    encoding holds lengths as uint32 */
  size_t min_len = min_match < 4 ? 4 : (size_t)min_match;
  if (out_cap < tpq_snappy_max_compressed_length(n)) return TPQ_ERR_BUFFER;
  size_t op = emit_uvarint(out, n);

  uint16_t table[HASH_SIZE];
  size_t lit_start = 0; /* ABSOLUTE: pending literals span blocks, so
    an incompressible input still compresses to one literal token —
    the decode side's zero-copy single-literal view depends on it */

  for (size_t base = 0; base < n; base += BLOCK_SIZE) {
    size_t blen = n - base < BLOCK_SIZE ? n - base : BLOCK_SIZE;
    const uint8_t *b = in + base;
    if (blen < 4)
      continue; /* tail bytes ride the final literal flush */
    memset(table, 0, sizeof(table));
    size_t pos = 0;
    size_t limit = blen - 4;
    uint32_t skip = 32; /* golang-style acceleration: skip>>5 per miss */
    while (pos <= limit) {
      uint32_t key = load32(b + pos);
      uint32_t h = hash32(key);
      size_t cand = table[h];
      table[h] = (uint16_t)pos;
      if (cand < pos && load32(b + cand) == key) {
        size_t len = 4;
        size_t max = blen - pos;
        /* extend 8 bytes at a time; the xor's lowest set bit locates
         * the first mismatch (little-endian), so long matches cost one
         * comparison per word instead of per byte */
        while (len + 8 <= max) {
          uint64_t a, w;
          memcpy(&a, b + cand + len, 8);
          memcpy(&w, b + pos + len, 8);
          uint64_t diff = a ^ w;
          if (diff) {
            len += (size_t)(__builtin_ctzll(diff) >> 3);
            goto matched;
          }
          len += 8;
        }
        while (len < max && b[cand + len] == b[pos + len]) len++;
      matched:;
        /* Short copies cost ~as many compressed bytes as the literal
         * they replace but decode token-at-a-time; dense 4..7-byte
         * matches (typical for numeric column data) would cap
         * decompression near 1 GB/s — hence the caller-set floor. */
        if (len < min_len) {
          size_t step = skip >> 5;
          pos += step;
          skip += (uint32_t)step;
          continue;
        }
        if (base + pos > lit_start)
          op += emit_literal(out + op, in + lit_start,
                             base + pos - lit_start);
        op += emit_copy(out + op, pos - cand, len);
        /* seed the table inside the match so long runs keep matching */
        size_t end = pos + len;
        if (end <= limit) {
          size_t seed = end - 1;
          table[hash32(load32(b + seed))] = (uint16_t)seed;
        }
        pos = end;
        lit_start = base + pos;
        skip = 32;
      } else {
        size_t step = skip >> 5;
        pos += step;
        skip += (uint32_t)step;
      }
    }
    /* no per-block literal flush: the pending run carries forward */
  }
  if (n > lit_start)
    op += emit_literal(out + op, in + lit_start, n - lit_start);
  *produced = op;
  return TPQ_OK;
}

int tpq_snappy_compress(const uint8_t *in, size_t n, uint8_t *out,
                        size_t out_cap, size_t *produced) {
  return tpq_snappy_compress_opt(in, n, out, out_cap, produced, 8);
}

/* ------------------------------------------------------------------ */
/* Token scan for the device (TPU) decompressor: parse the tag stream
 * into a token table + concatenated literal bytes WITHOUT materializing
 * the output.  Host work is O(#tokens + literal bytes); the copy
 * resolution runs on device as log2(n) pointer-doubling gathers.
 * Token i covers output [tok_out_end[i-1], tok_out_end[i]); tok_src[i]
 * is -(literal_offset+1) for literals, or the absolute output position
 * the copy reads from (strictly before its own start + within). */

int tpq_snappy_scan_tokens(const uint8_t *in, size_t n,
                           int64_t *tok_out_end, int64_t *tok_src,
                           int64_t cap_tokens,
                           uint8_t *lit_out, size_t lit_cap,
                           int64_t *n_tokens, size_t *lit_len,
                           uint64_t *out_len) {
  size_t pos = 0;
  uint64_t total;
  int rc = read_uvarint(in, n, &pos, &total);
  if (rc != TPQ_OK) return rc;

  size_t op = 0, lp = 0;
  int64_t t = 0;
  while (pos < n) {
    uint8_t tag = in[pos++];
    uint32_t kind = tag & 3;
    size_t len, off;
    if (t >= cap_tokens) return TPQ_ERR_BUFFER;
    if (kind == 0) {
      len = tag >> 2;
      if (len >= 60) {
        size_t extra = len - 59;
        if (pos + extra > n) return TPQ_ERR_CORRUPT;
        len = 0;
        for (size_t i = 0; i < extra; i++)
          len |= (size_t)in[pos + i] << (8 * i);
        pos += extra;
      }
      len += 1;
      if (pos + len > n || op + len > total) return TPQ_ERR_CORRUPT;
      if (lp + len > lit_cap) return TPQ_ERR_BUFFER;
      memcpy(lit_out + lp, in + pos, len);
      tok_src[t] = -((int64_t)lp + 1);
      lp += len;
      pos += len;
      op += len;
      tok_out_end[t++] = (int64_t)op;
      continue;
    }
    if (kind == 1) {
      if (pos >= n) return TPQ_ERR_CORRUPT;
      len = ((tag >> 2) & 0x7) + 4;
      off = ((size_t)(tag >> 5) << 8) | in[pos];
      pos += 1;
    } else if (kind == 2) {
      if (pos + 2 > n) return TPQ_ERR_CORRUPT;
      len = (tag >> 2) + 1;
      off = (size_t)in[pos] | ((size_t)in[pos + 1] << 8);
      pos += 2;
    } else {
      if (pos + 4 > n) return TPQ_ERR_CORRUPT;
      len = (tag >> 2) + 1;
      off = (size_t)in[pos] | ((size_t)in[pos + 1] << 8) |
            ((size_t)in[pos + 2] << 16) | ((size_t)in[pos + 3] << 24);
      pos += 4;
    }
    if (off == 0 || off > op || op + len > total) return TPQ_ERR_CORRUPT;
    tok_src[t] = (int64_t)(op - off);
    op += len;
    tok_out_end[t++] = (int64_t)op;
  }
  if (op != total) return TPQ_ERR_CORRUPT;
  *n_tokens = t;
  *lit_len = lp;
  *out_len = total;
  return TPQ_OK;
}
