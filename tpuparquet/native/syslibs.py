"""System-library codec bindings discovered via ctypes ``dlopen``.

GZIP binds the ubiquitous system zlib and ZSTD binds system libzstd —
neither is linked into ``_tpq_native.so`` (the build stays
dependency-free); both are resolved at runtime from the usual soname
candidates, overridable with ``TPQ_ZLIB_LIB``/``TPQ_ZSTD_LIB`` for
pinned or exotic installs.  Every accessor degrades to None when the
library is absent; ``compress.py`` then falls back to the ``zlib``
module (GZIP — same libz, byte-identical output) or the ``zstandard``
wheel (ZSTD) so the codec matrix stays loadable without either.

All entry points release the GIL across the library call (ctypes
CDLL semantics), so block-parallel compression gets real concurrency.
"""

from __future__ import annotations

import ctypes
import ctypes.util
import os
import threading

import numpy as np

from . import _as_u8

__all__ = ["NativeZlib", "NativeZstd", "zlib_native", "zstd_native"]


def _dlopen(env_var: str, candidates: tuple[str, ...]):
    """First loadable library among the env override + sonames, or
    None.  An explicit override that fails to load is an error the
    user asked for (loudly), not a silent fallback."""
    override = os.environ.get(env_var)
    if override:
        return ctypes.CDLL(override)  # raises OSError: surface it
    for name in candidates:
        try:
            return ctypes.CDLL(name)
        except OSError:
            continue
    found = ctypes.util.find_library(candidates[0].split(".")[0][3:])
    if found:
        try:
            return ctypes.CDLL(found)
        except OSError:
            return None
    return None


# ----------------------------------------------------------------------
# zlib (GZIP framing)
# ----------------------------------------------------------------------

_Z_OK = 0
_Z_STREAM_END = 1
_Z_FINISH = 4
_Z_DEFLATED = 8
_Z_DEFAULT_LEVEL = -1  # maps to 6 inside zlib, same as zlib.compressobj
_GZIP_WBITS = 31  # 15-bit window + gzip header/trailer
_DEF_MEM_LEVEL = 8  # zlib's DEF_MEM_LEVEL, what zlib.compressobj uses


class _ZStream(ctypes.Structure):
    _fields_ = [
        ("next_in", ctypes.c_void_p),
        ("avail_in", ctypes.c_uint),
        ("total_in", ctypes.c_ulong),
        ("next_out", ctypes.c_void_p),
        ("avail_out", ctypes.c_uint),
        ("total_out", ctypes.c_ulong),
        ("msg", ctypes.c_char_p),
        ("state", ctypes.c_void_p),
        ("zalloc", ctypes.c_void_p),
        ("zfree", ctypes.c_void_p),
        ("opaque", ctypes.c_void_p),
        ("data_type", ctypes.c_int),
        ("adler", ctypes.c_ulong),
        ("reserved", ctypes.c_ulong),
    ]


class NativeZlib:
    """Direct libz binding with gzip framing, caller-buffer I/O.

    ``compress_into`` runs deflate with exactly the parameters
    ``zlib.compressobj(wbits=31)`` uses (default level, memLevel 8,
    default strategy), so the native and module paths produce the SAME
    bytes from the same libz — the write-side parity anchor.
    ``decompress_into`` inflates multi-member streams (RFC 1952
    concatenation — what block-parallel compression emits)."""

    def __init__(self, lib: ctypes.CDLL):
        self._lib = lib
        ver = lib.zlibVersion
        ver.restype = ctypes.c_char_p
        ver.argtypes = []
        self._version = ver()
        for name in ("deflateInit2_", "inflateInit2_"):
            fn = getattr(lib, name)
            fn.restype = ctypes.c_int
        lib.deflateInit2_.argtypes = [
            ctypes.POINTER(_ZStream), ctypes.c_int, ctypes.c_int,
            ctypes.c_int, ctypes.c_int, ctypes.c_int,
            ctypes.c_char_p, ctypes.c_int,
        ]
        lib.inflateInit2_.argtypes = [
            ctypes.POINTER(_ZStream), ctypes.c_int,
            ctypes.c_char_p, ctypes.c_int,
        ]
        for name in ("deflate", "inflate", "deflateEnd", "inflateEnd",
                     "inflateReset"):
            fn = getattr(lib, name)
            fn.restype = ctypes.c_int
        lib.deflate.argtypes = [ctypes.POINTER(_ZStream), ctypes.c_int]
        lib.inflate.argtypes = [ctypes.POINTER(_ZStream), ctypes.c_int]
        lib.deflateEnd.argtypes = [ctypes.POINTER(_ZStream)]
        lib.inflateEnd.argtypes = [ctypes.POINTER(_ZStream)]
        lib.inflateReset.argtypes = [ctypes.POINTER(_ZStream)]

    def compress_bound(self, n: int) -> int:
        """Worst-case gzip size for ``n`` input bytes: deflate's stored
        blocks (5 bytes per 16 KiB window) + gzip header/trailer."""
        return n + (n >> 12) + (n >> 14) + (n >> 25) + 13 + 18

    def compress_into(self, src, out: np.ndarray,
                      level: int = _Z_DEFAULT_LEVEL) -> int:
        buf = _as_u8(src)
        if out.size < self.compress_bound(buf.size):
            raise ValueError("gzip: output buffer too small")
        strm = _ZStream()
        rc = self._lib.deflateInit2_(
            ctypes.byref(strm), level, _Z_DEFLATED, _GZIP_WBITS,
            _DEF_MEM_LEVEL, 0, self._version,
            ctypes.sizeof(_ZStream))
        if rc != _Z_OK:
            raise ValueError(f"gzip: deflateInit failed (rc={rc})")
        try:
            strm.next_in = ctypes.c_void_p(buf.ctypes.data)
            strm.avail_in = buf.size
            strm.next_out = ctypes.c_void_p(out.ctypes.data)
            strm.avail_out = out.size
            rc = self._lib.deflate(ctypes.byref(strm), _Z_FINISH)
            if rc != _Z_STREAM_END:
                raise ValueError(f"gzip: deflate failed (rc={rc})")
            return int(strm.total_out)
        finally:
            self._lib.deflateEnd(ctypes.byref(strm))

    def compress(self, data, level: int = _Z_DEFAULT_LEVEL) -> bytes:
        buf = _as_u8(data)
        out = np.empty(self.compress_bound(buf.size), dtype=np.uint8)
        return out[: self.compress_into(buf, out, level)].tobytes()

    def decompress_into(self, src, out: np.ndarray,
                        expected_size: int) -> int:
        """Inflate a (possibly multi-member) gzip stream into ``out``;
        returns the produced length (== ``expected_size`` on success)."""
        buf = _as_u8(src)
        if out.size < expected_size:
            raise ValueError("gzip: output buffer too small")
        strm = _ZStream()
        rc = self._lib.inflateInit2_(
            ctypes.byref(strm), _GZIP_WBITS, self._version,
            ctypes.sizeof(_ZStream))
        if rc != _Z_OK:
            raise ValueError(f"gzip: inflateInit failed (rc={rc})")
        produced = 0
        consumed = 0
        try:
            while True:
                strm.next_in = ctypes.c_void_p(buf.ctypes.data + consumed)
                strm.avail_in = buf.size - consumed
                strm.next_out = ctypes.c_void_p(out.ctypes.data + produced)
                # cap at expected: a lying stream must not scribble past
                # the caller's slab
                strm.avail_out = expected_size - produced
                strm.total_in = 0
                strm.total_out = 0
                rc = self._lib.inflate(ctypes.byref(strm), _Z_FINISH)
                produced += int(strm.total_out)
                consumed += int(strm.total_in)
                if rc == _Z_STREAM_END:
                    if consumed >= buf.size:
                        return produced
                    # multi-member stream: next member follows (a member
                    # overflowing expected_size dies on avail_out == 0)
                    rc = self._lib.inflateReset(ctypes.byref(strm))
                    if rc != _Z_OK:
                        raise ValueError(
                            f"gzip: inflateReset failed (rc={rc})")
                    continue
                raise ValueError(f"gzip: inflate failed (rc={rc})")
        finally:
            self._lib.inflateEnd(ctypes.byref(strm))

    def decompress(self, src, expected_size: int) -> bytes:
        out = np.empty(max(expected_size, 1), dtype=np.uint8)
        n = self.decompress_into(src, out, expected_size)
        return out[:n].tobytes()


# ----------------------------------------------------------------------
# zstd
# ----------------------------------------------------------------------

_ZSTD_CONTENTSIZE_UNKNOWN = 2**64 - 1
_ZSTD_CONTENTSIZE_ERROR = 2**64 - 2


class NativeZstd:
    """Direct libzstd binding (simple one-shot API), caller-buffer I/O.

    One-shot ``ZSTD_compress``/``ZSTD_decompress`` are thread-safe
    (each call uses its own implicit context) and ``ZSTD_decompress``
    decodes concatenated frames in one call — exactly the property
    block-parallel compression leans on.  ``frame_spans`` exposes the
    frame boundaries so the read side can decompress frames
    concurrently."""

    def __init__(self, lib: ctypes.CDLL):
        self._lib = lib
        names = ("ZSTD_compress", "ZSTD_decompress",
                 "ZSTD_compressBound", "ZSTD_isError",
                 "ZSTD_getFrameContentSize",
                 "ZSTD_findFrameCompressedSize")
        for name in names:
            if not hasattr(lib, name):
                raise RuntimeError(f"libzstd too old: missing {name}")
        lib.ZSTD_compress.restype = ctypes.c_size_t
        lib.ZSTD_compress.argtypes = [
            ctypes.c_void_p, ctypes.c_size_t,
            ctypes.c_void_p, ctypes.c_size_t, ctypes.c_int,
        ]
        lib.ZSTD_decompress.restype = ctypes.c_size_t
        lib.ZSTD_decompress.argtypes = [
            ctypes.c_void_p, ctypes.c_size_t,
            ctypes.c_void_p, ctypes.c_size_t,
        ]
        lib.ZSTD_compressBound.restype = ctypes.c_size_t
        lib.ZSTD_compressBound.argtypes = [ctypes.c_size_t]
        lib.ZSTD_isError.restype = ctypes.c_uint
        lib.ZSTD_isError.argtypes = [ctypes.c_size_t]
        lib.ZSTD_getFrameContentSize.restype = ctypes.c_ulonglong
        lib.ZSTD_getFrameContentSize.argtypes = [
            ctypes.c_void_p, ctypes.c_size_t]
        lib.ZSTD_findFrameCompressedSize.restype = ctypes.c_size_t
        lib.ZSTD_findFrameCompressedSize.argtypes = [
            ctypes.c_void_p, ctypes.c_size_t]
        self._err_name = getattr(lib, "ZSTD_getErrorName", None)
        if self._err_name is not None:
            self._err_name.restype = ctypes.c_char_p
            self._err_name.argtypes = [ctypes.c_size_t]

    def _check(self, code: int, what: str) -> int:
        if self._lib.ZSTD_isError(ctypes.c_size_t(code)):
            detail = ""
            if self._err_name is not None:
                name = self._err_name(ctypes.c_size_t(code))
                detail = f": {name.decode()}" if name else ""
            raise ValueError(f"zstd: {what} failed{detail}")
        return code

    def compress_bound(self, n: int) -> int:
        return int(self._lib.ZSTD_compressBound(n))

    def compress_into(self, src, out: np.ndarray, level: int = 3) -> int:
        buf = _as_u8(src)
        if out.size < self.compress_bound(buf.size):
            raise ValueError("zstd: output buffer too small")
        rc = self._lib.ZSTD_compress(out.ctypes.data, out.size,
                                     buf.ctypes.data, buf.size, level)
        return self._check(int(rc), "compress")

    def compress(self, data, level: int = 3) -> bytes:
        buf = _as_u8(data)
        out = np.empty(self.compress_bound(buf.size), dtype=np.uint8)
        return out[: self.compress_into(buf, out, level)].tobytes()

    def decompress_into(self, src, out: np.ndarray,
                        expected_size: int) -> int:
        """One-shot decompress (handles concatenated frames); returns
        the produced length.  ``out`` is capped at ``expected_size`` so
        a lying stream cannot scribble past the caller's slab."""
        buf = _as_u8(src)
        if out.size < expected_size:
            raise ValueError("zstd: output buffer too small")
        rc = self._lib.ZSTD_decompress(
            out.ctypes.data, ctypes.c_size_t(expected_size),
            buf.ctypes.data, buf.size)
        return self._check(int(rc), "decompress")

    def decompress(self, src, expected_size: int) -> bytes:
        out = np.empty(max(expected_size, 1), dtype=np.uint8)
        n = self.decompress_into(src, out, expected_size)
        return out[:n].tobytes()

    def frame_spans(self, src):
        """``[(offset, compressed_len, content_len), ...]`` for each
        frame of a (possibly concatenated) zstd stream, or None when
        any frame's content size is unrecorded (the parallel read path
        then falls back to the one-shot multi-frame decompress)."""
        buf = _as_u8(src)
        spans = []
        pos = 0
        while pos < buf.size:
            view = buf[pos:]
            clen = self._lib.ZSTD_findFrameCompressedSize(
                view.ctypes.data, view.size)
            if self._lib.ZSTD_isError(ctypes.c_size_t(clen)):
                raise ValueError("zstd: corrupt frame header")
            ulen = int(self._lib.ZSTD_getFrameContentSize(
                view.ctypes.data, view.size))
            if ulen in (_ZSTD_CONTENTSIZE_UNKNOWN,
                        _ZSTD_CONTENTSIZE_ERROR):
                return None
            spans.append((pos, int(clen), ulen))
            pos += int(clen)
        return spans


_lock = threading.Lock()
_zlib_inst: "NativeZlib | None | bool" = False  # False = not tried yet
_zstd_inst: "NativeZstd | None | bool" = False


def zlib_native() -> NativeZlib | None:
    """The process-wide libz binding, or None when unloadable."""
    global _zlib_inst
    with _lock:
        if _zlib_inst is False:
            try:
                lib = _dlopen("TPQ_ZLIB_LIB",
                              ("libz.so.1", "libz.so", "libz.dylib"))
                _zlib_inst = NativeZlib(lib) if lib is not None else None
            except (OSError, AttributeError):
                _zlib_inst = None
        return _zlib_inst


def zstd_native() -> NativeZstd | None:
    """The process-wide libzstd binding, or None when unloadable."""
    global _zstd_inst
    with _lock:
        if _zstd_inst is False:
            try:
                lib = _dlopen("TPQ_ZSTD_LIB",
                              ("libzstd.so.1", "libzstd.so",
                               "libzstd.dylib"))
                _zstd_inst = NativeZstd(lib) if lib is not None else None
            except (OSError, RuntimeError, AttributeError):
                _zstd_inst = None
        return _zstd_inst
