"""tpuparquet.serve — the long-lived multi-tenant scan server.

Composed from the proven pieces elsewhere in the library:

* :mod:`.arbiter` — ONE process-wide worker budget apportioned into
  per-tenant shares (adaptive: doctor bound-verdicts, digest p99s and
  SLO burn rates feed the rebalance), plus admission control that
  load-sheds with a retryable rejection instead of queueing forever.
* :mod:`.server` — per-tenant bounded queues multiplexing concurrent
  :class:`~tpuparquet.shard.scan.ShardedScan` drivers onto the shared
  plan cache, arena pool and watchdog, with graceful drain: SIGTERM /
  ``shutdown()`` stops admissions, checkpoints every in-flight scan
  via the durable-cursor discipline, flushes telemetry, and exits so
  a successor resumes every tenant duplicate-free and bit-exact.

The arbiter submodule imports eagerly (the thread-budget fast paths
consult it); the server — which pulls in the full scan stack — loads
on first attribute access.
"""

from .arbiter import (  # noqa: F401
    AdmissionRejected,
    ResourceArbiter,
    plan_budget,
    tenant_scope,
)

__all__ = [
    "AdmissionRejected",
    "ResourceArbiter",
    "ScanJob",
    "ScanServer",
    "plan_budget",
    "tenant_scope",
]


def __getattr__(name: str):
    if name in ("ScanServer", "ScanJob"):
        from . import server as _server

        return getattr(_server, name)
    raise AttributeError(
        f"module {__name__!r} has no attribute {name!r}")
