"""Process-wide resource arbiter for the multi-tenant scan server.

One long-lived serve process runs MANY concurrent tenant scans over
one core budget.  Before this module each scan sized its own pools
from ``TPQ_PLAN_THREADS``/``TPQ_WRITE_THREADS`` — N concurrent scans
on a C-core box ran N*C planner threads, and ``PLAN_SCALE_r06.json``
measured pipelined plan time degrading 2-3.5x under exactly that
oversubscription.  The arbiter replaces the per-scan knobs with ONE
global worker budget (``TPQ_SERVE_WORKERS``, default the usable
cores) apportioned into per-tenant integer shares:

* **fair sharing with anti-starvation floors** — largest-remainder
  apportionment over tenant weights; every registered tenant's share
  is at least 1 worker and the shares never sum past the budget when
  it covers the tenant count (the oversubscription clamp), so a
  greedy tenant cannot starve the others of planner threads.
* **adaptive feedback** — :meth:`ResourceArbiter.rebalance` folds the
  live attribution ledgers (the ``parquet-tool doctor`` bound
  verdict), the exact latency digests (per-tenant unit p99), and the
  windowed SLO burn rate back into the weights: a tenant burning its
  error budget or violating its latency target gets a bounded boost,
  and plan-bound tenants get more planners than read-bound ones.
* **admission control** — :meth:`ResourceArbiter.admit` sheds load
  BEFORE a scan starts: a full tenant queue, an exhausted byte
  budget, or a deadline the backlog cannot meet raises
  :class:`AdmissionRejected` (retryable, with a retry-after hint)
  instead of letting the request hang in line.

Scans join the arbiter by running under :func:`tenant_scope`; the
binding is a ``threading.local`` that
:func:`tpuparquet.deadline.call_with_deadline` propagates onto its
disposable workers exactly like the trace context, so a bounded
unit's planner pool sizes from its tenant's share.
``kernels/device._plan_threads`` (and the writer/prefetch budgets)
consult :func:`plan_budget` FIRST and fall back to the legacy env
knobs when no arbiter is active or the thread is unbound, so direct
scans behave exactly as before this module existed.

Lock discipline: the arbiter lock is a LEAF — no code path calls
into another locking module while holding it (rebalance gathers its
feedback from the obs registries BEFORE taking the lock, and the
share map is swapped wholesale so the hot ``plan_budget`` read path
never locks at all).
"""

from __future__ import annotations

import math
import os
import threading
import warnings
from contextlib import contextmanager

from ..errors import AdmissionRejected, ServeStateError

__all__ = [
    "AdmissionRejected",
    "ServeStateError",
    "ResourceArbiter",
    "activate",
    "active",
    "deactivate",
    "plan_budget",
    "write_budget",
    "current_binding",
    "tenant_scope",
    "serve_workers",
    "queue_bound_default",
    "rebalance_interval_default",
    "warn_if_oversubscribed",
]


def _usable_cpus() -> int:
    """Affinity-aware core count (mirrors ``kernels/device.
    _usable_cpus`` without importing the device stack — the arbiter
    must stay importable from the thread-budget fast paths)."""
    try:
        return len(os.sched_getaffinity(0)) or 1
    except (AttributeError, OSError):
        return os.cpu_count() or 1


def serve_workers() -> int:
    """Global worker budget for one serve process:
    ``TPQ_SERVE_WORKERS`` when set, else the usable core count."""
    v = os.environ.get("TPQ_SERVE_WORKERS")
    if v is not None:
        try:
            return max(int(v), 1)
        except ValueError:
            pass  # malformed override falls back to the default
    return _usable_cpus()


def queue_bound_default() -> int:
    """Per-tenant admission-queue depth bound (``TPQ_SERVE_QUEUE``,
    default 8): submissions past it are load-shed with a retryable
    :class:`AdmissionRejected` instead of queueing unboundedly."""
    v = os.environ.get("TPQ_SERVE_QUEUE")
    if v is not None:
        try:
            return max(int(v), 1)
        except ValueError:
            pass
    return 8


def rebalance_interval_default() -> float:
    """Adaptive rebalance cadence in seconds
    (``TPQ_SERVE_REBALANCE_S``, default 1.0)."""
    v = os.environ.get("TPQ_SERVE_REBALANCE_S")
    if v is not None:
        try:
            return max(float(v), 0.05)
        except ValueError:
            pass
    return 1.0


class _TenantState:
    """Arbiter-side per-tenant record; every field is written only
    under the owning arbiter's lock."""

    __slots__ = (
        "label", "weight", "byte_budget", "latency_target_ms",
        "error_rate_target", "share", "bytes_admitted", "admitted",
        "rejected", "jobs_done", "jobs_failed", "est_job_s",
        "last_bound", "last_burn", "last_p99_ms", "_base_counters",
    )

    def __init__(self, label: str, weight: float, byte_budget,
                 latency_target_ms, error_rate_target):
        self.label = label
        self.weight = max(float(weight), 1e-6)
        self.byte_budget = byte_budget
        self.latency_target_ms = latency_target_ms
        self.error_rate_target = error_rate_target
        self.share = 1
        self.bytes_admitted = 0
        self.admitted = 0
        self.rejected = 0
        self.jobs_done = 0
        self.jobs_failed = 0
        self.est_job_s = None
        self.last_bound = None      # doctor verdict, e.g. "plan-bound"
        self.last_burn = None       # windowed error-budget burn rate
        self.last_p99_ms = None     # unit p99 from the exact digests
        self._base_counters = {}    # ledger counters at last rebalance

    def as_dict(self) -> dict:
        return {
            "weight": self.weight,
            "share": self.share,
            "byte_budget": self.byte_budget,
            "bytes_admitted": self.bytes_admitted,
            "admitted": self.admitted,
            "rejected": self.rejected,
            "jobs_done": self.jobs_done,
            "jobs_failed": self.jobs_failed,
            "est_job_s": self.est_job_s,
            "bound": self.last_bound,
            "burn": self.last_burn,
            "p99_ms": self.last_p99_ms,
        }


class ResourceArbiter:
    """One global core budget apportioned into per-tenant shares.

    The share map is an immutable-by-convention dict REPLACED
    wholesale under the lock on every recompute; readers
    (:func:`plan_budget` on the unit hot path) take no lock at all —
    they read whichever complete map is current.  The arbiter lock is
    a leaf: nothing is called while holding it."""

    def __init__(self, total_workers: int | None = None):
        self.total_workers = (total_workers if total_workers is not None
                              else serve_workers())
        if self.total_workers < 1:
            raise ValueError("total_workers must be >= 1")
        self._lock = threading.Lock()
        self._tenants: dict[str, _TenantState] = {}
        self._shares: dict[str, int] = {}

    # -- tenant registry -------------------------------------------------

    def register(self, label: str, *, weight: float = 1.0,
                 byte_budget: int | None = None,
                 latency_target_ms: float | None = None,
                 error_rate_target: float | None = None) -> None:
        """Add (or re-weight) a tenant and recompute shares.

        ``byte_budget`` caps IN-FLIGHT admitted bytes (admission
        control, not a rate limit): :meth:`admit` charges the
        account, :meth:`release` refunds it when the job reaches a
        terminal state, so a shed job becomes admissible again once
        the budget frees up.  ``latency_target_ms`` /
        ``error_rate_target`` are this tenant's SLO targets — the
        adaptive loop boosts tenants violating them."""
        with self._lock:
            t = self._tenants.get(label)
            if t is None:
                t = _TenantState(label, weight, byte_budget,
                                 latency_target_ms, error_rate_target)
                self._tenants[label] = t
            else:
                t.weight = max(float(weight), 1e-6)
                t.byte_budget = byte_budget
                t.latency_target_ms = latency_target_ms
                t.error_rate_target = error_rate_target
            self._recompute_locked()

    def unregister(self, label: str) -> None:
        with self._lock:
            self._tenants.pop(label, None)
            self._recompute_locked()

    def tenants(self) -> list[str]:
        with self._lock:
            return sorted(self._tenants)

    def tenants_state(self) -> dict:
        """Per-tenant accounting snapshot (the ``parquet-tool
        tenants`` view)."""
        with self._lock:
            return {t.label: t.as_dict()
                    for t in self._tenants.values()}

    # -- shares ----------------------------------------------------------

    def shares(self) -> dict[str, int]:
        return dict(self._shares)

    def share_of(self, label: str) -> int | None:
        """Lock-free: reads the current complete share map."""
        return self._shares.get(label)

    def _effective_weight(self, t: _TenantState) -> float:
        """Feedback-adjusted weight; every boost is BOUNDED so one
        pathological tenant cannot absorb the whole budget."""
        w = t.weight
        if t.last_burn is not None and t.last_burn > 1.0:
            # burning its error budget: more workers shorten the unit
            # critical path and the retry/quarantine backlog
            w *= min(1.0 + math.log2(t.last_burn + 1.0), 4.0)
        if t.last_bound == "plan-bound":
            w *= 1.5  # planner threads are the direct lever
        if (t.latency_target_ms and t.last_p99_ms
                and t.last_p99_ms > t.latency_target_ms):
            w *= min(t.last_p99_ms / t.latency_target_ms, 4.0)
        return w

    def _recompute_locked(self) -> None:
        tenants = list(self._tenants.values())
        if not tenants:
            self._shares = {}
            return
        n, total = len(tenants), self.total_workers
        if total <= n:
            # more tenants than workers: the floor IS the share —
            # bounded oversubscription (one worker each), never zero
            shares = {t.label: 1 for t in tenants}
        else:
            weights = {t.label: self._effective_weight(t)
                       for t in tenants}
            wsum = sum(weights.values())
            rest = total - n  # after the 1-worker floors
            quota = {lb: rest * w / wsum for lb, w in weights.items()}
            shares = {lb: 1 + int(q) for lb, q in quota.items()}
            leftover = total - sum(shares.values())
            # largest remainder, label-ordered for determinism
            order = sorted(quota, key=lambda lb: (-(quota[lb] % 1), lb))
            for lb in order[:leftover]:
                shares[lb] += 1
        for t in tenants:
            t.share = shares[t.label]
        self._shares = shares  # wholesale swap: lock-free readers

    # -- admission control -----------------------------------------------

    def admit(self, label: str, *, est_bytes: int = 0,
              deadline_s: float | None = None, queue_depth: int = 0,
              queue_bound: int | None = None) -> None:
        """Admit one job or raise :class:`AdmissionRejected`.

        Checks, in order: bounded queue (``queue_depth`` vs
        ``queue_bound``), in-flight byte budget, and the deadline
        budget — a job whose ``deadline_s`` the current backlog
        cannot meet (estimated from the tenant's recent job-duration
        EWMA) is shed NOW rather than admitted to time out in line.
        On success the tenant's byte account is charged; a caller
        that fails to enqueue must :meth:`retract`."""
        bound = (queue_bound if queue_bound is not None
                 else queue_bound_default())
        with self._lock:
            t = self._tenants.get(label)
            if t is None:
                raise KeyError(f"unknown tenant {label!r}: "
                               f"register() it before submitting")
            retry = t.est_job_s if t.est_job_s is not None else 1.0
            if queue_depth >= bound:
                t.rejected += 1
                raise AdmissionRejected(
                    f"tenant {label!r} queue is full "
                    f"({queue_depth}/{bound}); retry in {retry:.1f}s",
                    tenant=label, reason="queue_full",
                    retry_after_s=retry)
            if (t.byte_budget is not None
                    and t.bytes_admitted + est_bytes > t.byte_budget):
                t.rejected += 1
                raise AdmissionRejected(
                    f"tenant {label!r} byte budget exhausted "
                    f"({t.bytes_admitted}+{est_bytes} > "
                    f"{t.byte_budget}); retry in {retry:.1f}s",
                    tenant=label, reason="byte_budget",
                    retry_after_s=retry)
            if (deadline_s is not None and t.est_job_s is not None
                    and t.est_job_s * (queue_depth + 1) > deadline_s):
                t.rejected += 1
                raise AdmissionRejected(
                    f"tenant {label!r} backlog (~{t.est_job_s:.1f}s x "
                    f"{queue_depth + 1} jobs) cannot meet the "
                    f"{deadline_s:g}s deadline; retry in {retry:.1f}s",
                    tenant=label, reason="deadline_budget",
                    retry_after_s=retry)
            t.bytes_admitted += est_bytes
            t.admitted += 1

    def retract(self, label: str, est_bytes: int = 0) -> None:
        """Roll back one :meth:`admit` whose job never enqueued."""
        with self._lock:
            t = self._tenants.get(label)
            if t is None:
                return
            t.bytes_admitted = max(t.bytes_admitted - est_bytes, 0)
            t.admitted = max(t.admitted - 1, 0)
            t.rejected += 1

    def release(self, label: str, est_bytes: int = 0) -> None:
        """Refund one finished job's byte charge.

        Unlike :meth:`retract` this is the NORMAL end of an admitted
        job's life (done, failed, or drained — the bytes are no
        longer in flight either way), so it does not touch the
        admitted/rejected tallies."""
        with self._lock:
            t = self._tenants.get(label)
            if t is None:
                return
            t.bytes_admitted = max(t.bytes_admitted - est_bytes, 0)

    def note_job_done(self, label: str, seconds: float, *,
                      ok: bool = True) -> None:
        """Fold one finished job into the duration EWMA the deadline
        admission check prices the backlog with."""
        with self._lock:
            t = self._tenants.get(label)
            if t is None:
                return
            t.jobs_done += 1
            if not ok:
                t.jobs_failed += 1
            t.est_job_s = (seconds if t.est_job_s is None
                           else 0.5 * t.est_job_s + 0.5 * seconds)

    # -- adaptive feedback -----------------------------------------------

    def rebalance(self) -> dict[str, int]:
        """Recompute shares from live feedback and return the new map.

        Feedback is gathered from the obs registries BEFORE the
        arbiter lock is taken (leaf-lock discipline): the per-label
        ledger counters give the doctor bound verdict and the
        WINDOWED error-budget burn (delta since the last rebalance),
        and the exact digests give the unit p99.  All three are
        optional — with telemetry off the arbiter degrades to static
        weighted fair sharing."""
        with self._lock:
            labels = list(self._tenants)
        if not labels:
            return {}
        from ..obs import attribution as _attr
        from ..obs import digest as _digest
        from ..obs.slo import error_rate

        led = _attr.ledgers_state()
        reg = _digest.digests()
        snap = reg.snapshot() if reg is not None else {}
        feedback = {}
        for label in labels:
            counters = (led.get(label) or {}).get("counters") or {}
            bound = _attr.stage_verdict(counters)
            d = snap.get((label, "unit"))
            p99_us = d.quantile(0.99) if d is not None and d.n else None
            feedback[label] = (bound, counters, p99_us)
        with self._lock:
            for label, (bound, counters, p99_us) in feedback.items():
                t = self._tenants.get(label)
                if t is None:
                    continue
                t.last_bound = bound
                t.last_p99_ms = (p99_us / 1000.0
                                 if p99_us is not None else None)
                window = {k: v - t._base_counters.get(k, 0)
                          for k, v in counters.items()}
                t._base_counters = dict(counters)
                rate, _, attempts = error_rate(window)
                t.last_burn = (rate / t.error_rate_target
                               if rate is not None and attempts
                               and t.error_rate_target else None)
            self._recompute_locked()
            return dict(self._shares)


# ----------------------------------------------------------------------
# Process-wide activation + thread binding
# ----------------------------------------------------------------------

_mod_lock = threading.Lock()
_active: ResourceArbiter | None = None
_binding = threading.local()


def activate(arb: ResourceArbiter) -> None:
    """Make ``arb`` THE process arbiter (one per process: two servers
    arbitrating the same cores independently would just rebuild the
    oversubscription this module exists to kill)."""
    global _active
    with _mod_lock:
        if _active is not None and _active is not arb:
            raise ServeStateError(
                "another ResourceArbiter is already active in this "
                "process; shut the other server down first")
        _active = arb


def deactivate(arb: ResourceArbiter) -> None:
    global _active
    with _mod_lock:
        if _active is arb:
            _active = None


def active() -> ResourceArbiter | None:
    return _active


@contextmanager
def tenant_scope(label: str | None):
    """Bind the calling thread to a tenant: thread-budget reads under
    this scope size from the tenant's arbiter share.  Re-entrant and
    restoring; ``label=None`` is the explicit unbind (a worker that
    adopted no binding)."""
    prev = getattr(_binding, "label", None)
    _binding.label = label
    try:
        yield
    finally:
        _binding.label = prev


def current_binding() -> str | None:
    """The calling thread's tenant label, for propagation onto worker
    threads (:func:`tpuparquet.deadline.call_with_deadline` captures
    this exactly like the trace context)."""
    return getattr(_binding, "label", None)


def plan_budget() -> int | None:
    """The calling thread's worker budget under the active arbiter,
    or None when no arbiter is active / the thread is unbound / the
    tenant is unknown — callers fall back to the legacy env knobs.
    Lock-free on purpose: this sits on the per-unit plan path."""
    arb = _active
    if arb is None:
        return None
    label = getattr(_binding, "label", None)
    if label is None:
        return None
    return arb.share_of(label)


def write_budget() -> int | None:
    """Writer-pool twin of :func:`plan_budget`: one tenant share
    bounds ALL of that tenant's workers — the library never runs the
    plan and encode pools for the same operation, so the share is not
    split between them."""
    return plan_budget()


# ----------------------------------------------------------------------
# Legacy-knob oversubscription guard
# ----------------------------------------------------------------------

_warn_lock = threading.Lock()
_warned_oversub = False


def warn_if_oversubscribed() -> int:
    """One-shot guard for the ``PLAN_SCALE_r06.json`` footgun: when
    the legacy ``TPQ_PLAN_THREADS`` + ``TPQ_WRITE_THREADS`` budgets
    are BOTH set and jointly exceed the usable cores, warn once
    (pointing at the arbiter) and publish the excess as the
    ``threads_oversubscribed`` registry gauge.  Returns the excess
    (0 = not oversubscribed / knobs unset / malformed)."""
    global _warned_oversub
    p = os.environ.get("TPQ_PLAN_THREADS")
    w = os.environ.get("TPQ_WRITE_THREADS")
    if not p or not w:
        return 0
    try:
        total = int(p) + int(w)
    except ValueError:
        return 0
    excess = total - _usable_cpus()
    if excess <= 0:
        return 0
    with _warn_lock:
        first = not _warned_oversub
        _warned_oversub = True
    if first:
        warnings.warn(
            f"TPQ_PLAN_THREADS+TPQ_WRITE_THREADS={total} exceeds the "
            f"{total - excess} usable core(s) by {excess}: concurrent "
            f"scan+write pools will contend (the PLAN_SCALE_r06 "
            f"regression); run under tpuparquet.serve.ResourceArbiter "
            f"for one global worker budget instead of per-pool knobs",
            RuntimeWarning, stacklevel=3)
    from ..obs.live import registry
    registry().gauge("threads_oversubscribed", float(excess))
    return excess


def _reset_oversub_warning() -> None:
    """Test hook: re-arm the one-shot warning."""
    global _warned_oversub
    with _warn_lock:
        _warned_oversub = False
